"""CramSink — single-file and multi-file CRAM write.

Reference parity: ``impl/formats/cram/CramSink.java`` (SURVEY.md §2.5):
per-shard container streams staged as parts, the driver writes the file
definition + SAM-header container prefix, concatenates, appends the CRAM
EOF container, and merges per-part ``.crai`` fragments with
offset-shifting (htsjdk ``CRAIIndexMerger``).
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

from disq_tpu.api import CraiWriteOption, TempPartsDirectoryWriteOption, WriteOption
from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.cram.codec import encode_container
from disq_tpu.cram.crai import CraiEntry, CraiIndex
from disq_tpu.cram.structure import (
    Block,
    ContainerHeader,
    EOF_CONTAINER,
    FILE_HEADER,
    GZIP,
    RAW,
    file_definition,
)
from disq_tpu.fsw.filesystem import resolve_path
from disq_tpu.util import shard_bounds

MAX_SLICE_RECORDS = 10_000


from disq_tpu.cram.refsource import fetcher_for_storage as _ref_fetcher


def run_cram_write_stage(storage, fs, batch, bounds, n_shards, ref_fetch,
                         part_path_for, assemble=None):
    """Shared shard fan-out for both CRAM sinks: container encoding
    (the dominant CPU cost — CRAM codecs compress inside
    ``encode_container``, so there is no separate deflate stage) runs
    on the write pipeline's encode workers while staged parts stream
    out on its I/O workers. ``assemble(part_bytes)`` optionally wraps
    each shard's container stream into a complete file (MULTIPLE
    cardinality). Per-shard ``record_counter_base`` is the shard's
    absolute record start, so output is worker-count invariant."""
    from disq_tpu.runtime.executor import (
        WriteShardTask,
        run_write_stage,
        write_retrier_for_storage,
        writer_for_storage,
    )
    from disq_tpu.runtime.tracing import wrap_span

    def make_task(k):
        def encode():
            lo, hi = int(bounds[k]), int(bounds[k + 1])
            part_bytes, entries = encode_part(
                batch.slice(lo, hi), lo if assemble is None else 0,
                ref_fetch,
            )
            if assemble is not None:
                part_bytes = assemble(part_bytes)
            return part_bytes, entries

        def stage(payload):
            part_bytes, entries = payload
            p = part_path_for(k)
            fs.write_all(p, part_bytes)
            return {"part": p, "len": len(part_bytes),
                    "crai": CraiIndex(entries)}

        return WriteShardTask(
            shard_id=k,
            encode=wrap_span("cram.write.encode", encode, shard=k),
            stage=wrap_span("cram.write.stage", stage, shard=k),
            retrier=write_retrier_for_storage(storage, part_path_for(k)),
            what="cram.part",
        )

    # storage+path wired through for the scheduler's write-direction
    # leasing gate (inert here: no StageManifest rides along)
    return run_write_stage(writer_for_storage(storage), n_shards,
                           make_task, storage=storage,
                           path=part_path_for(0))


def _header_container(header) -> bytes:
    """First container: the SAM header in a FILE_HEADER block."""
    text = header.text.encode()
    content = struct.pack("<i", len(text)) + text
    block = Block(FILE_HEADER, 0, content, RAW).to_bytes()
    hdr = ContainerHeader(
        length=len(block), ref_seq_id=0, ref_start=0, ref_span=0,
        n_records=0, record_counter=0, bases=0, n_blocks=1, landmarks=[],
    )
    return hdr.to_bytes() + block


def _ref_runs(batch: ReadBatch) -> List[tuple]:
    """Split a batch into (start, stop, refid) runs of equal refid, each
    capped at MAX_SLICE_RECORDS (single-ref slices)."""
    runs = []
    n = batch.count
    if n == 0:
        return runs
    refids = batch.refid
    change = np.nonzero(np.diff(refids))[0] + 1
    bounds = np.concatenate([[0], change, [n]])
    for a, b in zip(bounds[:-1], bounds[1:]):
        for s in range(int(a), int(b), MAX_SLICE_RECORDS):
            runs.append((s, min(s + MAX_SLICE_RECORDS, int(b)), int(refids[a])))
    return runs


def encode_part(
    batch: ReadBatch, record_counter_base: int, ref_fetch
) -> tuple[bytes, List[CraiEntry]]:
    """Shard worker: encode a batch into containers; crai entries carry
    part-relative container offsets."""
    out = bytearray()
    entries: List[CraiEntry] = []
    counter = record_counter_base
    for s, e, refid in _ref_runs(batch):
        part = batch.slice(s, e)
        container, info = encode_container(part, refid, counter, ref_fetch)
        entries.append(
            CraiEntry(
                seq_id=info["ref_seq_id"],
                start=info["ref_start"], span=info["ref_span"],
                container_offset=len(out),
                slice_offset=info["slice_offset"],
                slice_size=info["slice_size"],
            )
        )
        out += container
        counter += part.count
    return bytes(out), entries


class CramSink:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        from disq_tpu.runtime.executor import write_retrier_for_storage

        fs, path = resolve_path(path)
        header = dataset.header
        batch: ReadBatch = dataset.reads
        write_crai = any(
            isinstance(o, CraiWriteOption) and o.value for o in options
        )
        ref_fetch = _ref_fetcher(self._storage, header)
        temp_dir = next(
            (o.path for o in options if isinstance(o, TempPartsDirectoryWriteOption)),
            path + ".parts",
        )
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(temp_dir)
        try:
            prefix = file_definition() + _header_container(header)
            infos = run_cram_write_stage(
                self._storage, fs, batch, bounds, n_shards, ref_fetch,
                lambda k: os.path.join(temp_dir, f"part-{k:05d}"),
            )
            part_paths = [i["part"] for i in infos]
            part_lens = [i["len"] for i in infos]
            frags = [i["crai"] for i in infos]
            driver = write_retrier_for_storage(self._storage, path)
            prefix_path = os.path.join(temp_dir, "_prefix")
            driver.call(fs.write_all, prefix_path, prefix,
                        what="cram.merge")
            eof_path = os.path.join(temp_dir, "_eof")
            driver.call(fs.write_all, eof_path, EOF_CONTAINER,
                        what="cram.merge")
            driver.call(fs.concat, [prefix_path] + part_paths + [eof_path],
                        path, what="cram.merge")
            if write_crai:
                part_starts = np.zeros(len(part_lens), dtype=np.int64)
                np.cumsum(part_lens[:-1], out=part_starts[1:])
                part_starts += len(prefix)
                merged = CraiIndex.merge(frags, list(part_starts))
                driver.call(fs.write_all, path + ".crai",
                            merged.to_bytes(), what="cram.merge")
        finally:
            fs.delete(temp_dir, recursive=True)


class CramSinkMultiple:
    """Directory of complete per-shard CRAMs (``MULTIPLE`` cardinality)."""

    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        header = dataset.header
        batch = dataset.reads
        ref_fetch = _ref_fetcher(self._storage, header)
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(path)
        prefix = file_definition() + _header_container(header)
        run_cram_write_stage(
            self._storage, fs, batch, bounds, n_shards, ref_fetch,
            lambda k: os.path.join(path, f"part-r-{k:05d}.cram"),
            assemble=lambda part_bytes: prefix + part_bytes + EOF_CONTAINER,
        )
