class CramSink:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path, options=()):
        raise NotImplementedError(
            "CRAM write support is not built yet in this milestone "
            "(planned, SURVEY.md §2.5)"
        )
