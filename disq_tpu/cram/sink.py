"""CramSink — single-file and multi-file CRAM write.

Reference parity: ``impl/formats/cram/CramSink.java`` (SURVEY.md §2.5):
per-shard container streams staged as parts, the driver writes the file
definition + SAM-header container prefix, concatenates, appends the CRAM
EOF container, and merges per-part ``.crai`` fragments with
offset-shifting (htsjdk ``CRAIIndexMerger``).
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

from disq_tpu.api import CraiWriteOption, TempPartsDirectoryWriteOption, WriteOption
from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.cram.codec import encode_container
from disq_tpu.cram.crai import CraiEntry, CraiIndex
from disq_tpu.cram.structure import (
    Block,
    ContainerHeader,
    EOF_CONTAINER,
    FILE_HEADER,
    GZIP,
    RAW,
    file_definition,
)
from disq_tpu.fsw.filesystem import resolve_path
from disq_tpu.util import shard_bounds

MAX_SLICE_RECORDS = 10_000


from disq_tpu.cram.refsource import fetcher_for_storage as _ref_fetcher


def _header_container(header) -> bytes:
    """First container: the SAM header in a FILE_HEADER block."""
    text = header.text.encode()
    content = struct.pack("<i", len(text)) + text
    block = Block(FILE_HEADER, 0, content, RAW).to_bytes()
    hdr = ContainerHeader(
        length=len(block), ref_seq_id=0, ref_start=0, ref_span=0,
        n_records=0, record_counter=0, bases=0, n_blocks=1, landmarks=[],
    )
    return hdr.to_bytes() + block


def _ref_runs(batch: ReadBatch) -> List[tuple]:
    """Split a batch into (start, stop, refid) runs of equal refid, each
    capped at MAX_SLICE_RECORDS (single-ref slices)."""
    runs = []
    n = batch.count
    if n == 0:
        return runs
    refids = batch.refid
    change = np.nonzero(np.diff(refids))[0] + 1
    bounds = np.concatenate([[0], change, [n]])
    for a, b in zip(bounds[:-1], bounds[1:]):
        for s in range(int(a), int(b), MAX_SLICE_RECORDS):
            runs.append((s, min(s + MAX_SLICE_RECORDS, int(b)), int(refids[a])))
    return runs


def encode_part(
    batch: ReadBatch, record_counter_base: int, ref_fetch
) -> tuple[bytes, List[CraiEntry]]:
    """Shard worker: encode a batch into containers; crai entries carry
    part-relative container offsets."""
    out = bytearray()
    entries: List[CraiEntry] = []
    counter = record_counter_base
    for s, e, refid in _ref_runs(batch):
        part = batch.slice(s, e)
        container, info = encode_container(part, refid, counter, ref_fetch)
        entries.append(
            CraiEntry(
                seq_id=info["ref_seq_id"],
                start=info["ref_start"], span=info["ref_span"],
                container_offset=len(out),
                slice_offset=info["slice_offset"],
                slice_size=info["slice_size"],
            )
        )
        out += container
        counter += part.count
    return bytes(out), entries


class CramSink:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        header = dataset.header
        batch: ReadBatch = dataset.reads
        write_crai = any(
            isinstance(o, CraiWriteOption) and o.value for o in options
        )
        ref_fetch = _ref_fetcher(self._storage, header)
        temp_dir = next(
            (o.path for o in options if isinstance(o, TempPartsDirectoryWriteOption)),
            path + ".parts",
        )
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(temp_dir)
        try:
            prefix = file_definition() + _header_container(header)
            part_paths, part_lens, frags = [], [], []
            for k in range(n_shards):
                lo, hi = int(bounds[k]), int(bounds[k + 1])
                part_bytes, entries = encode_part(
                    batch.slice(lo, hi), lo, ref_fetch
                )
                p = os.path.join(temp_dir, f"part-{k:05d}")
                fs.write_all(p, part_bytes)
                part_paths.append(p)
                part_lens.append(len(part_bytes))
                frags.append(CraiIndex(entries))
            prefix_path = os.path.join(temp_dir, "_prefix")
            fs.write_all(prefix_path, prefix)
            eof_path = os.path.join(temp_dir, "_eof")
            fs.write_all(eof_path, EOF_CONTAINER)
            fs.concat([prefix_path] + part_paths + [eof_path], path)
            if write_crai:
                part_starts = np.zeros(len(part_lens), dtype=np.int64)
                np.cumsum(part_lens[:-1], out=part_starts[1:])
                part_starts += len(prefix)
                merged = CraiIndex.merge(frags, list(part_starts))
                fs.write_all(path + ".crai", merged.to_bytes())
        finally:
            fs.delete(temp_dir, recursive=True)


class CramSinkMultiple:
    """Directory of complete per-shard CRAMs (``MULTIPLE`` cardinality)."""

    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        header = dataset.header
        batch = dataset.reads
        ref_fetch = _ref_fetcher(self._storage, header)
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(path)
        prefix = file_definition() + _header_container(header)
        for k in range(n_shards):
            lo, hi = int(bounds[k]), int(bounds[k + 1])
            part_bytes, _ = encode_part(batch.slice(lo, hi), 0, ref_fetch)
            fs.write_all(
                os.path.join(path, f"part-r-{k:05d}.cram"),
                prefix + part_bytes + EOF_CONTAINER,
            )
