"""rANS 4x8 codec (CRAM 3.0 §13: rANS order-0 and order-1).

Replaces htsjdk's ``RANSExternalCompressor``/rANS codec classes. Stream
layout (matching htslib's rANS_static):

    order u8 · comp_size u32le · raw_size u32le · frequency table ·
    4 interleaved rANS states (u32le each) · renormalization bytes

Constants: 12-bit frequency precision (sum 4096), lower bound 1<<23,
byte-wise renormalization, 4 states round-robin over output positions.

Order 0 and order 1 are both implemented for encode and decode. The
writer emits order-0 for general blocks and order-1 for quality
scores (the htslib QS default; ``DISQ_TPU_CRAM_RANS_O1=0`` opts out).
Both encoders have native C fast paths byte-identical to the Python
implementations.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

TF_SHIFT = 12
TOTFREQ = 1 << TF_SHIFT      # 4096
RANS_LOW = 1 << 23


# -- frequency tables -------------------------------------------------------

def _normalize_freqs(counts: np.ndarray, total: int = TOTFREQ) -> np.ndarray:
    """Scale symbol counts to sum exactly ``total``, every present symbol
    keeping freq >= 1."""
    n = counts.sum()
    if n == 0:
        return counts.astype(np.int64)
    f = counts.astype(np.float64) * total / n
    out = np.floor(f).astype(np.int64)
    out[(counts > 0) & (out == 0)] = 1
    # Adjust to hit the exact total: add/remove from the largest symbols.
    # Stable sort (ties by symbol index) so the native C++ codec can
    # reproduce the same table byte-for-byte.
    diff = total - out.sum()
    order = np.argsort(-out, kind="stable")
    i = 0
    while diff != 0:
        s = order[i % len(order)]
        if out[s] > 0 or diff > 0:
            step = 1 if diff > 0 else -1
            if out[s] + step >= 1 or counts[s] == 0:
                out[s] += step
                diff -= step
        i += 1
    return out


def _write_freq_table0(freqs: np.ndarray) -> bytes:
    out = bytearray()
    syms = np.nonzero(freqs)[0]
    rle = 0
    for idx, s in enumerate(syms):
        if rle > 0:
            rle -= 1
        else:
            out.append(int(s))
            if idx > 0 and s == syms[idx - 1] + 1:
                # count run of consecutive symbols following s
                run = 0
                while idx + run + 1 < len(syms) and syms[idx + run + 1] == s + run + 1:
                    run += 1
                out.append(run)
                rle = run
        f = int(freqs[s])
        if f < 128:
            out.append(f)
        else:
            out.append(0x80 | (f >> 8))
            out.append(f & 0xFF)
    out.append(0)
    return bytes(out)


def _read_freq_table0(data, off: int) -> Tuple[np.ndarray, int]:
    freqs = np.zeros(256, dtype=np.int64)
    rle = 0
    sym = data[off]
    off += 1
    last = -2
    while True:
        f = data[off]
        off += 1
        if f >= 128:
            f = ((f & 0x7F) << 8) | data[off]
            off += 1
        freqs[sym] = f
        if rle > 0:
            rle -= 1
            last = sym
            sym = sym + 1
            continue
        last = sym
        nxt = data[off]
        off += 1
        if nxt == 0:
            break
        if nxt == last + 1:
            rle = data[off]
            off += 1
        sym = nxt
    return freqs, off


# -- order-0 encode ---------------------------------------------------------

def rans_encode_order0(raw: bytes) -> bytes:
    try:
        from disq_tpu.native import rans_encode0_native

        return rans_encode0_native(raw)
    except ImportError:
        pass
    data = np.frombuffer(raw, dtype=np.uint8)
    n = len(data)
    if n == 0:
        return struct.pack("<BII", 0, 0, 0)
    counts = np.bincount(data, minlength=256)
    freqs = _normalize_freqs(counts)
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(freqs, out=cum[1:])
    table = _write_freq_table0(freqs)

    states = [RANS_LOW] * 4
    out_rev = bytearray()  # renorm bytes, reversed at the end
    fr = freqs
    cm = cum
    # Encode in reverse; symbol i belongs to state i & 3.
    for i in range(n - 1, -1, -1):
        s = int(data[i])
        j = i & 3
        x = states[j]
        f = int(fr[s])
        x_max = ((RANS_LOW >> TF_SHIFT) << 8) * f
        while x >= x_max:
            out_rev.append(x & 0xFF)
            x >>= 8
        states[j] = ((x // f) << TF_SHIFT) + (x % f) + int(cm[s])
    payload = b"".join(struct.pack("<I", states[j]) for j in range(4))
    payload += bytes(reversed(out_rev))
    body = table + payload
    return struct.pack("<BII", 0, len(body), n) + body


# -- order-1 encode ---------------------------------------------------------

def rans_encode_order1(raw: bytes) -> bytes:
    """Order-1 rANS 4x8 (htslib wire format): 4 interleaved states, each
    encoding a contiguous quarter with the previous byte as context
    (context 0 at each quarter start). Exact inverse of ``_decode1`` —
    the decode loop pops renorm bytes round-robin per position, so the
    encoder walks that schedule in reverse.

    Reference behavior: htsjdk/htslib rANS order-1 (SURVEY.md §2.8 CRAM
    row; VERDICT r4 item 7)."""
    try:
        from disq_tpu.native import rans_encode1_native

        return rans_encode1_native(raw)
    except ImportError:
        pass
    data = np.frombuffer(raw, dtype=np.uint8)
    n = len(data)
    if n == 0:
        return struct.pack("<BII", 1, 0, 0)
    q = n // 4
    starts = [0, q, 2 * q, 3 * q]
    ends = [q, 2 * q, 3 * q, n]

    # per-context symbol counts (context = previous byte in the quarter,
    # 0 at quarter start)
    counts = np.zeros((256, 256), dtype=np.int64)
    for j in range(4):
        s, e = starts[j], ends[j]
        if e > s:
            seg = data[s:e]
            prev = np.concatenate([[np.uint8(0)], seg[:-1]])
            np.add.at(counts, (prev, seg), 1)
    present = np.flatnonzero(counts.sum(axis=1) > 0)
    freqs = np.zeros((256, 256), dtype=np.int64)
    for c in present:
        freqs[c] = _normalize_freqs(counts[c])
    cum = np.zeros((256, 257), dtype=np.int64)
    np.cumsum(freqs, axis=1, out=cum[:, 1:])

    # context table header mirroring _decode1's RLE-over-contexts parse
    table = bytearray()
    i = 0
    plist = [int(c) for c in present]
    while i < len(plist):
        run = 1
        while (i + run < len(plist)
               and plist[i + run] == plist[i] + run):
            run += 1
        table.append(plist[i])
        table += _write_freq_table0(freqs[plist[i]])
        if run > 1:
            # parser: nxt == last+1 -> read rle count, then auto-advance
            table.append(plist[i] + 1)
            table.append(run - 2)
            for k in range(1, run):
                table += _write_freq_table0(freqs[plist[i] + k])
        i += run
    table.append(0)  # terminator

    # encode: reverse of the decode schedule. Decode pops (k, j) in
    # order k=0..: j=0..3 (j active while k < len_j); we push reversed.
    lens = [ends[j] - starts[j] for j in range(4)]
    kmax = max(lens)
    states = [RANS_LOW] * 4
    out_rev = bytearray()
    for k in range(kmax - 1, -1, -1):
        for j in (3, 2, 1, 0):
            if k >= lens[j]:
                continue
            p = starts[j] + k
            s = int(data[p])
            c = 0 if k == 0 else int(data[p - 1])
            x = states[j]
            f = int(freqs[c][s])
            x_max = ((RANS_LOW >> TF_SHIFT) << 8) * f
            while x >= x_max:
                out_rev.append(x & 0xFF)
                x >>= 8
            states[j] = ((x // f) << TF_SHIFT) + (x % f) + int(cum[c][s])
    payload = b"".join(struct.pack("<I", states[j]) for j in range(4))
    payload += bytes(reversed(out_rev))
    body = bytes(table) + payload
    return struct.pack("<BII", 1, len(body), n) + body


# -- decode (order 0 and 1) -------------------------------------------------

def rans_decode(data: bytes) -> bytes:
    order, comp_size, raw_size = struct.unpack_from("<BII", data, 0)
    if raw_size == 0:
        return b""
    if order == 0:
        from disq_tpu.runtime.debug import env_flag

        import os

        if os.environ.get("DISQ_TPU_DEVICE_RANS", "").lower() == "legacy":
            # round-1 scalar kernel (one stream per grid program)
            from disq_tpu.ops.rans import rans0_decode_device

            return rans0_decode_device([data])[0]
        if env_flag("DISQ_TPU_DEVICE_RANS"):
            from disq_tpu.runtime import device_service

            if device_service.enabled():
                # cross-shard lane batching: this stream coalesces with
                # other decode workers' streams into full 128-lane
                # launches (runtime/device_service.py).  NOTE: with a
                # single decode worker there is nothing to coalesce
                # with, and every lone stream pays the batcher's flush
                # timeout — the service flag is for executor_workers>1
                # runs; leave it off for sequential decode.
                return device_service.get_service().submit_rans(
                    [data]).result()[0]
            # 128-lane SIMD kernel path: disq_tpu.ops.rans_simd.
            from disq_tpu.ops.rans_simd import rans0_decode_simd

            return rans0_decode_simd([data])[0]
    if order in (0, 1):
        try:
            from disq_tpu.native import rans_decode_native

            return rans_decode_native(data)
        except ImportError:
            pass
    body = memoryview(data)[9:9 + comp_size]
    if order == 0:
        return _decode0(body, raw_size)
    if order == 1:
        return _decode1(body, raw_size)
    raise ValueError(f"unknown rANS order {order}")


def _decode0(body, raw_size: int) -> bytes:
    freqs, off = _read_freq_table0(body, 0)
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(freqs, out=cum[1:])
    # symbol lookup over the 4096 slots
    lookup = np.repeat(np.arange(256, dtype=np.uint8), freqs)
    if len(lookup) != TOTFREQ:
        raise ValueError("rANS frequency table does not sum to 4096")
    states = list(struct.unpack_from("<4I", body, off))
    off += 16
    out = np.empty(raw_size, dtype=np.uint8)
    fr = freqs
    cm = cum
    ln = len(body)
    for i in range(raw_size):
        j = i & 3
        x = states[j]
        m = x & (TOTFREQ - 1)
        s = int(lookup[m])
        out[i] = s
        x = int(fr[s]) * (x >> TF_SHIFT) + m - int(cm[s])
        while x < RANS_LOW and off < ln:
            x = (x << 8) | body[off]
            off += 1
        states[j] = x
    return out.tobytes()


def _decode1(body, raw_size: int) -> bytes:
    """Order-1: 256 context tables (tables for contexts actually present,
    RLE over contexts like the order-0 symbol list)."""
    freqs = np.zeros((256, 256), dtype=np.int64)
    off = 0
    rle_i = 0
    i = body[off]
    off += 1
    last_i = -2
    while True:
        f, off = _read_freq_table0(body, off)
        freqs[i] = f
        if rle_i > 0:
            rle_i -= 1
            last_i = i
            i += 1
            continue
        last_i = i
        nxt = body[off]
        off += 1
        if nxt == 0:
            break
        if nxt == last_i + 1:
            rle_i = body[off]
            off += 1
        i = nxt
    cum = np.zeros((256, 257), dtype=np.int64)
    np.cumsum(freqs, axis=1, out=cum[:, 1:])
    lookups = {}
    states = list(struct.unpack_from("<4I", body, off))
    off += 16
    out = np.empty(raw_size, dtype=np.uint8)
    # 4 interleaved streams, each decoding a contiguous quarter.
    q = raw_size // 4
    ptrs = [0, q, 2 * q, 3 * q]
    ctx = [0, 0, 0, 0]
    ends = [q, 2 * q, 3 * q, raw_size]
    ln = len(body)
    remaining = raw_size
    # htslib decodes i4[] positions round-robin until each hits its end
    pos = ptrs[:]
    done = [False] * 4
    while remaining:
        for j in range(4):
            if pos[j] >= ends[j]:
                done[j] = True
                continue
            c = ctx[j]
            if c not in lookups:
                lk = np.repeat(np.arange(256, dtype=np.uint8), freqs[c])
                if len(lk) != TOTFREQ:
                    raise ValueError("rANS o1 table does not sum to 4096")
                lookups[c] = lk
            x = states[j]
            m = x & (TOTFREQ - 1)
            s = int(lookups[c][m])
            out[pos[j]] = s
            x = int(freqs[c][s]) * (x >> TF_SHIFT) + m - int(cum[c][s])
            while x < RANS_LOW and off < ln:
                x = (x << 8) | body[off]
                off += 1
            states[j] = x
            ctx[j] = s
            pos[j] += 1
            remaining -= 1
    return out.tobytes()
