"""CRAM low-level IO: ITF8 / LTF8 varints and byte cursors.

Replaces htsjdk's ``ITF8``/``LTF8``/``CramInt`` utilities (the CRAM 3.0
spec §2.3 integer encodings used throughout container/block headers).

ITF8: up to 5 bytes; the number of leading 1-bits in the first byte
(before the first 0) gives the count of additional bytes. LTF8: same
scheme for 64-bit values, up to 9 bytes.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np


def write_itf8(value: int) -> bytes:
    v = value & 0xFFFFFFFF
    if v < 0x80:
        return bytes([v])
    if v < 0x4000:
        return bytes([0x80 | (v >> 8), v & 0xFF])
    if v < 0x200000:
        return bytes([0xC0 | (v >> 16), (v >> 8) & 0xFF, v & 0xFF])
    if v < 0x10000000:
        return bytes([
            0xE0 | (v >> 24), (v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF
        ])
    return bytes([
        0xF0 | ((v >> 28) & 0x0F), (v >> 20) & 0xFF, (v >> 12) & 0xFF,
        (v >> 4) & 0xFF, v & 0x0F,
    ])


def write_itf8_array(vals) -> bytes:
    """Vectorized ITF8 encode of a whole value array — the encode-side
    mirror of the decode table (CRAM writers emit one varint per record
    per fixed series; per-value ``write_itf8`` was the hottest part of
    container encode). Byte-identical to ``write_itf8`` per value."""
    v = (np.asarray(vals, np.int64) & 0xFFFFFFFF).astype(np.uint32)
    n = len(v)
    if n == 0:
        return b""
    nb = np.full(n, 5, np.int64)
    nb[v < 0x10000000] = 4
    nb[v < 0x200000] = 3
    nb[v < 0x4000] = 2
    nb[v < 0x80] = 1
    off = np.zeros(n + 1, np.int64)
    np.cumsum(nb, out=off[1:])
    out = np.zeros(int(off[-1]), np.uint8)
    idx = off[:-1]
    m = nb == 1
    out[idx[m]] = v[m]
    m = nb == 2
    out[idx[m]] = 0x80 | (v[m] >> 8)
    out[idx[m] + 1] = v[m] & 0xFF
    m = nb == 3
    out[idx[m]] = 0xC0 | (v[m] >> 16)
    out[idx[m] + 1] = (v[m] >> 8) & 0xFF
    out[idx[m] + 2] = v[m] & 0xFF
    m = nb == 4
    out[idx[m]] = 0xE0 | (v[m] >> 24)
    out[idx[m] + 1] = (v[m] >> 16) & 0xFF
    out[idx[m] + 2] = (v[m] >> 8) & 0xFF
    out[idx[m] + 3] = v[m] & 0xFF
    m = nb == 5
    out[idx[m]] = 0xF0 | ((v[m] >> 28) & 0x0F)
    out[idx[m] + 1] = (v[m] >> 20) & 0xFF
    out[idx[m] + 2] = (v[m] >> 12) & 0xFF
    out[idx[m] + 3] = (v[m] >> 4) & 0xFF
    out[idx[m] + 4] = v[m] & 0x0F
    return out.tobytes()


def read_itf8(data, offset: int) -> Tuple[int, int]:
    """→ (value as signed int32, new offset)."""
    b0 = data[offset]
    if b0 < 0x80:
        v, off = b0, offset + 1
    elif b0 < 0xC0:
        v = ((b0 & 0x7F) << 8) | data[offset + 1]
        off = offset + 2
    elif b0 < 0xE0:
        v = ((b0 & 0x3F) << 16) | (data[offset + 1] << 8) | data[offset + 2]
        off = offset + 3
    elif b0 < 0xF0:
        v = (
            ((b0 & 0x1F) << 24) | (data[offset + 1] << 16)
            | (data[offset + 2] << 8) | data[offset + 3]
        )
        off = offset + 4
    else:
        v = (
            ((b0 & 0x0F) << 28) | (data[offset + 1] << 20)
            | (data[offset + 2] << 12) | (data[offset + 3] << 4)
            | (data[offset + 4] & 0x0F)
        )
        off = offset + 5
    if v >= 1 << 31:
        v -= 1 << 32
    return v, off


def write_ltf8(value: int) -> bytes:
    v = value & 0xFFFFFFFFFFFFFFFF
    if v < 0x80:
        return bytes([v])
    for extra in range(1, 8):
        # `extra` additional bytes carry 8*extra bits; the first byte
        # (extra leading ones, then 0) carries 7-extra more.
        if v < 1 << (7 + 7 * extra):
            lead = (0xFF << (8 - extra)) & 0xFF
            first = lead | (v >> (8 * extra))
            rest = [(v >> (8 * (extra - 1 - k))) & 0xFF for k in range(extra)]
            return bytes([first] + rest)
    return bytes([0xFF]) + struct.pack(">Q", v)


def read_ltf8(data, offset: int) -> Tuple[int, int]:
    b0 = data[offset]
    # count leading ones
    ones = 0
    while ones < 8 and (b0 << ones) & 0x80:
        ones += 1
    if ones == 0:
        v, off = b0, offset + 1
    elif ones == 8:
        (v,) = struct.unpack_from(">Q", bytes(data[offset + 1: offset + 9]), 0)
        off = offset + 9
    else:
        v = b0 & (0x7F >> ones)
        for k in range(ones):
            v = (v << 8) | data[offset + 1 + k]
        off = offset + 1 + ones
    if v >= 1 << 63:
        v -= 1 << 64
    return v, off


class Cursor:
    """Sequential reader over a bytes-like object.

    Streams that pull many ITF8 values (CRAM data-series external
    blocks read roughly one varint per record per series) opt in with
    ``itf8_table=True`` and switch to a vectorized
    decode-at-every-offset table after ``_ITF8_TABLE_AFTER`` scalar
    reads: one numpy pass precomputes (value, length) for all byte
    positions and each subsequent ``itf8()`` is two array indexes.
    Header cursors (a handful of varints over a whole-container buffer,
    where the O(len) build could never amortize) stay scalar."""

    _ITF8_TABLE_AFTER = 16

    def __init__(self, data, offset: int = 0, itf8_table: bool = False):
        self.data = data
        self.off = offset
        self._v = None
        self._nb = None
        self._ni = 0 if itf8_table else -(1 << 60)

    def _build_itf8_table(self) -> None:
        # uint32 arithmetic wraps exactly like the scalar reader's
        # masked shifts; .view(int32) restores the signed contract
        a = np.frombuffer(self.data, np.uint8).astype(np.uint32)
        n = len(a)
        p = np.concatenate([a, np.zeros(4, np.uint32)])
        b0 = p[:n]
        b1, b2, b3, b4 = p[1:n + 1], p[2:n + 2], p[3:n + 3], p[4:n + 4]
        conds = [b0 < 0x80, b0 < 0xC0, b0 < 0xE0, b0 < 0xF0]
        v = np.select(conds, [
            b0,
            ((b0 & 0x7F) << 8) | b1,
            ((b0 & 0x3F) << 16) | (b1 << 8) | b2,
            ((b0 & 0x1F) << 24) | (b1 << 16) | (b2 << 8) | b3,
        ], ((b0 & 0x0F) << 28) | (b1 << 20) | (b2 << 12) | (b3 << 4)
           | (b4 & 0x0F))
        self._v = v.view(np.int32)
        self._nb = np.select(conds, [1, 2, 3, 4], 5).astype(np.uint8)

    def itf8(self) -> int:
        v = self._v
        if v is not None:
            o = self.off
            nb_arr = self._nb
            if o >= len(v):
                raise IndexError("ITF8 read past end of stream")
            nb = int(nb_arr[o])
            if o + nb > len(v):
                # varint truncated at the stream end: the table decoded
                # against zero padding — raise like the scalar reader
                raise IndexError("truncated ITF8 at end of stream")
            self.off = o + nb
            return int(v[o])
        self._ni += 1
        if self._ni >= self._ITF8_TABLE_AFTER:
            self._build_itf8_table()
        v, self.off = read_itf8(self.data, self.off)
        return v

    def itf8_bulk(self, count: int) -> List[int]:
        """``count`` sequential ITF8 values in one fused walk over the
        decode table (the CRAM columnar fast path pulls whole
        per-series value streams with this). Raises IndexError past the
        stream end, like ``itf8``."""
        if count <= 0:
            return []
        if self._v is None:
            self._build_itf8_table()
        # the walk touches most of the stream, so list conversion
        # amortizes and python-list indexing beats numpy scalar reads
        vl = self._v.tolist()
        nbl = self._nb.tolist()
        ln = len(vl)
        off = self.off
        out = []
        ap = out.append
        for _ in range(count):
            if off >= ln:
                raise IndexError("ITF8 read past end of stream")
            w = nbl[off]
            if off + w > ln:
                raise IndexError("truncated ITF8 at end of stream")
            ap(vl[off])
            off += w
        self.off = off
        return out

    def len_prefixed_bulk(self, count: int) -> List[bytes]:
        """``count`` (ITF8 length, payload bytes) items from an
        interleaved stream (the layout CRAM BYTE_ARRAY_LEN uses when
        length and value share one block — e.g. tag value series).
        Raises IndexError past the stream end."""
        if count <= 0:
            return []
        if self._v is None:
            self._build_itf8_table()
        vl, nbl = self._v, self._nb
        ln_total = len(vl)
        data = self.data
        off = self.off
        out = []
        ap = out.append
        for _ in range(count):
            if off >= ln_total:
                raise IndexError("read past end of stream")
            w = int(nbl[off])
            if off + w > ln_total:
                raise IndexError("truncated ITF8 at end of stream")
            ln = int(vl[off])
            off += w
            if ln < 0 or off + ln > ln_total:
                raise IndexError("length-prefixed item overruns stream")
            ap(bytes(data[off: off + ln]))
            off += ln
        self.off = off
        return out

    def ltf8(self) -> int:
        v, self.off = read_ltf8(self.data, self.off)
        return v

    def bytes(self, n: int) -> bytes:
        b = bytes(self.data[self.off: self.off + n])
        if len(b) != n:
            raise ValueError("truncated CRAM stream")
        self.off += n
        return b

    def u8(self) -> int:
        v = self.data[self.off]
        self.off += 1
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from("<i", self.data, self.off)
        self.off += 4
        return v

    def itf8_array(self) -> List[int]:
        n = self.itf8()
        return [self.itf8() for _ in range(n)]
