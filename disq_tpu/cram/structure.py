"""CRAM 3.0 container / block structures.

Reference parity: htsjdk's ``Container``/``Block``/``CramHeader`` +
``CramContainerHeaderIterator`` (used by disq's ``CramSource``,
SURVEY.md §2.5). Layout per the CRAM 3.0 specification:

- file: magic ``CRAM`` + major.minor + 20-byte file id, then containers,
  ending with the fixed EOF container.
- container header: length i32 · ref_seq_id ITF8 · ref_start ITF8 ·
  ref_span ITF8 · n_records ITF8 · record_counter LTF8 · bases LTF8 ·
  n_blocks ITF8 · landmarks ITF8[] · crc32 u32.
- block: method u8 (0 raw · 1 gzip · 4 rans4x8) · content_type u8 ·
  content_id ITF8 · comp_size ITF8 · raw_size ITF8 · data · crc32 u32.
"""

from __future__ import annotations

import gzip as _gzip
import struct
import zlib
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from disq_tpu.cram.io import Cursor, write_itf8, write_ltf8
from disq_tpu.cram.rans import rans_decode, rans_encode_order0

CRAM_MAGIC = b"CRAM"
CRAM_VERSION = (3, 0)

# Block compression methods
RAW, GZIP, BZIP2, LZMA, RANS = 0, 1, 2, 3, 4
# Block content types
FILE_HEADER, COMPRESSION_HEADER, MAPPED_SLICE, EXTERNAL, CORE = 0, 1, 2, 4, 5

# The fixed 38-byte EOF container (CRAM 3.0 spec §9; byte-for-byte).
EOF_CONTAINER = bytes.fromhex(
    "0f000000ffffffff0fe0454f4600000000010005bdd94f0001000606010001"
    "000100ee63014b"
)


def file_definition(file_id: bytes = b"\x00" * 20) -> bytes:
    assert len(file_id) == 20
    return CRAM_MAGIC + bytes(CRAM_VERSION) + file_id


def read_file_definition(data, offset: int = 0) -> Tuple[Tuple[int, int], int]:
    if bytes(data[offset:offset + 4]) != CRAM_MAGIC:
        raise ValueError("not a CRAM file (bad magic)")
    major, minor = data[offset + 4], data[offset + 5]
    if major != 3:
        raise ValueError(f"unsupported CRAM version {major}.{minor} (need 3.x)")
    return (major, minor), offset + 26


@dataclass
class Block:
    content_type: int
    content_id: int
    data: bytes                  # raw (uncompressed) content
    method: int = RAW            # method to use when serializing
    rans_order: int = 0          # RANS method: 0 or 1 (order-1 for QS)

    def to_bytes(self) -> bytes:
        if self.method == RAW:
            comp = self.data
        elif self.method == GZIP:
            comp = _gzip.compress(self.data, compresslevel=6, mtime=0)
        elif self.method == RANS:
            if self.rans_order == 1:
                from disq_tpu.cram.rans import rans_encode_order1

                comp = rans_encode_order1(self.data)
            else:
                comp = rans_encode_order0(self.data)
        else:
            raise ValueError(f"unsupported write method {self.method}")
        body = (
            bytes([self.method, self.content_type])
            + write_itf8(self.content_id)
            + write_itf8(len(comp))
            + write_itf8(len(self.data))
            + comp
        )
        return body + struct.pack("<I", zlib.crc32(body))

    @classmethod
    def read(cls, cur: Cursor) -> "Block":
        start = cur.off
        method = cur.u8()
        content_type = cur.u8()
        content_id = cur.itf8()
        comp_size = cur.itf8()
        raw_size = cur.itf8()
        comp = cur.bytes(comp_size)
        body = bytes(cur.data[start:cur.off])
        (crc,) = struct.unpack("<I", cur.bytes(4))
        if zlib.crc32(body) != crc:
            raise ValueError("CRAM block CRC mismatch")
        try:
            if method == RAW:
                data = comp
            elif method == GZIP:
                data = _gzip.decompress(comp)
            elif method == RANS:
                data = rans_decode(comp)
            elif method == BZIP2:
                import bz2

                data = bz2.decompress(comp)
            elif method == LZMA:
                import lzma

                data = lzma.decompress(comp)
            else:
                raise ValueError(f"unsupported CRAM block method {method}")
        except ValueError:
            raise
        except Exception as e:   # zlib.error / OSError / LZMAError ...
            raise ValueError(
                f"corrupt CRAM block body (method {method}): {e}") from e
        if len(data) != raw_size:
            raise ValueError("CRAM block raw size mismatch")
        return cls(content_type, content_id, data, method)


@dataclass
class ContainerHeader:
    length: int          # byte length of all blocks in the container
    ref_seq_id: int
    ref_start: int
    ref_span: int
    n_records: int
    record_counter: int
    bases: int
    n_blocks: int
    landmarks: List[int]

    def to_bytes(self) -> bytes:
        body = (
            struct.pack("<i", self.length)
            + write_itf8(self.ref_seq_id)
            + write_itf8(self.ref_start)
            + write_itf8(self.ref_span)
            + write_itf8(self.n_records)
            + write_ltf8(self.record_counter)
            + write_ltf8(self.bases)
            + write_itf8(self.n_blocks)
            + write_itf8(len(self.landmarks))
            + b"".join(write_itf8(x) for x in self.landmarks)
        )
        return body + struct.pack("<I", zlib.crc32(body))

    @classmethod
    def read(cls, cur: Cursor) -> "ContainerHeader":
        start = cur.off
        length = cur.i32()
        ref_seq_id = cur.itf8()
        ref_start = cur.itf8()
        ref_span = cur.itf8()
        n_records = cur.itf8()
        record_counter = cur.ltf8()
        bases = cur.ltf8()
        n_blocks = cur.itf8()
        landmarks = cur.itf8_array()
        body = bytes(cur.data[start:cur.off])
        (crc,) = struct.unpack("<I", cur.bytes(4))
        if zlib.crc32(body) != crc:
            raise ValueError("CRAM container header CRC mismatch")
        return cls(
            length, ref_seq_id, ref_start, ref_span, n_records,
            record_counter, bases, n_blocks, landmarks,
        )

    @property
    def is_eof(self) -> bool:
        return self.n_records == 0 and self.ref_seq_id == -1 and self.length == 15


@dataclass
class SliceHeader:
    ref_seq_id: int
    ref_start: int
    ref_span: int
    n_records: int
    record_counter: int
    n_blocks: int
    content_ids: List[int]
    embedded_ref_id: int = -1
    md5: bytes = b"\x00" * 16

    def to_bytes(self) -> bytes:
        return (
            write_itf8(self.ref_seq_id)
            + write_itf8(self.ref_start)
            + write_itf8(self.ref_span)
            + write_itf8(self.n_records)
            + write_ltf8(self.record_counter)
            + write_itf8(self.n_blocks)
            + write_itf8(len(self.content_ids))
            + b"".join(write_itf8(x) for x in self.content_ids)
            + write_itf8(self.embedded_ref_id)
            + self.md5
        )

    @classmethod
    def parse(cls, data: bytes) -> "SliceHeader":
        cur = Cursor(data)
        ref_seq_id = cur.itf8()
        ref_start = cur.itf8()
        ref_span = cur.itf8()
        n_records = cur.itf8()
        record_counter = cur.ltf8()
        n_blocks = cur.itf8()
        content_ids = cur.itf8_array()
        embedded = cur.itf8()
        md5 = cur.bytes(16)
        return cls(
            ref_seq_id, ref_start, ref_span, n_records, record_counter,
            n_blocks, content_ids, embedded, md5,
        )


def read_container_header_at(
    fs, path: str, pos: int, file_length: int
) -> Tuple[ContainerHeader, int]:
    """Read one container header at ``pos`` → (header, header byte size).
    Retries with a doubled window when a header (e.g. one with many
    landmarks in a multi-slice container) exceeds the initial read."""
    want = 256
    while True:
        data = fs.read_range(path, pos, min(want, file_length - pos))
        cur = Cursor(data)
        try:
            hdr = ContainerHeader.read(cur)
            return hdr, cur.off
        except (IndexError, ValueError, struct.error):
            if want >= file_length - pos:
                raise
            want *= 4


def walk_container_offsets(
    fs, path: str, retrier=None, ctx=None
) -> List[Tuple[int, ContainerHeader]]:
    """Enumerate (offset, header) of every container by reading headers
    and skipping payloads — the ``CramContainerHeaderIterator`` walk the
    reference runs on the driver (SURVEY.md §3.5). Seek-dominated.

    ``retrier`` (a ``runtime.errors.ShardRetrier``) makes each header
    read individually retryable: one read per container means a
    whole-walk retry would never converge under a sustained transient
    fault rate.

    ``ctx`` (a ``ShardErrorContext``) governs a *corrupt* container
    header: STRICT raises with the container's coordinates; skip and
    quarantine count one corrupt unit and stop the walk there — CRAM
    has no BGZF-style chain re-sync, so the containers beyond a broken
    length field are unreachable and their loss is bounded, explicit,
    and counted."""
    from disq_tpu.runtime.errors import is_transient

    length = fs.get_file_length(path)
    out: List[Tuple[int, ContainerHeader]] = []
    # File definition is 26 bytes.
    pos = 26
    while pos < length:
        try:
            if retrier is not None:
                hdr, hdr_size = retrier.call(
                    read_container_header_at, fs, path, pos, length,
                    what="container_header",
                )
            else:
                hdr, hdr_size = read_container_header_at(
                    fs, path, pos, length)
        except Exception as e:  # noqa: BLE001 — classified below
            if ctx is None or is_transient(e):
                raise
            ctx.handle_corrupt_block(
                e, block_offset=pos, kind="CRAM container header")
            break
        if hdr.length < 0:
            # A garbage length would walk pos backwards (or loop):
            # classify as corrupt rather than spin.
            err = ValueError(
                f"container at {pos} claims negative length {hdr.length}")
            if ctx is None:
                raise err
            ctx.handle_corrupt_block(
                err, block_offset=pos, kind="CRAM container header")
            break
        out.append((pos, hdr))
        pos += hdr_size + hdr.length
        if hdr.is_eof:
            break
    return out
