"""CRAM 3.0 support (reference parity: ``impl/formats/cram/``).

Container walk, codec kernels, and reference-based reconstruction land
in a dedicated milestone; until then source/sink raise cleanly.
"""
