"""CRAM 3.0 record codec: columnar ``ReadBatch`` ⇄ slice data series.

Replaces htsjdk's ``CramCompressionRecord`` + ``Cram(Record)Codec`` +
``CramNormalizer`` stack (SURVEY.md §2.5, §2.8). Profile implemented:

- write side emits every data series EXTERNAL (ITF8 ints / bytes in
  per-series blocks) by default — a legal CRAM 3.0 layout — or, with
  ``DISQ_TPU_CRAM_CORE``, routes CF/MQ/FN through CORE-block bit codecs
  (canonical Huffman / BETA / GAMMA). The read side understands the
  CORE bit codecs foreign htsjdk/samtools CRAMs use — full canonical
  HUFFMAN, BETA, GAMMA and SUBEXP — plus BYTE_ARRAY_STOP and
  BYTE_ARRAY_LEN, and rejects anything else with a clear error;
- write side emits single-reference slices (ref runs split into
  slices), detached mate info, absolute AP; the READ side additionally
  handles foreign shapes: multi-reference slices (refid -2 with a
  per-record RI series) and AP-delta coding;
- sequence via read features: M-runs that match the reference are
  *omitted* (reference-based compression — requires the reference at
  read time, like the reference's ``CRAMReferenceSource``); mismatching
  or reference-less M-runs are embedded verbatim as 'b' (BB) features;
  I/S/D/N/H/P CIGAR ops map to their feature codes. ``=``/``X`` ops
  canonicalize to ``M`` (inherent to CRAM's feature model; htsjdk does
  the same);
- qualities always stored (CF quality-scores-stored), names preserved
  (RN preservation), tags via the TD tag-line dictionary with per-tag
  EXTERNAL value series.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from disq_tpu.bam.columnar import _NT16_CHARS, ReadBatch, SEQ_NT16
from disq_tpu.cram.io import Cursor, write_itf8, write_itf8_array
from disq_tpu.index.bai import bins_from_cigars
from disq_tpu.runtime.errors import MissingReferenceError

# Encoding codec ids (CRAM 3.0 §12)
E_EXTERNAL = 1
E_HUFFMAN = 3
E_BYTE_ARRAY_LEN = 4
E_BYTE_ARRAY_STOP = 5
E_BETA = 6
E_SUBEXP = 7
E_GAMMA = 9

# CF compression bit flags
CF_QS_STORED = 0x1
CF_DETACHED = 0x2
CF_HAS_MATE_DOWNSTREAM = 0x4
CF_UNKNOWN_BASES = 0x8

# External block content ids, one per data series we emit.
SERIES = [
    "BF", "CF", "RL", "AP", "RG", "RN", "MF", "NS", "NP", "TS", "TL",
    "MQ", "QS", "FN", "FC", "FP", "BB_LEN", "BB_VAL", "IN", "SC", "DL",
    "RS", "HC", "PD",
    "RI",   # per-record reference id — multi-ref (refid -2) slices
]
CID = {name: i + 1 for i, name in enumerate(SERIES)}
TAG_CID_BASE = 0x10000  # tag series ids live above the fixed series

_CHAR_TO_NT16 = np.zeros(256, dtype=np.uint8)
for _i, _c in enumerate(SEQ_NT16):
    _CHAR_TO_NT16[ord(_c)] = _i
    _CHAR_TO_NT16[ord(_c.lower())] = _i


def _tag_key(tag2: bytes, typ: int) -> int:
    return (tag2[0] << 16) | (tag2[1] << 8) | typ


def split_tags(tags: bytes) -> List[Tuple[int, bytes]]:
    """Binary BAM tag block → [(key3, value_bytes)] (key = tag chars +
    type byte; value = the BAM-serialized value without the prefix)."""
    out = []
    p, n = 0, len(tags)
    while p < n:
        key = _tag_key(tags[p:p + 2], tags[p + 2])
        typ = chr(tags[p + 2])
        p += 3
        start = p
        if typ == "A" or typ in "cC":
            p += 1
        elif typ in "sS":
            p += 2
        elif typ in "iIf":
            p += 4
        elif typ in "ZH":
            p = tags.index(b"\x00", p) + 1
        elif typ == "B":
            sub = chr(tags[p])
            (cnt,) = struct.unpack_from("<I", tags, p + 1)
            size = {"c": 1, "C": 1, "s": 2, "S": 2, "i": 4, "I": 4, "f": 4}[sub]
            p += 5 + cnt * size
        else:
            raise ValueError(f"unknown tag type {typ!r}")
        out.append((key, tags[start:p]))
    return out


def join_tags(entries: List[Tuple[int, bytes]]) -> bytes:
    out = bytearray()
    for key, val in entries:
        out += bytes([(key >> 16) & 0xFF, (key >> 8) & 0xFF, key & 0xFF])
        out += val
    return bytes(out)


# -- encodings in the compression header ------------------------------------

def _enc_external(cid: int) -> bytes:
    params = write_itf8(cid)
    return write_itf8(E_EXTERNAL) + write_itf8(len(params)) + params


def _enc_byte_array_stop(stop: int, cid: int) -> bytes:
    params = bytes([stop]) + write_itf8(cid)
    return write_itf8(E_BYTE_ARRAY_STOP) + write_itf8(len(params)) + params


def _enc_byte_array_len(len_cid: int, val_cid: int) -> bytes:
    len_enc = _enc_external(len_cid)
    val_enc = _enc_external(val_cid)
    params = len_enc + val_enc
    return write_itf8(E_BYTE_ARRAY_LEN) + write_itf8(len(params)) + params


@dataclass
class Encoding:
    codec: int
    # EXTERNAL: cid; BYTE_ARRAY_STOP: (stop, cid);
    # BYTE_ARRAY_LEN: (len Encoding, val Encoding)
    params: object

    @classmethod
    def parse(cls, cur: Cursor) -> "Encoding":
        codec = cur.itf8()
        plen = cur.itf8()
        sub = Cursor(cur.bytes(plen))
        if codec == E_EXTERNAL:
            return cls(codec, sub.itf8())
        if codec == E_BYTE_ARRAY_STOP:
            stop = sub.u8()
            return cls(codec, (stop, sub.itf8()))
        if codec == E_BYTE_ARRAY_LEN:
            len_enc = Encoding.parse(sub)
            val_enc = Encoding.parse(sub)
            return cls(codec, (len_enc, val_enc))
        if codec == E_HUFFMAN:
            n = sub.itf8()
            syms = [sub.itf8() for _ in range(n)]
            m = sub.itf8()
            lens = [sub.itf8() for _ in range(m)]
            return cls(codec, (syms, lens))
        if codec == E_BETA:
            return cls(codec, (sub.itf8(), sub.itf8()))  # offset, nbits
        if codec == E_SUBEXP:
            return cls(codec, (sub.itf8(), sub.itf8()))  # offset, k
        if codec == E_GAMMA:
            return cls(codec, sub.itf8())                # offset
        return cls(codec, None)


class BitCursor:
    """MSB-first bit reader over the CORE block (CRAM 3.0 §2:
    "bit stream ... packed MSB first")."""

    __slots__ = ("data", "pos")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def bit(self) -> int:
        b = (self.data[self.pos >> 3] >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return b

    def bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.bit()
        return v


class BitWriter:
    """MSB-first bit writer (encode-side core block)."""

    def __init__(self) -> None:
        self.out = bytearray()
        self._acc = 0
        self._nb = 0

    def write(self, value: int, nbits: int) -> None:
        for i in range(nbits - 1, -1, -1):
            self._acc = (self._acc << 1) | ((value >> i) & 1)
            self._nb += 1
            if self._nb == 8:
                self.out.append(self._acc)
                self._acc = 0
                self._nb = 0

    def flush(self) -> bytes:
        if self._nb:
            self.out.append(self._acc << (8 - self._nb))
            self._acc = 0
            self._nb = 0
        return bytes(self.out)


def huffman_code_lengths(freqs: Dict[int, int]) -> Dict[int, int]:
    """Package-free Huffman code lengths (heap merge) for the observed
    symbols; single-symbol alphabets get the zero-bit constant code."""
    import heapq

    if len(freqs) == 1:
        return {next(iter(freqs)): 0}
    heap = [(f, i, (s,)) for i, (s, f) in enumerate(sorted(freqs.items()))]
    heapq.heapify(heap)
    depth: Dict[int, int] = {s: 0 for s in freqs}
    tick = len(heap)
    while len(heap) > 1:
        fa, _, sa = heapq.heappop(heap)
        fb, _, sb = heapq.heappop(heap)
        for s in sa + sb:
            depth[s] += 1
        heapq.heappush(heap, (fa + fb, tick, sa + sb))
        tick += 1
    return depth


def canonical_assign(syms, lens) -> Dict[int, Tuple[int, int]]:
    """Canonical code assignment ordered by (length, value) — the
    htsjdk CanonicalHuffmanIntegerCodec convention. Returns
    sym -> (code, len)."""
    pairs = sorted(zip(lens, syms))
    codes: Dict[int, Tuple[int, int]] = {}
    code = 0
    prev_len = 0
    for ln, s in pairs:
        code <<= (ln - prev_len)
        codes[s] = (code, ln)
        code += 1
        prev_len = ln
    return codes


def _gamma_write(bw: BitWriter, value: int, offset: int) -> None:
    v = value + offset
    assert v >= 1, "gamma codes require value + offset >= 1"
    nb = v.bit_length() - 1
    bw.write(0, nb)
    bw.write(v, nb + 1)


def _gamma_read(bc: BitCursor, offset: int) -> int:
    z = 0
    while bc.bit() == 0:
        z += 1
    v = (1 << z) | bc.bits(z)
    return v - offset


def _subexp_write(bw: BitWriter, value: int, offset: int, k: int) -> None:
    v = value + offset
    if v < (1 << k):
        bw.write(0, 1)
        bw.write(v, k)
    else:
        b = v.bit_length() - 1
        u = b - k + 1
        bw.write((1 << u) - 1, u)
        bw.write(0, 1)
        bw.write(v & ((1 << b) - 1), b)   # top bit implicit


def _subexp_read(bc: BitCursor, offset: int, k: int) -> int:
    u = 0
    while bc.bit() == 1:
        u += 1
    if u == 0:
        v = bc.bits(k)
    else:
        b = k + u - 1
        v = (1 << b) | bc.bits(b)
    return v - offset


def _enc_raw(codec: int, params: bytes) -> bytes:
    return write_itf8(codec) + write_itf8(len(params)) + params


def enc_bytes_beta(offset: int, nbits: int) -> bytes:
    return _enc_raw(E_BETA, write_itf8(offset) + write_itf8(nbits))


def enc_bytes_gamma(offset: int) -> bytes:
    return _enc_raw(E_GAMMA, write_itf8(offset))


def enc_bytes_subexp(offset: int, k: int) -> bytes:
    return _enc_raw(E_SUBEXP, write_itf8(offset) + write_itf8(k))


def enc_bytes_huffman(syms, lens) -> bytes:
    p = write_itf8(len(syms)) + b"".join(write_itf8(s) for s in syms)
    p += write_itf8(len(lens)) + b"".join(write_itf8(x) for x in lens)
    return _enc_raw(E_HUFFMAN, p)


@dataclass
class CompressionHeader:
    rn_preserved: bool = True
    ap_delta: bool = False
    ref_required: bool = True
    tag_lines: List[List[int]] = field(default_factory=list)  # TD
    series_enc: Dict[str, Encoding] = field(default_factory=dict)
    tag_enc: Dict[int, Encoding] = field(default_factory=dict)
    # encode-side: raw encoding bytes overriding the default EXTERNAL
    # wiring for a series (core bit codecs)
    enc_overrides: Dict[str, bytes] = field(default_factory=dict)

    def to_bytes(self) -> bytes:
        # preservation map
        td_blob = bytearray()
        for line in self.tag_lines:
            for key in line:
                td_blob += bytes([(key >> 16) & 0xFF, (key >> 8) & 0xFF, key & 0xFF])
            td_blob.append(0)
        pres_entries = [
            (b"RN", bytes([1 if self.rn_preserved else 0])),
            (b"AP", bytes([1 if self.ap_delta else 0])),
            (b"RR", bytes([1 if self.ref_required else 0])),
            (b"TD", write_itf8(len(td_blob)) + bytes(td_blob)),
        ]
        pres = write_itf8(len(pres_entries)) + b"".join(
            k + v for k, v in pres_entries
        )
        pres = write_itf8(len(pres)) + pres

        # data series encodings (all EXTERNAL except byte-array series)
        entries = []
        for name in SERIES:
            # BB_* fold into the BB byte-array encoding; RI is read-only
            # support (our writer emits single-ref slices, so declaring
            # an RI series with no backing block would be a dangling
            # ref) unless a multi-ref builder overrides it explicitly
            if name in ("BB_LEN", "BB_VAL") or (
                    name == "RI" and "RI" not in self.enc_overrides):
                continue
            if name in self.enc_overrides:
                enc = self.enc_overrides[name]
            elif name == "RN":
                enc = _enc_byte_array_stop(0, CID["RN"])
            elif name in ("IN", "SC"):
                enc = _enc_byte_array_stop(0, CID[name])
            else:
                enc = _enc_external(CID[name])
            entries.append((name.encode(), enc))
        entries.append((b"BB", _enc_byte_array_len(CID["BB_LEN"], CID["BB_VAL"])))
        dse = write_itf8(len(entries)) + b"".join(k + v for k, v in entries)
        dse = write_itf8(len(dse)) + dse

        # tag encodings
        tag_keys = sorted({k for line in self.tag_lines for k in line})
        tentries = []
        for key in tag_keys:
            cid = TAG_CID_BASE + key
            tentries.append(
                (write_itf8(key), _enc_byte_array_len(cid, cid))
            )
        tenc = write_itf8(len(tentries)) + b"".join(k + v for k, v in tentries)
        tenc = write_itf8(len(tenc)) + tenc
        return bytes(pres + dse + tenc)

    @classmethod
    def parse(cls, data: bytes) -> "CompressionHeader":
        cur = Cursor(data)
        out = cls(tag_lines=[])
        # preservation map
        cur.itf8()  # size in bytes
        n = cur.itf8()
        for _ in range(n):
            key = cur.bytes(2)
            if key in (b"RN", b"AP", b"RR"):
                v = cur.u8() != 0
                if key == b"RN":
                    out.rn_preserved = v
                elif key == b"AP":
                    out.ap_delta = v
                else:
                    out.ref_required = v
            elif key == b"SM":
                cur.bytes(5)
            elif key == b"TD":
                blob_len = cur.itf8()
                blob = cur.bytes(blob_len)
                for line in blob.split(b"\x00")[:-1]:
                    entries = [
                        _tag_key(line[i:i + 2], line[i + 2])
                        for i in range(0, len(line), 3)
                    ]
                    out.tag_lines.append(entries)
            else:
                raise ValueError(f"unknown preservation key {key!r}")
        if not out.tag_lines:
            out.tag_lines = [[]]
        # data series encodings
        cur.itf8()
        n = cur.itf8()
        for _ in range(n):
            key = cur.bytes(2).decode()
            out.series_enc[key] = Encoding.parse(cur)
        # tag encodings
        cur.itf8()
        n = cur.itf8()
        for _ in range(n):
            key = cur.itf8()
            out.tag_enc[key] = Encoding.parse(cur)
        return out


# -- stream helpers ---------------------------------------------------------

class _Streams:
    """Per-content-id byte streams being built (encode side)."""

    def __init__(self):
        self.data: Dict[int, bytearray] = {}

    def buf(self, cid: int) -> bytearray:
        return self.data.setdefault(cid, bytearray())

    def put_itf8(self, cid: int, v: int) -> None:
        self.buf(cid).extend(write_itf8(v))

    def put_bytes(self, cid: int, b: bytes) -> None:
        self.buf(cid).extend(b)


class _Readers:
    """Per-content-id cursors + CORE bit cursor (decode side)."""

    def __init__(self, blocks: Dict[int, bytes], core: bytes = b""):
        self.cur = {
            cid: Cursor(data, itf8_table=True)
            for cid, data in blocks.items()
        }
        self.core = BitCursor(core or b"")
        self._huff_cache: Dict[int, object] = {}

    def _huffman(self, enc: Encoding):
        key = id(enc)
        tbl = self._huff_cache.get(key)
        if tbl is None:
            syms, lens = enc.params
            codes = canonical_assign(syms, lens)
            # decode walk tables: (len -> first code, offset) + sorted syms
            by = sorted((ln, c, s) for s, (c, ln) in codes.items())
            tbl = by
            self._huff_cache[key] = tbl
        return tbl

    def _c(self, cid: int) -> Cursor:
        try:
            return self.cur[cid]
        except KeyError:
            raise ValueError(f"missing external block {cid}") from None

    def read_int(self, enc: Encoding) -> int:
        if enc.codec == E_EXTERNAL:
            return self._c(enc.params).itf8()
        if enc.codec == E_HUFFMAN:
            if len(enc.params[0]) == 1:
                return enc.params[0][0]  # zero-bit constant (htsjdk idiom)
            return self._read_huffman(enc)
        if enc.codec == E_BETA:
            offset, nbits = enc.params
            return self.core.bits(nbits) - offset
        if enc.codec == E_GAMMA:
            return _gamma_read(self.core, enc.params)
        if enc.codec == E_SUBEXP:
            offset, k = enc.params
            return _subexp_read(self.core, offset, k)
        raise ValueError(f"unsupported int encoding codec {enc.codec}")

    def _read_huffman(self, enc: Encoding) -> int:
        by = self._huffman(enc)   # sorted (len, code, sym)
        code = 0
        ln = 0
        i = 0
        while i < len(by):
            want_len = by[i][0]
            code = (code << (want_len - ln)) | self.core.bits(want_len - ln)
            ln = want_len
            while i < len(by) and by[i][0] == ln:
                if by[i][1] == code:
                    return by[i][2]
                i += 1
        raise ValueError("invalid canonical Huffman code in CORE stream")

    def read_byte(self, enc: Encoding) -> int:
        if enc.codec == E_EXTERNAL:
            return self._c(enc.params).u8()
        if enc.codec in (E_HUFFMAN, E_BETA, E_GAMMA, E_SUBEXP):
            return self.read_int(enc)
        raise ValueError(f"unsupported byte encoding codec {enc.codec}")

    def read_bytes_len(self, enc: Encoding, n: int) -> bytes:
        if enc.codec == E_EXTERNAL:
            return self._c(enc.params).bytes(n)
        raise ValueError(f"unsupported byte-array encoding codec {enc.codec}")

    def read_array(self, enc: Encoding) -> bytes:
        if enc.codec == E_BYTE_ARRAY_STOP:
            stop, cid = enc.params
            c = self._c(cid)
            data = c.data
            try:
                end = data.index(stop, c.off)   # C-speed scan
            except AttributeError:              # memoryview has no index
                end = c.off
                while data[end] != stop:
                    end += 1
            out = bytes(data[c.off:end])
            c.off = end + 1
            return out
        if enc.codec == E_BYTE_ARRAY_LEN:
            len_enc, val_enc = enc.params
            n = self.read_int(len_enc)
            return self.read_bytes_len(val_enc, n)
        raise ValueError(f"unsupported array encoding codec {enc.codec}")


# -- slice/container encode -------------------------------------------------

def _seq_chars(batch: ReadBatch, i: int) -> np.ndarray:
    s, e = batch.seq_offsets[i], batch.seq_offsets[i + 1]
    return _NT16_CHARS[batch.seqs[s:e]]


def _qs_order1() -> bool:
    # order-1 QS (the htslib default, typically 10-20% smaller) is now
    # the default: the native encoder runs at ~200 MB/s and is
    # byte-identical to the Python fallback, so output bytes don't
    # depend on whether the native library is built. Opt out with
    # DISQ_TPU_CRAM_RANS_O1=0.
    from disq_tpu.runtime.debug import env_flag

    return env_flag("DISQ_TPU_CRAM_RANS_O1", default="1")


def encode_container(
    batch: ReadBatch,
    refid: int,
    record_counter: int,
    ref_fetch=None,
    core_profile: Optional[bool] = None,
) -> Tuple[bytes, dict]:
    """Encode one single-ref slice (all records share ``refid``) into a
    complete container. ``ref_fetch(refid, start0, length) -> bytes``
    enables reference-based M-run omission. Returns (container bytes,
    crai entry info dict).

    ``core_profile`` (default: the ``DISQ_TPU_CRAM_CORE`` env flag)
    routes CF through a canonical core Huffman code, MQ through
    BETA(0,8) and FN through GAMMA(1) — the CORE-block bit codecs
    foreign htsjdk/samtools CRAMs use, exercised end-to-end."""
    from disq_tpu.cram.structure import (
        Block, COMPRESSION_HEADER, CORE, ContainerHeader, EXTERNAL,
        GZIP, MAPPED_SLICE, RANS, RAW, SliceHeader,
    )

    if core_profile is None:
        from disq_tpu.runtime.debug import env_flag

        core_profile = env_flag("DISQ_TPU_CRAM_CORE")
    n = batch.count
    # The bulk QS/RN encoders below trust the batch's flat arrays to be
    # exactly tiled by their offsets (QS copies ``batch.quals`` whole;
    # RN inserts NULs at ``name_offsets[1:]``). A batch whose flat
    # arrays carry slack — offsets not starting at 0, or ending before
    # the array does — would silently emit wrong bytes; fail loudly
    # instead (ADVICE r5 #2).
    if n:
        so, no_ = batch.seq_offsets, batch.name_offsets
        if int(so[0]) != 0 or int(so[-1]) != len(batch.seqs) \
                or len(batch.quals) != len(batch.seqs):
            raise ValueError(
                "encode_container: seq_offsets must tile the flat "
                f"seq/qual arrays exactly (offsets [{int(so[0])}, "
                f"{int(so[-1])}], len(seqs)={len(batch.seqs)}, "
                f"len(quals)={len(batch.quals)})"
            )
        if int(no_[0]) != 0 or int(no_[-1]) != len(batch.names):
            raise ValueError(
                "encode_container: name_offsets must tile the flat "
                f"names array exactly (offsets [{int(no_[0])}, "
                f"{int(no_[-1])}], len(names)={len(batch.names)})"
            )
    streams = _Streams()
    bw = BitWriter()
    cf_codes = None
    # one CF formula for both the huffman pre-pass and the encode loop
    seq_lens = np.diff(batch.seq_offsets)
    cf_vals = (CF_QS_STORED | CF_DETACHED
               | np.where(seq_lens == 0, CF_UNKNOWN_BASES, 0)).astype(int)
    if core_profile:
        freq: Dict[int, int] = {}
        for v in cf_vals.tolist():
            freq[v] = freq.get(v, 0) + 1
        lens_map = huffman_code_lengths(freq) if freq else {}
        cf_syms = sorted(lens_map)
        cf_lens = [lens_map[s] for s in cf_syms]
        cf_codes = canonical_assign(cf_syms, cf_lens)
    tag_line_index: Dict[tuple, int] = {}
    tag_lines: List[List[int]] = []
    tl_vals: List[int] = []
    fn_vals: List[int] = []
    total_bases = 0
    any_ref_omitted = False

    ends = batch.alignment_ends()
    for i in range(n):
        l_seq = int(batch.seq_offsets[i + 1] - batch.seq_offsets[i])
        cig_s, cig_e = batch.cigar_offsets[i], batch.cigar_offsets[i + 1]
        cigar = batch.cigars[cig_s:cig_e]
        if l_seq == 0 and len(cigar) > 0:
            raise ValueError(
                "CRAM profile limitation: record with CIGAR but no "
                "sequence bases is not representable via read features"
            )
        cf = int(cf_vals[i])
        # fixed one-value-per-record series (BF/CF/RL/AP/RG/RN/MF/NS/
        # NP/TS/MQ/QS) are bulk-encoded after the loop — per-cid stream
        # order is record order either way, and the vectorized ITF8
        # array encoder replaces ~12 put_itf8 calls per record
        if cf_codes is not None:
            code, nb = cf_codes[cf]
            bw.write(code, nb)
        # tags
        entries = split_tags(
            batch.tags[batch.tag_offsets[i]:batch.tag_offsets[i + 1]].tobytes()
        )
        line = tuple(k for k, _ in entries)
        tl = tag_line_index.get(line)
        if tl is None:
            tl = tag_line_index[line] = len(tag_lines)
            tag_lines.append(list(line))
        tl_vals.append(tl)
        for key, val in entries:
            cid = TAG_CID_BASE + key
            streams.put_itf8(cid, len(val))
            streams.put_bytes(cid, val)
        total_bases += l_seq

        # read features from CIGAR + seq (vs reference)
        seq = _seq_chars(batch, i)
        features: List[Tuple[int, str, object]] = []  # (read_pos1, code, payload)
        rp = 1                      # 1-based read position
        ref_pos = int(batch.pos[i])  # 0-based ref position
        for op_word in cigar:
            op = int(op_word) & 0xF
            ln = int(op_word) >> 4
            code = "MIDNSHP=XB"[op] if op < 9 else "?"
            if code in ("M", "=", "X"):
                run = seq[rp - 1: rp - 1 + ln]
                omit = False
                if ref_fetch is not None and refid >= 0:
                    ref_run = ref_fetch(refid, ref_pos, ln)
                    if (
                        ref_run is not None
                        and len(ref_run) == ln
                        and np.array_equal(
                            np.frombuffer(ref_run.upper(), np.uint8), run
                        )
                    ):
                        omit = True
                if not omit:
                    features.append((rp, "b", run.tobytes()))
                else:
                    any_ref_omitted = True
                rp += ln
                ref_pos += ln
            elif code == "I":
                features.append((rp, "I", seq[rp - 1: rp - 1 + ln].tobytes()))
                rp += ln
            elif code == "S":
                features.append((rp, "S", seq[rp - 1: rp - 1 + ln].tobytes()))
                rp += ln
            elif code == "D":
                features.append((rp, "D", ln))
                ref_pos += ln
            elif code == "N":
                features.append((rp, "N", ln))
                ref_pos += ln
            elif code == "H":
                features.append((rp, "H", ln))
            elif code == "P":
                features.append((rp, "P", ln))
            else:
                raise ValueError(f"unsupported CIGAR op {code!r} for CRAM")
        if rp - 1 < l_seq:
            # Bases not covered by CIGAR (typically unmapped records with
            # no CIGAR at all): embed them verbatim.
            features.append((rp, "b", seq[rp - 1:].tobytes()))
        if core_profile:
            _gamma_write(bw, len(features), 1)   # GAMMA(offset=1)
        else:
            fn_vals.append(len(features))
        prev = 0
        for fpos, code, payload in features:
            streams.put_bytes(CID["FC"], code.encode())
            streams.put_itf8(CID["FP"], fpos - prev)
            prev = fpos
            if code == "b":
                streams.put_itf8(CID["BB_LEN"], len(payload))
                streams.put_bytes(CID["BB_VAL"], payload)
            elif code in ("I", "S"):
                streams.put_bytes(CID[{"I": "IN", "S": "SC"}[code]], payload + b"\x00")
            elif code == "D":
                streams.put_itf8(CID["DL"], payload)
            elif code == "N":
                streams.put_itf8(CID["RS"], payload)
            elif code == "H":
                streams.put_itf8(CID["HC"], payload)
            elif code == "P":
                streams.put_itf8(CID["PD"], payload)
        # MQ + QS come AFTER the read-feature list (CRAM 3.0 record
        # layout; htsjdk CramRecordReader) — load-bearing once any of
        # these series shares the CORE bit stream
        if core_profile:
            bw.write(int(batch.mapq[i]), 8)      # BETA(0, 8)

    if n:
        # bulk-encoded fixed series (see the loop comment): one
        # vectorized ITF8 pass per series instead of per-record varints
        flags64 = batch.flag.astype(np.int64)
        streams.put_bytes(CID["BF"], write_itf8_array(flags64))
        if cf_codes is None:
            streams.put_bytes(CID["CF"], write_itf8_array(cf_vals))
        streams.put_bytes(CID["RL"], write_itf8_array(seq_lens))
        streams.put_bytes(
            CID["AP"], write_itf8_array(batch.pos.astype(np.int64) + 1))
        streams.put_bytes(CID["RG"], write_itf8(-1) * n)  # constant series
        # RN: a NUL terminator after every name, in one insert
        rn = np.insert(
            batch.names,
            np.asarray(batch.name_offsets[1:], dtype=np.int64), 0)
        streams.put_bytes(CID["RN"], rn.tobytes())
        mf_vals = ((flags64 >> 5) & 1) | (((flags64 >> 3) & 1) << 1)
        streams.put_bytes(CID["MF"], write_itf8_array(mf_vals))
        streams.put_bytes(
            CID["NS"], write_itf8_array(batch.next_refid.astype(np.int64)))
        streams.put_bytes(
            CID["NP"],
            write_itf8_array(batch.next_pos.astype(np.int64) + 1))
        streams.put_bytes(
            CID["TS"], write_itf8_array(batch.tlen.astype(np.int64)))
        streams.put_bytes(CID["TL"], write_itf8_array(tl_vals))
        if not core_profile:
            streams.put_bytes(CID["FN"], write_itf8_array(fn_vals))
            streams.put_bytes(
                CID["MQ"], write_itf8_array(batch.mapq.astype(np.int64)))
        # QS: quals are contiguous in record order already
        streams.put_bytes(CID["QS"], np.ascontiguousarray(
            batch.quals).tobytes())

    comp_header = CompressionHeader(
        rn_preserved=True, ap_delta=False,
        ref_required=any_ref_omitted, tag_lines=tag_lines or [[]],
    )
    if core_profile:
        comp_header.enc_overrides["CF"] = enc_bytes_huffman(
            cf_syms, cf_lens)
        comp_header.enc_overrides["MQ"] = enc_bytes_beta(0, 8)
        comp_header.enc_overrides["FN"] = enc_bytes_gamma(1)
    ch_block = Block(COMPRESSION_HEADER, 0, comp_header.to_bytes(), GZIP)

    # slice bounds
    if refid >= 0 and n:
        starts = batch.pos.astype(np.int64)
        ref_start = int(starts.min()) + 1
        ref_span = int(ends.max()) - int(starts.min())
    else:
        ref_start, ref_span = 0, 0

    ext_blocks = []
    content_ids = []
    for cid in sorted(streams.data):
        payload = bytes(streams.data[cid])
        method = RANS if cid == CID["QS"] else GZIP
        # QS rides order-1 rANS by default (htslib's QS choice)
        order = 1 if (cid == CID["QS"] and _qs_order1()) else 0
        ext_blocks.append(Block(EXTERNAL, cid, payload, method, order))
        content_ids.append(cid)
    core_block = Block(CORE, 0, bw.flush() if core_profile else b"", RAW)
    slice_hdr = SliceHeader(
        ref_seq_id=refid, ref_start=ref_start, ref_span=ref_span,
        n_records=n, record_counter=record_counter,
        n_blocks=1 + len(ext_blocks), content_ids=content_ids,
    )
    slice_hdr_block = Block(MAPPED_SLICE, 0, slice_hdr.to_bytes(), RAW)

    ch_bytes = ch_block.to_bytes()
    slice_bytes = (
        slice_hdr_block.to_bytes()
        + core_block.to_bytes()
        + b"".join(b.to_bytes() for b in ext_blocks)
    )
    landmarks = [len(ch_bytes)]
    blocks_bytes = ch_bytes + slice_bytes
    hdr = ContainerHeader(
        length=len(blocks_bytes), ref_seq_id=refid, ref_start=ref_start,
        ref_span=ref_span, n_records=n, record_counter=record_counter,
        bases=total_bases, n_blocks=2 + 1 + len(ext_blocks),
        landmarks=landmarks,
    )
    container = hdr.to_bytes() + blocks_bytes
    crai_info = dict(
        ref_seq_id=refid, ref_start=ref_start, ref_span=ref_span,
        slice_offset=landmarks[0], slice_size=len(slice_bytes),
    )
    return container, crai_info


# -- container decode -------------------------------------------------------

def decode_container_records(
    container_blocks: bytes, ref_fetch=None
) -> ReadBatch:
    """Decode the block section of one data container → ReadBatch."""
    from disq_tpu.cram.structure import (
        Block, COMPRESSION_HEADER, CORE, EXTERNAL, MAPPED_SLICE, SliceHeader,
    )

    cur = Cursor(container_blocks)
    ch_block = Block.read(cur)
    if ch_block.content_type != COMPRESSION_HEADER:
        raise ValueError("expected compression header block")
    comp = CompressionHeader.parse(ch_block.data)
    batches = []
    while cur.off < len(container_blocks):
        sh_block = Block.read(cur)
        if sh_block.content_type != MAPPED_SLICE:
            raise ValueError("expected slice header block")
        slice_hdr = SliceHeader.parse(sh_block.data)
        blocks: Dict[int, bytes] = {}
        core = None
        for _ in range(slice_hdr.n_blocks):
            b = Block.read(cur)
            if b.content_type == EXTERNAL:
                blocks[b.content_id] = b.data
            elif b.content_type == CORE:
                core = b.data
        batches.append(_decode_slice(slice_hdr, comp, blocks, core, ref_fetch))
    return ReadBatch.concat(batches)


_FIXED_SERIES = ("BF", "CF", "RL", "AP", "RG", "MF", "NS", "NP", "TS",
                 "TL", "FN", "MQ")


def _enc_cids(e: Encoding) -> List[int]:
    """External block ids an encoding reads from (nested for LEN)."""
    if e.codec == E_EXTERNAL:
        return [e.params]
    if e.codec == E_BYTE_ARRAY_STOP:
        return [e.params[1]]
    if e.codec == E_BYTE_ARRAY_LEN:
        return _enc_cids(e.params[0]) + _enc_cids(e.params[1])
    return []


def _external_cids_excluding(comp, enc, exclude) -> List[int]:
    """External block ids consumed by every encoding EXCEPT the named
    series — the exclusivity scan both bulk fast paths share."""
    used: List[int] = []
    for k, e in enc.items():
        if k not in exclude:
            used += _enc_cids(e)
    for e in comp.tag_enc.values():
        used += _enc_cids(e)
    return used


def _bulk_fixed_series(rd, comp, enc, n, multi_ref):
    """Pre-decode the fixed one-value-per-record series into plain
    lists when each is EXTERNAL over its own block (shared or exotic
    layouts fall back to the per-record loop — returns None). A stream
    shorter than n values (e.g. a foreign file whose mate fields are
    not one-per-record) also falls back, so the loop path reports the
    real error."""
    fixed = _FIXED_SERIES + (("RI",) if multi_ref else ())
    if not all(s in enc and enc[s].codec == E_EXTERNAL for s in fixed):
        return None
    cids = [enc[s].params for s in fixed]
    if len(set(cids)) != len(cids):
        return None
    if set(cids) & set(_external_cids_excluding(comp, enc, set(fixed))):
        return None
    if not all(cid in rd.cur for cid in cids):
        return None
    # RG and MF are consumed-and-discarded by the loop; their blocks
    # are exclusive (checked above) and per-slice, so the fast path
    # need not walk them at all
    decoded = [s for s in fixed if s not in ("RG", "MF")]
    curs = [rd.cur[enc[s].params] for s in decoded]
    saved = [c.off for c in curs]
    try:
        return {s: c.itf8_bulk(n) for s, c in zip(decoded, curs)}
    except IndexError:
        # rewind every partially-consumed cursor so the loop path
        # re-reads from the true positions and reports the real error
        for c, o in zip(curs, saved):
            c.off = o
        return None


def _bulk_split_names(rd, comp, enc, n) -> Optional[List[bytes]]:
    """All n read names in one C-speed split when RN is a stop-byte
    array over a block no other encoding reads; None → per-record
    reads."""
    if not comp.rn_preserved:
        return None
    rne = enc.get("RN")
    if rne is None or rne.codec != E_BYTE_ARRAY_STOP:
        return None
    stop, cid = rne.params
    if cid in _external_cids_excluding(comp, enc, ("RN",)):
        return None
    c = rd.cur.get(cid)
    if c is None:
        return None
    segs = bytes(c.data[c.off:]).split(bytes([stop]))
    if len(segs) < n + 1:
        return None   # fewer names than records: loop path reports it
    segs = segs[:n]
    c.off += sum(len(s) for s in segs) + n
    return segs


def _bulk_feature_streams(rd, comp, enc, cols):
    """Pre-slice the FC byte stream and pre-decode the FP delta stream
    for all features of the slice (counts known from the bulk FN
    column), when both are EXTERNAL over exclusive blocks. Returns
    (fc_bytes, fp_deltas) or None → per-feature reads."""
    fce, fpe = enc.get("FC"), enc.get("FP")
    if (fce is None or fpe is None
            or fce.codec != E_EXTERNAL or fpe.codec != E_EXTERNAL
            or fce.params == fpe.params):
        return None
    used = _external_cids_excluding(comp, enc, ("FC", "FP"))
    if fce.params in used or fpe.params in used:
        return None
    cfc, cfp = rd.cur.get(fce.params), rd.cur.get(fpe.params)
    if cfc is None or cfp is None:
        return None
    total = int(sum(cols["FN"]))
    if len(cfc.data) - cfc.off < total:
        return None
    saved = cfp.off
    try:
        fp_all = cfp.itf8_bulk(total)
    except IndexError:
        cfp.off = saved
        return None
    fc_all = bytes(cfc.data[cfc.off: cfc.off + total])
    cfc.off += total
    return fc_all, fp_all


def _bulk_bb(rd, comp, enc, fstreams):
    """All 'b'-feature payloads of the slice (count known from the bulk
    FC stream) when BB is BYTE_ARRAY_LEN over two distinct exclusive
    EXTERNAL blocks — our writer's and the usual layout. Returns the
    payload list or None → per-feature reads."""
    if fstreams is None:
        return None
    bbe = enc.get("BB")
    if bbe is None or bbe.codec != E_BYTE_ARRAY_LEN:
        return None
    len_e, val_e = bbe.params
    if (len_e.codec != E_EXTERNAL or val_e.codec != E_EXTERNAL
            or len_e.params == val_e.params):
        return None
    used = _external_cids_excluding(comp, enc, ("BB",))
    if len_e.params in used or val_e.params in used:
        return None
    cl, cv = rd.cur.get(len_e.params), rd.cur.get(val_e.params)
    if cl is None or cv is None:
        return None
    count_b = fstreams[0].count(ord("b"))
    saved = cl.off
    try:
        lens = cl.itf8_bulk(count_b)
    except IndexError:
        cl.off = saved
        return None
    total = sum(lens)
    if any(ln < 0 for ln in lens) or len(cv.data) - cv.off < total:
        cl.off = saved
        return None
    data = cv.data
    off = cv.off
    out = []
    for ln in lens:
        out.append(bytes(data[off: off + ln]))
        off += ln
    cv.off = off
    return out


def _bulk_tags(rd, comp, enc, cols):
    """Per-tag-key value iterators for keys whose value series is the
    interleaved (length, bytes) layout over one exclusive EXTERNAL
    block — our writer's layout. Keys with any other layout simply stay
    on per-record reads."""
    from collections import Counter

    keys = {k for line in comp.tag_lines for k in line}
    if not keys:
        return {}
    counts: Dict[int, int] = {k: 0 for k in keys}
    lines = comp.tag_lines
    for tl, c_tl in Counter(cols["TL"]).items():
        for k in lines[tl]:
            counts[k] += c_tl
    # one cid-occurrence count across every encoding: a same-cid
    # BYTE_ARRAY_LEN tag contributes exactly its own 2 refs (len+val),
    # so any count above 2 means the block is shared with something
    cid_refs = Counter()
    for e2 in enc.values():
        cid_refs.update(_enc_cids(e2))
    for e2 in comp.tag_enc.values():
        cid_refs.update(_enc_cids(e2))
    out: Dict[int, object] = {}
    for k in keys:
        e = comp.tag_enc.get(k)
        if e is None or e.codec != E_BYTE_ARRAY_LEN:
            continue
        len_e, val_e = e.params
        if (len_e.codec != E_EXTERNAL or val_e.codec != E_EXTERNAL
                or len_e.params != val_e.params):
            continue
        cid = len_e.params
        if cid_refs[cid] != 2:
            continue
        c = rd.cur.get(cid)
        if c is None:
            continue
        try:
            # len_prefixed_bulk commits the cursor only on full success
            out[k] = iter(c.len_prefixed_bulk(counts[k]))
        except IndexError:
            pass
    return out


def _bulk_quals(rd, comp, enc, cols):
    """The slice's whole QS byte stream in one read when every record
    stores qualities and QS is EXTERNAL over an exclusive block.
    Returns the bytes or None → per-record reads."""
    qse = enc.get("QS")
    if qse is None or qse.codec != E_EXTERNAL:
        return None
    if any((cf & CF_QS_STORED) == 0 for cf in cols["CF"]):
        return None
    if qse.params in _external_cids_excluding(comp, enc, ("QS",)):
        return None
    c = rd.cur.get(qse.params)
    total_bases = int(sum(cols["RL"]))
    if c is None or len(c.data) - c.off < total_bases:
        return None
    blob = bytes(c.data[c.off: c.off + total_bases])
    c.off += total_bases
    return blob


def _decode_slice(
    slice_hdr, comp: CompressionHeader, blocks: Dict[int, bytes], core,
    ref_fetch,
) -> ReadBatch:
    rd = _Readers(blocks, core or b"")
    enc = comp.series_enc
    n = slice_hdr.n_records
    refid = slice_hdr.ref_seq_id
    multi_ref = refid == -2
    if multi_ref and "RI" not in enc:
        raise ValueError(
            "multi-reference CRAM slice without an RI series encoding")

    refid_l = np.full(n, refid, np.int32)
    prev_ap = slice_hdr.ref_start  # AP-delta seed (htsjdk convention)
    pos_l = np.empty(n, np.int32)
    mapq_l = np.empty(n, np.uint8)
    flag_l = np.empty(n, np.uint16)
    nref_l = np.empty(n, np.int32)
    npos_l = np.empty(n, np.int32)
    tlen_l = np.empty(n, np.int32)
    bin_l = np.zeros(n, np.uint16)
    # flat byte accumulators + per-record lengths (one frombuffer per
    # column at the end instead of n tiny arrays + concatenate)
    names, seqs_l, quals_l, tags_l = (
        bytearray(), bytearray(), bytearray(), bytearray())
    name_lens: List[int] = []
    cig_flat: List[int] = []
    cig_lens: List[int] = []
    seq_lens: List[int] = []
    tag_lens: List[int] = []

    # Columnar fast path: when every fixed per-record series is
    # EXTERNAL with its own block (the htslib/our-writer layout), pull
    # each series' whole value stream in one fused walk and index
    # arrays in the loop, instead of 12 read_int dispatches per record.
    # The value order within each block is identical to the loop's
    # consumption order because these series are one-value-per-record.
    cols = _bulk_fixed_series(rd, comp, enc, n, multi_ref)
    if cols is not None and comp.ap_delta:
        ap_cum = slice_hdr.ref_start + np.cumsum(
            np.asarray(cols["AP"], np.int64))
        cols["AP"] = ap_cum.tolist()
    rn_names = _bulk_split_names(rd, comp, enc, n) if cols is not None \
        else None
    fstreams = _bulk_feature_streams(rd, comp, enc, cols) \
        if cols is not None else None
    qs_blob = _bulk_quals(rd, comp, enc, cols) \
        if cols is not None else None
    bb_vals = _bulk_bb(rd, comp, enc, fstreams)
    tag_bulk = _bulk_tags(rd, comp, enc, cols) if cols is not None else {}
    fidx = 0
    bidx = 0
    qoff = 0

    for i in range(n):
        if cols is not None:
            flag = cols["BF"][i]
            cf = cols["CF"][i]
            rl = cols["RL"][i]
            if multi_ref:
                refid_l[i] = cols["RI"][i]
            ap = cols["AP"][i]
        else:
            flag = rd.read_int(enc["BF"])
            cf = rd.read_int(enc["CF"])
            rl = rd.read_int(enc["RL"])
            if multi_ref:
                refid_l[i] = rd.read_int(enc["RI"])
            ap = rd.read_int(enc["AP"])
            if comp.ap_delta:
                ap = prev_ap + ap
                prev_ap = ap
            rd.read_int(enc["RG"])
        if rn_names is not None:
            name = rn_names[i]
        else:
            name = rd.read_array(enc["RN"]) if comp.rn_preserved else b""
        if not (cf & CF_DETACHED):
            raise ValueError("only detached mate records supported")
        if cols is not None:
            ns, np_, ts = cols["NS"][i], cols["NP"][i], cols["TS"][i]
            tl = cols["TL"][i]
        else:
            rd.read_int(enc["MF"])
            ns = rd.read_int(enc["NS"])
            np_ = rd.read_int(enc["NP"])
            ts = rd.read_int(enc["TS"])
            tl = rd.read_int(enc["TL"])
        tag_entries = []
        for key in comp.tag_lines[tl]:
            it = tag_bulk.get(key)
            val = next(it) if it is not None \
                else rd.read_array(comp.tag_enc[key])
            tag_entries.append((key, val))
        # features (MQ follows them — CRAM 3.0 record layout)
        fn = cols["FN"][i] if cols is not None else rd.read_int(enc["FN"])
        # fast shape: exactly one whole-read 'b' feature at read
        # position 1 (the dominant reference-less record) — equivalent
        # to the generic reconstruction with no gap, no tail and a
        # single M run; unmapped flags clear the CIGAR as below
        if (fstreams is not None and bb_vals is not None and fn == 1
                and not (cf & CF_UNKNOWN_BASES)
                and fstreams[0][fidx] == 98          # ord('b')
                and fstreams[1][fidx] == 1
                and rl > 0 and len(bb_vals[bidx]) == rl):
            fidx += 1
            payload = bb_vals[bidx]
            bidx += 1
            pos0 = ap - 1
            seq = _CHAR_TO_NT16[np.frombuffer(payload, np.uint8)]
            cigar_ops = [] if flag & 0x4 else [rl << 4]
        else:
            features = []
            fpos = 0
            for _ in range(fn):
                if fstreams is not None:
                    code = chr(fstreams[0][fidx])
                    fpos += fstreams[1][fidx]
                    fidx += 1
                else:
                    code = chr(rd.read_byte(enc["FC"]))
                    fpos += rd.read_int(enc["FP"])
                if code == "b":
                    if bb_vals is not None:
                        payload = bb_vals[bidx]
                        bidx += 1
                    else:
                        payload = rd.read_array(enc["BB"])
                elif code == "I":
                    payload = rd.read_array(enc["IN"])
                elif code == "S":
                    payload = rd.read_array(enc["SC"])
                elif code == "D":
                    payload = rd.read_int(enc["DL"])
                elif code == "N":
                    payload = rd.read_int(enc["RS"])
                elif code == "H":
                    payload = rd.read_int(enc["HC"])
                elif code == "P":
                    payload = rd.read_int(enc["PD"])
                else:
                    raise ValueError(f"unsupported read feature {code!r}")
                features.append((fpos, code, payload))

            # reconstruct seq + cigar
            pos0 = ap - 1
            seq = np.zeros(rl, dtype=np.uint8)
            cigar_ops: List[int] = []

            def push(op_char: str, ln: int):
                if ln <= 0:
                    return
                op = "MIDNSHP=X".index(op_char)
                if cigar_ops and (cigar_ops[-1] & 0xF) == op:
                    cigar_ops[-1] += ln << 4
                else:
                    cigar_ops.append((ln << 4) | op)

            rp = 1
            ref_pos = pos0
            if cf & CF_UNKNOWN_BASES:
                features = []
            for fpos, code, payload in features:
                gap = fpos - rp
                if gap > 0:
                    # reference-matching M stretch
                    if ref_fetch is None:
                        raise MissingReferenceError(
                            "reference required to decode this CRAM slice "
                            "(set reference_source_path)"
                        )
                    rb = ref_fetch(int(refid_l[i]), ref_pos, gap)
                    if rb is None or len(rb) < gap:
                        raise MissingReferenceError(
                            f"reference contig for refid {int(refid_l[i])} is "
                            f"missing or too short in the configured FASTA"
                        )
                    seq[rp - 1: rp - 1 + gap] = _CHAR_TO_NT16[
                        np.frombuffer(rb.upper(), np.uint8)
                    ]
                    push("M", gap)
                    rp += gap
                    ref_pos += gap
                if code == "b":
                    ln = len(payload)
                    seq[rp - 1: rp - 1 + ln] = _CHAR_TO_NT16[
                        np.frombuffer(payload, np.uint8)
                    ]
                    push("M", ln)
                    rp += ln
                    ref_pos += ln
                elif code in ("I", "S"):
                    ln = len(payload)
                    seq[rp - 1: rp - 1 + ln] = _CHAR_TO_NT16[
                        np.frombuffer(payload, np.uint8)
                    ]
                    push(code, ln)
                    rp += ln
                elif code in ("D", "N"):
                    push(code, payload)
                    ref_pos += payload
                elif code in ("H", "P"):
                    push(code, payload)
            tail = rl - (rp - 1)
            if tail > 0 and not (cf & CF_UNKNOWN_BASES):
                if (flag & 0x4) == 0 and int(refid_l[i]) >= 0:
                    if ref_fetch is None:
                        raise MissingReferenceError(
                            "reference required to decode this CRAM slice "
                            "(set reference_source_path)"
                        )
                    rb = ref_fetch(int(refid_l[i]), ref_pos, tail)
                    if rb is None or len(rb) < tail:
                        raise MissingReferenceError(
                            f"reference contig for refid {int(refid_l[i])} is "
                            f"missing or too short in the configured FASTA"
                        )
                    seq[rp - 1:] = _CHAR_TO_NT16[np.frombuffer(rb.upper(), np.uint8)]
                    push("M", tail)
                else:
                    raise ValueError("unmapped record with missing base features")

            if flag & 0x4:
                # Unmapped records carry no CIGAR ('*'); any cover-all 'b'
                # feature existed only to transport the bases.
                cigar_ops = []
        mq = cols["MQ"][i] if cols is not None else rd.read_int(enc["MQ"])
        if qs_blob is not None:
            quals = qs_blob[qoff: qoff + rl]
            qoff += rl
        else:
            quals = (rd.read_bytes_len(enc["QS"], rl)
                     if cf & CF_QS_STORED else b"\xff" * rl)
        pos_l[i] = pos0
        mapq_l[i] = mq
        flag_l[i] = flag
        nref_l[i] = ns
        npos_l[i] = np_ - 1
        tlen_l[i] = ts
        names += name
        name_lens.append(len(name))
        cig_flat.extend(cigar_ops)
        cig_lens.append(len(cigar_ops))
        seqs_l += seq.data
        seq_lens.append(rl)
        quals_l += quals     # always length rl — seq_lens covers both
        tb = join_tags(tag_entries)
        tags_l += tb
        tag_lens.append(len(tb))

    def ragged(lens, buf, dtype):
        off = np.zeros(n + 1, dtype=np.int64)
        if lens:
            np.cumsum(lens, out=off[1:])
        # frombuffer over the bytearray directly: no second whole-column
        # copy; the accumulator is never mutated after this point
        flat = (np.frombuffer(buf, dtype) if len(buf)
                else np.zeros(0, dtype=dtype))
        return off, flat

    name_off, names_f = ragged(name_lens, names, np.uint8)
    seq_off, seqs_f = ragged(seq_lens, seqs_l, np.uint8)
    quals_f = (np.frombuffer(quals_l, np.uint8) if len(quals_l)
               else np.zeros(0, np.uint8))
    tag_off, tags_f = ragged(tag_lens, tags_l, np.uint8)
    cigar_off = np.zeros(n + 1, dtype=np.int64)
    if cig_lens:
        np.cumsum(cig_lens, out=cigar_off[1:])
    cigars_f = np.asarray(cig_flat, dtype=np.uint32)
    # bin: recompute (CRAM does not store it) — vectorized over the
    # whole slice, shared with the SAM text parser
    bin_l = bins_from_cigars(cigars_f, cigar_off, pos_l).astype(bin_l.dtype)
    return ReadBatch(
        refid=refid_l, pos=pos_l, mapq=mapq_l, bin=bin_l, flag=flag_l,
        next_refid=nref_l, next_pos=npos_l, tlen=tlen_l,
        name_offsets=name_off, names=names_f,
        cigar_offsets=cigar_off, cigars=cigars_f,
        seq_offsets=seq_off, seqs=seqs_f, quals=quals_f,
        tag_offsets=tag_off, tags=tags_f,
    )
