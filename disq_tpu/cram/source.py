class CramSource:
    def __init__(self, storage=None):
        self._storage = storage

    def get_reads(self, path, traversal=None):
        raise NotImplementedError(
            "CRAM read support is not built yet in this milestone "
            "(planned: container walk + rANS/gzip block codecs, "
            "SURVEY.md §2.5)"
        )
