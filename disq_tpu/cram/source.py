"""CramSource — the parallel CRAM read path.

Reference parity: ``impl/formats/cram/CramSource.java`` (SURVEY.md §2.5,
call stack §3.5): container start offsets are enumerated by walking
container headers (payloads skipped — cheap, seek-dominated); containers
are assigned to byte-range splits by the "container start in [start,
end)" first-owner rule; each split decodes its containers with the
reference supplied via ``reference_source_path`` (REQUIRED for
reference-compressed data, as in the reference). Interval traversal
prunes containers through ``.crai`` when present.
"""

from __future__ import annotations

import struct
from typing import List, Optional

import numpy as np

from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.bam.header import SamHeader
from disq_tpu.cram.codec import decode_container_records
from disq_tpu.cram.crai import CraiIndex
from disq_tpu.cram.io import Cursor
from disq_tpu.cram.structure import (
    Block,
    ContainerHeader,
    FILE_HEADER,
    read_container_header_at,
    read_file_definition,
    walk_container_offsets,
)
from disq_tpu.fsw.filesystem import (
    FileSystemWrapper,
    compute_path_splits,
    resolve_path,
)


def read_cram_header(fs: FileSystemWrapper, path: str) -> SamHeader:
    """SAM header from the first (FILE_HEADER) container."""
    head = fs.read_range(path, 0, min(fs.get_file_length(path), 1 << 20))
    _, off = read_file_definition(head)
    cur = Cursor(head, off)
    hdr = ContainerHeader.read(cur)
    need = cur.off + hdr.length
    if need > len(head):
        head = fs.read_range(path, 0, need)
        cur = Cursor(head, off)
        hdr = ContainerHeader.read(cur)
    block = Block.read(cur)
    if block.content_type != FILE_HEADER:
        raise ValueError("first CRAM container does not hold the SAM header")
    (l_text,) = struct.unpack_from("<i", block.data, 0)
    text = block.data[4:4 + l_text].decode(errors="replace").rstrip("\x00")
    return SamHeader.from_text(text)


class CramSource:
    def __init__(self, storage=None):
        self._storage = storage

    @property
    def split_size(self) -> int:
        return getattr(self._storage, "_split_size", 128 * 1024 * 1024)

    def _ref_fetch(self, header: SamHeader):
        from disq_tpu.cram.refsource import fetcher_for_storage

        return fetcher_for_storage(self._storage, header)

    # -- public -------------------------------------------------------------

    def get_reads(self, path: str, traversal=None):
        from disq_tpu.api import ReadsDataset
        from disq_tpu.runtime import ShardCounters, reduce_counters
        from disq_tpu.runtime.errors import context_for_storage

        fs, path = resolve_path(path)
        ctx = context_for_storage(self._storage, path)
        header = ctx.retrier.call(read_cram_header, fs, path, what="header")
        ref_fetch = self._ref_fetch(header)
        containers = walk_container_offsets(
            fs, path, retrier=ctx.retrier, ctx=ctx)
        data_containers = [
            (off, hdr) for off, hdr in containers[1:] if not hdr.is_eof
        ]
        if traversal is not None:
            # Index-driven reads retry transient faults whole-phase (the
            # read is bounded by the queried intervals); corrupt
            # containers inside the traversal always raise.
            batch = ctx.retrier.call(
                self._read_with_traversal, fs, path, header, ref_fetch,
                data_containers, traversal, what="traversal",
            )
            counters = reduce_counters([])
            counters.retried_reads += ctx.retrier.retried
            return ReadsDataset(header=header, reads=batch,
                                counters=counters)
        # Containers run through the shard executor: stage A range-reads
        # every container payload a split owns, stage B decodes them
        # (rANS/gzip + record assembly — the CPU-bound phase CRAM is
        # serialization-bound on), stage C emits per split in order.
        import functools

        from disq_tpu.runtime import ShardTask
        from disq_tpu.runtime.errors import (
            DisqOptions,
            deadline_fallback_for,
        )
        from disq_tpu.runtime.executor import (
            executor_for_storage,
            read_ledger_for_storage,
        )
        from disq_tpu.runtime.tracing import wrap_span

        opts = getattr(self._storage, "_options", None) or DisqOptions()
        tasks, shard_ctxs, owned_by_shard = [], [], []
        for i, s in enumerate(compute_path_splits(fs, path, self.split_size)):
            owned = [
                (off, hdr) for off, hdr in data_containers
                if s.start <= off < s.end
            ]
            shard_ctx = ctx.for_shard(i)
            shard_ctxs.append(shard_ctx)
            owned_by_shard.append(owned)
            tasks.append(ShardTask(
                shard_id=i,
                # Per-split timeline spans carrying shard id, byte range
                # and owned-container count.
                fetch=wrap_span(
                    "cram.split.fetch",
                    functools.partial(
                        self._fetch_split_containers, fs, path, owned,
                        shard_ctx),
                    shard=i, start=s.start, end=s.end,
                    containers=len(owned)),
                decode=wrap_span(
                    "cram.split.decode",
                    functools.partial(
                        self._decode_split_containers, ref_fetch=ref_fetch,
                        shard_ctx=shard_ctx),
                    shard=i, containers=len(owned)),
                retrier=shard_ctx.retrier,
                what=f"cram-shard{i}",
                # Over-deadline splits under skip/quarantine are set
                # aside as zero containers instead of aborting.
                deadline_fallback=deadline_fallback_for(
                    opts, shard_ctx, list),
                # Scheduler locality coordinate (split byte window).
                byte_range=(s.start, s.end),
            ))
        from disq_tpu.runtime.introspect import note_shard_counters
        from disq_tpu.runtime.scheduler import scheduled_map_ordered

        batches = []
        shard_counters = []
        ledger = read_ledger_for_storage(self._storage, path, len(tasks))
        # scheduler off (default): falls through to
        # map_ordered_resumable; on: container splits lease from the
        # shared cross-host queue.
        for res in scheduled_map_ordered(
                self._storage, fs, path,
                executor_for_storage(self._storage), tasks, ledger):
            shard_batches = res.value
            shard_ctx = shard_ctxs[res.shard_id]
            owned = owned_by_shard[res.shard_id]
            batches.extend(shard_batches)
            c = ShardCounters(
                shard_id=res.shard_id,
                records=sum(b.count for b in shard_batches),
                blocks=len(owned),
                bytes_compressed=sum(h.length for _, h in owned),
                wall_seconds=res.wall_seconds,
                skipped_blocks=shard_ctx.skipped_blocks,
                quarantined_blocks=shard_ctx.quarantined_blocks,
                retried_reads=shard_ctx.retrier.retried,
            )
            shard_counters.append(c)
            note_shard_counters("read", c)  # live /progress feed
        counters = reduce_counters(shard_counters)
        # Walk/header-phase events happened on the top-level context,
        # outside any shard's counters.
        counters.retried_reads += ctx.retrier.retried
        counters.skipped_blocks += ctx.skipped_blocks
        counters.quarantined_blocks += ctx.quarantined_blocks
        return ReadsDataset(header=header, reads=ReadBatch.concat(batches),
                            counters=counters)

    # -- internals ----------------------------------------------------------

    def _decode_at(self, fs, path: str, offset: int, ref_fetch) -> ReadBatch:
        hdr, hdr_size = read_container_header_at(
            fs, path, offset, fs.get_file_length(path)
        )
        blocks = fs.read_range(path, offset + hdr_size, hdr.length)
        return decode_container_records(blocks, ref_fetch)

    def _fetch_split_containers(
        self, fs, path: str, owned, shard_ctx
    ) -> List[tuple]:
        """Stage A: range-read every container payload this split owns.
        Returns [(offset, header bytes, payload bytes), …]. Transient
        faults propagate (the executor retries the whole shard fetch);
        a container whose *header* no longer parses is corrupt — policy
        applies here, and the surviving list simply omits it."""
        from disq_tpu.runtime.errors import is_transient

        # A retried attempt must not double-count the previous attempt's
        # corrupt containers (quarantine sidecar writes are idempotent).
        shard_ctx.skipped_blocks = 0
        shard_ctx.quarantined_blocks = 0
        length = fs.get_file_length(path)
        items = []
        for off, hdr in owned:
            try:
                h, hdr_size = read_container_header_at(fs, path, off, length)
                head = fs.read_range(path, off, hdr_size)
                payload = fs.read_range(path, off + hdr_size, h.length)
            except Exception as e:  # noqa: BLE001 — classified below
                if is_transient(e):
                    raise
                self._handle_corrupt_container(
                    fs, path, off, hdr, b"", e, shard_ctx)
                continue
            items.append((off, head, payload))
        return items

    def _decode_split_containers(
        self, items, ref_fetch, shard_ctx
    ) -> List[ReadBatch]:
        """Stage B: decode the staged containers under the shard's error
        policy: configuration errors (missing reference) always
        propagate; transient faults (the reference fetch can read)
        propagate for the executor's refetch path; anything else is a
        corrupt container — strict raises with coordinates, skip drops
        it, quarantine copies the whole container (header + payload,
        already staged — no re-fetch) to the sidecar."""
        from disq_tpu.runtime.errors import MissingReferenceError, is_transient

        batches = []
        for off, head, payload in items:
            try:
                batches.append(decode_container_records(payload, ref_fetch))
            except MissingReferenceError:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if is_transient(e):
                    raise
                shard_ctx.handle_corrupt_block(
                    e, block_offset=off, raw=head + payload,
                    kind="CRAM container",
                )
        return batches

    def _handle_corrupt_container(
        self, fs, path: str, offset: int, hdr, raw, error, shard_ctx
    ) -> None:
        """Policy dispatch for a container that failed before its bytes
        were staged: quarantine re-reads best-effort (skip/strict never
        pay for bytes they would discard)."""
        from disq_tpu.runtime.errors import ErrorPolicy

        if shard_ctx.policy is ErrorPolicy.QUARANTINE and not raw:
            try:
                length = fs.get_file_length(path)
                raw = fs.read_range(
                    path, offset,
                    min(hdr.length + 1024, max(0, length - offset)))
            except Exception:  # noqa: BLE001 — forensics best-effort
                raw = b""
        shard_ctx.handle_corrupt_block(
            error, block_offset=offset, raw=raw, kind="CRAM container"
        )

    def _read_with_traversal(
        self, fs, path, header, ref_fetch, data_containers, traversal
    ) -> ReadBatch:
        batches: List[ReadBatch] = []
        crai: Optional[CraiIndex] = None
        if fs.exists(path + ".crai"):
            crai = CraiIndex.from_bytes(fs.read_all(path + ".crai"))
        if traversal.intervals is not None and len(traversal.intervals) > 0:
            if crai is not None:
                offsets = set()
                for iv in traversal.intervals:
                    refid = header.ref_index(iv.contig)
                    offsets.update(
                        crai.containers_for_interval(refid, iv.start, iv.end)
                    )
                chosen = sorted(offsets)
            else:
                chosen = [off for off, _ in data_containers]
            sub = []
            for off in chosen:
                sub.append(self._decode_at(fs, path, off, ref_fetch))
            if sub:
                merged = ReadBatch.concat(sub)
                from disq_tpu.traversal.bai_query import overlap_mask

                batches.append(
                    merged.filter(overlap_mask(merged, header, traversal.intervals))
                )
        if traversal.traverse_unplaced_unmapped:
            unmapped_offs = (
                [e.container_offset for e in crai.entries if e.seq_id == -1]
                if crai is not None
                else [off for off, hdr in data_containers if hdr.ref_seq_id == -1]
            )
            for off in sorted(set(unmapped_offs)):
                sub = self._decode_at(fs, path, off, ref_fetch)
                batches.append(sub.filter(sub.refid == -1))
        if not batches:
            return ReadBatch.empty()
        return ReadBatch.concat(batches)
