"""CRAM reference source — FASTA + .fai access.

Reference parity: htsjdk ``ReferenceSource`` built by disq's
``CramReferenceSourceBuilder`` from ``referenceSourcePath`` (SURVEY.md
§2.5): reading reference-compressed CRAM REQUIRES the reference; lookups
are cached per contig. Works over any ``FileSystemWrapper``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from disq_tpu.fsw.filesystem import FileSystemWrapper, resolve_path


class CramReferenceSource:
    def __init__(self, fs: FileSystemWrapper, path: str):
        self.fs = fs
        self.path = path
        self._fai = self._load_fai()
        self._cache: Dict[str, bytes] = {}
        self._names: List[str] = list(self._fai)

    def _load_fai(self) -> Dict[str, Tuple[int, int, int, int]]:
        fai_path = self.path + ".fai"
        if self.fs.exists(fai_path):
            out = {}
            for line in self.fs.read_all(fai_path).decode().splitlines():
                if not line.strip():
                    continue
                name, length, offset, linebases, linewidth = line.split("\t")[:5]
                out[name] = (int(length), int(offset), int(linebases), int(linewidth))
            return out
        return self._index_fasta()

    def _index_fasta(self) -> Dict[str, Tuple[int, int, int, int]]:
        """Build an in-memory .fai when none exists (small references)."""
        data = self.fs.read_all(self.path)
        out: Dict[str, Tuple[int, int, int, int]] = {}
        pos = 0
        name = None
        seq_start = 0
        linebases = linewidth = 0
        length = 0
        for line in data.split(b"\n"):
            ll = len(line) + 1
            if line.startswith(b">"):
                if name is not None:
                    out[name] = (length, seq_start, linebases, linewidth)
                name = line[1:].split()[0].decode()
                seq_start = pos + ll
                length = 0
                linebases = linewidth = 0
            elif line and name is not None:
                if linebases == 0:
                    linebases, linewidth = len(line), ll
                length += len(line)
            pos += ll
        if name is not None:
            out[name] = (length, seq_start, linebases, linewidth)
        return out

    def contig_length(self, name: str) -> int:
        return self._fai[name][0]

    def bases_by_name(self, name: str, start0: int, length: int) -> bytes:
        """Uppercase reference bases [start0, start0+length)."""
        seq = self._cache.get(name)
        if seq is None:
            total, offset, linebases, linewidth = self._fai[name]
            if linebases <= 0:
                return b""
            n_lines = -(-total // linebases)
            raw = self.fs.read_range(
                self.path, offset, n_lines * linewidth
            )
            seq = raw.replace(b"\n", b"").replace(b"\r", b"")[:total].upper()
            self._cache[name] = seq
        return seq[start0: start0 + length]

    def fetcher(self, contig_names: List[str]):
        """→ ``ref_fetch(refid, start0, length) -> bytes | None`` resolving
        refids through the SAM header's sequence dictionary order."""

        def fetch(refid: int, start0: int, length: int) -> Optional[bytes]:
            if refid < 0 or refid >= len(contig_names):
                return None
            name = contig_names[refid]
            if name not in self._fai:
                return None
            return self.bases_by_name(name, start0, length)

        return fetch


def write_fasta(
    fs: FileSystemWrapper, path: str, contigs: List[Tuple[str, bytes]],
    line_width: int = 60,
) -> None:
    """Utility: write a FASTA + .fai pair (used by tests/benchmarks)."""
    out = bytearray()
    fai_lines = []
    for name, seq in contigs:
        out += b">" + name.encode() + b"\n"
        offset = len(out)
        for i in range(0, len(seq), line_width):
            out += seq[i: i + line_width] + b"\n"
        fai_lines.append(
            f"{name}\t{len(seq)}\t{offset}\t{line_width}\t{line_width + 1}"
        )
    fs.write_all(path, bytes(out))
    fs.write_all(path + ".fai", ("\n".join(fai_lines) + "\n").encode())


def fetcher_for_storage(storage, header):
    """Resolve ``storage.reference_source_path`` → a refid-keyed fetcher
    (shared by the CRAM read and write paths), or None when unset."""
    path = getattr(storage, "_reference_source_path", None)
    if not path:
        return None
    fs, path = resolve_path(path)
    src = CramReferenceSource(fs, path)
    return src.fetcher([s.name for s in header.sequences])
