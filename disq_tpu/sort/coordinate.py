"""Coordinate sort — first-class in this framework.

Upstream disq does NOT sort (SURVEY.md §2.1 note: ``write`` trusts
``header.getSortOrder()``; GATK does a Spark ``sortBy`` shuffle before
calling it). Here the sort is owned by the framework: the single-host
path below sorts a columnar batch by the 64-bit coordinate key; the
multi-chip path (``disq_tpu.sort.sharded``) buckets records across the
device mesh with a psum histogram + all_to_all exchange over ICI and
reuses the same key.

SAM coordinate order: ascending refID (unmapped refID=-1 LAST), then
ascending pos; ties keep input order (stable).
"""

from __future__ import annotations

import numpy as np

from disq_tpu.bam.columnar import ReadBatch

# Key layout: (refid+1) in the high 32 bits with unmapped (refid -1)
# remapped ABOVE all real refs, pos+1 in the low 32. Monotone w.r.t.
# coordinate order, so one u64 radix/merge sort suffices.


def coordinate_keys(refid: np.ndarray, pos: np.ndarray) -> np.ndarray:
    rid = refid.astype(np.int64)
    rid = np.where(rid < 0, np.int64(0x7FFFFFFF), rid)
    return (rid.astype(np.uint64) << np.uint64(32)) | (
        (pos.astype(np.int64) + 1).astype(np.uint64) & np.uint64(0xFFFFFFFF)
    )


def coordinate_sort_batch(batch: ReadBatch, use_mesh: bool = True,
                          keep_resident: bool = False) -> ReadBatch:
    """Sort a batch into coordinate order.

    A device-backed ``ColumnarBatch`` (the HBM-resident fused-decode
    currency) sorts from its resident refid/pos columns: key build +
    lexsort run on device and only the (n,) i32 permutation crosses
    d2h — the u64 key vectors never materialize host-side. Otherwise
    the permutation comes from the device mesh when more than one
    device is attached (psum/all_to_all exchange,
    ``disq_tpu.sort.sharded``); ragged columns are reordered host-side
    by one vectorized segment gather either way.

    ``keep_resident`` (the symmetric write path) returns
    ``batch.permuted(order)`` instead of materializing host records:
    the sorted batch stays a device-backed ``ColumnarBatch`` whose
    fixed columns were permuted on device and whose record bytes feed
    the resident encode → deflate chain (``runtime/device_write.py``)
    — whether the permutation came from the single-chip lexsort or the
    multi-chip psum/all_to_all exchange.
    """
    from disq_tpu.runtime.columnar import ColumnarBatch

    if isinstance(batch, ColumnarBatch):
        if batch.device_backed and batch.count > 0:
            # resident sort-key extraction: byte-identical to the host
            # argsort (same key, both stable), zero key traffic.  A
            # mesh-sharded batch routes through the multi-chip
            # psum-histogram exchange (sharded.resident_coordinate_sort)
            # with the same byte-identity contract — rows ride as the
            # least-significant lexsort component, so duplicate keys
            # keep original-index order at any device count.
            order = batch.sort_permutation()
            if keep_resident and batch.encode_source() is not None:
                return batch.permuted(order)
            return batch.take(order)
        resident_src = batch if keep_resident else None
        batch = batch.to_read_batch()
    else:
        resident_src = None
    keys = coordinate_keys(batch.refid, batch.pos)
    order = None
    if use_mesh and batch.count > 0:
        # Deliberate: only "mesh has a single device" selects the host
        # path. A real failure inside the sharded sort must propagate —
        # swallowing it here would let a broken mesh path silently degrade
        # to the host argsort and never fail a test.
        import jax

        if len(jax.devices()) > 1:
            from disq_tpu.sort.sharded import sharded_coordinate_sort

            _, order = sharded_coordinate_sort(keys)
    if order is None:
        order = np.argsort(keys, kind="stable")
    if resident_src is not None and resident_src.encode_source() is not None:
        return resident_src.permuted(order)
    return batch.take(order)
