from disq_tpu.sort.coordinate import (  # noqa: F401
    coordinate_sort_batch,
    coordinate_keys,
)
