"""Multi-chip coordinate sort over a `jax.sharding.Mesh`.

This replaces the Spark ``sortBy`` shuffle that the reference relies on
its caller to run (SURVEY.md §2.9, §3.3): the only all-to-all in disq's
world. TPU-native design (BASELINE.json north star; scaling-book recipe:
pick a mesh, annotate shardings, let XLA insert collectives):

1. each shard holds ``per_shard`` coordinate keys + row ids. Keys are
   **u32 pairs** (hi = remapped refID, lo = pos+1) rather than one u64 —
   TPUs are 32-bit-native and this framework keeps x64 emulation off the
   hot path by construction;
2. splitters (device count − 1 quantiles, sampled on host) define the
   target shard of every key — a *range* partition, so after the exchange
   the shards concatenate into global order;
3. ``shard_map`` stage: group local keys by destination (one stable local
   lexsort), scatter into a fixed-capacity ``(n_shards, cap)`` send
   buffer, ``lax.all_to_all`` over the mesh axis (rides ICI on real
   hardware), then one local lexsort of the received buffer;
4. sentinel padding (``0xFFFFFFFF`` pairs) sorts to the end and is
   dropped by the validity count; a ``psum`` over per-destination counts
   flags capacity overflow (``ok``) without host round-trips inside the
   step.

Everything is static-shape and jit-compatible: no data-dependent Python
control flow (XLA traces once); capacity overflow is handled by re-running
with a larger ``capacity_factor`` (a host-side decision) — the
deterministic, restartable-phase-plan shape from SURVEY.md §5.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SENT32 = jnp.uint32(0xFFFFFFFF)


def make_mesh(n_devices: Optional[int] = None, axis: str = "shards") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (axis,))


def split_u64_keys(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host u64 coordinate keys → (hi, lo) u32 pairs for the device sort."""
    return (
        (keys >> np.uint64(32)).astype(np.uint32),
        (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32),
    )


def sample_splitters(keys: np.ndarray, n_shards: int, oversample: int = 64) -> np.ndarray:
    """Host-side quantile splitters ((n_shards-1,) u64) for the range
    partition. Deterministic (seeded): part of the restartable phase plan."""
    if n_shards <= 1 or len(keys) == 0:
        return np.zeros(max(n_shards - 1, 0), dtype=np.uint64)
    rng = np.random.default_rng(0)
    m = min(len(keys), n_shards * oversample)
    sample = np.sort(rng.choice(keys, size=m, replace=False))
    qs = (np.arange(1, n_shards) * m) // n_shards
    return sample[qs].astype(np.uint64)


def _dest_shard(hi, lo, s_hi, s_lo):
    """Range-partition destination: number of splitters strictly less-or-
    equal (side='right' semantics) computed by broadcast compare —
    O(S·m) u32 ops, MXU/VPU-friendly, no 64-bit arithmetic."""
    le = (s_hi[:, None] < hi[None, :]) | (
        (s_hi[:, None] == hi[None, :]) & (s_lo[:, None] <= lo[None, :])
    )
    return jnp.sum(le, axis=0, dtype=jnp.int32)


def _group_scatter(bucket, nb, cap, arrs, fills):
    """Group-by-destination scatter shared by every exchange stage:
    stable-sort by bucket, rank within each group, scatter each array
    into a fixed-capacity (nb, cap) send buffer (phantom bucket ``nb``
    and over-capacity entries fall outside and are dropped), and return
    the per-bucket valid counts for overflow detection."""
    order = jnp.argsort(bucket, stable=True)
    b_g = bucket[order]
    group_start = jnp.searchsorted(b_g, b_g, side="left")
    within = jnp.arange(b_g.shape[0]) - group_start
    outs = []
    for a, fill in zip(arrs, fills):
        buf_shape = (nb, cap) + a.shape[1:]
        buf = jnp.full(buf_shape, fill, dtype=a.dtype)
        outs.append(buf.at[b_g, within].set(a[order], mode="drop"))
    counts = jnp.bincount(
        jnp.where(b_g < nb, b_g, 0),
        weights=(b_g < nb).astype(jnp.int32), length=nb,
    ).astype(jnp.int32)
    return outs, counts


def _sort_stage(hi, lo, rows, s_hi, s_lo, *, axis: str, n_shards: int, cap: int):
    """Per-shard body under shard_map. hi/lo/rows: (1, per_shard) blocks
    with sentinel padding; s_hi/s_lo: (n_shards-1,) replicated."""
    hi, lo, rows = hi.reshape(-1), lo.reshape(-1), rows.reshape(-1)
    valid = ~((hi == SENT32) & (lo == SENT32))
    dest = _dest_shard(hi, lo, s_hi, s_lo)
    # Invalid (padding) entries route to a phantom bucket n_shards so they
    # group after every real bucket and never inflate a real rank.
    dest = jnp.where(valid, dest, n_shards)
    (send_hi, send_lo, send_rows), counts = _group_scatter(
        dest, n_shards, cap, (hi, lo, rows), (SENT32, SENT32, 0))
    ok = jnp.all(lax.psum((counts > cap).astype(jnp.int32), axis) == 0)
    # The exchange — rides ICI on real hardware.
    recv_hi = lax.all_to_all(send_hi, axis, split_axis=0, concat_axis=0)
    recv_lo = lax.all_to_all(send_lo, axis, split_axis=0, concat_axis=0)
    recv_rows = lax.all_to_all(send_rows, axis, split_axis=0, concat_axis=0)
    fh, fl, fr = recv_hi.reshape(-1), recv_lo.reshape(-1), recv_rows.reshape(-1)
    # rows as the least-significant tie-break: duplicate keys keep
    # original-index order on EVERY exchange shape (the hierarchical
    # path's arrival order differs from the flat path's, so relying on
    # arrival stability would make tie order topology-dependent)
    final = jnp.lexsort((fr, fl, fh))
    out_hi, out_lo, out_rows = fh[final], fl[final], fr[final]
    n_valid = jnp.sum(~((out_hi == SENT32) & (out_lo == SENT32))).astype(jnp.int32)
    return out_hi[None], out_lo[None], out_rows[None], n_valid[None], ok[None]


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "capacity_factor"))
def sharded_sort_step(
    hi: jax.Array,
    lo: jax.Array,
    rows: jax.Array,
    s_hi: jax.Array,
    s_lo: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "shards",
    capacity_factor: float = 2.0,
):
    """One full sort exchange over the mesh.

    Inputs (n_shards, per_shard), sharded over ``axis`` on dim 0, sentinel-
    padded. Returns (hi, lo, rows, valid_counts, ok): each output shard
    holds its key range ascending with sentinel tail; concatenating shards
    trimmed to their valid counts yields the global order.
    """
    n_shards = mesh.shape[axis]
    per_shard = hi.shape[1]
    cap = min(int(per_shard * capacity_factor / n_shards) + 1, per_shard)
    body = functools.partial(_sort_stage, axis=axis, n_shards=n_shards, cap=cap)
    return _shard_map()(
        body,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None), P(None), P(None)),
        out_specs=(P(axis, None), P(axis, None), P(axis, None), P(axis), P(axis)),
    )(hi, lo, rows, s_hi, s_lo)


def _sort_stage_payload(
    hi, lo, rows, vals, s_hi, s_lo, *, axis: str, n_shards: int, cap: int
):
    """As ``_sort_stage``, but the exchange also carries a fixed-width
    payload matrix — whole records ride the ICI all_to_all, not just
    keys. vals: (1, per_shard, W) u32 blocks."""
    hi, lo, rows = hi.reshape(-1), lo.reshape(-1), rows.reshape(-1)
    vals = vals.reshape(hi.shape[0], -1)
    w = vals.shape[1]
    valid = ~((hi == SENT32) & (lo == SENT32))
    dest = _dest_shard(hi, lo, s_hi, s_lo)
    dest = jnp.where(valid, dest, n_shards)
    (send_hi, send_lo, send_rows, send_vals), counts = _group_scatter(
        dest, n_shards, cap, (hi, lo, rows, vals), (SENT32, SENT32, 0, 0))
    ok = jnp.all(lax.psum((counts > cap).astype(jnp.int32), axis) == 0)
    recv_hi = lax.all_to_all(send_hi, axis, split_axis=0, concat_axis=0)
    recv_lo = lax.all_to_all(send_lo, axis, split_axis=0, concat_axis=0)
    recv_rows = lax.all_to_all(send_rows, axis, split_axis=0, concat_axis=0)
    recv_vals = lax.all_to_all(send_vals, axis, split_axis=0, concat_axis=0)
    fh, fl, fr = recv_hi.reshape(-1), recv_lo.reshape(-1), recv_rows.reshape(-1)
    fv = recv_vals.reshape(-1, w)
    final = jnp.lexsort((fr, fl, fh))
    out_hi, out_lo, out_rows = fh[final], fl[final], fr[final]
    out_vals = fv[final]
    n_valid = jnp.sum(~((out_hi == SENT32) & (out_lo == SENT32))).astype(jnp.int32)
    return (
        out_hi[None], out_lo[None], out_rows[None], out_vals[None],
        n_valid[None], ok[None],
    )


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "capacity_factor"))
def sharded_sort_payload_step(
    hi, lo, rows, vals, s_hi, s_lo, *,
    mesh: Mesh, axis: str = "shards", capacity_factor: float = 2.0,
):
    """One sort exchange moving keys AND a (n_shards, per_shard, W)
    u32 payload (the packed fixed record columns)."""
    n_shards = mesh.shape[axis]
    per_shard = hi.shape[1]
    cap = min(int(per_shard * capacity_factor / n_shards) + 1, per_shard)
    body = functools.partial(
        _sort_stage_payload, axis=axis, n_shards=n_shards, cap=cap
    )
    return _shard_map()(
        body,
        mesh=mesh,
        in_specs=(
            P(axis, None), P(axis, None), P(axis, None), P(axis, None, None),
            P(None), P(None),
        ),
        out_specs=(
            P(axis, None), P(axis, None), P(axis, None), P(axis, None, None),
            P(axis), P(axis),
        ),
    )(hi, lo, rows, vals, s_hi, s_lo)


# Packed fixed-column layout for the record exchange (all u32):
_PAYLOAD_COLS = (
    "refid", "pos", "flag_mapq", "bin", "next_refid", "next_pos", "tlen",
    # ragged section byte-lengths (name, cigar, seq, qual, tags) — the
    # offset arrays are rebuilt from these by prefix sum after the sort
    "len_name", "len_cig", "len_seq", "len_qual", "len_tag",
)

# Padded-matrix caps: per-record ragged bytes, and the whole matrix
# (pathological batches ride the host fallback instead of OOMing).
_MAX_RAGGED_BYTES = 64 * 1024
_MAX_RAGGED_MATRIX = 2 << 30


def _ragged_lens(batch):
    name_len = np.diff(batch.name_offsets).astype(np.int64)
    cig_len = (np.diff(batch.cigar_offsets) * 4).astype(np.int64)
    seq_len = np.diff(batch.seq_offsets).astype(np.int64)
    tag_len = np.diff(batch.tag_offsets).astype(np.int64)
    return (name_len, cig_len, seq_len, seq_len, tag_len)


def _ragged_scatter(batch, lens, parent_u32: np.ndarray,
                    col_off_words: int) -> None:
    """Pack each record's ragged bytes (name|cigar|seq|qual|tags) into
    ``parent_u32[i, col_off_words:]`` via flat byte indexing into the
    CONTIGUOUS parent (a column-slice view's reshape would silently
    copy). This is what rides the all_to_all — whole records move on
    the mesh, no host-side segment gather afterwards."""
    n = batch.count
    assert parent_u32.flags.c_contiguous
    flat = parent_u32.view(np.uint8).reshape(-1)
    stride = parent_u32.shape[1] * 4
    sources = (
        batch.names,
        np.ascontiguousarray(batch.cigars).view(np.uint8)
        if batch.cigars.size else np.zeros(0, np.uint8),
        batch.seqs, batch.quals, batch.tags,
    )
    start = np.zeros(n, dtype=np.int64)
    row_base = np.arange(n, dtype=np.int64) * stride + col_off_words * 4
    for ln, src in zip(lens, sources):
        tot = int(ln.sum())
        if tot:
            # byte k of record i lands at row_base[i] + start[i] + k
            intra = np.arange(tot, dtype=np.int64) - np.repeat(
                np.concatenate([[0], np.cumsum(ln)[:-1]]), ln)
            dst = np.repeat(row_base + start, ln) + intra
            flat[dst] = np.asarray(src, dtype=np.uint8)[:tot]
        start += ln


def _rebuild_ragged(parent_u32: np.ndarray, col_off_words: int,
                    lens_cols: np.ndarray):
    """Inverse of ``_ragged_scatter`` for the post-exchange rows:
    contiguous (n, W) u32 + (n, 5) lengths → per-section concatenated
    arrays and prefix-sum offsets. Flat index gathers — O(total bytes),
    no (n, width) mask temporaries."""
    n = parent_u32.shape[0]
    parent_u32 = np.ascontiguousarray(parent_u32)
    flat = parent_u32.view(np.uint8).reshape(-1)
    stride = parent_u32.shape[1] * 4
    row_base = np.arange(n, dtype=np.int64) * stride + col_off_words * 4
    start = np.zeros(n, dtype=np.int64)
    out = []
    for s in range(5):
        ln = lens_cols[:, s].astype(np.int64)
        tot = int(ln.sum())
        if tot:
            intra = np.arange(tot, dtype=np.int64) - np.repeat(
                np.concatenate([[0], np.cumsum(ln)[:-1]]), ln)
            src = np.repeat(row_base + start, ln) + intra
            data = flat[src]
        else:
            data = np.zeros(0, np.uint8)
        offs = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(ln, out=offs[1:])
        out.append((data, offs))
        start += ln
    return out


def sharded_sort_read_batch(batch, mesh: Optional[Mesh] = None,
                            axis: str = "shards",
                            capacity_factor: float = 2.0):
    """Coordinate-sort a ``ReadBatch`` with the WHOLE record riding the
    mesh exchange: fixed columns packed as u32 and every ragged column
    (name/cigar/seq/qual/tags) packed into a padded byte matrix, all
    moved by the same all_to_all. Offsets are rebuilt from the carried
    section lengths by prefix sum — there is no host-side segment
    gather on the success path (VERDICT r4 item 5; SURVEY.md §2.9/§3.3:
    the sort shuffle IS the collective).

    Returns (sorted_batch, permutation).
    """
    from disq_tpu.bam.columnar import ReadBatch  # local: avoid cycle
    from disq_tpu.sort.coordinate import coordinate_keys

    mesh = mesh or make_mesh()
    # a two-axis mesh (runtime/multihost.global_mesh's (dcn, shards))
    # routes through the hierarchical two-stage exchange; the contract
    # is explicit: the trailing axis (named by ``axis``) is ICI, the
    # leading one is the DCN/host boundary — a swapped mesh would
    # silently invert the bandwidth layering
    two_level = len(mesh.axis_names) == 2
    if two_level:
        if mesh.axis_names[-1] != axis:
            raise ValueError(
                f"two-axis mesh must be (dcn_axis, {axis!r}) with the "
                f"per-host ICI axis last; got {mesh.axis_names}")
        dcn_axis, ici_axis = mesh.axis_names
        n_shards = mesh.shape[dcn_axis] * mesh.shape[ici_axis]
    else:
        n_shards = mesh.shape[axis]
    n = batch.count
    if n == 0:
        return batch, np.zeros(0, dtype=np.int64)
    keys = coordinate_keys(np.asarray(batch.refid), np.asarray(batch.pos))
    per_shard = -(-n // n_shards)
    padded = per_shard * n_shards
    keys_p = np.full(padded, np.uint64(0xFFFFFFFFFFFFFFFF), dtype=np.uint64)
    keys_p[:n] = keys
    hi_p, lo_p = split_u64_keys(keys_p)
    rows_p = np.zeros(padded, dtype=np.uint32)
    rows_p[:n] = np.arange(n, dtype=np.uint32)
    lens = _ragged_lens(batch)
    rw_bytes = int(sum(lens).max()) if n else 0
    rw_words = -(-rw_bytes // 4)
    nfixed = len(_PAYLOAD_COLS)
    # refuse BEFORE allocating: a pathological record (or sheer batch
    # size) must not OOM building the padded matrix
    if (rw_bytes > _MAX_RAGGED_BYTES
            or padded * (nfixed + rw_words) * 4 > _MAX_RAGGED_MATRIX):
        order = np.argsort(keys, kind="stable")
        return batch.take(order), order
    vals_p = np.zeros((padded, nfixed + rw_words), dtype=np.uint32)
    _ragged_scatter(batch, lens, vals_p, nfixed)
    vals_p[:n, 0] = np.asarray(batch.refid).view(np.uint32)
    vals_p[:n, 1] = np.asarray(batch.pos).view(np.uint32)
    vals_p[:n, 2] = (
        np.asarray(batch.flag).astype(np.uint32)
        | (np.asarray(batch.mapq).astype(np.uint32) << 16)
    )
    vals_p[:n, 3] = np.asarray(batch.bin).astype(np.uint32)
    vals_p[:n, 4] = np.asarray(batch.next_refid).view(np.uint32)
    vals_p[:n, 5] = np.asarray(batch.next_pos).view(np.uint32)
    vals_p[:n, 6] = np.asarray(batch.tlen).view(np.uint32)
    for s in range(5):
        vals_p[:n, 7 + s] = lens[s].astype(np.uint32)
    splitters = sample_splitters(keys, n_shards)
    s_hi, s_lo = split_u64_keys(splitters)
    if two_level:
        kshape = (mesh.shape[dcn_axis], mesh.shape[ici_axis], per_shard)
        shard_k = NamedSharding(mesh, P(dcn_axis, ici_axis, None))
        shard_v = NamedSharding(mesh, P(dcn_axis, ici_axis, None, None))
        repl = NamedSharding(mesh, P())
        step = functools.partial(
            hierarchical_sort_payload_step, mesh=mesh,
            dcn_axis=dcn_axis, ici_axis=ici_axis)
    else:
        kshape = (n_shards, per_shard)
        shard_k = NamedSharding(mesh, P(axis, None))
        shard_v = NamedSharding(mesh, P(axis, None, None))
        repl = NamedSharding(mesh, P(None))
        step = functools.partial(
            sharded_sort_payload_step, mesh=mesh, axis=axis)
    args = (
        jax.device_put(hi_p.reshape(kshape), shard_k),
        jax.device_put(lo_p.reshape(kshape), shard_k),
        jax.device_put(rows_p.reshape(kshape), shard_k),
        jax.device_put(vals_p.reshape(kshape + (-1,)), shard_v),
        jax.device_put(s_hi, repl),
        jax.device_put(s_lo, repl),
    )
    for _ in range(3):
        oh, ol, orows, ovals, counts, ok = step(
            *args, capacity_factor=capacity_factor
        )
        if bool(jnp.all(ok)):
            cnt = np.asarray(counts).reshape(-1)
            ovals_h = np.asarray(ovals).reshape(
                (n_shards, -1) + np.asarray(ovals).shape[-1:])
            orows_h = np.asarray(orows).reshape(n_shards, -1)
            vh = np.concatenate(
                [ovals_h[i, : cnt[i]] for i in range(n_shards)]
            )
            perm = np.concatenate(
                [orows_h[i, : cnt[i]] for i in range(n_shards)]
            ).astype(np.int64)
            # every byte of the record arrived through the all_to_all;
            # rebuild offsets from the carried section lengths
            (names, name_off), (cig_b, _cigoff), (seqs, seq_off), \
                (quals, _qoff), (tags, tag_off) = _rebuild_ragged(
                    vh, nfixed, vh[:, 7:12])
            cigars = np.ascontiguousarray(cig_b).view("<u4")
            cigar_off = np.zeros(len(vh) + 1, dtype=np.int64)
            np.cumsum(vh[:, 8].astype(np.int64) // 4, out=cigar_off[1:])
            sorted_batch = ReadBatch(
                refid=vh[:, 0].view(np.int32),
                pos=vh[:, 1].view(np.int32),
                mapq=(vh[:, 2] >> 16).astype(np.uint8),
                bin=vh[:, 3].astype(np.uint16),
                flag=(vh[:, 2] & 0xFFFF).astype(np.uint16),
                next_refid=vh[:, 4].view(np.int32),
                next_pos=vh[:, 5].view(np.int32),
                tlen=vh[:, 6].view(np.int32),
                name_offsets=name_off, names=names,
                cigar_offsets=cigar_off, cigars=cigars,
                seq_offsets=seq_off, seqs=seqs, quals=quals,
                tag_offsets=tag_off, tags=tags,
            )
            return sorted_batch, perm
        capacity_factor *= 2.0
    # Skew defeated the capacity retries: host fallback.
    from disq_tpu.sort.coordinate import coordinate_sort_batch

    order = np.argsort(keys, kind="stable")
    return coordinate_sort_batch(batch, use_mesh=False), order


def _keys_exchange_host_wrapper(
    keys_np: np.ndarray, n_shards: int, put, run,
    capacity_factor: float, max_retries: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Shared pad/splitter/retry/trim protocol around a keys-only sort
    exchange. ``put(hi, lo, rows, s_hi, s_lo, per_shard)`` places the
    padded host arrays on the mesh; ``run(args, cf)`` executes one
    exchange and returns (hi, lo, rows, counts, ok). Retries with a
    doubled capacity on the (rare, skew-driven) overflow signal, and
    falls back to one host argsort only if skew defeats
    ``max_retries`` capacity doublings."""
    n = len(keys_np)
    if n == 0:
        return keys_np.copy(), np.zeros(0, dtype=np.int64)
    per_shard = -(-n // n_shards)
    padded = per_shard * n_shards
    keys_p = np.full(padded, np.uint64(0xFFFFFFFFFFFFFFFF), dtype=np.uint64)
    keys_p[:n] = keys_np
    hi_p, lo_p = split_u64_keys(keys_p)
    rows_p = np.zeros(padded, dtype=np.uint32)
    rows_p[:n] = np.arange(n, dtype=np.uint32)
    splitters = sample_splitters(keys_np, n_shards)
    s_hi, s_lo = split_u64_keys(splitters)
    args = put(hi_p, lo_p, rows_p, s_hi, s_lo, per_shard)
    for _ in range(max_retries):
        oh, ol, orows, counts, ok = run(args, capacity_factor)
        if bool(jnp.all(ok)):
            oh_h = np.asarray(oh).reshape(n_shards, -1)
            ol_h = np.asarray(ol).reshape(n_shards, -1)
            or_h = np.asarray(orows).reshape(n_shards, -1)
            cnt = np.asarray(counts).reshape(-1)
            out_keys = np.concatenate(
                [
                    (oh_h[i, : cnt[i]].astype(np.uint64) << np.uint64(32))
                    | ol_h[i, : cnt[i]].astype(np.uint64)
                    for i in range(n_shards)
                ]
            )
            out_rows = np.concatenate(
                [or_h[i, : cnt[i]] for i in range(n_shards)]
            ).astype(np.int64)
            return out_keys, out_rows
        capacity_factor *= 2.0
    order = np.argsort(keys_np, kind="stable")
    return keys_np[order], order


def sharded_coordinate_sort(
    keys_np: np.ndarray,
    mesh: Optional[Mesh] = None,
    axis: str = "shards",
    capacity_factor: float = 2.0,
    max_retries: int = 3,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host convenience wrapper: u64 keys → (sorted keys, permutation)
    over the flat 1-D mesh exchange (protocol in
    ``_keys_exchange_host_wrapper``)."""
    mesh = mesh or make_mesh()
    n_shards = mesh.shape[axis]

    def put(hi_p, lo_p, rows_p, s_hi, s_lo, per_shard):
        shard2d = NamedSharding(mesh, P(axis, None))
        repl = NamedSharding(mesh, P(None))
        return (
            jax.device_put(hi_p.reshape(n_shards, per_shard), shard2d),
            jax.device_put(lo_p.reshape(n_shards, per_shard), shard2d),
            jax.device_put(rows_p.reshape(n_shards, per_shard), shard2d),
            jax.device_put(s_hi, repl),
            jax.device_put(s_lo, repl),
        )

    def run(args, cf):
        return sharded_sort_step(*args, mesh=mesh, axis=axis,
                                 capacity_factor=cf)

    return _keys_exchange_host_wrapper(
        keys_np, n_shards, put, run, capacity_factor, max_retries)


# ---------------------------------------------------------------------------
# Hierarchical (DCN, ICI) exchange — the multi-host layering.


def _two_stage_exchange(
    arrs, fills, s_hi, s_lo, *, dcn_axis: str, ici_axis: str,
    n_hosts: int, per_host: int, cap1: int, cap2: int,
):
    """Two-stage exchange of every array in ``arrs`` (whose first two
    entries must be the hi/lo key columns; trailing dims ride along):
    stage 1 groups by destination HOST and exchanges over the DCN axis
    (each device talks to its same-ordinal peer on every other host —
    n_hosts-1 large messages instead of n_devices-1 small ones crossing
    the network); stage 2 groups by destination device within the host
    and exchanges over the ICI axis. Returns (exchanged arrays, ok)."""
    n_shards = n_hosts * per_host

    def stage(arrs, bucket, nb, cap, axis):
        sends, counts = _group_scatter(bucket, nb, cap, arrs, fills)
        ok = (counts <= cap).all()
        recv = [lax.all_to_all(s, axis, split_axis=0, concat_axis=0)
                for s in sends]
        return [r.reshape((-1,) + r.shape[2:]) for r in recv], ok

    hi, lo = arrs[0], arrs[1]
    valid = ~((hi == SENT32) & (lo == SENT32))
    dest = jnp.where(valid, _dest_shard(hi, lo, s_hi, s_lo), n_shards)
    dest_host = dest // per_host            # phantom -> n_hosts
    arrs1, ok1 = stage(arrs, dest_host, n_hosts, cap1, dcn_axis)

    hi1, lo1 = arrs1[0], arrs1[1]
    valid1 = ~((hi1 == SENT32) & (lo1 == SENT32))
    dest1 = jnp.where(valid1, _dest_shard(hi1, lo1, s_hi, s_lo), n_shards)
    my_host = lax.axis_index(dcn_axis)
    local = jnp.where(
        valid1, dest1 - my_host * per_host, per_host)  # phantom
    final_arrs, ok2 = stage(arrs1, local, per_host, cap2, ici_axis)
    # all-devices ok: reduce over both axes
    ok = lax.psum(
        lax.psum((~ok1 | ~ok2).astype(jnp.int32), dcn_axis), ici_axis) == 0
    return final_arrs, ok


def _shard_map():
    try:
        from jax import shard_map  # jax >= 0.6 location
    except ImportError:
        from jax.experimental.shard_map import shard_map
    return shard_map


def _hier_geometry(mesh, dcn_axis, ici_axis, per_shard, capacity_factor):
    """(n_hosts, per_host, cap1, cap2) — the single source of the
    two-stage capacity formulas for both step wrappers."""
    n_hosts = mesh.shape[dcn_axis]
    per_host = mesh.shape[ici_axis]
    cap1 = min(int(per_shard * capacity_factor / n_hosts) + 1, per_shard)
    cap2 = min(int(per_shard * capacity_factor / per_host) + 1,
               n_hosts * cap1)
    return n_hosts, per_host, cap1, cap2


def _finish_two_level(fh, fl, fr, ok, fv=None):
    """Final local order + validity count for a two-stage exchange.
    rows tie-break: the two-stage arrival order differs from the flat
    exchange's, so duplicate keys MUST be ordered by original index
    here or multi-host output would diverge from single-host output."""
    final = jnp.lexsort((fr, fl, fh))
    out_hi, out_lo, out_rows = fh[final], fl[final], fr[final]
    n_valid = jnp.sum(
        ~((out_hi == SENT32) & (out_lo == SENT32))).astype(jnp.int32)
    head = (out_hi[None, None], out_lo[None, None], out_rows[None, None])
    if fv is not None:
        head = head + (fv[final][None, None],)
    return head + (n_valid[None, None], ok[None, None])


def _sort_stage_2level(
    hi, lo, rows, s_hi, s_lo, *, dcn_axis: str, ici_axis: str,
    n_hosts: int, per_host: int, cap1: int, cap2: int,
):
    """Keys-only two-stage body under shard_map over a (dcn, shards)
    mesh (``runtime/multihost.global_mesh``). Device (h, j) ends up
    holding global range chunk h*per_host + j, so concatenation order
    matches the flat exchange."""
    (fh, fl, fr), ok = _two_stage_exchange(
        [hi.reshape(-1), lo.reshape(-1), rows.reshape(-1)],
        (SENT32, SENT32, 0), s_hi, s_lo,
        dcn_axis=dcn_axis, ici_axis=ici_axis,
        n_hosts=n_hosts, per_host=per_host, cap1=cap1, cap2=cap2)
    return _finish_two_level(fh, fl, fr, ok)


def _sort_stage_2level_payload(
    hi, lo, rows, vals, s_hi, s_lo, *, dcn_axis: str, ici_axis: str,
    n_hosts: int, per_host: int, cap1: int, cap2: int,
):
    """As ``_sort_stage_2level`` but the WHOLE record (fixed columns +
    padded ragged bytes) rides both stages of the exchange."""
    m = hi.reshape(-1).shape[0]
    (fh, fl, fr, fv), ok = _two_stage_exchange(
        [hi.reshape(-1), lo.reshape(-1), rows.reshape(-1),
         vals.reshape(m, -1)],
        (SENT32, SENT32, 0, 0), s_hi, s_lo,
        dcn_axis=dcn_axis, ici_axis=ici_axis,
        n_hosts=n_hosts, per_host=per_host, cap1=cap1, cap2=cap2)
    return _finish_two_level(fh, fl, fr, ok, fv)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "dcn_axis", "ici_axis", "capacity_factor"))
def hierarchical_sort_step(
    hi, lo, rows, s_hi, s_lo, *, mesh: Mesh,
    dcn_axis: str = "dcn", ici_axis: str = "shards",
    capacity_factor: float = 2.0,
):
    """One two-stage sort exchange over a (dcn, shards) mesh.

    Inputs (n_hosts, per_host, per_shard), sharded over both mesh axes
    on dims 0/1, sentinel-padded like ``sharded_sort_step``. Returns
    (hi, lo, rows, valid_counts, ok) with the same global-order
    concatenation contract as the flat exchange.
    """
    n_hosts, per_host, cap1, cap2 = _hier_geometry(
        mesh, dcn_axis, ici_axis, hi.shape[2], capacity_factor)
    body = functools.partial(
        _sort_stage_2level, dcn_axis=dcn_axis, ici_axis=ici_axis,
        n_hosts=n_hosts, per_host=per_host, cap1=cap1, cap2=cap2)
    return _shard_map()(
        body,
        mesh=mesh,
        in_specs=(
            P(dcn_axis, ici_axis, None), P(dcn_axis, ici_axis, None),
            P(dcn_axis, ici_axis, None), P(None), P(None),
        ),
        out_specs=(
            P(dcn_axis, ici_axis, None), P(dcn_axis, ici_axis, None),
            P(dcn_axis, ici_axis, None), P(dcn_axis, ici_axis),
            P(dcn_axis, ici_axis),
        ),
    )(hi, lo, rows, s_hi, s_lo)


@functools.partial(jax.jit, static_argnames=(
    "mesh", "dcn_axis", "ici_axis", "capacity_factor"))
def hierarchical_sort_payload_step(
    hi, lo, rows, vals, s_hi, s_lo, *, mesh: Mesh,
    dcn_axis: str = "dcn", ici_axis: str = "shards",
    capacity_factor: float = 2.0,
):
    """Two-stage exchange moving keys AND the (n_hosts, per_host,
    per_shard, W) u32 record payload — whole records cross DCN once in
    host-sized messages, then fan out over ICI."""
    n_hosts, per_host, cap1, cap2 = _hier_geometry(
        mesh, dcn_axis, ici_axis, hi.shape[2], capacity_factor)
    body = functools.partial(
        _sort_stage_2level_payload, dcn_axis=dcn_axis, ici_axis=ici_axis,
        n_hosts=n_hosts, per_host=per_host, cap1=cap1, cap2=cap2)
    return _shard_map()(
        body,
        mesh=mesh,
        in_specs=(
            P(dcn_axis, ici_axis, None), P(dcn_axis, ici_axis, None),
            P(dcn_axis, ici_axis, None),
            P(dcn_axis, ici_axis, None, None), P(None), P(None),
        ),
        out_specs=(
            P(dcn_axis, ici_axis, None), P(dcn_axis, ici_axis, None),
            P(dcn_axis, ici_axis, None),
            P(dcn_axis, ici_axis, None, None),
            P(dcn_axis, ici_axis), P(dcn_axis, ici_axis),
        ),
    )(hi, lo, rows, vals, s_hi, s_lo)


def hierarchical_coordinate_sort(
    keys_np: np.ndarray, mesh: Mesh,
    dcn_axis: str = "dcn", ici_axis: str = "shards",
    capacity_factor: float = 2.0, max_retries: int = 3,
) -> Tuple[np.ndarray, np.ndarray]:
    """u64 keys → (sorted keys, permutation) over a (dcn, shards) mesh
    (see ``runtime/multihost.global_mesh``). Same contract and retry
    protocol as ``sharded_coordinate_sort``; the exchange runs in two
    stages so inter-host traffic crosses DCN once, in host-sized
    messages, and the fan-out to devices rides ICI."""
    n_hosts = mesh.shape[dcn_axis]
    per_host = mesh.shape[ici_axis]
    n_shards = n_hosts * per_host

    def put(hi_p, lo_p, rows_p, s_hi, s_lo, per_shard):
        shard3d = NamedSharding(mesh, P(dcn_axis, ici_axis, None))
        repl = NamedSharding(mesh, P())
        shape3 = (n_hosts, per_host, per_shard)
        return (
            jax.device_put(hi_p.reshape(shape3), shard3d),
            jax.device_put(lo_p.reshape(shape3), shard3d),
            jax.device_put(rows_p.reshape(shape3), shard3d),
            jax.device_put(s_hi, repl),
            jax.device_put(s_lo, repl),
        )

    def run(args, cf):
        return hierarchical_sort_step(
            *args, mesh=mesh, dcn_axis=dcn_axis, ici_axis=ici_axis,
            capacity_factor=cf)

    return _keys_exchange_host_wrapper(
        keys_np, n_shards, put, run, capacity_factor, max_retries)


# ---------------------------------------------------------------------------
# Resident multi-chip sort (ROADMAP item 3 tentpole b): the coordinate
# sort consumed straight from a mesh-sharded ColumnarBatch — keys never
# exist on the host; splitters come from per-device key histograms
# exchanged via lax.psum (the SNIPPETS north-star "psum histogram
# exchange") instead of a host sample.


@functools.lru_cache(maxsize=16)
def _resident_keys_compiled(mesh: Mesh, axis: str, n_shards: int):
    """Key build over batch-sharded refid/pos columns: same formula as
    the single-device ``coord_perm`` (unmapped → 0x7FFFFFFF, bucket
    padding → full-sentinel pairs) plus global row ids, reshaped to the
    (n_shards, per) exchange layout with zero resharding."""
    def build(refid, pos, n):
        m = refid.shape[0]
        valid = jnp.arange(m, dtype=jnp.int32) < n
        rid = jnp.where(refid < 0, jnp.uint32(0x7FFFFFFF),
                        refid.astype(jnp.uint32))
        hi = jnp.where(valid, rid, SENT32)
        lo = jnp.where(valid, (pos + 1).astype(jnp.uint32), SENT32)
        rows = jnp.arange(m, dtype=jnp.uint32)
        shp = (n_shards, m // n_shards)
        return hi.reshape(shp), lo.reshape(shp), rows.reshape(shp)

    out_sh = NamedSharding(mesh, P(axis, None))
    return jax.jit(build, out_shardings=(out_sh, out_sh, out_sh))


def _key_byte(hi, lo, level: int):
    """Byte ``level`` (7 = most significant) of the (hi, lo) u64 key."""
    if level >= 4:
        return (hi >> jnp.uint32(8 * (level - 4))) & jnp.uint32(0xFF)
    return (lo >> jnp.uint32(8 * level)) & jnp.uint32(0xFF)


@functools.lru_cache(maxsize=64)
def _hist_level_compiled(mesh: Mesh, axis: str, n_cuts: int, level: int):
    """One refinement level of the psum-histogram splitter search:
    every device bins byte ``level`` of its LOCAL keys restricted to
    each cut's already-resolved prefix (levels above ``level``), then
    one ``lax.psum`` over the mesh axis makes the (n_cuts, 256)
    histogram global. Only that small table crosses d2h per level —
    the keys themselves never move."""
    def body(hi, lo, pref):
        hi, lo = hi.reshape(-1), lo.reshape(-1)
        valid = ~((hi == SENT32) & (lo == SENT32))
        tgt = _key_byte(hi, lo, level).astype(jnp.int32)
        rows = []
        for c in range(n_cuts):
            mask = valid
            for up in range(level + 1, 8):
                mask = mask & (
                    _key_byte(hi, lo, up).astype(jnp.int32) == pref[c, up])
            rows.append(jnp.bincount(
                jnp.where(mask, tgt, 256), length=257)[:256])
        hist = jnp.stack(rows).astype(jnp.int32)
        return lax.psum(hist, axis)

    return jax.jit(_shard_map()(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(None, None)),
        out_specs=P(None, None)))


def _psum_splitters(hi2, lo2, n: int, mesh: Mesh, axis: str,
                    n_shards: int) -> Tuple[np.ndarray, np.ndarray]:
    """Exact quantile splitters for the range partition, computed by
    MSB→LSB byte refinement over psum'd per-device histograms: level 7
    bins the top byte of every key; each cut picks the bin its target
    rank falls in, subtracts the mass below it, and descends — after 8
    levels the accumulated bytes ARE the key value at that rank.
    Monotone by construction (prefix order = key order), so the range
    partition stays valid; returns (s_hi, s_lo) u32 pairs."""
    from disq_tpu.runtime.tracing import count_transfer, counter

    n_cuts = n_shards - 1
    if n_cuts <= 0 or n == 0:
        z = np.zeros(max(n_cuts, 0), dtype=np.uint32)
        return z, z.copy()
    # 0-indexed target ranks among the n valid keys (value-at-quantile,
    # like sample_splitters' sample[qs])
    remaining = np.array(
        [max(0, ((c + 1) * n) // n_shards - 1) for c in range(n_cuts)],
        dtype=np.int64)
    pref = np.full((n_cuts, 8), -1, dtype=np.int32)
    repl = NamedSharding(mesh, P(None, None))
    for level in range(7, -1, -1):
        pref_dev = jax.device_put(jnp.asarray(pref), repl)
        hist = np.asarray(_hist_level_compiled(
            mesh, axis, n_cuts, level)(hi2, lo2, pref_dev))
        # the psum fans each device's (n_cuts, 257) partial over ICI;
        # the prefix table replicates h2d per device
        counter("device.mesh.exchange_bytes").inc(
            (hist.nbytes + 4 * n_cuts) * n_shards)
        count_transfer("h2d", pref.nbytes)
        count_transfer("d2h", hist.nbytes)
        cum = np.cumsum(hist, axis=1)
        for c in range(n_cuts):
            v = int(np.searchsorted(cum[c], remaining[c], side="right"))
            v = min(v, 255)
            pref[c, level] = v
            if v > 0:
                remaining[c] -= int(cum[c, v - 1])
    key = np.zeros(n_cuts, dtype=np.uint64)
    for level in range(8):
        key |= pref[:, level].astype(np.uint64) << np.uint64(8 * level)
    return split_u64_keys(key)


def resident_coordinate_sort(
    refid_dev, pos_dev, n: int, mesh: Mesh,
    axis: Optional[str] = None,
    capacity_factor: float = 2.0, max_retries: int = 3,
) -> np.ndarray:
    """Multi-chip coordinate sort of a RESIDENT batch-sharded column
    pair (tentpole b): key build, psum-histogram splitters, and the
    all_to_all range exchange all run on the mesh — the only d2h is
    the per-level histogram table and the final row-id permutation.

    Byte-identity contract: rows ride as the least-significant lexsort
    component, so duplicate coordinates keep original-index order and
    the returned permutation equals the host
    ``np.argsort(keys, kind="stable")`` exactly — sorted BAM + BAI
    built from it are byte-identical to the single-device output at
    any device count."""
    from disq_tpu.runtime.mesh import MESH_AXIS
    from disq_tpu.runtime.tracing import (
        count_transfer, counter, device_span)

    if axis is None:
        axis = MESH_AXIS if MESH_AXIS in mesh.axis_names \
            else mesh.axis_names[0]
    n_shards = int(mesh.shape[axis])
    m = int(refid_dev.shape[0])
    per_shard = m // n_shards
    # staged pre-guard with its mesh placement (4 bytes, replicated) —
    # an implicit reshard inside the guard would raise
    n_arr = jax.device_put(
        jnp.asarray(np.int32(n)), NamedSharding(mesh, P()))
    with device_span("device.kernel", kernel="mesh_sort_keys",
                     records=n, devices=n_shards) as fence:
        with jax.transfer_guard("disallow"):
            hi2, lo2, rows2 = _resident_keys_compiled(
                mesh, axis, n_shards)(refid_dev, pos_dev, n_arr)
            jax.block_until_ready(rows2)
        fence.sync(rows2)
    s_hi_np, s_lo_np = _psum_splitters(hi2, lo2, n, mesh, axis, n_shards)
    repl = NamedSharding(mesh, P(None))
    s_hi = jax.device_put(jnp.asarray(s_hi_np), repl)
    s_lo = jax.device_put(jnp.asarray(s_lo_np), repl)
    count_transfer("h2d", s_hi_np.nbytes + s_lo_np.nbytes)
    cf = capacity_factor
    for _ in range(max_retries):
        cap = min(int(per_shard * cf / n_shards) + 1, per_shard)
        with device_span("device.kernel", kernel="mesh_sort_exchange",
                         records=n, devices=n_shards) as fence:
            oh, ol, orows, counts, ok = sharded_sort_step(
                hi2, lo2, rows2, s_hi, s_lo,
                mesh=mesh, axis=axis, capacity_factor=cf)
            fence.sync(counts)
        # send buffers: 3 u32 arrays of (n_shards, cap) per device
        counter("device.mesh.exchange_bytes").inc(
            3 * 4 * cap * n_shards * n_shards)
        if bool(jnp.all(ok)):
            cnt = np.asarray(counts).reshape(-1)
            or_h = np.asarray(orows).reshape(n_shards, -1)
            count_transfer("d2h", cnt.nbytes + or_h.nbytes)
            return np.concatenate(
                [or_h[i, : cnt[i]] for i in range(n_shards)]
            ).astype(np.int64)
        cf *= 2.0
    # pathological skew defeated the capacity retries: fetch the key
    # columns once and finish on host (counted — this is the documented
    # fallback, not an implicit copy)
    hi_h = np.asarray(hi2).reshape(-1)[:n]
    lo_h = np.asarray(lo2).reshape(-1)[:n]
    count_transfer("d2h", hi_h.nbytes + lo_h.nbytes)
    keys = (hi_h.astype(np.uint64) << np.uint64(32)) | \
        lo_h.astype(np.uint64)
    return np.argsort(keys, kind="stable")
