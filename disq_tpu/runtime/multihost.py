"""Multi-host execution scaffold — the DCN/ICI story (SURVEY.md §5).

disq scales by adding Spark executors over the network; the TPU-native
equivalent is multi-process jax: one process per host, every process
sees the global device set, and collectives route over ICI within a
slice and DCN across slices. This module wraps the two pieces the rest
of the framework needs:

- ``initialize(...)`` — ``jax.distributed.initialize`` with the
  coordinator bootstrap (the Spark-driver analogue; no-op when
  single-process).
- ``global_mesh(...)`` — a mesh over ALL processes' devices with the
  host boundary as the leading ``dcn`` axis and per-host devices on the
  ``shards`` axis, so the sort exchange's ``all_to_all`` rides ICI and
  only inter-host reductions cross DCN (the scaling-book layering).

No multi-host hardware exists in this environment; the axis-planning
arithmetic is pure and unit-tested, the single-process path degrades to
the ordinary local mesh, and the 8-virtual-device suite exercises the
resulting meshes end to end.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def plan_axes(n_devices_total: int, n_processes: int) -> Tuple[int, int]:
    """(dcn, shards) axis sizes: hosts on the outer (DCN) axis, the
    per-host device count on the inner (ICI) axis."""
    if n_processes <= 0:
        raise ValueError("n_processes must be positive")
    if n_devices_total <= 0:
        raise ValueError("n_devices_total must be positive")
    if n_devices_total % n_processes:
        raise ValueError(
            f"{n_devices_total} devices do not split over "
            f"{n_processes} processes")
    return n_processes, n_devices_total // n_processes


def process_id() -> int:
    """This process's id in the multi-process job — the label every
    introspection endpoint stamps on its output so a cluster
    aggregation can tell N workers' metrics apart.

    Resolution order: ``DISQ_TPU_PROCESS_ID`` (explicit override —
    also how CPU-only subprocess tests and non-jax launchers assign
    distinct ids; negative values are rejected and fall through, the
    way ``process_count`` clamps to ≥ 1 — a negative id would corrupt
    cluster labeling and the aggregator's unique-id fallback), then
    ``jax.process_index()``, then 0."""
    raw = os.environ.get("DISQ_TPU_PROCESS_ID")
    if raw is not None and raw != "":
        try:
            value = int(raw)
            if value >= 0:
                return value
        except ValueError:
            pass
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — host-only deployments
        return 0


def process_count() -> int:
    """Total processes in the job (``DISQ_TPU_PROCESS_COUNT`` override,
    else ``jax.process_count()``, else 1)."""
    raw = os.environ.get("DISQ_TPU_PROCESS_COUNT")
    if raw is not None and raw != "":
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    try:
        import jax

        return int(jax.process_count())
    except Exception:  # noqa: BLE001
        return 1


def initialize(coordinator_address: Optional[str] = None,
               num_processes: int = 1,
               process_id: int = 0) -> None:
    """Bootstrap multi-process jax (no-op for a single process).

    ``coordinator_address`` is ``host:port`` of process 0 — the same
    rendezvous role the Spark driver plays for executors.
    """
    if num_processes <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(dcn_axis: str = "dcn", ici_axis: str = "shards"):
    """Mesh over every device of every process: (n_hosts, per_host),
    DCN-boundary outer, ICI inner. Single-process: (1, n_local)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n_proc = jax.process_count()
    dcn, per_host = plan_axes(len(devs), n_proc)
    arr = np.empty((dcn, per_host), dtype=object)
    for d, ordinal in _local_ordinals(devs).items():
        # jax orders devices by (process_index, local ordinal); place
        # explicitly so the DCN axis is exactly the host boundary
        arr[d.process_index, ordinal] = d
    return Mesh(arr, (dcn_axis, ici_axis))


def _local_ordinals(devs) -> dict:
    """``{device: local ordinal}`` for every device, computed in ONE
    pass — one sort per process group instead of the old per-device
    re-sort (O(n²·log n) across a large mesh, where n is the global
    device count)."""
    by_proc: dict = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    ordinals: dict = {}
    for same in by_proc.values():
        for i, d in enumerate(sorted(same, key=lambda d: d.id)):
            ordinals[d] = i
    return ordinals
