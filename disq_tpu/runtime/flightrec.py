"""Flight recorder — postmortem bundles for runs that die.

``runtime/tracing.py`` answers "what did this run cost" and
``runtime/introspect.py`` answers "is it making progress right now" —
but both live in the process: when a run aborts (a pipeline
first-error-abort, a ``WatchdogStallError``, a breaker storm, SIGKILL,
a segfault in ``native/``) every metric, span and heartbeat evaporates
with it, and the disq heritage this repo reproduces was precisely that
a *failed* cluster run stayed diagnosable after the fact.  This module
is that postmortem half:

- **Event ring** (:class:`FlightRecorder`): a bounded, lock-cheap ring
  of recent *events* — error classifications, retry escalations, hedge
  launches, deadline expiries, breaker transitions, watchdog stalls,
  device-service flushes, quarantines, scheduler control-plane
  transitions (membership joins/losses, lease expiries, steals, and
  the failover ladder: ``sched_coordinator_lost`` →
  ``sched_rediscovered`` / ``sched_takeover`` → ``sched_rejoin``) —
  fed by one-line ``record_event(kind, ...)`` hooks in ``errors.py``,
  ``resilience.py``, ``executor.py``, ``device_service.py``,
  ``scheduler.py`` and ``introspect.py``.  Spans sample *durations*;
  the event ring keeps the *decisions* (why did shard 7 get hedged,
  when did the breaker open, who won the standby election) that
  explain an abort.
- **Postmortem bundles**: on any abort path (the pipelines'
  first-error-abort, a watchdog abort, a ``BreakerOpenError`` storm,
  or an explicit :func:`dump`) a bundle directory is written under
  ``DisqOptions.postmortem_dir`` / ``DISQ_TPU_POSTMORTEM_DIR``:
  all-thread stacks (``sys._current_frames``), the Prometheus metrics
  snapshot, the span-ring tail, the event ring, ``/healthz`` +
  ``/progress`` JSON, tails of every quarantine / stage-manifest /
  read-ledger file the run touched, and the resolved options + env +
  ``RUN_ID``.  ``scripts/trace_report.py --postmortem <bundle>``
  renders it into a one-page verdict.
- **Native-crash wiring**: enabling the recorder also points
  ``faulthandler`` at ``crash-<pid>.log`` inside the postmortem dir,
  so a segfault in ``disq_tpu/native`` leaves Python tracebacks
  instead of dying silently.

Zero overhead when disabled (the default): no recorder object exists,
``record_event`` / ``note_artifact`` / ``note_abort`` return after one
global-is-None test, no ring is allocated and no file is opened —
enforced by ``scripts/check_overhead.py``.
"""

from __future__ import annotations

import faulthandler
import json
import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from disq_tpu.runtime.tracing import REGISTRY, RUN_ID, current_trace

DEFAULT_RING = 4096       # events kept; overflow drops the oldest
LEDGER_TAIL_BYTES = 65536  # per noted ledger file in a bundle
SPAN_TAIL = 2048          # span-ring tail lines in a bundle
MAX_BUNDLES = 16          # per-process cap: an abort storm must not
                          # fill the disk with identical bundles

_LOCK = threading.RLock()
_RECORDER: Optional["FlightRecorder"] = None
_env_resolved = False


def thread_stacks_text() -> str:
    """Every live thread's current Python stack, named — the same text
    the ``/debug/stacks`` endpoint serves and every bundle embeds.
    Thread names matter here: the pipelines name their workers
    ``disq-<stage>``, so a hung stack is stage-attributed at sight."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = [
        f"pid {os.getpid()} run {RUN_ID} "
        f"threads {len(names)} at {time.time():.3f}",
        "",
    ]
    for tid, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        out.extend(
            line.rstrip("\n")
            for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


class FlightRecorder:
    """Bounded event ring + bundle writer (see module docstring).

    Mutators are dict/deque appends under one lock; the ring holds
    plain dicts so a dump is a JSON walk, never a pickle."""

    def __init__(self, postmortem_dir: str,
                 capacity: int = DEFAULT_RING) -> None:
        self.postmortem_dir = postmortem_dir
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(16, int(capacity)))
        # path -> short name: ledger files whose tails belong in a
        # bundle (quarantine manifest, stage manifest, read ledger).
        self._artifacts: Dict[str, str] = {}
        self._options: Dict[str, Any] = {}
        self._bundles: List[str] = []
        # Aborts dedupe by exception identity: the same error object
        # can surface from both a stage worker, the emit frontier and
        # the api-level backstop.  Strong references compared by
        # identity (BaseException has no __weakref__, so a WeakSet is
        # not an option; bare id()s would falsely match a recycled
        # address).  maxlen stays SMALL: dedupe only needs to span one
        # abort's double-fire window, and every held exception pins
        # its traceback (frames whose locals hold shard buffers).
        self._aborted: "deque[BaseException]" = deque(maxlen=8)
        self._crash_log = None

    # -- feeding ------------------------------------------------------------

    def record(self, kind: str, /, **fields: Any) -> None:
        # ``kind`` is positional-only so hooks can carry a ``kind=``
        # *field* (e.g. the corrupt-block kind) without colliding.
        rec = {"ts": round(time.time(), 6),
               "mono": round(time.perf_counter(), 6),
               "kind": kind}
        rec.update(fields)
        rec["kind"] = kind  # the event kind always wins the key
        ctx = current_trace()
        if ctx is not None:
            # request-scoped causality: events recorded under an active
            # trace context join that request's stitched timeline
            rec.setdefault("trace", ctx.trace_id)
            rec.setdefault("tenant", ctx.tenant)
        with self._lock:
            self._ring.append(rec)
        REGISTRY.counter("flightrec.events").inc(kind=kind)

    def note_artifact(self, name: str, path: str) -> None:
        with self._lock:
            self._artifacts.setdefault(path, name)

    def set_options(self, opts: Any) -> None:
        """Remember the resolved options of the most recent run that
        configured this recorder (dumped into ``options.json``)."""
        import dataclasses

        try:
            doc = dataclasses.asdict(opts)
        except TypeError:
            doc = {"repr": repr(opts)}
        with self._lock:
            self._options = doc

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    # -- abort / dump -------------------------------------------------------

    def abort(self, exc: BaseException, where: str = "") -> Optional[str]:
        """The abort chokepoint: record one ``abort`` event and write a
        bundle (once per distinct exception object)."""
        with self._lock:
            if any(seen is exc for seen in self._aborted):
                return None
            self._aborted.append(exc)
        reason = _abort_reason(exc)
        self.record(
            "abort", reason=reason, where=where,
            error=f"{type(exc).__name__}: {exc}",
            shard=getattr(exc, "shard_id", None),
            stage=getattr(exc, "stage", None))
        return self.dump(reason, exc=exc)

    def dump(self, reason: str = "explicit",
             exc: Optional[BaseException] = None) -> Optional[str]:
        """Write one bundle directory and return its path (None once
        the per-process bundle cap is reached).  Every artifact write
        is individually best-effort: a failing subsystem (a torn
        ledger, a dead health board) must never cost the bundle the
        artifacts that *did* survive."""
        with self._lock:
            if len(self._bundles) >= MAX_BUNDLES:
                return None
            seq = len(self._bundles)
            bundle = os.path.join(
                self.postmortem_dir, f"bundle-{RUN_ID}-{seq:02d}")
            self._bundles.append(bundle)
        try:
            os.makedirs(bundle, exist_ok=True)
        except OSError:
            # An unwritable/full postmortem dir must never mask the
            # abort that brought us here — the dump is best-effort
            # end to end, not just per artifact.
            return None
        artifacts: List[str] = []

        def put(name: str, render) -> None:
            try:
                body = render()
                if body is None:
                    return
                if isinstance(body, str):
                    body = body.encode()
                with open(os.path.join(bundle, name), "wb") as f:
                    f.write(body)
                artifacts.append(name)
            except Exception:  # noqa: BLE001 — best-effort per artifact
                pass

        put("stacks.txt", thread_stacks_text)
        put("metrics.prom", self._render_metrics)
        put("spans.jsonl", self._render_spans)
        put("events.jsonl", self._render_events)
        put("healthz.json", lambda: self._render_introspect("healthz"))
        put("progress.json", lambda: self._render_introspect("progress"))
        put("options.json", lambda: self._render_options(reason, exc))
        put("profile.collapsed", self._render_profile)
        with self._lock:
            ledgers = dict(self._artifacts)
        for i, (path, name) in enumerate(sorted(ledgers.items())):
            put(f"ledger-{name}-{i:02d}.tail",
                lambda p=path: _file_tail(p))
        put("MANIFEST.json", lambda: json.dumps({
            "run_id": RUN_ID, "pid": os.getpid(), "reason": reason,
            "epoch": round(time.time(), 6),
            "error": (f"{type(exc).__name__}: {exc}"
                      if exc is not None else None),
            "artifacts": sorted(artifacts),
            "ledgers": {name: path for path, name in ledgers.items()},
        }, indent=2, default=str))
        REGISTRY.counter("flightrec.dumps").inc(reason=reason)
        return bundle

    # -- bundle artifact renderers ------------------------------------------

    @staticmethod
    def _render_metrics() -> str:
        from disq_tpu.runtime import tracing

        return tracing.metrics_text()

    @staticmethod
    def _render_spans() -> str:
        from disq_tpu.runtime import tracing

        ring = tracing.spans()[-SPAN_TAIL:]
        return "".join(
            json.dumps(s, default=str) + "\n" for s in ring)

    def _render_events(self) -> str:
        return "".join(
            json.dumps(e, default=str) + "\n" for e in self.events())

    @staticmethod
    def _render_introspect(view: str) -> str:
        from disq_tpu.runtime.introspect import HEALTH

        doc = getattr(HEALTH, view)()
        return json.dumps(doc, default=str)

    def _render_options(self, reason: str,
                        exc: Optional[BaseException]) -> str:
        with self._lock:
            opts = dict(self._options)
        return json.dumps({
            "run_id": RUN_ID,
            "pid": os.getpid(),
            "reason": reason,
            "error": (f"{type(exc).__name__}: {exc}"
                      if exc is not None else None),
            "options": opts,
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith(("DISQ_TPU_", "JAX_PLATFORMS"))},
        }, indent=2, default=str)

    @staticmethod
    def _render_profile() -> Optional[str]:
        from disq_tpu.runtime import profiler

        active = profiler.active_profiler()
        if active is None or not active.samples:
            return None
        return active.collapsed()

    # -- native-crash wiring -------------------------------------------------

    def wire_faulthandler(self) -> None:
        """Point ``faulthandler`` at a crash log inside the postmortem
        dir so a native segfault (``disq_tpu/native``) leaves Python
        tracebacks next to the bundles instead of dying silently."""
        if self._crash_log is not None:
            return
        try:
            os.makedirs(self.postmortem_dir, exist_ok=True)
            self._crash_log = open(
                os.path.join(self.postmortem_dir,
                             f"crash-{os.getpid()}.log"), "a")
            faulthandler.enable(file=self._crash_log)
        except OSError:
            # An unwritable postmortem dir must not fail the run that
            # merely configured it; the event ring still works.
            self._crash_log = None


def _abort_reason(exc: BaseException) -> str:
    # Local name check instead of an import: errors.py imports this
    # module, so classifying by type identity would be a cycle.
    name = type(exc).__name__
    if name == "WatchdogStallError":
        return "watchdog_abort"
    if name == "BreakerOpenError":
        return "breaker_open"
    if name == "DeadlineExceededError":
        return "deadline"
    return "pipeline_abort"


def _file_tail(path: str, nbytes: int = LEDGER_TAIL_BYTES) -> bytes:
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - nbytes))
        return f.read()


# ---------------------------------------------------------------------------
# Module-level hooks — the only surface the hot paths touch
# ---------------------------------------------------------------------------


def enable(postmortem_dir: str,
           capacity: int = DEFAULT_RING) -> FlightRecorder:
    """Turn the flight recorder on (idempotent for an unchanged dir);
    also wires ``faulthandler`` into the dir for native crashes.  A
    dir change re-points the recorder, carrying the live event ring /
    artifacts along and closing the old crash log (no fd leak, no
    silently emptied ``events.jsonl`` right after the switch)."""
    global _RECORDER
    with _LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder(postmortem_dir, capacity)
        elif _RECORDER.postmortem_dir != postmortem_dir:
            old = _RECORDER
            fresh = FlightRecorder(postmortem_dir, capacity)
            with old._lock:
                fresh._ring.extend(old._ring)
                fresh._artifacts.update(old._artifacts)
                fresh._options = dict(old._options)
                if old._crash_log is not None:
                    faulthandler.disable()
                    old._crash_log.close()
                    old._crash_log = None
            _RECORDER = fresh
        _RECORDER.wire_faulthandler()
        return _RECORDER


def enabled() -> bool:
    return _RECORDER is not None


def recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def record_event(kind: str, /, **fields: Any) -> None:
    """The one-line hook every subsystem calls: free (one global read)
    when the recorder is off."""
    rec = _RECORDER
    if rec is None:
        return
    rec.record(kind, **fields)


def note_artifact(name: str, path: str) -> None:
    rec = _RECORDER
    if rec is None:
        return
    rec.note_artifact(name, path)


def note_abort(exc: BaseException, where: str = "") -> None:
    """Abort-path hook (pipeline first-error-abort, inline stage
    raise): records the abort and writes a bundle when enabled.  Never
    raises — a failing dump on the abort path would mask ``exc``, the
    very error the caller is about to surface."""
    rec = _RECORDER
    if rec is None:
        return
    try:
        rec.abort(exc, where=where)
    except Exception:  # noqa: BLE001 — postmortem is best-effort
        pass


def dump(reason: str = "explicit",
         exc: Optional[BaseException] = None) -> Optional[str]:
    """Explicitly write a bundle now; None when disabled."""
    rec = _RECORDER
    if rec is None:
        return None
    return rec.dump(reason, exc=exc)


def _resolve_env() -> None:
    global _env_resolved
    if _env_resolved:
        return
    with _LOCK:
        if _env_resolved:
            return
        _env_resolved = True
        path = os.environ.get("DISQ_TPU_POSTMORTEM_DIR")
    if path:
        enable(path)


def configure_from_options(opts) -> None:
    """Resolve one ``DisqOptions``' postmortem knob (and the env knob,
    once).  The default path — no knob, no env — changes nothing and
    allocates nothing."""
    _resolve_env()
    d = getattr(opts, "postmortem_dir", None) if opts is not None else None
    if d:
        enable(d).set_options(opts)
    elif _RECORDER is not None and opts is not None:
        _RECORDER.set_options(opts)


def reset_flightrec() -> None:
    """Test hook: drop the recorder and re-allow env resolution
    (``faulthandler`` is disabled again so a later test owns it)."""
    global _RECORDER, _env_resolved
    with _LOCK:
        if _RECORDER is not None and _RECORDER._crash_log is not None:
            faulthandler.disable()
            _RECORDER._crash_log.close()
        _RECORDER = None
        _env_resolved = False
