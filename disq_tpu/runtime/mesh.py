"""Batch-axis device mesh for the mesh-native resident pipeline.

ROADMAP item 3 / SNIPPETS.md [1]: one sharded decode→sort→reduce
program across all local chips instead of N independent single-device
lanes.  This module owns the ONE mesh the process ever builds — a 1-D
``Mesh(devices[:n], ("batch",))`` — plus the ``NamedSharding`` helpers
every mesh-aware stage shares, so the sharding vocabulary cannot drift
between the parse (`runtime/device_pipeline.py`), the columnar currency
(`runtime/columnar.py`), the multi-chip sort (`sort/sharded.py`) and
the psum reductions (`ops/flagstat.py`, `ops/depth.py`).

Zero-overhead-when-off contract (scripts/check_overhead.py section 1d):
with no knob set — ``DisqOptions.mesh is None`` and ``DISQ_TPU_MESH``
unset — nothing here touches jax: no mesh object is built
(``mesh_if_built() is None``), no resharding happens, and every caller
takes the identical single-device dispatch it took before this module
existed.  A knob that resolves to <= 1 usable device (a 1-chip host,
``mesh=1``, or ``DISQ_TPU_MESH=1``) is the same OFF path: callers get
``None`` back and never branch onto mesh code.

Knob semantics (README "Mesh-native pipeline"):

- ``DisqOptions.mesh``: ``None`` = off; ``0`` = all local devices;
  ``n >= 1`` = the first ``n`` local devices.  Builders:
  ``DisqOptions.with_mesh`` / ``ReadsStorage.mesh`` /
  ``VariantsStorage.mesh``.
- ``DISQ_TPU_MESH`` env: unset/""/"0"/"off" = off; ``all``/``auto`` =
  all local devices; an integer = that many devices.
- Device counts round DOWN to a power of two (2/4/8/...): the batch
  axis shards power-of-two-bucketed compile shapes
  (``util.bucket_pow2``), so a pow2 axis always divides them evenly.
- Absent devices: asking for more devices than exist clamps to what is
  present (an 8-way knob on a 4-chip host runs 4-wide); a host left
  with one device runs the plain single-device pipeline — the knob is
  a capacity hint, never a hard failure.
"""

from __future__ import annotations

import os
import threading
from typing import Any, List, Optional

MESH_AXIS = "batch"

_MESH_CACHE: dict = {}
_MESH_LOCK = threading.Lock()


def _env_devices() -> Optional[int]:
    """``DISQ_TPU_MESH`` → requested device count (0 = all), or None
    when the env knob is off."""
    raw = os.environ.get("DISQ_TPU_MESH", "").strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return None
    if raw in ("all", "auto", "true", "on", "yes"):
        return 0
    try:
        n = int(raw)
    except ValueError:
        return 0
    return n if n > 0 else None


def mesh_devices_requested(storage: Any = None) -> Optional[int]:
    """Resolve the knob without touching jax: ``DisqOptions.mesh``
    first, then ``DISQ_TPU_MESH``; None means off."""
    opts = getattr(storage, "_options", None) if storage is not None \
        else None
    n = getattr(opts, "mesh", None) if opts is not None else None
    if n is not None:
        return int(n)
    return _env_devices()


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def get_mesh(requested: int = 0):
    """The cached batch-axis mesh over the first ``requested`` local
    devices (0 = all), rounded DOWN to a power of two; ``None`` when
    that resolves to a single device (the off path).  Only this
    function ever constructs a Mesh — ``mesh_if_built`` is the
    overhead guard's witness that the off path built nothing."""
    import jax

    devs = jax.devices()
    n = len(devs) if requested <= 0 else min(requested, len(devs))
    n = _pow2_floor(max(1, n))
    if n <= 1:
        return None
    with _MESH_LOCK:
        mesh = _MESH_CACHE.get(n)
        if mesh is None:
            import numpy as np
            from jax.sharding import Mesh

            mesh = Mesh(np.array(devs[:n]), (MESH_AXIS,))
            _MESH_CACHE[n] = mesh
            from disq_tpu.runtime.tracing import observe_gauge

            observe_gauge("device.mesh.devices", float(n))
    return mesh


def mesh_if_built():
    """The largest mesh this process has built, or None — the
    check_overhead witness that mesh-off allocated nothing."""
    with _MESH_LOCK:
        if not _MESH_CACHE:
            return None
        return _MESH_CACHE[max(_MESH_CACHE)]


def mesh_for_storage(storage: Any):
    """Storage-scoped entry: the batch mesh when the knob is armed and
    more than one device is usable, else None.  Cheap when off — two
    attribute reads and one env lookup, no jax import."""
    req = mesh_devices_requested(storage)
    if req is None:
        return None
    return get_mesh(req)


def shard_count(mesh) -> int:
    return int(mesh.shape[MESH_AXIS])


def batch_sharding(mesh):
    """NamedSharding splitting axis 0 over the batch axis (SNIPPETS.md
    [1]: shard dim 0 when it divides, which bucketed shapes always
    do)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(MESH_AXIS))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def mesh_put(x, mesh, batch: bool = True):
    """Normalize an array onto the mesh (batch-sharded or replicated),
    booking moved bytes into ``device.mesh.reshard_bytes`` when the
    placement actually changes.  Already-conforming arrays pass through
    untouched — the permute/concat hot path pays one sharding
    comparison, not a copy."""
    import jax

    sh = batch_sharding(mesh) if batch else replicated(mesh)
    cur = getattr(x, "sharding", None)
    try:
        if cur is not None and cur.is_equivalent_to(sh, x.ndim):
            return x
    except Exception:  # noqa: BLE001 — unequal mesh shapes compare False
        pass
    from disq_tpu.runtime.tracing import counter

    nbytes = int(x.size) * x.dtype.itemsize
    if not batch:
        # replication fans the buffer out to every device
        nbytes *= shard_count(mesh)
    counter("device.mesh.reshard_bytes").inc(nbytes)
    return jax.device_put(x, sh)


def service_devices() -> List[Any]:
    """Dispatch targets for the device decode service: the mesh's
    devices when the knob is armed at service start, else ``[None]``
    (= default-device semantics, byte-identical to the pre-mesh
    service).  Snapshotted once at service creation."""
    req = _env_devices()
    mesh = get_mesh(req) if req is not None else mesh_if_built()
    if mesh is None:
        return [None]
    return list(mesh.devices.flat)
