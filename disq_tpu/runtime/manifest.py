"""Stage manifest — deterministic, restartable phase plan.

The reference gets fault tolerance for free from Spark (task retry +
lineage re-execution, SURVEY.md §5) and adds an idempotent write
protocol: parts staged to a temp dir, driver merge as the commit point.
disq_tpu keeps the commit protocol and replaces Spark's retry with a
*stage manifest*: a JSON file on disk recording, per named stage, which
shards have completed and any small result payload (part path, length,
counters). A restarted run re-executes only the missing shards; the
commit step runs once all shards of the final stage are present.

The manifest is written atomically (tmp file + rename) after every
shard completion, so a crash at any point leaves a consistent file.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

FORMAT_VERSION = 1


class StageManifest:
    """Shard-level checkpoint ledger for a multi-stage pipeline run.

    Keyed by ``(stage, shard_id)``. The optional ``params`` fingerprint
    guards against resuming with different inputs: if the stored
    fingerprint differs from the current one, the manifest is reset.

    Durability model: every flush writes the whole document atomically
    (tmp + fsync + rename), so a crash at any point leaves a consistent
    file. ``flush_interval_s`` batches flushes: 0 (the default) flushes
    on every ``mark_done`` — the historical behavior; a positive
    interval (armed by the write-leasing path, which completes shards
    at RPC rate) defers the rewrite+fsync so at most one disk round
    trip per interval happens, at the cost of a bounded durability
    window — a SIGKILL can lose at most the last ``flush_interval_s``
    of completion records, whose staged parts simply re-run
    idempotently on resume.
    """

    def __init__(self, path: str, params: Optional[Dict[str, Any]] = None,
                 flush_interval_s: float = 0.0):
        from disq_tpu.runtime import flightrec
        from disq_tpu.runtime.tracing import RUN_ID

        self.path = path
        self.flush_interval_s = float(flush_interval_s)
        # Postmortem join: a bundle embeds this ledger's tail, so an
        # aborted run's "which shards were done" survives the process.
        flightrec.note_artifact("stage_manifest", path)
        # The parallel write pipeline records shard completion from its
        # stage workers as each shard's part lands — mark_done (ledger
        # mutation + atomic flush) must not interleave across threads.
        self._lock = threading.RLock()
        self._dirty = False
        self._last_flush = 0.0
        # Shared mode (write leasing): several processes mark shards
        # into one manifest file; each flush then merges the on-disk
        # document first so a whole-file rewrite cannot drop another
        # host's completions.
        self._shared = False
        self._state: Dict[str, Any] = {
            "version": FORMAT_VERSION,
            "params": params or {},
            "stages": {},
            # Telemetry join key: the run that created this manifest.
            # Per-shard completions additionally record the run that
            # marked them (a resumed manifest mixes runs), so the
            # resume ledger joins span/progress JSONL on run_id.
            "run_id": RUN_ID,
        }
        if os.path.exists(path):
            try:
                with open(path, "r") as f:
                    stored = json.load(f)
            except (json.JSONDecodeError, OSError, UnicodeDecodeError):
                # A damaged manifest must not block recovery — treat it
                # exactly like an incompatible one: start fresh.
                stored = {}
            if stored.get("version") != FORMAT_VERSION or (
                params is not None and stored.get("params") != params
            ):
                # Incompatible resume: start fresh (old manifest is
                # replaced on the next _flush).
                pass
            else:
                self._state = stored

    # -- persistence -----------------------------------------------------

    def _merge_stored_locked(self) -> None:
        """Fold on-disk shard records this object doesn't have into
        ``_state`` (another process appended them). Caller holds the
        lock. Incompatible/damaged documents are ignored — the next
        flush replaces them, exactly like the constructor's reset."""
        try:
            with open(self.path, "r") as f:
                stored = json.load(f)
        except (json.JSONDecodeError, OSError, UnicodeDecodeError):
            return
        if (not isinstance(stored, dict)
                or stored.get("version") != FORMAT_VERSION
                or stored.get("params") != self._state["params"]):
            return
        for stage, st in (stored.get("stages") or {}).items():
            mine = self._stage(stage)
            for sid, info in (st.get("shards") or {}).items():
                mine["shards"].setdefault(sid, info)
            for sid, rid in (st.get("runs") or {}).items():
                mine.setdefault("runs", {}).setdefault(sid, rid)

    def _flush(self) -> None:
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        if self._shared:
            # Cross-process read-merge-rewrite must be atomic as a
            # UNIT, not just the rename: two hosts interleaving their
            # merges would lose the slower one's shards.
            import fcntl

            with open(self.path + ".lock", "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    self._merge_stored_locked()
                    self._rewrite_locked(d)
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)
        else:
            self._rewrite_locked(d)
        self._dirty = False
        self._last_flush = time.monotonic()

    def _rewrite_locked(self, d: str) -> None:
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".manifest-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._state, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def flush(self) -> None:
        """Force any batched completion records to disk now."""
        with self._lock:
            if self._dirty:
                self._flush()

    def mark_shared(self, flush_interval_s: Optional[float] = None) -> None:
        """Arm shared-manifest mode (several processes marking into one
        file — the write-leasing durable side): flushes merge the
        on-disk document first, and ``flush_interval_s`` (when given)
        batches the rewrite+fsync behind that interval."""
        with self._lock:
            self._shared = True
            if flush_interval_s is not None:
                self.flush_interval_s = float(flush_interval_s)

    def reload(self) -> None:
        """Pick up shard records other processes flushed since we last
        read the file (shared write leasing: the per-shard infos of
        shards another host staged live only on disk)."""
        with self._lock:
            self._merge_stored_locked()

    # -- shard ledger ----------------------------------------------------

    def _stage(self, stage: str) -> Dict[str, Any]:
        return self._state["stages"].setdefault(stage, {"shards": {}})

    def is_done(self, stage: str, shard_id: int) -> bool:
        with self._lock:
            return str(shard_id) in self._stage(stage)["shards"]

    def shard_info(self, stage: str, shard_id: int) -> Any:
        with self._lock:
            return self._stage(stage)["shards"][str(shard_id)]

    def mark_done(self, stage: str, shard_id: int, info: Any = None) -> None:
        from disq_tpu.runtime.tracing import RUN_ID

        with self._lock:
            st = self._stage(stage)
            st["shards"][str(shard_id)] = info
            # Which run completed this shard (keyed beside "shards" so
            # shard_info() keeps returning the caller's payload
            # verbatim; resumed manifests mix run ids here).
            st.setdefault("runs", {})[str(shard_id)] = RUN_ID
            self._dirty = True
            if (self.flush_interval_s <= 0.0
                    or time.monotonic() - self._last_flush
                    >= self.flush_interval_s):
                self._flush()

    def shard_run_id(self, stage: str, shard_id: int) -> Optional[str]:
        """The ``run_id`` that marked this shard done (None for shards
        recorded by a pre-run_id manifest)."""
        with self._lock:
            return self._stage(stage).get("runs", {}).get(str(shard_id))

    def completed_shards(self, stage: str) -> List[int]:
        with self._lock:
            return sorted(int(k) for k in self._stage(stage)["shards"])

    # -- stage execution -------------------------------------------------

    def run_stage(
        self,
        stage: str,
        n_shards: int,
        fn: Callable[[int], Any],
        retries: int = 1,
    ) -> List[Any]:
        """Run ``fn(shard_id)`` for every shard not already recorded as
        done, retrying each failed shard up to ``retries`` extra times
        (the analogue of Spark task retry). Returns the per-shard info
        list in shard order, mixing cached and fresh results.

        ``fn``'s return value must be JSON-serializable (it is stored in
        the manifest and returned verbatim on resume).
        """
        out: List[Any] = [None] * n_shards
        for k in range(n_shards):
            if self.is_done(stage, k):
                out[k] = self.shard_info(stage, k)
                continue
            last: Optional[BaseException] = None
            for _attempt in range(retries + 1):
                try:
                    info = fn(k)
                    last = None
                    break
                except Exception as e:  # noqa: BLE001 — shard-level retry
                    last = e
            if last is not None:
                raise RuntimeError(
                    f"stage {stage!r} shard {k} failed after "
                    f"{retries + 1} attempts"
                ) from last
            self.mark_done(stage, k, info)
            out[k] = info
        return out

    def finish(self) -> None:
        """Commit point reached: remove the manifest (the staged parts'
        directory is cleaned separately by the caller)."""
        if os.path.exists(self.path):
            os.unlink(self.path)


class ReadLedger:
    """Crash-resumable *read* checkpointing — the read-side
    generalization of ``StageManifest``'s write resume.

    A write stage's shard result is naturally durable (the staged part
    file); a read shard's result is an in-memory decoded value, so the
    ledger spills it: as each shard emits from the ordered pipeline,
    its decoded value is pickled to ``shard-<k>.pkl`` (atomic tmp +
    rename) and the shard is marked done in an embedded
    ``StageManifest``.  A killed process restarted with the same ledger
    directory loads finished shards from their spills and re-runs only
    the unfinished ones (``runtime/executor.py:map_ordered_resumable``).

    ``params`` fingerprints the input (path, shard count, options that
    change decoded bytes): resuming against a different input resets
    the ledger rather than serving stale shards.
    """

    STAGE = "read.shards"

    def __init__(self, base_dir: str,
                 params: Optional[Dict[str, Any]] = None) -> None:
        from disq_tpu.runtime import flightrec

        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self.manifest = StageManifest(
            os.path.join(base_dir, "MANIFEST.json"), params)
        flightrec.note_artifact(
            "read_ledger", os.path.join(base_dir, "MANIFEST.json"))

    def _spill_path(self, shard_id: int) -> str:
        return os.path.join(self.base_dir, f"shard-{shard_id}.pkl")

    def is_done(self, shard_id: int) -> bool:
        if not self.manifest.is_done(self.STAGE, shard_id):
            return False
        # A recorded shard whose spill vanished (manual cleanup, torn
        # crash between spill rename and a *future* format change) is
        # treated as not-done: re-running it is always safe.
        return os.path.exists(self._spill_path(shard_id))

    def record(self, shard_id: int, value: Any) -> None:
        import pickle

        spill = self._spill_path(shard_id)
        fd, tmp = tempfile.mkstemp(dir=self.base_dir, prefix=".shard-")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(value, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, spill)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        self.manifest.mark_done(self.STAGE, shard_id, {"spill": spill})

    def load(self, shard_id: int) -> Any:
        import pickle

        with open(self._spill_path(shard_id), "rb") as f:
            return pickle.load(f)

    def completed_shards(self) -> List[int]:
        return [k for k in self.manifest.completed_shards(self.STAGE)
                if os.path.exists(self._spill_path(k))]

    def shard_run_id(self, shard_id: int) -> Optional[str]:
        return self.manifest.shard_run_id(self.STAGE, shard_id)

    def finish(self) -> None:
        """Commit point: the read completed — drop the manifest and
        every spill (a later run starts fresh)."""
        self.manifest.finish()
        for name in os.listdir(self.base_dir):
            if name.startswith("shard-") and name.endswith(".pkl"):
                os.unlink(os.path.join(self.base_dir, name))


QUARANTINE_FORMAT_VERSION = 1


class QuarantineManifest:
    """Sidecar ledger for corrupt blocks set aside under
    ``ErrorPolicy.QUARANTINE`` (``runtime/errors.py``).

    Layout under ``base_dir`` (default ``<input>.quarantine``):

    - ``MANIFEST.jsonl`` — line 1 is ``{"version": 1}``; every further
      line is one quarantined-block record ``{"path", "shard_id",
      "block_offset", "virtual_offset", "kind", "error", "sidecar",
      "length", "run_id"}``, appended as the block is set aside
      (``run_id`` is the process-wide telemetry run id, so the ledger
      joins span/progress JSONL from the same run). Append-only
      keeps the ledger O(1) per corrupt block — quarantine exists
      precisely for heavily damaged inputs, where rewriting a JSON
      document per block would be quadratic. A crash can tear at most
      the final line, which the loader ignores; re-quarantining the
      same ``(path, block_offset)`` appends a newer record and readers
      take the last one (idempotent under shard re-execution / retry).
    - ``block-<pathtag>-<offset>.bin`` — the verbatim corrupt
      *compressed* bytes, for offline forensics or re-decode with a
      repaired codec. ``pathtag`` is a digest of the input path, so
      multiple inputs sharing one ``quarantine_dir`` never collide.
    """

    MANIFEST_NAME = "MANIFEST.jsonl"

    def __init__(self, base_dir: str):
        from disq_tpu.runtime import flightrec

        self.base_dir = base_dir
        self.path = os.path.join(base_dir, self.MANIFEST_NAME)
        flightrec.note_artifact("quarantine_manifest", self.path)
        self._entries: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._header_ok = False
        if os.path.exists(self.path):
            try:
                with open(self.path, "r") as f:
                    lines = f.read().splitlines()
            except (OSError, UnicodeDecodeError):
                lines = []
            for i, line in enumerate(lines):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    if i == 0:
                        break  # headerless/torn ledger: don't trust it
                    continue  # torn tail line from a crash
                if i == 0:
                    if (not isinstance(rec, dict)
                            or rec.get("version")
                            != QUARANTINE_FORMAT_VERSION):
                        break  # foreign ledger: don't merge into it
                    self._header_ok = True
                    continue
                if not isinstance(rec, dict):
                    continue
                key = (rec.get("path", ""), rec.get("block_offset", -1))
                self._entries[key] = rec

    @property
    def entries(self) -> List[Dict[str, Any]]:
        return list(self._entries.values())

    def _append(self, rec: Dict[str, Any]) -> None:
        if (not self._header_ok and os.path.exists(self.path)
                and os.path.getsize(self.path) > 0):
            # A headerless (torn at creation) or foreign-version ledger:
            # appending v1 records into it would corrupt it for its own
            # readers. Set it aside and start fresh.
            os.replace(self.path, self.path + ".bak")
        with open(self.path, "a") as f:
            if not self._header_ok:
                f.write(json.dumps(
                    {"version": QUARANTINE_FORMAT_VERSION}) + "\n")
                self._header_ok = True
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def quarantine(
        self,
        path: str,
        block_offset: int,
        raw: bytes,
        *,
        shard_id: int = -1,
        virtual_offset: Optional[int] = None,
        error: str = "",
        kind: str = "block",
    ) -> str:
        """Copy one corrupt block aside; returns the sidecar path.
        Timed as a ``quarantine.write`` span so a slow quarantine disk
        shows up on the shard timeline, not just as mystery stall."""
        from disq_tpu.runtime.tracing import RUN_ID, span

        with span("quarantine.write", shard=shard_id,
                  block_offset=block_offset, kind=kind):
            os.makedirs(self.base_dir, exist_ok=True)
            tag = hashlib.sha1(path.encode()).hexdigest()[:8]
            sidecar = os.path.join(
                self.base_dir, f"block-{tag}-{block_offset}.bin")
            # Atomic sidecar commit: a crash between sidecar write and
            # ledger append must not leave a truncated sidecar that a
            # recorded entry later points at.
            fd, tmp = tempfile.mkstemp(dir=self.base_dir, prefix=".block-")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(raw)
                os.replace(tmp, sidecar)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            entry = {
                "path": path,
                "shard_id": shard_id,
                "block_offset": block_offset,
                "virtual_offset": virtual_offset,
                "kind": kind,
                "error": error,
                "sidecar": sidecar,
                "length": len(raw),
                # Telemetry join key: correlate this ledger line with
                # the span/progress JSONL of the run that set the
                # block aside.
                "run_id": RUN_ID,
            }
            self._entries[(path, block_offset)] = entry
            self._append(entry)
            return sidecar


JOURNAL_FORMAT_VERSION = 1


class SchedJournal:
    """Durable append-only journal of scheduler state transitions —
    the replication log behind coordinator failover
    (``runtime/scheduler.py``).

    Same JSONL shape as ``QuarantineManifest``: line 1 is
    ``{"version": 1}``; every further line is one transition record
    ``{"op", ...fields, "t"}`` where ``op`` is one of ``run`` / ``join``
    / ``lease`` / ``done`` / ``steal`` / ``expire`` / ``takeover`` and
    ``t`` is the coordinator's monotonic clock at the transition.  A
    crash can tear at most the final line, which ``load()`` skips; a
    standby that replays the surviving prefix therefore reconstructs a
    state the dead coordinator actually passed through, and lease
    expiry re-derives anything the torn tail would have changed.

    Writes land in the OS file immediately (a standby tails a complete
    record as soon as ``append`` returns) but ``fsync`` is batched —
    every ``fsync_every`` records or whenever ``fsync_interval_s`` has
    elapsed — so journaling done/lease at RPC rate doesn't serialize on
    disk.  The durability bound: power loss (not mere process death)
    can drop at most the unsynced suffix; everything a SIGKILL'd
    *process* wrote survives regardless.
    """

    def __init__(self, path: str, fsync_every: int = 8,
                 fsync_interval_s: float = 0.05) -> None:
        from disq_tpu.runtime import flightrec

        self.path = path
        self.fsync_every = max(1, int(fsync_every))
        self.fsync_interval_s = float(fsync_interval_s)
        self._lock = threading.Lock()
        self._f = None
        self._header_ok = False
        self._since_fsync = 0
        self._last_fsync = 0.0
        flightrec.note_artifact("sched_journal", path)

    @staticmethod
    def load(path: str) -> List[Dict[str, Any]]:
        """All surviving transition records (header excluded), torn
        tail tolerated — what ``replay_journal`` consumes.  A missing,
        headerless or foreign-version journal loads as empty."""
        try:
            with open(path, "r") as f:
                lines = f.read().splitlines()
        except (OSError, UnicodeDecodeError):
            return []
        records: List[Dict[str, Any]] = []
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if i == 0:
                    break  # headerless/torn journal: don't trust it
                continue  # torn tail line from a crash
            if i == 0:
                if (not isinstance(rec, dict)
                        or rec.get("version") != JOURNAL_FORMAT_VERSION):
                    break  # foreign journal: don't replay it
                continue
            if isinstance(rec, dict):
                records.append(rec)
        return records

    def _ends_with_newline(self) -> bool:
        try:
            with open(self.path, "rb") as f:
                f.seek(-1, os.SEEK_END)
                return f.read(1) == b"\n"
        except OSError:
            return True

    def _open_locked(self):
        if self._f is not None:
            return self._f
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
            # Trust an existing journal iff load() would: a takeover
            # continues the dead coordinator's file so a SECOND
            # failover still sees the full history. A headerless or
            # foreign-version file is set aside, as QuarantineManifest
            # does.
            ok = False
            try:
                with open(self.path, "r") as f:
                    first = f.readline()
                head = json.loads(first)
                ok = (isinstance(head, dict)
                      and head.get("version") == JOURNAL_FORMAT_VERSION)
            except (OSError, UnicodeDecodeError, json.JSONDecodeError):
                ok = False
            if not ok:
                os.replace(self.path, self.path + ".bak")
            elif not self._ends_with_newline():
                # The dead coordinator tore the final line: terminate
                # it so the FIRST record this process appends (the
                # standby's ``takeover``) stays its own line instead
                # of merging into the torn one and vanishing with it.
                with open(self.path, "a") as f:
                    f.write("\n")
            self._header_ok = ok
        self._f = open(self.path, "a")
        if not self._header_ok:
            self._f.write(json.dumps(
                {"version": JOURNAL_FORMAT_VERSION}) + "\n")
            self._f.flush()
            self._header_ok = True
        return self._f

    def append(self, op: str, **fields: Any) -> None:
        from disq_tpu.runtime.tracing import counter

        rec = {"op": op}
        rec.update(fields)
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            f = self._open_locked()
            f.write(line)
            f.flush()  # visible to a tailing standby immediately
            self._since_fsync += 1
            now = time.monotonic()
            if (self._since_fsync >= self.fsync_every
                    or now - self._last_fsync >= self.fsync_interval_s):
                self._fsync_locked(now)
        counter("sched.journal.records").inc(op=op)

    def _fsync_locked(self, now: float) -> None:
        from disq_tpu.runtime.tracing import counter

        os.fsync(self._f.fileno())
        self._since_fsync = 0
        self._last_fsync = now
        counter("sched.journal.fsyncs").inc()

    def sync(self) -> None:
        """Force the unsynced suffix to disk now (pass completion,
        orderly shutdown)."""
        with self._lock:
            if self._f is not None and self._since_fsync:
                self._fsync_locked(time.monotonic())

    def close(self) -> None:
        with self._lock:
            if self._f is None:
                return
            if self._since_fsync:
                self._fsync_locked(time.monotonic())
            self._f.close()
            self._f = None
