"""Stage manifest — deterministic, restartable phase plan.

The reference gets fault tolerance for free from Spark (task retry +
lineage re-execution, SURVEY.md §5) and adds an idempotent write
protocol: parts staged to a temp dir, driver merge as the commit point.
disq_tpu keeps the commit protocol and replaces Spark's retry with a
*stage manifest*: a JSON file on disk recording, per named stage, which
shards have completed and any small result payload (part path, length,
counters). A restarted run re-executes only the missing shards; the
commit step runs once all shards of the final stage are present.

The manifest is written atomically (tmp file + rename) after every
shard completion, so a crash at any point leaves a consistent file.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Callable, Dict, List, Optional

FORMAT_VERSION = 1


class StageManifest:
    """Shard-level checkpoint ledger for a multi-stage pipeline run.

    Keyed by ``(stage, shard_id)``. The optional ``params`` fingerprint
    guards against resuming with different inputs: if the stored
    fingerprint differs from the current one, the manifest is reset.
    """

    def __init__(self, path: str, params: Optional[Dict[str, Any]] = None):
        self.path = path
        self._state: Dict[str, Any] = {
            "version": FORMAT_VERSION,
            "params": params or {},
            "stages": {},
        }
        if os.path.exists(path):
            try:
                with open(path, "r") as f:
                    stored = json.load(f)
            except (json.JSONDecodeError, OSError, UnicodeDecodeError):
                # A damaged manifest must not block recovery — treat it
                # exactly like an incompatible one: start fresh.
                stored = {}
            if stored.get("version") != FORMAT_VERSION or (
                params is not None and stored.get("params") != params
            ):
                # Incompatible resume: start fresh (old manifest is
                # replaced on the next _flush).
                pass
            else:
                self._state = stored

    # -- persistence -----------------------------------------------------

    def _flush(self) -> None:
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".manifest-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._state, f)
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # -- shard ledger ----------------------------------------------------

    def _stage(self, stage: str) -> Dict[str, Any]:
        return self._state["stages"].setdefault(stage, {"shards": {}})

    def is_done(self, stage: str, shard_id: int) -> bool:
        return str(shard_id) in self._stage(stage)["shards"]

    def shard_info(self, stage: str, shard_id: int) -> Any:
        return self._stage(stage)["shards"][str(shard_id)]

    def mark_done(self, stage: str, shard_id: int, info: Any = None) -> None:
        self._stage(stage)["shards"][str(shard_id)] = info
        self._flush()

    def completed_shards(self, stage: str) -> List[int]:
        return sorted(int(k) for k in self._stage(stage)["shards"])

    # -- stage execution -------------------------------------------------

    def run_stage(
        self,
        stage: str,
        n_shards: int,
        fn: Callable[[int], Any],
        retries: int = 1,
    ) -> List[Any]:
        """Run ``fn(shard_id)`` for every shard not already recorded as
        done, retrying each failed shard up to ``retries`` extra times
        (the analogue of Spark task retry). Returns the per-shard info
        list in shard order, mixing cached and fresh results.

        ``fn``'s return value must be JSON-serializable (it is stored in
        the manifest and returned verbatim on resume).
        """
        out: List[Any] = [None] * n_shards
        for k in range(n_shards):
            if self.is_done(stage, k):
                out[k] = self.shard_info(stage, k)
                continue
            last: Optional[BaseException] = None
            for _attempt in range(retries + 1):
                try:
                    info = fn(k)
                    last = None
                    break
                except Exception as e:  # noqa: BLE001 — shard-level retry
                    last = e
            if last is not None:
                raise RuntimeError(
                    f"stage {stage!r} shard {k} failed after "
                    f"{retries + 1} attempts"
                ) from last
            self.mark_done(stage, k, info)
            out[k] = info
        return out

    def finish(self) -> None:
        """Commit point reached: remove the manifest (the staged parts'
        directory is cleaned separately by the caller)."""
        if os.path.exists(self.path):
            os.unlink(self.path)
