"""Fleet tier — replicated, cache-locality-routed serving (ROADMAP
item 1: one coherent service over N ``runtime/serve.py`` replicas).

One serving daemon answers region queries out of its
:class:`~disq_tpu.runtime.serve.HotBlockCache`; a fleet of them is
only faster than one if each query lands on the replica that already
holds its blocks. This module is that routing layer:

- **Locality routing**: every replica advertises a compact cache
  digest on its introspection plane (``GET /serve/cachemap`` —
  ``(path, 64 KiB bucket)`` sets, refreshed incrementally via the
  digest op log). The router resolves a query's intervals to BAI/TBI
  chunks with the same index machinery the daemon uses, scores each
  replica by digest overlap — the shard scheduler's block-locality
  signal (``scheduler._locality_score``), re-keyed by
  ``(path, coffset range)`` — and forwards to the best. Cold queries
  fall back to rendezvous hashing so repeats of the same region stick
  to one replica and *become* warm.
- **Cross-replica hedging**: tail-latency requests reuse
  ``resilience.HedgeController`` — a slow primary races a duplicate
  sent to the second-best replica, first response wins, the loser is
  cancelled (or its payload discarded on landing), and
  ``X-Disq-Trace-*`` headers ride both legs so ``trace_report
  --request`` stitches the full router -> replica -> device waterfall.
- **Fleet-wide admission**: per-replica ``TenantAdmission`` stats are
  aggregated router-side, so a tenant spraying requests across
  replicas still hits one fleet-wide 429 ceiling.
- **Epoch invalidation**: ``register`` fans out to every replica;
  ``/serve/register`` bumps the dataset's epoch and drops stale
  ``(path, coffset)`` cache entries, and the epochs ride
  ``/serve/cachemap`` so routers shed stale digests too.
- **Keep-alive transport**: each replica gets a small pool of
  persistent HTTP/1.1 connections with Nagle off — a per-request
  TCP+slow-start handshake would bury every hot-cache hit under the
  same ~40ms floor the serve plane already engineered away.

Zero-overhead-when-off contract (guarded by
``scripts/check_overhead.py``): no router, no thread, no socket and no
import of this module happens until :func:`start_fleet` runs;
:func:`fleet_if_running` NEVER creates, and :func:`handle_http`
answers 503 without allocating. The router itself owns no threads —
requests run on the introspect server's request threads, and the
hedge pool appears only once a hedge actually launches.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from disq_tpu.runtime.flightrec import record_event
from disq_tpu.runtime.serve import (
    DEFAULT_TENANT, DEFAULT_TENANT_QUEUE, DEFAULT_TENANT_SLOTS,
    IndexCache, ServeDaemon, digest_buckets)
from disq_tpu.runtime.tracing import (
    activate_trace, counter, current_trace, deactivate_trace, gauge,
    histogram, inject_trace_headers, mint_trace, record_span, span,
    trace_requests_enabled)

DEFAULT_REFRESH_S = 1.0      # cachemap/stats refresh cadence
DEFAULT_PROBE_S = 2.0        # dead-replica re-probe cadence
DEFAULT_HEDGE_QUANTILE = 0.95
DEFAULT_HEDGE_MIN_S = 0.05
DEFAULT_HEDGE_WORKERS = 16   # both legs of a hedge ride this pool
MAX_IDLE_CONNS = 4

POLICIES = ("locality", "random", "roundrobin")


class ReplicaError(RuntimeError):
    """Transport-level failure talking to one replica (connection
    refused/reset, timeout) — distinct from an HTTP error status,
    which is the replica *answering*. The router maps this to
    "replica lost": mark dead, reroute, re-probe later."""

    def __init__(self, endpoint: str, cause: BaseException) -> None:
        super().__init__(f"replica {endpoint}: "
                         f"{type(cause).__name__}: {cause}")
        self.endpoint = endpoint
        self.cause = cause


class ReplicaClient:
    """Persistent keep-alive HTTP client for one replica.

    Connections are pooled (borrowed exclusively per request, parked
    on return, at most :data:`MAX_IDLE_CONNS` idle) with TCP_NODELAY
    set — hedged requests need two concurrent sockets, and hot-cache
    hits must not pay TCP handshake + slow-start per query. A parked
    connection the replica closed while idle is retried once on a
    fresh one before the failure counts as a :class:`ReplicaError`.
    """

    def __init__(self, endpoint: str, timeout_s: float = 30.0) -> None:
        self.endpoint = endpoint
        host, _, port = endpoint.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        self._timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._idle: List[http.client.HTTPConnection] = []

    def _connect(self) -> http.client.HTTPConnection:
        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=self._timeout_s)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def _borrow(self) -> Tuple[http.client.HTTPConnection, bool]:
        with self._lock:
            if self._idle:
                return self._idle.pop(), True
        return self._connect(), False

    def _park(self, conn: http.client.HTTPConnection) -> None:
        with self._lock:
            if len(self._idle) < MAX_IDLE_CONNS:
                self._idle.append(conn)
                return
        conn.close()

    def request(self, method: str, path: str,
                doc: Optional[Dict[str, Any]] = None,
                headers: Optional[Dict[str, str]] = None,
                ) -> Tuple[int, Dict[str, Any]]:
        """One request over a pooled connection -> ``(status, doc)``.
        Raises :class:`ReplicaError` when the replica is unreachable.
        """
        body = json.dumps(doc).encode("utf-8") if doc is not None else None
        hdrs = dict(headers or {})
        if body is not None:
            hdrs.setdefault("Content-Type", "application/json")
        last_exc: Optional[BaseException] = None
        for _attempt in (0, 1):
            try:
                conn, reused = self._borrow()
            except Exception as e:  # noqa: BLE001 — connect failure
                raise ReplicaError(self.endpoint, e)
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                payload = resp.read()  # drain fully so conn is reusable
            except Exception as e:  # noqa: BLE001 — transport failure
                conn.close()
                last_exc = e
                if reused:
                    continue  # stale keep-alive conn: retry fresh once
                raise ReplicaError(self.endpoint, e)
            self._park(conn)
            try:
                out = json.loads(payload) if payload else {}
            except ValueError:
                out = {"raw": payload.decode("utf-8", "replace")}
            if not isinstance(out, dict):
                out = {"value": out}
            return resp.status, out
        raise ReplicaError(self.endpoint, last_exc)  # type: ignore[arg-type]

    def close(self) -> None:
        with self._lock:
            conns, self._idle = self._idle, []
        for conn in conns:
            conn.close()


class _Replica:
    """Router-side view of one serving replica."""

    __slots__ = ("endpoint", "client", "alive", "digest", "seq",
                 "epochs", "stats", "routed")

    def __init__(self, endpoint: str, client: Any) -> None:
        self.endpoint = endpoint
        self.client = client
        self.alive = True
        self.digest: Dict[str, set] = {}   # path -> warm buckets
        self.seq = None                    # last cachemap seq seen
        self.epochs: Dict[str, int] = {}
        self.stats: Dict[str, Any] = {}
        self.routed = 0


class FleetRouter:
    """The routing layer: forwards each ``/query/*`` to the replica
    whose cache already holds the query's blocks, hedging tail
    requests to the runner-up. Owns no threads; liveness and digest
    refresh are lazy (amortized on the query path against the
    injected ``clock``, which tests fake)."""

    def __init__(self, endpoints: List[str], *,
                 policy: str = "locality",
                 hedge_quantile: Optional[float] = DEFAULT_HEDGE_QUANTILE,
                 hedge_min_s: float = DEFAULT_HEDGE_MIN_S,
                 hedge_workers: int = DEFAULT_HEDGE_WORKERS,
                 tenant_slots: int = DEFAULT_TENANT_SLOTS,
                 tenant_queue: int = DEFAULT_TENANT_QUEUE,
                 refresh_s: float = DEFAULT_REFRESH_S,
                 probe_s: float = DEFAULT_PROBE_S,
                 timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 client_factory: Optional[Callable[[str], Any]] = None,
                 ) -> None:
        if not endpoints:
            raise ValueError("fleet needs at least one replica endpoint")
        if policy not in POLICIES:
            raise ValueError(
                f"unknown routing policy {policy!r}; pick from {POLICIES}")
        factory = client_factory or (
            lambda ep: ReplicaClient(ep, timeout_s))
        self.policy = policy
        self._replicas = [_Replica(ep, factory(ep)) for ep in endpoints]
        if hedge_quantile is not None and len(self._replicas) > 1:
            from disq_tpu.runtime.resilience import HedgeController

            self._hedge: Optional[Any] = HedgeController(
                hedge_quantile, hedge_min_s, max_workers=hedge_workers)
        else:
            self._hedge = None
        self._tenant_slots = int(tenant_slots)
        self._tenant_queue = int(tenant_queue)
        self._refresh_s = float(refresh_s)
        self._probe_s = float(probe_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._datasets: Dict[str, Tuple[str, str]] = {}  # name->(path,kind)
        self._indexes = IndexCache()
        self._inflight: Dict[str, int] = {}
        self._last_refresh: Optional[float] = None
        self._last_probe: Optional[float] = None
        self._rr = 0
        self._rng = random.Random(0x5EED)
        gauge("fleet.replicas").observe(len(self._replicas))

    # -- membership: lazy refresh + lazy liveness --------------------------

    def _live(self) -> List[_Replica]:
        return [r for r in self._replicas if r.alive]

    def _mark_dead(self, endpoint: str, reason: str) -> None:
        with self._lock:
            changed = False
            for r in self._replicas:
                if r.endpoint == endpoint and r.alive:
                    r.alive = False
                    r.seq = None      # force a full digest resync on return
                    r.digest.clear()
                    changed = True
            live = len(self._live())
        if changed:
            record_event("fleet.replica_lost", endpoint=endpoint,
                         reason=reason, live=live)
            gauge("fleet.replicas").observe(live)

    def _maybe_refresh(self) -> None:
        """Amortized upkeep on the query path: refresh live replicas'
        digests/stats every ``refresh_s``, re-probe dead ones every
        ``probe_s``. No background thread — a fleet-off process must
        not grow one, and an idle router costs nothing."""
        now = self._clock()
        with self._lock:
            refresh = (self._last_refresh is None
                       or now - self._last_refresh >= self._refresh_s)
            if refresh:
                self._last_refresh = now
            probe = (self._last_probe is None
                     or now - self._last_probe >= self._probe_s)
            if probe:
                self._last_probe = now
        if refresh:
            for r in self._live():
                self._refresh_one(r)
        if probe:
            for r in self._replicas:
                if not r.alive:
                    self._probe_one(r)

    def _refresh_one(self, r: _Replica) -> None:
        qs = f"?since={r.seq}" if r.seq is not None else ""
        try:
            with span("fleet.cachemap", replica=r.endpoint):
                status, doc = r.client.request(
                    "GET", "/serve/cachemap" + qs)
                st_status, st_doc = r.client.request("GET", "/serve/stats")
        except ReplicaError as e:
            self._mark_dead(r.endpoint, str(e.cause))
            return
        with self._lock:
            if status == 200 and "seq" in doc:
                self._apply_cachemap(r, doc)
            else:
                r.seq = None  # replica serve plane down/older: no digest
                r.digest.clear()
            r.stats = st_doc if st_status == 200 else {}

    def _apply_cachemap(self, r: _Replica, doc: Dict[str, Any]) -> None:
        # caller holds self._lock
        epochs = {str(p): int(e)
                  for p, e in (doc.get("epochs") or {}).items()}
        for path, epoch in epochs.items():
            if r.epochs.get(path, epoch) != epoch:
                # dataset re-registered: this replica invalidated its
                # cache, and so must our view of it
                r.digest.pop(path, None)
        r.epochs = epochs
        if "paths" in doc:
            r.digest = {str(p): set(b)
                        for p, b in (doc["paths"] or {}).items()}
        else:
            for op, path, bucket in doc.get("delta") or []:
                if op == "add":
                    r.digest.setdefault(str(path), set()).add(int(bucket))
                else:
                    warm = r.digest.get(str(path))
                    if warm is not None:
                        warm.discard(int(bucket))
                        if not warm:
                            del r.digest[str(path)]
        r.seq = int(doc["seq"])

    def _probe_one(self, r: _Replica) -> None:
        try:
            status, _doc = r.client.request("GET", "/healthz")
        except ReplicaError:
            return
        # answered at all (even 503-degraded) => the process is back,
        # same verdict cluster.probe_liveness gives the scheduler
        with self._lock:
            r.alive = True
            live = len(self._live())
        record_event("fleet.replica_restored", endpoint=r.endpoint,
                     status=status, live=live)
        gauge("fleet.replicas").observe(live)

    # -- the locality signal -----------------------------------------------

    def _resolve(self, doc: Dict[str, Any]
                 ) -> Tuple[str, Optional[List[int]]]:
        """``(path_key, digest buckets)`` of one query — the query's
        BAI/TBI chunks run through the same ``digest_buckets`` math
        the replica caches advertise, so overlap scoring compares
        like with like. Any resolution failure degrades to
        ``buckets=None`` (rendezvous fallback), never an error: the
        replica will produce the authoritative 4xx."""
        from disq_tpu.fsw.filesystem import resolve_path

        name = str(doc.get("dataset") or doc.get("path") or "")
        with self._lock:
            ds = self._datasets.get(name)
        if ds is not None:
            path, kind = ds
        else:
            path, kind = name, None
        try:
            fs, fs_path = resolve_path(path)
        except Exception:  # noqa: BLE001 — fallback routing key
            return name, None
        try:
            from disq_tpu.runtime.serve import _sniff_kind

            kind = kind or _sniff_kind(fs_path)
            intervals = ServeDaemon._parse_intervals(doc)
            chunks: List[Tuple[int, int]] = []
            if kind == "reads":
                from disq_tpu.traversal.bai_query import chunks_for_intervals

                header, _first_vo, bai = self._indexes.get(
                    fs, fs_path, ServeDaemon._build_bam_meta)
                chunks = list(chunks_for_intervals(header, bai, intervals))
            else:
                _header, tbi = self._indexes.get(
                    fs, fs_path, ServeDaemon._build_vcf_meta)
                for iv in intervals:
                    chunks += tbi.chunks_for_interval(
                        iv.contig, iv.start - 1, iv.end)
            buckets = sorted({b for cb, ce in chunks
                              for b in digest_buckets(cb, ce)})
            return fs_path, buckets
        except Exception:  # noqa: BLE001 — fallback routing key
            return fs_path, None

    @staticmethod
    def _rendezvous(key: str, endpoint: str) -> int:
        h = hashlib.md5(f"{key}|{endpoint}".encode("utf-8")).digest()
        return int.from_bytes(h[:8], "big")

    def _rank(self, path_key: str,
              buckets: Optional[List[int]]) -> List[_Replica]:
        """Live replicas, best routing target first."""
        # The tie-break key carries the query's *region* (its first
        # digest bucket), not just the dataset path: rendezvous then
        # spreads distinct cold regions across the fleet — that is
        # what partitions a working set bigger than any one replica's
        # cache — while repeats of one region still stick together.
        tie = (f"{path_key}#{buckets[0]}" if buckets else path_key)
        with self._lock:
            live = self._live()
            if not live:
                return []
            if self.policy == "roundrobin":
                self._rr += 1
                k = self._rr % len(live)
                return live[k:] + live[:k]
            if self.policy == "random":
                order = list(live)
                self._rng.shuffle(order)
                return order
            want = set(buckets or ())
            scored = sorted(
                live,
                key=lambda r: (-len(want & r.digest.get(path_key, set())),
                               -self._rendezvous(tie, r.endpoint)))
            hit = bool(want & scored[0].digest.get(path_key, set()))
        counter("fleet.route").inc(result="hit" if hit else "miss")
        return scored

    # -- fleet-wide admission ----------------------------------------------

    def _admit(self, tenant: str) -> bool:
        """Fleet-wide token check: a tenant's aggregate slots+queue
        usage across every live replica (from their ``/serve/stats``)
        — or the router's own in-flight count, whichever is worse —
        must stay under the fleet's aggregate capacity."""
        with self._lock:
            live = self._live()
            capacity = used = 0
            for r in live:
                adm = (r.stats or {}).get("admission") or {}
                capacity += (int(adm.get("slots",
                                         self._tenant_slots))
                             + int(adm.get("queue_depth",
                                           self._tenant_queue)))
                td = (adm.get("tenants") or {}).get(tenant) or {}
                used += int(td.get("active", 0)) + int(td.get("queued", 0))
            inflight = self._inflight.get(tenant, 0)
        return max(used, inflight) < max(capacity, 1)

    # -- query path --------------------------------------------------------

    def _send(self, r: _Replica, qpath: str, doc: Dict[str, Any],
              headers: Dict[str, str]) -> Tuple[_Replica, int,
                                                Dict[str, Any]]:
        status, body = r.client.request("POST", qpath, doc, headers)
        return r, status, body

    def _book_hedge(self, winner: str, hedged: bool) -> None:
        if hedged:
            counter("fleet.hedge.launched").inc()
            counter("fleet.hedge.won").inc(winner=winner)

    def _dispatch(self, ranked: List[_Replica], qpath: str,
                  doc: Dict[str, Any], headers: Dict[str, str]
                  ) -> Tuple[_Replica, int, Dict[str, Any]]:
        """Send to ``ranked[0]``; when hedging is armed and a runner-up
        exists, a slow primary races a duplicate on the second-best
        replica — region queries are idempotent reads, so first
        response wins and the loser is discarded."""
        targets = ranked[:2]
        if self._hedge is None or len(targets) < 2:
            return self._send(targets[0], qpath, doc, headers)
        state = {"next": 0}
        pick = threading.Lock()

        def attempt() -> Tuple[_Replica, int, Dict[str, Any]]:
            with pick:
                i = min(state["next"], len(targets) - 1)
                state["next"] += 1
            return self._send(targets[i], qpath, doc, headers)

        return self._hedge.call(attempt, on_outcome=self._book_hedge)

    def query(self, qpath: str,
              doc: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        """Route one ``/query/*`` request -> ``(status, body)``,
        retrying across survivors when a replica dies mid-request."""
        self._maybe_refresh()
        tenant = str(doc.get("tenant") or DEFAULT_TENANT)
        endpoint = qpath.rsplit("/", 1)[-1]
        t0 = time.perf_counter()
        if not self._admit(tenant):
            counter("fleet.admission").inc(result="shed", tenant=tenant)
            record_event("fleet.shed", tenant=tenant, endpoint=endpoint)
            return 429, {"error": f"fleet admission: tenant {tenant!r} "
                                  "saturates aggregate replica capacity",
                         "tenant": tenant}
        counter("fleet.admission").inc(result="admit", tenant=tenant)
        # The router is the fleet's serving edge: adopt the client's
        # context or mint the root here, and capture the outbound
        # headers ONCE — contextvars do not follow the hedge pool's
        # threads, the header dict does.
        ctx = current_trace()
        token = None
        if ctx is None and trace_requests_enabled():
            ctx = mint_trace(tenant)
            token = activate_trace(ctx)
        with self._lock:
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        status = 503
        try:
            headers = inject_trace_headers({})
            path_key, buckets = self._resolve(doc)
            last_error = "no live replicas"
            for _attempt in range(len(self._replicas)):
                ranked = self._rank(path_key, buckets)
                if not ranked:
                    break
                try:
                    replica, status, body = self._dispatch(
                        ranked, qpath, doc, headers)
                except ReplicaError as e:
                    self._mark_dead(e.endpoint, str(e.cause))
                    last_error = str(e)
                    continue
                with self._lock:
                    replica.routed += 1
                counter("fleet.routed").inc(endpoint=endpoint,
                                            replica=replica.endpoint)
                return status, body
            status = 503
            return 503, {"error": f"fleet: {last_error}"}
        finally:
            with self._lock:
                self._inflight[tenant] = max(
                    0, self._inflight.get(tenant, 0) - 1)
            dur = time.perf_counter() - t0
            histogram("fleet.request").observe(
                dur, endpoint=endpoint, tenant=tenant)
            if ctx is not None:
                # the stitched waterfall's root on the router hop
                record_span("fleet.request.trace", dur,
                            endpoint=endpoint, tenant=tenant,
                            status=status)
            if token is not None:
                deactivate_trace(token)

    # -- registry fan-out --------------------------------------------------

    def register(self, name: str, path: str,
                 kind: Optional[str] = None) -> Tuple[int, Dict[str, Any]]:
        """Fan a dataset registration out to every live replica. Each
        replica bumps the dataset's epoch and invalidates its stale
        cache entries; the router drops its own digest view so the
        next refresh resyncs."""
        self._maybe_refresh()
        headers = inject_trace_headers({})
        body = {"name": name, "path": path}
        if kind:
            body["kind"] = kind
        per_replica: Dict[str, Any] = {}
        epoch = 0
        ok = 0
        for r in self._live():
            try:
                status, doc = r.client.request(
                    "POST", "/serve/register", body, headers)
            except ReplicaError as e:
                self._mark_dead(r.endpoint, str(e.cause))
                per_replica[r.endpoint] = {"error": str(e)}
                continue
            per_replica[r.endpoint] = doc
            if status == 200:
                ok += 1
                epoch = max(epoch, int(doc.get("epoch", 1)))
            else:
                return status, {"error": doc.get("error",
                                                 f"HTTP {status}"),
                                "endpoint": r.endpoint}
        if ok == 0:
            return 503, {"error": "fleet: no live replicas to register on",
                         "replicas": per_replica}
        resolved_kind = str(
            next(iter(per_replica.values())).get("kind") or kind or "")
        try:
            from disq_tpu.fsw.filesystem import resolve_path

            _fs, fs_path = resolve_path(path)
        except Exception:  # noqa: BLE001 — digest key best-effort
            fs_path = path
        with self._lock:
            self._datasets[name] = (path, resolved_kind)
            for r in self._replicas:
                # stale digests die with the old epoch; the next
                # cachemap refresh rebuilds the warm view
                r.digest.pop(fs_path, None)
        record_event("fleet.register", name=name, epoch=epoch,
                     replicas=ok)
        return 200, {"name": name, "path": path, "kind": resolved_kind,
                     "epoch": epoch, "replicas": per_replica}

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        lat = histogram("fleet.request")
        with self._lock:
            replicas = [
                {"endpoint": r.endpoint, "alive": r.alive,
                 "routed": r.routed, "digest_seq": r.seq,
                 "digest_paths": len(r.digest),
                 "digest_buckets": sum(len(b) for b in r.digest.values())}
                for r in self._replicas
            ]
            datasets = {n: {"path": p, "kind": k}
                        for n, (p, k) in sorted(self._datasets.items())}
            inflight = {t: n for t, n in sorted(self._inflight.items())
                        if n > 0}
        return {
            "policy": self.policy,
            "hedge": self._hedge is not None,
            "replicas": replicas,
            "live": sum(1 for r in replicas if r["alive"]),
            "datasets": datasets,
            "inflight": inflight,
            "latency": {
                "p50_ms": lat.percentile(50) * 1e3,
                "p99_ms": lat.percentile(99) * 1e3,
            },
        }

    def handle(self, method: str, path: str,
               doc: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        if method == "GET" and path == "/fleet/stats":
            return 200, self.stats()
        if method != "POST":
            return 405, {"error": f"{path} expects POST"}
        if path == "/fleet/register":
            name = str(doc.get("name") or doc.get("path") or "")
            if not doc.get("path"):
                return 400, {"error": "register needs 'path'"}
            return self.register(name, str(doc["path"]), doc.get("kind"))
        if path.startswith("/fleet/query/"):
            return self.query(path[len("/fleet"):], doc)
        return 404, {"error": f"unknown fleet path {path}",
                     "endpoints": ["/fleet/query/reads",
                                   "/fleet/query/variants",
                                   "/fleet/query/stats",
                                   "/fleet/register", "/fleet/stats"]}

    def close(self) -> None:
        if self._hedge is not None:
            self._hedge.close()
        for r in self._replicas:
            try:
                r.client.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# -- module-level router lifecycle ------------------------------------------

_LOCK = threading.RLock()
_ROUTER: Optional[FleetRouter] = None


def fleet_if_running() -> Optional[FleetRouter]:
    """The live router, or None. NEVER creates one — the overhead
    guard (``scripts/check_overhead.py``) calls this to prove the
    fleet-off path allocates nothing."""
    return _ROUTER


def start_fleet(endpoints: List[str], port: int = 0,
                **router_kwargs: Any) -> str:
    """Create the router (idempotent) and return the ``host:port`` of
    the introspection HTTP server now also answering ``/fleet/*``."""
    global _ROUTER
    with _LOCK:
        if _ROUTER is None:
            _ROUTER = FleetRouter(list(endpoints), **router_kwargs)
    from disq_tpu.runtime.introspect import start_introspect_server

    return start_introspect_server(port)


def stop_fleet() -> None:
    """Drop the router (connections, hedge pool, digest state). The
    introspection server is shared — its starter stops it."""
    global _ROUTER
    with _LOCK:
        router, _ROUTER = _ROUTER, None
    if router is not None:
        router.close()


def handle_http(method: str, path: str,
                doc: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
    """Route one fleet-plane request; 503 (allocating nothing) when no
    router is running."""
    router = _ROUTER
    if router is None:
        return 503, {
            "error": "fleet tier not started — call "
                     "disq_tpu.api.serve_fleet() or scripts/serve.py "
                     "--fleet"}
    return router.handle(method, path, doc)
