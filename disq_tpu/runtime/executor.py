"""Pipelined parallel shard executor — bounded stage overlap in both
directions.

The reference gets cross-split parallelism for free from Spark: one
task per split, scheduled across executors. disq_tpu's read path walked
splits one at a time in a single host thread (only the C++ inflate
inside a block batch was threaded), so remote/HTTP reads and
stage-serialized formats (CRAM) were latency-bound. This module is the
Spark-scheduler analogue: a bounded staged pipeline shared by every
format source — and, since the write-path generalization, by every
format sink.

Two directions over one core (``_BoundedStagePipeline``):

- **Read** (``ShardPipelineExecutor``): fetch (I/O) → decode (CPU) →
  ordered emit.
- **Write** (``ShardWritePipeline``): encode (batch slice + record
  encode, CPU) → deflate (BGZF/gzip compress + voffset arithmetic,
  native-threaded) → stage (``fs.write_all`` of parts + index
  fragments, I/O) → ordered emit of per-shard part records. Shard
  ``i+1`` encodes while shard ``i`` deflates and shard ``i-1`` stages;
  the driver-side concat/merge consumes results in shard order, so
  output is byte-identical to the sequential loop at any worker count.

- **Stage A — fetch**: ``ShardTask.fetch()`` range-reads the split's
  byte window through the fsw layer (so HTTP prefetch and
  ``FaultInjectingFileSystemWrapper`` compose) and walks/collects its
  compressed structure. Runs on the fetch pool.
- **Stage B — decode**: ``ShardTask.decode(payload)`` inflates and
  parses records. Runs on the decode worker pool.
- **Stage C — emit**: ``map_ordered`` yields results **in shard
  order**, streaming — shard i+1 can be fetching/decoding while shard
  i's result is being consumed.

Guarantees:

- **Order and byte identity.** Results are emitted in task order
  regardless of worker count; the stages run the exact same per-shard
  code the sequential path runs, so output is byte-identical for any
  ``workers``.
- **Sequential-compatible default.** ``workers=1`` runs everything
  inline on the caller's thread in the same call order as the
  pre-executor loop — no threads, no queues.
- **Bounded in-flight window.** At most ``prefetch_shards`` shards past
  the emit frontier are admitted, so a retry storm or a quarantine on
  shard i delays shards ``i+k`` only once they fall inside the window
  (and memory stays bounded by ``window × shard bytes``).
- **ErrorPolicy / ShardRetrier semantics.** Each task carries its own
  per-shard ``ShardRetrier``; transient faults in fetch retry the fetch,
  transient faults escaping decode (salvage re-reads, CRAM reference
  fetch) re-run the shard from fetch under the same retrier. Corrupt
  data follows the shard's ``ErrorPolicy`` exactly as in the sequential
  path; the first raising shard aborts the pipeline.
- **Observability.** Per-stage, per-shard telemetry spans
  (``executor.fetch`` / ``executor.decode`` / ``executor.emit.stall``,
  each labeled with the shard id and feeding the same-named latency
  histogram) plus ``ExecutorStats`` (stage seconds, emit-stall
  seconds, max queue depth) and the ``executor.in_flight`` gauge make
  the overlap measurable, not asserted.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import (
    Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple)

from disq_tpu.runtime import flightrec
from disq_tpu.runtime.errors import (
    DeadlineExceededError,
    DisqOptions,
    ShardRetrier,
    is_transient,
)
from disq_tpu.runtime.tracing import observe_gauge, record_span, span

# Sentinel a fetch stage emits when the shard's deadline expired and
# the task carries a fallback: the decode stage then produces the
# fallback value instead of decoding (runtime/resilience.py ladder).
_DEADLINE_MISS = object()


@dataclass
class ShardTask:
    """One split's pipeline work. ``fetch`` does the I/O (stage A) and
    returns an opaque payload; ``decode`` turns that payload into the
    shard's result (stage B). Both close over their shard's
    ``ShardErrorContext`` for policy dispatch; ``retrier`` is that
    context's retrier (None ⇒ no transient retry).

    ``deadline_fallback`` (set by sources when the error policy is
    skip/quarantine and ``DisqOptions.shard_deadline_s`` is armed)
    produces the shard's stand-in value — typically an empty batch,
    booked through the shard's quarantine machinery — when the shard's
    deadline expires; without it a ``DeadlineExceededError`` aborts the
    run (the strict-policy behavior).

    ``byte_range`` is the shard's compressed byte window ``(lo, hi)``
    in the input file — the coordinate the cross-host scheduler's
    locality scorer matches against a worker's HTTP block-cache
    occupancy (``runtime/scheduler.py``; None ⇒ never locality-routed)."""

    shard_id: int
    fetch: Callable[[], Any]
    decode: Callable[[Any], Any]
    retrier: Optional[ShardRetrier] = None
    what: str = "shard"
    deadline_fallback: Optional[Callable[[], Any]] = None
    byte_range: Optional[tuple] = None


@dataclass
class ShardResult:
    """Ordered emission unit: the decoded value plus per-stage wall
    time, so emit-side counter assembly can report real shard cost."""

    shard_id: int
    value: Any
    fetch_seconds: float = 0.0
    decode_seconds: float = 0.0

    @property
    def wall_seconds(self) -> float:
        return self.fetch_seconds + self.decode_seconds


@dataclass
class ExecutorStats:
    """Aggregate pipeline observability for one ``map_ordered`` run
    (cumulative across runs on the same executor instance)."""

    workers: int = 0
    window: int = 0
    shards: int = 0
    fetch_seconds: float = 0.0
    decode_seconds: float = 0.0
    emit_stall_seconds: float = 0.0
    max_in_flight: int = 0

    def as_dict(self) -> Dict[str, float]:
        return {
            "workers": self.workers,
            "window": self.window,
            "shards": self.shards,
            "fetch_seconds": round(self.fetch_seconds, 6),
            "decode_seconds": round(self.decode_seconds, 6),
            "emit_stall_seconds": round(self.emit_stall_seconds, 6),
            "max_in_flight": self.max_in_flight,
        }


class _BoundedStagePipeline:
    """The bounded-window machinery shared by the read executor and the
    write pipeline: N stages, one worker pool per stage, streaming
    ordered emit keyed by task-list index, first-error abort.

    ``stage_fns[i](task, payload)`` runs stage ``i`` (``payload`` is
    None for stage 0; each stage's return feeds the next). The
    ``on_admit(depth)`` / ``on_result(seconds)`` / ``on_stall(seconds,
    task)`` hooks keep stats accounting and metric *names* in the
    direction-specific wrappers, so ``executor.*`` and ``writer.*``
    stay literal at their call sites (the metric-name lint scans
    literals). ``on_result`` and ``on_stall`` run with the pipeline
    condition held — keep them cheap and non-blocking.

    When a run is live-introspected (``health`` is a
    ``PipelineHealth`` board and ``health_token`` its run token), every
    stage worker stamps a per-shard heartbeat as it starts a stage and
    clears it when the stage returns, and the watchdog can cancel the
    run through the existing first-error-abort path: the injected
    ``WatchdogStallError`` is recorded at the emit frontier, so the
    consumer raises it deterministically at its next ``next()``. With
    ``health=None`` (the default) none of this code runs.
    """

    def __init__(
        self,
        workers: int,
        window: int,
        stage_fns: Sequence[Callable[[Any, Any], Any]],
        thread_prefixes: Sequence[str],
        on_admit: Callable[[int], None],
        on_result: Callable[[List[float]], None],
        on_stall: Callable[[float, Any], None],
        drain_on_close: bool = False,
        stage_names: Sequence[str] = (),
        health=None,
        health_token: Optional[int] = None,
    ) -> None:
        self.workers = workers
        self.window = window
        self.stage_fns = list(stage_fns)
        self.thread_prefixes = list(thread_prefixes)
        self.on_admit = on_admit
        self.on_result = on_result
        self.on_stall = on_stall
        # The write direction drains running jobs at close so an
        # aborting sink never races an in-flight part write against its
        # own temp-dir cleanup; the read direction keeps wait=False (a
        # stalled remote fetch must not block the caller's error).
        self.drain_on_close = drain_on_close
        self.stage_names = list(stage_names)
        self.health = health
        self.health_token = health_token

    def run(self, tasks: List[Any]) -> Iterator[tuple]:
        """Admit the first window EAGERLY (stage-0 work is in flight
        before the caller's first ``next()``) and return the
        ordered-emit generator yielding ``(index, value,
        per_stage_seconds)`` in task order."""
        n_stages = len(self.stage_fns)
        cond = threading.Condition()
        results: Dict[int, tuple] = {}
        errors: Dict[int, BaseException] = {}
        state = {"next_admit": 0, "next_emit": 0, "in_flight": 0,
                 "aborted": False}
        pools = [
            ThreadPoolExecutor(max_workers=self.workers,
                               thread_name_prefix=prefix)
            for prefix in self.thread_prefixes
        ]

        health, token = self.health, self.health_token
        if health is not None and token is not None:
            # The watchdog's abort path: record the stall error at the
            # emit frontier so the consumer's next ``next()`` raises it
            # (same mechanics as a stage failure on that shard).
            def inject_abort(exc: BaseException) -> None:
                with cond:
                    if state["aborted"]:
                        return
                    errors.setdefault(state["next_emit"], exc)
                    cond.notify_all()

            health.set_abort(token, inject_abort)

        def stage_name(stage: int) -> str:
            return (self.stage_names[stage]
                    if stage < len(self.stage_names) else str(stage))

        def record_error(idx: int, exc: BaseException) -> None:
            with cond:
                errors[idx] = exc
                state["in_flight"] -= 1
                cond.notify_all()

        def job(stage: int, idx: int, task: Any, payload: Any,
                seconds: List[float]) -> None:
            if stage == 0:
                with cond:
                    if state["aborted"]:
                        state["in_flight"] -= 1
                        cond.notify_all()
                        return
            shard = getattr(task, "shard_id", idx)
            if health is not None:
                health.beat(token, stage_name(stage), shard)
            t0 = time.perf_counter()
            try:
                value = self.stage_fns[stage](task, payload)
            except BaseException as e:  # noqa: BLE001 — re-raised at emit
                if health is not None:
                    health.clear(token, stage_name(stage), shard)
                record_error(idx, e)
                return
            if health is not None:
                health.clear(token, stage_name(stage), shard)
            seconds.append(time.perf_counter() - t0)
            if stage + 1 < n_stages:
                pools[stage + 1].submit(job, stage + 1, idx, task, value,
                                        seconds)
                return
            with cond:
                results[idx] = (value, seconds)
                state["in_flight"] -= 1
                self.on_result(seconds)
                cond.notify_all()

        def admit_locked() -> None:
            # caller holds cond
            while (not state["aborted"]
                   and state["next_admit"] < len(tasks)
                   and state["next_admit"]
                   < state["next_emit"] + self.window):
                idx = state["next_admit"]
                state["next_admit"] += 1
                state["in_flight"] += 1
                self.on_admit(state["in_flight"])
                pools[0].submit(job, 0, idx, tasks[idx], None, [])

        with cond:
            admit_locked()

        def emit() -> Iterator[tuple]:
            try:
                for i in range(len(tasks)):
                    with cond:
                        t0 = time.perf_counter()
                        while i not in results and i not in errors:
                            cond.wait()
                        self.on_stall(time.perf_counter() - t0, tasks[i])
                        if i in errors:
                            state["aborted"] = True
                            # The pipeline's first-error-abort IS the
                            # postmortem moment: every stage worker is
                            # still live, so the bundle's thread stacks
                            # show what each one was doing.
                            flightrec.note_abort(errors[i], where="emit")
                            raise errors[i]
                        value, seconds = results.pop(i)
                        state["next_emit"] = i + 1
                        admit_locked()
                    yield i, value, seconds
            finally:
                with cond:
                    state["aborted"] = True
                for pool in pools:
                    pool.shutdown(wait=self.drain_on_close,
                                  cancel_futures=True)

        return emit()


def _check_abort(health, token: Optional[int]) -> None:
    """Cooperative watchdog-abort pickup for the inline (workers=1)
    paths, which have no pipeline to inject an error into: raise the
    parked WatchdogStallError at the stage boundary where the run's
    own thread next surfaces."""
    if health is not None and token is not None:
        exc = health.take_abort(token)
        if exc is not None:
            raise exc


def _tracked(inner: Iterator, health, token: int) -> Iterator:
    """Wrap an ordered-emit iterator so each yielded shard is marked
    done on the health board and the run is closed out when the
    iterator ends (normally, by error, or abandoned)."""
    try:
        for res in inner:
            health.shard_done(token, res.shard_id)
            yield res
    finally:
        health.finish_run(token)


class ShardPipelineExecutor:
    """Bounded three-stage shard pipeline (see module docstring).

    ``workers`` sizes the decode pool (and the fetch pool — fetches are
    I/O-bound and cheap to oversubscribe, but one pool bound keeps the
    fsw request concurrency predictable). ``prefetch_shards`` bounds
    how many shards past the emit frontier may be in flight; default
    ``2 × workers`` keeps every worker busy while the consumer drains.
    """

    def __init__(self, workers: int = 1,
                 prefetch_shards: Optional[int] = None,
                 health=None,
                 watchdog_stall_s: Optional[float] = None,
                 watchdog_policy: str = "warn",
                 resilience=None) -> None:
        self.workers = max(1, int(workers))
        if prefetch_shards is None:
            prefetch_shards = 2 * self.workers
        self.prefetch_shards = max(1, int(prefetch_shards))
        # prefetch_shards IS the documented in-flight bound: an
        # explicit value below ``workers`` caps memory at the cost of
        # idle workers, exactly as the caller asked.
        self.stats = ExecutorStats(
            workers=self.workers,
            window=self.prefetch_shards,
        )
        # Live introspection (None = disabled, the zero-overhead path):
        # a PipelineHealth board receiving run registration, per-shard
        # heartbeats and completions — see runtime/introspect.py.
        self._health = health
        self._watchdog_stall_s = watchdog_stall_s
        self._watchdog_policy = watchdog_policy
        # Adaptive resilience (None = disabled, zero overhead): a
        # ResilienceManager providing hedged fetches and per-shard
        # deadlines — see runtime/resilience.py.
        self._resilience = resilience

    # -- public -------------------------------------------------------------

    def map_ordered(
        self, tasks: Sequence[ShardTask]
    ) -> Iterator[ShardResult]:
        """Run every task through fetch→decode, yielding results in
        task order as they become ready (streaming — stage C)."""
        tasks = list(tasks)
        self.stats.shards += len(tasks)
        if not tasks:
            return iter(())
        token = None
        if self._health is not None:
            token = self._health.register_run(
                "read", len(tasks), self._watchdog_stall_s,
                self._watchdog_policy)
        if self.workers == 1:
            inner = self._run_sequential(tasks, token)
        else:
            inner = self._run_pipelined(tasks, token)
        if token is None:
            return inner
        return _tracked(inner, self._health, token)

    # -- sequential (workers=1): the exact pre-executor call order ----------

    def _run_sequential(self, tasks: List[ShardTask],
                        token: Optional[int] = None
                        ) -> Iterator[ShardResult]:
        try:
            for task in tasks:
                yield self._run_one_inline(task, token)
        except GeneratorExit:
            # Consumer stopped iterating early — a normal close, not
            # an abort; no postmortem.
            raise
        except BaseException as e:
            # Inline first-error-abort: same postmortem moment as the
            # pipelined emit raise.
            flightrec.note_abort(e, where="inline")
            raise
        finally:
            if self._resilience is not None:
                self._resilience.close()

    def _run_one_inline(self, task: ShardTask,
                        token: Optional[int] = None) -> ShardResult:
        """Whole-shard work under ONE retrier budget — identical
        semantics (and retry accounting) to the historical
        ``retrier.call(decode_range, …)`` per-shard loop."""
        times = [0.0, 0.0]
        health = self._health if token is not None else None
        res = self._resilience
        deadline = (res.new_deadline(task.shard_id)
                    if res is not None else None)
        if deadline is not None and task.retrier is not None:
            task.retrier.deadline = deadline

        def attempt():
            t0 = time.perf_counter()
            _check_abort(health, token)
            if deadline is not None:
                deadline.check(what=task.what)
            if health is not None:
                health.beat(token, "fetch", task.shard_id)
            with span("executor.fetch", shard=task.shard_id):
                if res is not None:
                    payload = res.fetch(task.fetch, task.shard_id, deadline)
                else:
                    payload = task.fetch()
            t1 = time.perf_counter()
            times[0] += t1 - t0
            _check_abort(health, token)
            if deadline is not None:
                deadline.check(what=task.what)
            if health is not None:
                health.beat(token, "decode", task.shard_id)
            with span("executor.decode", shard=task.shard_id):
                value = task.decode(payload)
            times[1] += time.perf_counter() - t1
            if health is not None:
                health.clear(token, "decode", task.shard_id)
            _check_abort(health, token)
            return value

        try:
            if task.retrier is not None:
                value = task.retrier.call(attempt, what=task.what)
            else:
                value = attempt()
        except DeadlineExceededError:
            if task.deadline_fallback is None:
                raise
            value = task.deadline_fallback()
        self.stats.fetch_seconds += times[0]
        self.stats.decode_seconds += times[1]
        return ShardResult(task.shard_id, value, times[0], times[1])

    # -- pipelined (workers>1) ----------------------------------------------

    def _run_pipelined(self, tasks: List[ShardTask],
                       token: Optional[int] = None
                       ) -> Iterator[ShardResult]:
        """Two stages over the shared bounded core: fetch (with the
        per-shard retrier, hedged when resilience is armed) and decode
        (with the transient-escape refetch hatch)."""
        res = self._resilience
        deadlines: Dict[int, Any] = {}
        if res is not None:
            for t in tasks:
                dl = res.new_deadline(t.shard_id)
                if dl is not None:
                    deadlines[t.shard_id] = dl
                    if t.retrier is not None:
                        t.retrier.deadline = dl

        def fetch_once(task: ShardTask) -> Any:
            if res is not None:
                return res.fetch(task.fetch, task.shard_id,
                                 deadlines.get(task.shard_id))
            return task.fetch()

        def fetch_fn(task: ShardTask, _payload: Any) -> Any:
            with span("executor.fetch", shard=task.shard_id):
                dl = deadlines.get(task.shard_id)
                try:
                    if dl is not None:
                        dl.check(what=task.what)
                    if task.retrier is not None:
                        return task.retrier.call(
                            lambda: fetch_once(task),
                            what=f"{task.what}.fetch")
                    return fetch_once(task)
                except DeadlineExceededError:
                    if task.deadline_fallback is None:
                        raise
                    return _DEADLINE_MISS

        def decode_fn(task: ShardTask, payload: Any) -> Any:
            with span("executor.decode", shard=task.shard_id):
                if payload is _DEADLINE_MISS:
                    return task.deadline_fallback()
                dl = deadlines.get(task.shard_id)
                try:
                    if dl is not None:
                        dl.check(what=task.what)
                    return self._decode_with_refetch(task, payload)
                except DeadlineExceededError:
                    if task.deadline_fallback is None:
                        raise
                    return task.deadline_fallback()

        def on_admit(depth: int) -> None:
            if depth > self.stats.max_in_flight:
                self.stats.max_in_flight = depth
            observe_gauge("executor.in_flight", depth)

        def on_result(seconds: List[float]) -> None:
            self.stats.fetch_seconds += seconds[0]
            self.stats.decode_seconds += seconds[1]

        def on_stall(stall: float, task: ShardTask) -> None:
            self.stats.emit_stall_seconds += stall
            if stall > 0.0005:
                # only meaningful waits become trace spans
                record_span("executor.emit.stall", stall,
                            shard=task.shard_id)

        core = _BoundedStagePipeline(
            workers=self.workers,
            window=self.stats.window,
            stage_fns=(fetch_fn, decode_fn),
            thread_prefixes=("disq-fetch", "disq-decode"),
            on_admit=on_admit,
            on_result=on_result,
            on_stall=on_stall,
            stage_names=("fetch", "decode"),
            health=self._health if token is not None else None,
            health_token=token,
        )
        inner = core.run(tasks)  # admits the first window eagerly

        def adapt() -> Iterator[ShardResult]:
            try:
                for idx, value, secs in inner:
                    yield ShardResult(tasks[idx].shard_id, value,
                                      secs[0], secs[1])
            finally:
                # Same lifecycle as the stage pools (core.run's emit
                # closes them in ITS finally): an abort or exhausted
                # run must not leave hedge duplicates in flight.
                if res is not None:
                    res.close()

        return adapt()

    def _decode_with_refetch(self, task: ShardTask, payload: Any) -> Any:
        """Stage B with the transient-escape hatch: decode is normally
        pure CPU over fetched bytes, but the salvage paths (BGZF
        re-sync, VCF line extension) and CRAM reference fetch can issue
        fresh reads. A transient there re-runs the shard from fetch
        under the task's retrier — the bounded equivalent of the
        sequential path's whole-shard retry."""
        try:
            return task.decode(payload)
        except Exception as e:  # noqa: BLE001 — classified below
            if task.retrier is None or not is_transient(e):
                raise
            task.retrier.retried += 1  # the attempt that just failed

            def rerun():
                return task.decode(task.fetch())

            return task.retrier.call(rerun, what=task.what)


def executor_for_storage(storage) -> ShardPipelineExecutor:
    """Build the shard executor from a storage builder's
    ``DisqOptions`` (absent/None ⇒ sequential-compatible defaults).
    This is also where live introspection and adaptive resilience turn
    on for a read: the options' endpoint / watchdog / progress-log /
    hedging / deadline knobs are resolved once per run, and the
    default (nothing configured) hands the executor ``health=None`` /
    ``resilience=None`` — the no-op path."""
    from disq_tpu.runtime import profiler
    from disq_tpu.runtime.introspect import configure_from_options
    from disq_tpu.runtime.resilience import resilience_for_options

    opts = getattr(storage, "_options", None) or DisqOptions()
    flightrec.configure_from_options(opts)
    profiler.configure_from_options(opts)
    cache_blocks = getattr(opts, "http_cache_blocks", None)
    if cache_blocks:
        from disq_tpu.fsw.http import configure_cache_blocks

        configure_cache_blocks(cache_blocks)
    return ShardPipelineExecutor(
        workers=getattr(opts, "executor_workers", 1),
        prefetch_shards=getattr(opts, "prefetch_shards", None),
        health=configure_from_options(opts),
        watchdog_stall_s=getattr(opts, "watchdog_stall_s", None),
        watchdog_policy=getattr(opts, "watchdog_policy", "warn"),
        resilience=resilience_for_options(opts),
    )


def read_ledger_for_storage(storage, path: str, n_shards: int):
    """The crash-resume read ledger for one read, or None when
    ``DisqOptions.read_ledger`` is unset (the default — no directory,
    no spill I/O).  The params fingerprint ties the ledger to this
    exact input shape AND to every option that changes what a shard
    decodes to (policy, deadline fallback): resuming against a
    different path, split count, or decode-affecting option resets the
    ledger instead of serving stale shards."""
    opts = getattr(storage, "_options", None) or DisqOptions()
    base = getattr(opts, "read_ledger", None)
    if not base:
        return None
    from disq_tpu.runtime.errors import ErrorPolicy
    from disq_tpu.runtime.manifest import ReadLedger

    from disq_tpu.runtime.columnar import resident_decode_enabled

    return ReadLedger(base, params={
        "path": path,
        "shards": int(n_shards),
        "error_policy": ErrorPolicy.coerce(opts.error_policy).value,
        "shard_deadline_s": getattr(opts, "shard_deadline_s", None),
        # resident decode changes the spilled shard *type* (ColumnarBatch
        # spills rebuild device-side on load) — toggling it between a
        # crashed and a resumed run must reset the ledger, not serve
        # stale host-form spills
        "resident_decode": bool(resident_decode_enabled(storage)),
    })


def map_ordered_resumable(executor: ShardPipelineExecutor,
                          tasks: Sequence[ShardTask],
                          ledger=None) -> Iterator[ShardResult]:
    """``executor.map_ordered`` with read-side crash resume: shards the
    ledger already holds are served from their spills (zero fetch /
    decode), fresh shards run through the executor and are spilled as
    they emit, and a fully consumed run hits the ledger's commit point
    (``finish`` — spills dropped, next run starts clean).  Without a
    ledger this is exactly ``map_ordered`` (the zero-overhead path)."""
    tasks = list(tasks)
    if ledger is None:
        return executor.map_ordered(tasks)

    def gen() -> Iterator[ShardResult]:
        cached = {t.shard_id for t in tasks if ledger.is_done(t.shard_id)}
        fresh = executor.map_ordered(
            [t for t in tasks if t.shard_id not in cached])
        for t in tasks:
            if t.shard_id in cached:
                yield ShardResult(t.shard_id, ledger.load(t.shard_id))
            else:
                res = next(fresh)
                ledger.record(res.shard_id, res.value)
                yield res
        ledger.finish()

    return gen()


# ---------------------------------------------------------------------------
# Write direction: encode → deflate → stage
# ---------------------------------------------------------------------------


@dataclass
class WriteShardTask:
    """One shard's write-direction pipeline work. ``encode`` slices the
    batch and encodes records (CPU); ``deflate`` compresses and does
    voffset/index arithmetic (native-threaded CPU; None ⇒ pass-through
    for uncompressed formats); ``stage`` durably writes the part +
    index fragments (I/O; None ⇒ the caller consumes the payload at
    ordered emit — single-stream sinks like BCF). ``retrier`` guards
    only the stage step: encode/deflate are pure CPU, while a staged
    write can hit the same transient faults a read can."""

    shard_id: int
    encode: Callable[[], Any]
    deflate: Optional[Callable[[Any], Any]] = None
    stage: Optional[Callable[[Any], Any]] = None
    retrier: Optional[ShardRetrier] = None
    what: str = "write"
    # estimated output byte range of this shard's part within the
    # merged file (uncompressed record bytes) — the write-lease
    # locality hint: scheduled_write_stage registers it with the
    # coordinator so write leases score contiguity/cache locality the
    # way read leases do, instead of FIFO-only.  None (default) keeps
    # the pure-FIFO write lease.
    byte_range: Optional[Tuple[int, int]] = None


@dataclass
class WriteShardResult:
    """Ordered emission unit of the write pipeline: the stage step's
    return value (the shard's part record) plus per-stage wall time."""

    shard_id: int
    value: Any
    encode_seconds: float = 0.0
    deflate_seconds: float = 0.0
    stage_seconds: float = 0.0

    @property
    def wall_seconds(self) -> float:
        return (self.encode_seconds + self.deflate_seconds
                + self.stage_seconds)


@dataclass
class WriterStats:
    """Aggregate write-pipeline observability (cumulative across runs
    on the same pipeline instance)."""

    workers: int = 0
    window: int = 0
    shards: int = 0
    encode_seconds: float = 0.0
    deflate_seconds: float = 0.0
    stage_seconds: float = 0.0
    emit_stall_seconds: float = 0.0
    max_in_flight: int = 0

    def as_dict(self) -> Dict[str, float]:
        return {
            "workers": self.workers,
            "window": self.window,
            "shards": self.shards,
            "encode_seconds": round(self.encode_seconds, 6),
            "deflate_seconds": round(self.deflate_seconds, 6),
            "stage_seconds": round(self.stage_seconds, 6),
            "emit_stall_seconds": round(self.emit_stall_seconds, 6),
            "max_in_flight": self.max_in_flight,
        }


class ShardWritePipeline:
    """Bounded three-stage write pipeline over the shared core: encode
    → deflate → stage, ordered streaming emit.

    Guarantees mirror the read executor's: results emit in task order,
    per-shard bytes are produced by the exact per-shard code the
    sequential loop runs (⇒ byte-identical merged output at any
    ``workers``), ``workers=1`` runs everything inline on the caller's
    thread in the historical call order, and at most
    ``prefetch_shards`` shards past the emit frontier are in flight
    (default ``2 × workers``), bounding peak memory to ``window ×
    (uncompressed + compressed shard bytes)``."""

    def __init__(self, workers: int = 1,
                 prefetch_shards: Optional[int] = None,
                 health=None,
                 watchdog_stall_s: Optional[float] = None,
                 watchdog_policy: str = "warn") -> None:
        self.workers = max(1, int(workers))
        if prefetch_shards is None:
            prefetch_shards = 2 * self.workers
        self.prefetch_shards = max(1, int(prefetch_shards))
        # As in the read executor, prefetch_shards IS the in-flight
        # bound (the memory cap the docstring promises), even below
        # ``workers``.
        self.stats = WriterStats(
            workers=self.workers,
            window=self.prefetch_shards,
        )
        # Live introspection (see ShardPipelineExecutor / introspect.py).
        self._health = health
        self._watchdog_stall_s = watchdog_stall_s
        self._watchdog_policy = watchdog_policy

    # -- public -------------------------------------------------------------

    def map_ordered(
        self, tasks: Sequence[WriteShardTask]
    ) -> Iterator[WriteShardResult]:
        tasks = list(tasks)
        self.stats.shards += len(tasks)
        if not tasks:
            return iter(())
        token = None
        if self._health is not None:
            token = self._health.register_run(
                "write", len(tasks), self._watchdog_stall_s,
                self._watchdog_policy)
        if self.workers == 1:
            inner = self._run_sequential(tasks, token)
        else:
            inner = self._run_pipelined(tasks, token)
        if token is None:
            return inner
        return _tracked(inner, self._health, token)

    # -- stage bodies (shared by both paths) --------------------------------

    @staticmethod
    def _encode(task: WriteShardTask, _payload: Any) -> Any:
        return task.encode()

    @staticmethod
    def _deflate(task: WriteShardTask, payload: Any) -> Any:
        if task.deflate is None:
            return payload
        return task.deflate(payload)

    @staticmethod
    def _stage(task: WriteShardTask, payload: Any) -> Any:
        if task.stage is None:
            return payload
        if task.retrier is not None:
            return task.retrier.call(
                lambda: task.stage(payload), what=f"{task.what}.stage")
        return task.stage(payload)

    # -- sequential (workers=1): the historical per-shard loop order --------

    def _run_sequential(
        self, tasks: List[WriteShardTask],
        token: Optional[int] = None,
    ) -> Iterator[WriteShardResult]:
        health = self._health if token is not None else None
        try:
            for task in tasks:
                secs = []
                payload = None
                for name, fn in (("encode", self._encode),
                                 ("deflate", self._deflate),
                                 ("stage", self._stage)):
                    _check_abort(health, token)
                    if health is not None:
                        health.beat(token, name, task.shard_id)
                    t0 = time.perf_counter()
                    payload = fn(task, payload)
                    secs.append(time.perf_counter() - t0)
                    if health is not None:
                        health.clear(token, name, task.shard_id)
                self.stats.encode_seconds += secs[0]
                self.stats.deflate_seconds += secs[1]
                self.stats.stage_seconds += secs[2]
                yield WriteShardResult(task.shard_id, payload, *secs)
        except GeneratorExit:
            raise  # early close of the iterator, not an abort
        except BaseException as e:
            flightrec.note_abort(e, where="inline")
            raise

    # -- pipelined (workers>1) ----------------------------------------------

    def _run_pipelined(
        self, tasks: List[WriteShardTask],
        token: Optional[int] = None,
    ) -> Iterator[WriteShardResult]:
        def on_admit(depth: int) -> None:
            if depth > self.stats.max_in_flight:
                self.stats.max_in_flight = depth
            observe_gauge("writer.in_flight", depth)

        # A stage that is None on EVERY task (SAM/CRAM have no deflate,
        # BCF's stream write happens at emit) is dropped from the
        # pipeline entirely — no idle thread pool, no per-shard queue
        # hop for an identity function.
        stage_attrs = [("encode_seconds", self._encode, "disq-encode")]
        if any(t.deflate is not None for t in tasks):
            stage_attrs.append(
                ("deflate_seconds", self._deflate, "disq-deflate"))
        if any(t.stage is not None for t in tasks):
            stage_attrs.append(("stage_seconds", self._stage, "disq-stage"))
        attr_names = [a for a, _f, _p in stage_attrs]

        def on_result(seconds: List[float]) -> None:
            for name, s in zip(attr_names, seconds):
                setattr(self.stats, name, getattr(self.stats, name) + s)

        def on_stall(stall: float, task: WriteShardTask) -> None:
            self.stats.emit_stall_seconds += stall
            if stall > 0.0005:
                record_span("writer.emit.stall", stall,
                            shard=task.shard_id)

        core = _BoundedStagePipeline(
            workers=self.workers,
            window=self.stats.window,
            stage_fns=[f for _a, f, _p in stage_attrs],
            thread_prefixes=[p for _a, _f, p in stage_attrs],
            on_admit=on_admit,
            on_result=on_result,
            on_stall=on_stall,
            drain_on_close=True,
            # "encode_seconds" -> heartbeat stage name "encode", etc.
            stage_names=[a.split("_", 1)[0] for a in attr_names],
            health=self._health if token is not None else None,
            health_token=token,
        )
        inner = core.run(tasks)  # admits the first window eagerly

        def adapt() -> Iterator[WriteShardResult]:
            for idx, value, secs in inner:
                by_attr = dict(zip(attr_names, secs))
                yield WriteShardResult(
                    tasks[idx].shard_id, value,
                    by_attr.get("encode_seconds", 0.0),
                    by_attr.get("deflate_seconds", 0.0),
                    by_attr.get("stage_seconds", 0.0),
                )

        return adapt()


def writer_for_storage(storage) -> ShardWritePipeline:
    """Build the write pipeline from a storage builder's
    ``DisqOptions`` (absent/None ⇒ sequential-compatible defaults).
    Live-introspection knobs resolve here for writes, mirroring
    ``executor_for_storage`` for reads."""
    from disq_tpu.runtime import profiler
    from disq_tpu.runtime.introspect import configure_from_options

    opts = getattr(storage, "_options", None) or DisqOptions()
    flightrec.configure_from_options(opts)
    profiler.configure_from_options(opts)
    return ShardWritePipeline(
        workers=getattr(opts, "writer_workers", 1),
        prefetch_shards=getattr(opts, "writer_prefetch_shards", None),
        health=configure_from_options(opts),
        watchdog_stall_s=getattr(opts, "watchdog_stall_s", None),
        watchdog_policy=getattr(opts, "watchdog_policy", "warn"),
    )


def write_retrier_for_storage(storage, path: Optional[str] = None
                              ) -> ShardRetrier:
    """A fresh per-shard retrier sized from the storage's retry knobs —
    the write-side analogue of ``context_for_storage().for_shard()``
    (writes carry no corrupt-block policy, only transient retry).
    With ``path`` and an armed ``breaker_window``, the retrier is also
    gated by the per-filesystem circuit breaker guarding the output's
    store, and every write retry draws from the shared retry budget."""
    opts = getattr(storage, "_options", None) or DisqOptions()
    breaker = None
    if (getattr(opts, "retry_budget_tokens", None) is not None
            or getattr(opts, "breaker_window", None) is not None):
        from disq_tpu.runtime.resilience import (
            breaker_for,
            configure_globals_from_options,
        )

        configure_globals_from_options(opts)
        if path is not None:
            breaker = breaker_for(path)
    return ShardRetrier(opts.max_retries, opts.retry_backoff_s,
                        breaker=breaker)


def _retrying(fn: Optional[Callable], retries: int) -> Optional[Callable]:
    """``fn`` re-run up to ``retries`` extra times on ANY exception —
    the per-shard Spark-task-retry analogue ``StageManifest.run_stage``
    applies, preserved for checkpointed pipeline runs (the pipeline's
    own ``ShardRetrier`` only retries transient-classified faults)."""
    if fn is None or retries <= 0:
        return fn

    def wrapped(*args: Any):
        last: Optional[BaseException] = None
        for _attempt in range(retries + 1):
            try:
                return fn(*args)
            except Exception as e:  # noqa: BLE001 — shard-level retry
                last = e
        raise last

    return wrapped


def run_write_stage(
    pipeline: ShardWritePipeline,
    n_shards: int,
    make_task: Callable[[int], WriteShardTask],
    manifest=None,
    stage_name: str = "write.parts",
    retries: int = 1,
    storage=None,
    path: Optional[str] = None,
    fs=None,
) -> List[Any]:
    """Run one write stage's shards through ``pipeline``, shard-level
    resumable. With a manifest, shards already recorded are skipped,
    each stage step keeps ``run_stage``'s any-exception shard retry
    (``retries`` extra attempts), and each fresh shard is recorded the
    moment its stage step durably completes — in *completion* order on
    the stage worker, not emit order, so a crash mid-run preserves
    every staged shard even when a straggler holds up the ordered
    emit. Returns the per-shard info list in shard order, mixing
    cached and fresh results.

    With ``storage`` + ``path`` AND a manifest AND the shard scheduler
    armed, the stage instead leases its shards through the coordinator
    (``scheduler.scheduled_write_stage`` — the write direction of the
    distributed data plane, with the manifest as the durable side);
    ``fs`` (the destination filesystem) feeds the worker's block-cache
    locality hint into those leases.  Otherwise this inline path runs
    unchanged, allocating nothing extra."""
    from dataclasses import replace

    if manifest is not None and storage is not None and path is not None:
        from disq_tpu.runtime import scheduler

        if scheduler.write_leasing_armed(storage):
            return scheduler.scheduled_write_stage(
                storage, path, pipeline, n_shards, make_task, manifest,
                stage_name=stage_name, retries=retries, fs=fs)

    infos: List[Any] = [None] * n_shards
    pending: List[int] = []
    for k in range(n_shards):
        if manifest is not None and manifest.is_done(stage_name, k):
            infos[k] = manifest.shard_info(stage_name, k)
        else:
            pending.append(k)

    tasks = []
    for k in pending:
        task = make_task(k)
        if manifest is not None:
            inner = _retrying(task.stage, retries)

            def marked(payload, _inner=inner, _k=k):
                info = _inner(payload) if _inner is not None else payload
                manifest.mark_done(stage_name, _k, info)
                return info

            task = replace(
                task,
                encode=_retrying(task.encode, retries),
                deflate=_retrying(task.deflate, retries),
                stage=marked,
            )
        tasks.append(task)

    for res in pipeline.map_ordered(tasks):
        infos[res.shard_id] = res.value
    return infos
