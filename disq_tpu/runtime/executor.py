"""Pipelined parallel shard executor — overlap fetch, decode, and emit.

The reference gets cross-split parallelism for free from Spark: one
task per split, scheduled across executors. disq_tpu's read path walked
splits one at a time in a single host thread (only the C++ inflate
inside a block batch was threaded), so remote/HTTP reads and
stage-serialized formats (CRAM) were latency-bound. This module is the
Spark-scheduler analogue: a bounded three-stage pipeline shared by
every format source.

- **Stage A — fetch**: ``ShardTask.fetch()`` range-reads the split's
  byte window through the fsw layer (so HTTP prefetch and
  ``FaultInjectingFileSystemWrapper`` compose) and walks/collects its
  compressed structure. Runs on the fetch pool.
- **Stage B — decode**: ``ShardTask.decode(payload)`` inflates and
  parses records. Runs on the decode worker pool.
- **Stage C — emit**: ``map_ordered`` yields results **in shard
  order**, streaming — shard i+1 can be fetching/decoding while shard
  i's result is being consumed.

Guarantees:

- **Order and byte identity.** Results are emitted in task order
  regardless of worker count; the stages run the exact same per-shard
  code the sequential path runs, so output is byte-identical for any
  ``workers``.
- **Sequential-compatible default.** ``workers=1`` runs everything
  inline on the caller's thread in the same call order as the
  pre-executor loop — no threads, no queues.
- **Bounded in-flight window.** At most ``prefetch_shards`` shards past
  the emit frontier are admitted, so a retry storm or a quarantine on
  shard i delays shards ``i+k`` only once they fall inside the window
  (and memory stays bounded by ``window × shard bytes``).
- **ErrorPolicy / ShardRetrier semantics.** Each task carries its own
  per-shard ``ShardRetrier``; transient faults in fetch retry the fetch,
  transient faults escaping decode (salvage re-reads, CRAM reference
  fetch) re-run the shard from fetch under the same retrier. Corrupt
  data follows the shard's ``ErrorPolicy`` exactly as in the sequential
  path; the first raising shard aborts the pipeline.
- **Observability.** Per-stage, per-shard telemetry spans
  (``executor.fetch`` / ``executor.decode`` / ``executor.emit.stall``,
  each labeled with the shard id and feeding the same-named latency
  histogram) plus ``ExecutorStats`` (stage seconds, emit-stall
  seconds, max queue depth) and the ``executor.in_flight`` gauge make
  the overlap measurable, not asserted.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

from disq_tpu.runtime.errors import DisqOptions, ShardRetrier, is_transient
from disq_tpu.runtime.tracing import observe_gauge, record_span, span


@dataclass
class ShardTask:
    """One split's pipeline work. ``fetch`` does the I/O (stage A) and
    returns an opaque payload; ``decode`` turns that payload into the
    shard's result (stage B). Both close over their shard's
    ``ShardErrorContext`` for policy dispatch; ``retrier`` is that
    context's retrier (None ⇒ no transient retry)."""

    shard_id: int
    fetch: Callable[[], Any]
    decode: Callable[[Any], Any]
    retrier: Optional[ShardRetrier] = None
    what: str = "shard"


@dataclass
class ShardResult:
    """Ordered emission unit: the decoded value plus per-stage wall
    time, so emit-side counter assembly can report real shard cost."""

    shard_id: int
    value: Any
    fetch_seconds: float = 0.0
    decode_seconds: float = 0.0

    @property
    def wall_seconds(self) -> float:
        return self.fetch_seconds + self.decode_seconds


@dataclass
class ExecutorStats:
    """Aggregate pipeline observability for one ``map_ordered`` run
    (cumulative across runs on the same executor instance)."""

    workers: int = 0
    window: int = 0
    shards: int = 0
    fetch_seconds: float = 0.0
    decode_seconds: float = 0.0
    emit_stall_seconds: float = 0.0
    max_in_flight: int = 0

    def as_dict(self) -> Dict[str, float]:
        return {
            "workers": self.workers,
            "window": self.window,
            "shards": self.shards,
            "fetch_seconds": round(self.fetch_seconds, 6),
            "decode_seconds": round(self.decode_seconds, 6),
            "emit_stall_seconds": round(self.emit_stall_seconds, 6),
            "max_in_flight": self.max_in_flight,
        }


class ShardPipelineExecutor:
    """Bounded three-stage shard pipeline (see module docstring).

    ``workers`` sizes the decode pool (and the fetch pool — fetches are
    I/O-bound and cheap to oversubscribe, but one pool bound keeps the
    fsw request concurrency predictable). ``prefetch_shards`` bounds
    how many shards past the emit frontier may be in flight; default
    ``2 × workers`` keeps every worker busy while the consumer drains.
    """

    def __init__(self, workers: int = 1,
                 prefetch_shards: Optional[int] = None) -> None:
        self.workers = max(1, int(workers))
        if prefetch_shards is None:
            prefetch_shards = 2 * self.workers
        self.prefetch_shards = max(1, int(prefetch_shards))
        self.stats = ExecutorStats(
            workers=self.workers,
            window=max(self.workers, self.prefetch_shards),
        )

    # -- public -------------------------------------------------------------

    def map_ordered(
        self, tasks: Sequence[ShardTask]
    ) -> Iterator[ShardResult]:
        """Run every task through fetch→decode, yielding results in
        task order as they become ready (streaming — stage C)."""
        tasks = list(tasks)
        self.stats.shards += len(tasks)
        if not tasks:
            return iter(())
        if self.workers == 1:
            return self._run_sequential(tasks)
        return self._run_pipelined(tasks)

    # -- sequential (workers=1): the exact pre-executor call order ----------

    def _run_sequential(self, tasks: List[ShardTask]) -> Iterator[ShardResult]:
        for task in tasks:
            yield self._run_one_inline(task)

    def _run_one_inline(self, task: ShardTask) -> ShardResult:
        """Whole-shard work under ONE retrier budget — identical
        semantics (and retry accounting) to the historical
        ``retrier.call(decode_range, …)`` per-shard loop."""
        times = [0.0, 0.0]

        def attempt():
            t0 = time.perf_counter()
            with span("executor.fetch", shard=task.shard_id):
                payload = task.fetch()
            t1 = time.perf_counter()
            times[0] += t1 - t0
            with span("executor.decode", shard=task.shard_id):
                value = task.decode(payload)
            times[1] += time.perf_counter() - t1
            return value

        if task.retrier is not None:
            value = task.retrier.call(attempt, what=task.what)
        else:
            value = attempt()
        self.stats.fetch_seconds += times[0]
        self.stats.decode_seconds += times[1]
        return ShardResult(task.shard_id, value, times[0], times[1])

    # -- pipelined (workers>1) ----------------------------------------------

    def _run_pipelined(self, tasks: List[ShardTask]) -> Iterator[ShardResult]:
        """Set up the pools and admit the first window EAGERLY (fetches
        are in flight before the caller's first ``next()``), returning
        the ordered-emit generator."""
        window = self.stats.window
        cond = threading.Condition()
        results: Dict[int, ShardResult] = {}
        errors: Dict[int, BaseException] = {}
        state = {"next_admit": 0, "next_emit": 0, "in_flight": 0,
                 "aborted": False}
        fetch_pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="disq-fetch")
        decode_pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="disq-decode")

        def record_error(idx: int, exc: BaseException) -> None:
            with cond:
                errors[idx] = exc
                state["in_flight"] -= 1
                cond.notify_all()

        def decode_job(task: ShardTask, payload: Any, tf: float) -> None:
            t0 = time.perf_counter()
            try:
                with span("executor.decode", shard=task.shard_id):
                    value = self._decode_with_refetch(task, payload)
            except BaseException as e:  # noqa: BLE001 — re-raised at emit
                record_error(task.shard_id, e)
                return
            td = time.perf_counter() - t0
            with cond:
                results[task.shard_id] = ShardResult(
                    task.shard_id, value, tf, td)
                state["in_flight"] -= 1
                self.stats.fetch_seconds += tf
                self.stats.decode_seconds += td
                cond.notify_all()

        def fetch_job(task: ShardTask) -> None:
            with cond:
                if state["aborted"]:
                    state["in_flight"] -= 1
                    cond.notify_all()
                    return
            t0 = time.perf_counter()
            try:
                with span("executor.fetch", shard=task.shard_id):
                    if task.retrier is not None:
                        payload = task.retrier.call(
                            task.fetch, what=f"{task.what}.fetch")
                    else:
                        payload = task.fetch()
            except BaseException as e:  # noqa: BLE001 — re-raised at emit
                record_error(task.shard_id, e)
                return
            decode_pool.submit(decode_job, task, payload,
                               time.perf_counter() - t0)

        def admit_locked() -> None:
            # caller holds cond
            while (not state["aborted"]
                   and state["next_admit"] < len(tasks)
                   and state["next_admit"] < state["next_emit"] + window):
                task = tasks[state["next_admit"]]
                state["next_admit"] += 1
                state["in_flight"] += 1
                if state["in_flight"] > self.stats.max_in_flight:
                    self.stats.max_in_flight = state["in_flight"]
                observe_gauge("executor.in_flight", state["in_flight"])
                fetch_pool.submit(fetch_job, task)

        with cond:
            admit_locked()

        def emit() -> Iterator[ShardResult]:
            try:
                for i in range(len(tasks)):
                    with cond:
                        t0 = time.perf_counter()
                        while i not in results and i not in errors:
                            cond.wait()
                        stall = time.perf_counter() - t0
                        self.stats.emit_stall_seconds += stall
                        if stall > 0.0005:
                            # only meaningful waits become trace spans
                            record_span("executor.emit.stall", stall,
                                        shard=i)
                        if i in errors:
                            state["aborted"] = True
                            raise errors[i]
                        res = results.pop(i)
                        state["next_emit"] = i + 1
                        admit_locked()
                    yield res
            finally:
                with cond:
                    state["aborted"] = True
                fetch_pool.shutdown(wait=False, cancel_futures=True)
                decode_pool.shutdown(wait=False, cancel_futures=True)

        return emit()

    def _decode_with_refetch(self, task: ShardTask, payload: Any) -> Any:
        """Stage B with the transient-escape hatch: decode is normally
        pure CPU over fetched bytes, but the salvage paths (BGZF
        re-sync, VCF line extension) and CRAM reference fetch can issue
        fresh reads. A transient there re-runs the shard from fetch
        under the task's retrier — the bounded equivalent of the
        sequential path's whole-shard retry."""
        try:
            return task.decode(payload)
        except Exception as e:  # noqa: BLE001 — classified below
            if task.retrier is None or not is_transient(e):
                raise
            task.retrier.retried += 1  # the attempt that just failed

            def rerun():
                return task.decode(task.fetch())

            return task.retrier.call(rerun, what=task.what)


def executor_for_storage(storage) -> ShardPipelineExecutor:
    """Build the shard executor from a storage builder's
    ``DisqOptions`` (absent/None ⇒ sequential-compatible defaults)."""
    opts = getattr(storage, "_options", None) or DisqOptions()
    return ShardPipelineExecutor(
        workers=getattr(opts, "executor_workers", 1),
        prefetch_shards=getattr(opts, "prefetch_shards", None),
    )
