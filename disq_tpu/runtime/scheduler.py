"""Cross-host shard scheduler — the distributed data plane.

``runtime/multihost.py`` plans meshes and ``runtime/cluster.py``
aggregates metrics, but until this module a multi-process run was N
*static* partitions: every host decoded ``shards[i::N]`` and the job's
wall clock was the slowest host (ROADMAP item 3).  This is the native
replacement for the retained "Spark driver" scheduling role: one
process (the coordinator) serves a shared shard work-queue over the
existing introspection HTTP plane, and every process — coordinator
included — *leases* shards in small batches instead of receiving a
fixed split.  The GATK-on-Spark cluster study (PAPERS.md 1806.00788)
shows partition skew and straggler executors gate scaling; the
BWT-on-Spark work (PAPERS.md 2107.03341) shows dynamic redistribution
recovers it.  Three mechanisms ride on the queue:

- **Locality-aware assignment.**  A worker's lease request carries its
  HTTP block-cache occupancy (``fsw/http.py`` LRU keys).  The
  coordinator routes a shard to the host whose cache already holds
  blocks of its byte range (``sched.locality{result=hit}``), falling
  back to plain FIFO order (``miss``) — a warm cache never re-downloads
  a range another host would fetch cold.
- **Work stealing.**  An idle worker (empty lease) calls
  ``/sched/steal``: the coordinator reassigns the *oldest* lease held
  past ``steal_after_s`` by the most-loaded other host — the cross-host
  complement of the in-process hedged fetches (``runtime/resilience``).
  First ``/sched/done`` wins; the loser's completion books
  ``sched.dup_done`` and its result is dropped by the losing worker, so
  exactly one host emits each shard.
- **Elastic membership + crash handoff.**  Processes ``/sched/join``
  (and may disappear) mid-run; membership changes are booked as flight
  recorder events.  A lease not completed within ``lease_s`` expires
  back into the queue (``sched.lease_expired``), so a SIGKILLed host's
  unfinished shards are re-leased to survivors.  With a *shared*
  ``ReadLedger`` (``DisqOptions.read_ledger`` on a common directory)
  the dead host's already-completed shards stay completed — their
  decoded spills survive in the ledger — and a successor leasing a
  spilled shard serves it from the ledger instead of re-decoding, so
  handoff never re-does finished work.

Zero overhead when disabled (the default): ``client_for_storage``
returns ``None``, ``scheduled_map_ordered`` falls straight through to
``map_ordered_resumable``, and no coordinator object, thread or socket
exists (``scripts/check_overhead.py`` guards this structurally).

Knobs (``DisqOptions`` fields / env — env wins for the ``sched_*``
tuning knobs so subprocess workers are configured by their launcher):

- ``scheduler`` / ``DISQ_TPU_SCHED``: ``None`` off (default);
  ``"serve"`` host the coordinator in-process (on the introspection
  endpoint) and work; ``"host:port"`` join that coordinator.
- ``sched_lease_n`` / ``DISQ_TPU_SCHED_LEASE_N``: shards per lease
  round (default 2 — small batches are what makes stealing effective).
- ``sched_lease_s`` / ``DISQ_TPU_SCHED_LEASE_S``: lease expiry seconds
  (default 10; the crash-detection latency).
- ``sched_steal`` / ``DISQ_TPU_SCHED_STEAL``: enable stealing
  (default on).
- ``DISQ_TPU_SCHED_HOST``: host identity override (default
  ``p<process_id()>``).
- ``DISQ_TPU_SCHED_STATIC=k,N``: bench/compare mode — lease only
  shards ``≡ k (mod N)`` and exit when that class drains: a *static*
  split expressed through the same machinery, so scheduler-vs-static
  comparisons pay identical RPC overhead.
- ``DISQ_TPU_SCHED_SALT``: appended to the run key so repeated reads
  of the same input register as distinct runs (bench reps).
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from disq_tpu.runtime import flightrec
from disq_tpu.runtime.tracing import (
    REGISTRY,
    counter,
    observe_gauge,
    record_span,
    span,
)

DEFAULT_LEASE_N = 2
DEFAULT_LEASE_S = 10.0
_IDLE_SLEEP_MIN_S = 0.02
_IDLE_SLEEP_MAX_S = 0.25
_RPC_RETRIES = 3
_RPC_BACKOFF_S = 0.05


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------


class _Run:
    """One registered read's queue state on the coordinator."""

    def __init__(self, key: str, path: str,
                 ranges: Dict[int, Optional[Tuple[int, int]]]) -> None:
        self.key = key
        self.path = path
        self.ranges = ranges
        self.joined: set = set()  # hosts that joined THIS pass
        self.epoch = 1            # pass number for this run key
        self.pending: List[int] = sorted(ranges)   # ascending shard ids
        self.leases: Dict[int, Tuple[str, float]] = {}  # shard -> (host, since)
        self.done: Dict[int, str] = {}             # shard -> winning host
        self.requeued: List[int] = []              # expiry-reclaimed shards
        self.stolen: List[int] = []                # steal-reassigned shards
        self.locality_hits = 0
        self.locality_misses = 0

    @property
    def finished(self) -> bool:
        return len(self.done) == len(self.ranges)


class ShardCoordinator:
    """The shared work-queue process 0 serves (see module docstring).

    All state mutations run under one lock; expiry sweeps piggyback on
    every request (no dedicated thread — the scheduler-off path must
    stay thread-free, and the scheduler-on path is request-driven
    anyway).  ``clock`` is injectable for deterministic expiry tests.
    """

    def __init__(self, lease_s: float = DEFAULT_LEASE_S,
                 steal_after_s: Optional[float] = None,
                 clock=time.monotonic) -> None:
        self.lease_s = float(lease_s)
        self.steal_after_s = (float(steal_after_s) if steal_after_s
                              is not None else self.lease_s / 3.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._runs: Dict[str, _Run] = {}
        self._hosts: Dict[str, float] = {}  # host -> last_seen
        self._epochs: Dict[str, int] = {}   # run key -> last pass number

    # -- sweeps -------------------------------------------------------------

    def _sweep_locked(self, now: float) -> None:
        """Reclaim expired leases and drop silent members.  Caller
        holds the lock."""
        expired: List[Tuple[str, str, int]] = []
        for run in self._runs.values():
            for shard, (host, since) in list(run.leases.items()):
                if now - since >= self.lease_s:
                    del run.leases[shard]
                    bisect.insort(run.pending, shard)
                    run.requeued.append(shard)
                    expired.append((run.key, host, shard))
        for host, seen in list(self._hosts.items()):
            if now - seen < 2.0 * self.lease_s:
                continue
            if any(host == h for run in self._runs.values()
                   for h, _s in run.leases.values()):
                continue
            del self._hosts[host]
            flightrec.record_event("sched_member_lost", host=host)
        if expired:
            observe_gauge("sched.members", len(self._hosts))
        for key, host, shard in expired:
            counter("sched.lease_expired").inc()
            flightrec.record_event("sched_lease_expired", host=host,
                                   shard=shard, run=key)

    # -- requests -----------------------------------------------------------

    def join(self, host: str, run: Optional[Dict[str, Any]] = None
             ) -> Dict[str, Any]:
        """Register ``host`` (idempotent) and, when ``run`` carries a
        shard table, register the run (first registration wins; all
        workers compute the identical table from the same input)."""
        now = self._clock()
        with self._lock:
            fresh = host not in self._hosts
            self._hosts[host] = now
            registered = False
            if run and run.get("key"):
                key = str(run["key"])
                existing = self._runs.get(key)
                if (existing is not None and existing.finished
                        and host in existing.joined):
                    # A host that participated in the (now finished)
                    # pass is registering the same input again: that is
                    # a NEW read, not a late same-pass joiner — start a
                    # fresh pass.  A host joining a finished run it
                    # never participated in arrived after the work was
                    # done and correctly emits nothing.
                    del self._runs[key]
                    existing = None
                if existing is None:
                    ranges = {
                        int(sid): (tuple(rng) if rng else None)
                        for sid, rng in (run.get("shards") or {}).items()
                    }
                    fresh_run = _Run(key, str(run.get("path", "")),
                                     ranges)
                    fresh_run.epoch = self._epochs.get(key, 0) + 1
                    self._epochs[key] = fresh_run.epoch
                    self._runs[key] = fresh_run
                    registered = True
                self._runs[key].joined.add(host)
                epoch = self._runs[key].epoch
            else:
                epoch = None
            members = len(self._hosts)
        observe_gauge("sched.members", members)
        if fresh:
            flightrec.record_event("sched_join", host=host)
        return {"host": host, "registered": registered, "members": members,
                "epoch": epoch}

    @staticmethod
    def _locality_score(rng: Optional[Tuple[int, int]],
                        blocks: frozenset, block_size: int) -> int:
        if rng is None or not blocks or block_size <= 0:
            return 0
        lo, hi = rng
        if hi <= lo:
            return 0
        first, last = lo // block_size, (hi - 1) // block_size
        # walk whichever side is smaller: shard spans are a few blocks
        # wide normally, but a tiny reported block_size must not turn
        # one score into a giant range scan
        if last - first + 1 > len(blocks):
            return sum(1 for b in blocks if first <= b <= last)
        return sum(1 for b in range(first, last + 1) if b in blocks)

    def lease(self, host: str, key: str, want: int = DEFAULT_LEASE_N,
              block_size: Optional[int] = None,
              blocks: Optional[Sequence[int]] = None,
              static_of: Optional[Tuple[int, int]] = None,
              epoch: Optional[int] = None) -> Dict[str, Any]:
        """Hand ``host`` up to ``want`` pending shards: locality-scored
        picks first (shards whose byte range overlaps the host's cached
        blocks), then FIFO ascending.  ``static_of=(k, N)`` restricts
        eligibility to ``shard % N == k`` — the static-split compare
        mode."""
        now = self._clock()
        want = max(1, int(want))
        cached = frozenset(int(b) for b in blocks) if blocks else frozenset()
        with self._lock:
            self._hosts[host] = now
            self._sweep_locked(now)
            run = self._runs.get(key)
            if run is None:
                return {"error": f"unknown run {key!r}", "shards": []}
            if epoch is not None and epoch != run.epoch:
                # the caller belongs to a previous pass of this key —
                # its pass is over; it must not drain the new pass
                return {"shards": [], "finished": True, "stale": True}
            eligible = [s for s in run.pending
                        if static_of is None
                        or s % static_of[1] == static_of[0]]
            picked: List[int] = []
            hits = 0
            if cached and block_size:
                scored = sorted(
                    ((self._locality_score(run.ranges.get(s), cached,
                                           int(block_size)), s)
                     for s in eligible),
                    key=lambda t: (-t[0], t[1]))
                for score, s in scored:
                    if len(picked) >= want or score <= 0:
                        break
                    picked.append(s)
                    hits += 1
            for s in eligible:
                if len(picked) >= want:
                    break
                if s not in picked:
                    picked.append(s)
            for s in picked:
                run.pending.remove(s)
                run.leases[s] = (host, now)
            run.locality_hits += hits
            run.locality_misses += len(picked) - hits
            pending_n = len(run.pending)
            outstanding = len(run.leases)
            finished = run.finished
        if picked:
            counter("sched.leases").inc(len(picked), host=host)
            if hits:
                counter("sched.locality").inc(hits, result="hit")
            if len(picked) - hits:
                counter("sched.locality").inc(len(picked) - hits,
                                              result="miss")
        observe_gauge("sched.queue_depth", pending_n)
        return {"shards": sorted(picked), "pending": pending_n,
                "outstanding": outstanding, "finished": finished}

    def done(self, host: str, key: str, shard: int,
             epoch: Optional[int] = None) -> Dict[str, Any]:
        """Mark one shard complete.  First completion wins; a losing
        (stolen-race) completion returns ``won=False`` so the worker
        drops its duplicate result."""
        now = self._clock()
        shard = int(shard)
        with self._lock:
            self._hosts[host] = now
            run = self._runs.get(key)
            if run is None:
                return {"error": f"unknown run {key!r}", "won": False}
            if epoch is not None and epoch != run.epoch:
                # a straggler completion from a previous pass: that
                # pass already finished (reset requires finished), so
                # every shard it could report was already won there
                return {"won": False, "finished": True, "stale": True}
            newly = shard not in run.done
            if newly:
                run.done[shard] = host
                run.leases.pop(shard, None)
                # a late completion of a shard that expired back into
                # the queue still wins — retract the duplicate work
                if shard in run.pending:
                    run.pending.remove(shard)
            # Idempotent for the WINNER: the client retries a done POST
            # whose response was lost — telling the true winner
            # won=False would make it drop the only copy of the shard's
            # result.  Only a DIFFERENT host's completion is a lost
            # race.
            won = run.done[shard] == host
            by_host = sum(1 for h in run.done.values() if h == host)
            finished = run.finished
        if newly:
            REGISTRY.gauge("sched.shards").observe(by_host, host=host)
        elif not won:
            counter("sched.dup_done").inc()
        return {"won": won, "finished": finished}

    def steal(self, host: str, key: str,
              epoch: Optional[int] = None) -> Dict[str, Any]:
        """Reassign to ``host`` the oldest lease held past
        ``steal_after_s`` by the most-loaded *other* host — the
        idle-worker path when the queue is dry but stragglers still
        hold work."""
        now = self._clock()
        with self._lock:
            self._hosts[host] = now
            self._sweep_locked(now)
            run = self._runs.get(key)
            if run is None:
                return {"error": f"unknown run {key!r}", "shards": []}
            if epoch is not None and epoch != run.epoch:
                return {"shards": [], "finished": True, "stale": True}
            stale: Dict[str, List[Tuple[float, int]]] = {}
            for shard, (holder, since) in run.leases.items():
                if holder != host and now - since >= self.steal_after_s:
                    stale.setdefault(holder, []).append((since, shard))
            victim = max(stale, key=lambda h: len(stale[h])) if stale else None
            if victim is None:
                return {"shards": [], "pending": len(run.pending),
                        "outstanding": len(run.leases),
                        "finished": run.finished}
            _since, shard = min(stale[victim])
            run.leases[shard] = (host, now)
            run.stolen.append(shard)
            finished = run.finished
        counter("sched.steals").inc(victim=victim)
        flightrec.record_event("sched_steal", thief=host, victim=victim,
                               shard=shard, run=key)
        return {"shards": [shard], "victim": victim, "finished": finished}

    def stats(self, key: Optional[str] = None) -> Dict[str, Any]:
        """Observability snapshot (``GET /sched/stats``) — what the
        bench and the handoff tests assert on."""
        with self._lock:
            self._sweep_locked(self._clock())
            runs = {}
            for k, run in self._runs.items():
                if key is not None and k != key:
                    continue
                total_local = run.locality_hits + run.locality_misses
                runs[k] = {
                    "path": run.path,
                    "epoch": run.epoch,
                    "shards": len(run.ranges),
                    "pending": list(run.pending),
                    "leases": {str(s): {"host": h, "age_s": round(
                        self._clock() - since, 3)}
                        for s, (h, since) in run.leases.items()},
                    "done": {str(s): h for s, h in run.done.items()},
                    "requeued": list(run.requeued),
                    "stolen": list(run.stolen),
                    "locality_hits": run.locality_hits,
                    "locality_misses": run.locality_misses,
                    "locality_hit_rate": round(
                        run.locality_hits / total_local, 3)
                    if total_local else 0.0,
                    "finished": run.finished,
                }
            return {"members": sorted(self._hosts), "runs": runs}


# ---------------------------------------------------------------------------
# Module coordinator lifecycle + HTTP dispatch (runtime/introspect.py
# routes /sched/* here only when a request arrives — zero overhead off)
# ---------------------------------------------------------------------------

_COORD_LOCK = threading.Lock()
_COORDINATOR: Optional[ShardCoordinator] = None


def active_coordinator() -> Optional[ShardCoordinator]:
    return _COORDINATOR


def serve_coordinator(lease_s: float = DEFAULT_LEASE_S,
                      steal_after_s: Optional[float] = None,
                      port: int = 0) -> str:
    """Host the coordinator in this process on the introspection
    endpoint (started if needed); idempotent.  Returns ``host:port``."""
    global _COORDINATOR
    from disq_tpu.runtime.introspect import start_introspect_server

    with _COORD_LOCK:
        if _COORDINATOR is None:
            _COORDINATOR = ShardCoordinator(lease_s, steal_after_s)
    return start_introspect_server(port)


def stop_coordinator() -> None:
    """Test hook: forget the coordinator (the introspection server, if
    any, keeps running — ``reset_introspection`` owns that)."""
    global _COORDINATOR
    with _COORD_LOCK:
        _COORDINATOR = None


def handle_http(method: str, path: str,
                doc: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
    """Dispatch one ``/sched/*`` request from the introspection server.
    Returns ``(status, json_doc)``."""
    coord = _COORDINATOR
    if coord is None:
        return 409, {"error": "no scheduler coordinator in this process "
                              "(DisqOptions.scheduler='serve' arms one)"}
    op = path[len("/sched/"):]
    try:
        if op == "stats" and method == "GET":
            return 200, coord.stats(doc.get("run"))
        host = str(doc.get("host", ""))
        if not host:
            return 400, {"error": "missing host"}
        if op == "join":
            return 200, coord.join(host, doc.get("run"))
        epoch = doc.get("epoch")
        if epoch is not None:
            epoch = int(epoch)
        if op == "lease":
            static_of = doc.get("static_of")
            return 200, coord.lease(
                host, str(doc.get("run", "")),
                want=int(doc.get("want", DEFAULT_LEASE_N)),
                block_size=doc.get("block_size"),
                blocks=doc.get("blocks"),
                static_of=(tuple(int(x) for x in static_of)
                           if static_of else None),
                epoch=epoch)
        if op == "done":
            return 200, coord.done(host, str(doc.get("run", "")),
                                   int(doc["shard"]), epoch=epoch)
        if op == "steal":
            return 200, coord.steal(host, str(doc.get("run", "")),
                                    epoch=epoch)
    except (KeyError, TypeError, ValueError) as e:
        return 400, {"error": f"bad request: {type(e).__name__}: {e}"}
    return 404, {"error": f"unknown scheduler path {path!r}",
                 "endpoints": ["/sched/join", "/sched/lease",
                               "/sched/done", "/sched/steal",
                               "/sched/stats"]}


# ---------------------------------------------------------------------------
# Worker client
# ---------------------------------------------------------------------------


class SchedulerClient:
    """Worker-side JSON-over-HTTP client for the coordinator plane."""

    def __init__(self, address: str, host: str,
                 lease_n: int = DEFAULT_LEASE_N, steal: bool = True,
                 static_of: Optional[Tuple[int, int]] = None,
                 serves: bool = False, timeout_s: float = 10.0) -> None:
        self.address = address
        self.host = host
        self.lease_n = max(1, int(lease_n))
        self.steal = bool(steal)
        self.static_of = static_of
        self.serves = serves  # this process hosts the coordinator
        self.timeout_s = timeout_s
        self.run_key: Optional[str] = None
        self.epoch: Optional[int] = None  # pass number, set by join()

    def _call(self, op: str, doc: Dict[str, Any]) -> Dict[str, Any]:
        url = f"http://{self.address}/sched/{op}"
        body = json.dumps(doc).encode()
        last: Optional[Exception] = None
        with span("sched.rpc", op=op):
            for attempt in range(_RPC_RETRIES):
                try:
                    req = urllib.request.Request(
                        url, data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(
                            req, timeout=self.timeout_s) as resp:
                        return json.loads(resp.read())
                except urllib.error.HTTPError as e:
                    # coordinator answered: surface its error verbatim
                    try:
                        return json.loads(e.read())
                    except ValueError:
                        raise IOError(
                            f"scheduler {op} failed: HTTP {e.code}") from e
                except (urllib.error.URLError, OSError, ValueError) as e:
                    last = e
                    time.sleep(_RPC_BACKOFF_S * (attempt + 1))
        raise IOError(
            f"scheduler coordinator at {self.address} unreachable "
            f"({op}): {last}") from last

    def join(self, run_doc: Dict[str, Any]) -> Dict[str, Any]:
        self.run_key = str(run_doc["key"])
        resp = self._call("join", {"host": self.host, "run": run_doc})
        self.epoch = resp.get("epoch")
        return resp

    def lease(self, cache: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"host": self.host, "run": self.run_key,
                               "want": self.lease_n, "epoch": self.epoch}
        if cache:
            doc.update(cache)
        if self.static_of is not None:
            doc["static_of"] = list(self.static_of)
        return self._call("lease", doc)

    def steal_once(self) -> Dict[str, Any]:
        return self._call("steal", {"host": self.host,
                                    "run": self.run_key,
                                    "epoch": self.epoch})

    def done(self, shard: int) -> Dict[str, Any]:
        return self._call("done", {"host": self.host, "run": self.run_key,
                                   "shard": int(shard),
                                   "epoch": self.epoch})


def _env_number(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except ValueError:
        return default


def client_for_storage(storage) -> Optional[SchedulerClient]:
    """The scheduler client for one read, or None when the scheduler is
    off (the default — ``scheduled_map_ordered`` then falls through to
    the static path with zero extra work)."""
    from disq_tpu.runtime.errors import DisqOptions
    from disq_tpu.runtime.multihost import process_id

    opts = getattr(storage, "_options", None) or DisqOptions()
    mode = getattr(opts, "scheduler", None)
    if mode is None:
        mode = os.environ.get("DISQ_TPU_SCHED") or None
    if not mode:
        return None
    lease_n = _env_number("DISQ_TPU_SCHED_LEASE_N",
                          getattr(opts, "sched_lease_n", DEFAULT_LEASE_N),
                          int)
    lease_s = _env_number("DISQ_TPU_SCHED_LEASE_S",
                          getattr(opts, "sched_lease_s", DEFAULT_LEASE_S),
                          float)
    steal = bool(_env_number("DISQ_TPU_SCHED_STEAL",
                             1 if getattr(opts, "sched_steal", True) else 0,
                             int))
    static_raw = os.environ.get("DISQ_TPU_SCHED_STATIC")
    static_of = None
    if static_raw:
        try:
            k, n = static_raw.replace("/", ",").split(",")
            static_of = (int(k), int(n))
        except ValueError:
            static_of = None
    serves = mode in ("serve", "1", "coordinator")
    if serves:
        port = getattr(opts, "introspect_port", None)
        address = serve_coordinator(lease_s=lease_s, port=port or 0)
    else:
        address = mode
    host = os.environ.get("DISQ_TPU_SCHED_HOST") or f"p{process_id()}"
    return SchedulerClient(address, host, lease_n=lease_n, steal=steal,
                           static_of=static_of, serves=serves)


# ---------------------------------------------------------------------------
# The scheduled split loop — what the sources call instead of
# map_ordered_resumable
# ---------------------------------------------------------------------------


def _cache_hints(fs, path: str) -> Optional[Dict[str, Any]]:
    """The worker's locality hint: its HTTP block-cache occupancy for
    ``path`` (None for non-HTTP filesystems — every pick then scores as
    a locality miss, which is the truth)."""
    from disq_tpu.fsw.http import HttpFileSystemWrapper

    inner = fs
    if hasattr(fs, "inner"):  # FaultInjectingFileSystemWrapper et al.
        if hasattr(fs, "_strip"):
            path = fs._strip(path)
        inner = fs.inner
    if not isinstance(inner, HttpFileSystemWrapper):
        return None
    return {"block_size": inner.block_size,
            "blocks": inner.cached_block_indices(path)}


def run_key_for(path: str, n_shards: int) -> str:
    salt = os.environ.get("DISQ_TPU_SCHED_SALT", "")
    return f"{path}#{n_shards}" + (f"#{salt}" if salt else "")


def scheduled_map_ordered(storage, fs, path: str, executor, tasks,
                          ledger=None) -> Iterator:
    """``map_ordered_resumable`` behind the shard scheduler: with the
    scheduler off (default) this IS ``map_ordered_resumable`` — same
    iterator, same inline path.  With it on, this worker joins the
    run's shared queue and yields exactly the shards it wins, in
    ascending shard order per lease batch (a single-worker scheduled
    run therefore emits the identical sequence the static path does).

    Handoff contract: with a shared ``ledger`` every emitted shard is
    spilled *before* its ``/sched/done``, so a shard completed by a
    host that later dies never re-decodes — a successor leasing a
    spilled shard serves it from the ledger."""
    from disq_tpu.runtime.executor import map_ordered_resumable

    client = client_for_storage(storage)
    if client is None:
        return map_ordered_resumable(executor, tasks, ledger)
    return _scheduled_iter(client, storage, fs, path, executor,
                           list(tasks), ledger)


def _scheduled_iter(client: SchedulerClient, storage, fs, path: str,
                    executor, tasks: List, ledger) -> Iterator:
    from disq_tpu.runtime.executor import ShardResult, executor_for_storage

    by_id = {t.shard_id: t for t in tasks}
    run_doc = {
        "key": run_key_for(path, len(tasks)),
        "path": path,
        "shards": {
            str(t.shard_id): (list(t.byte_range)
                              if getattr(t, "byte_range", None) else None)
            for t in tasks
        },
    }
    client.join(run_doc)
    # The executor's resilience manager (hedge pool, deadlines) is
    # single-use: its close() runs when one map_ordered exhausts.  The
    # scheduled loop runs one map_ordered PER LEASE BATCH, so with
    # resilience armed each batch gets a fresh executor; without it the
    # one executor is reusable (pools are per-run, health tokens too).
    single_use = getattr(executor, "_resilience", None) is not None
    current = executor
    idle = _IDLE_SLEEP_MIN_S
    finished = False
    while True:
        resp = client.lease(_cache_hints(fs, path))
        if resp.get("error"):
            # coordinator restarted / forgot the run: fail the read
            # loudly — spinning here would hang the caller forever
            raise IOError(f"scheduler lease failed: {resp['error']}")
        ids = list(resp.get("shards") or [])
        if not ids and client.steal and client.static_of is None:
            with span("sched.steal"):
                sresp = client.steal_once()
            if sresp.get("error"):
                raise IOError(f"scheduler steal failed: {sresp['error']}")
            ids = list(sresp.get("shards") or [])
            if not ids and sresp.get("finished"):
                finished = True
        if not ids:
            if resp.get("finished") or finished:
                finished = True
                break
            if client.static_of is not None:
                # static-compare mode: this host's residue class is
                # drained — exit like a static split would
                break
            record_span("sched.wait", idle)
            time.sleep(idle)
            idle = min(_IDLE_SLEEP_MAX_S, idle * 1.7)
            continue
        idle = _IDLE_SLEEP_MIN_S
        ids.sort()
        cached = {i for i in ids
                  if ledger is not None and ledger.is_done(i)}
        if current is None:
            current = executor_for_storage(storage)
        fresh_iter = current.map_ordered(
            [by_id[i] for i in ids if i not in cached])
        for i in ids:
            if i in cached:
                res = ShardResult(i, ledger.load(i))
            else:
                res = next(fresh_iter)
                if ledger is not None:
                    # spill BEFORE done: once the coordinator believes
                    # the shard complete its bytes must be recoverable
                    ledger.record(res.shard_id, res.value)
            if client.done(res.shard_id).get("won", True):
                yield res
            # a lost steal race: the thief already emitted this shard —
            # drop the duplicate so exactly one host owns it
        # exhaust the batch iterator so its close-out (resilience /
        # stage-pool teardown) runs now, not at GC time
        if next(fresh_iter, None) is not None:
            raise RuntimeError("scheduled batch emitted extra shards")
        if single_use:
            current = None
    if finished and client.serves and ledger is not None:
        # the coordinator host observed completion: commit the shared
        # ledger (spills dropped) exactly like the static path's finish
        ledger.finish()
