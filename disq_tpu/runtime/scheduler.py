"""Cross-host shard scheduler — the distributed data plane.

``runtime/multihost.py`` plans meshes and ``runtime/cluster.py``
aggregates metrics, but until this module a multi-process run was N
*static* partitions: every host decoded ``shards[i::N]`` and the job's
wall clock was the slowest host (ROADMAP item 3).  This is the native
replacement for the retained "Spark driver" scheduling role: one
process (the coordinator) serves a shared shard work-queue over the
existing introspection HTTP plane, and every process — coordinator
included — *leases* shards in small batches instead of receiving a
fixed split.  The GATK-on-Spark cluster study (PAPERS.md 1806.00788)
shows partition skew and straggler executors gate scaling; the
BWT-on-Spark work (PAPERS.md 2107.03341) shows dynamic redistribution
recovers it.  Three mechanisms ride on the queue:

- **Locality-aware assignment.**  A worker's lease request carries its
  HTTP block-cache occupancy (``fsw/http.py`` LRU keys).  The
  coordinator routes a shard to the host whose cache already holds
  blocks of its byte range (``sched.locality{result=hit}``), falling
  back to plain FIFO order (``miss``) — a warm cache never re-downloads
  a range another host would fetch cold.
- **Work stealing.**  An idle worker (empty lease) calls
  ``/sched/steal``: the coordinator reassigns the *oldest* lease held
  past ``steal_after_s`` by the most-loaded other host — the cross-host
  complement of the in-process hedged fetches (``runtime/resilience``).
  First ``/sched/done`` wins; the loser's completion books
  ``sched.dup_done`` and its result is dropped by the losing worker, so
  exactly one host emits each shard.
- **Elastic membership + crash handoff.**  Processes ``/sched/join``
  (and may disappear) mid-run; membership changes are booked as flight
  recorder events.  A lease not completed within ``lease_s`` expires
  back into the queue (``sched.lease_expired``), so a SIGKILLed host's
  unfinished shards are re-leased to survivors.  With a *shared*
  ``ReadLedger`` (``DisqOptions.read_ledger`` on a common directory)
  the dead host's already-completed shards stay completed — their
  decoded spills survive in the ledger — and a successor leasing a
  spilled shard serves it from the ledger instead of re-decoding, so
  handoff never re-does finished work.

Control-plane hardening (armed by ``DISQ_TPU_SCHED_FAILOVER`` — a
shared directory; off by default):

- **Coordinator failover.**  The coordinator journals every state
  transition (run registration, join, lease, done, steal, expiry) to a
  durable ``SchedJournal`` (``runtime/manifest.py`` — append-only
  JSONL, fsync'd batches) and advertises its address in
  ``<dir>/coordinator.addr``.  When a worker's RPC fails past its
  in-call retries, it rediscovers: re-read the address file, and if
  the coordinator is truly gone, elect a standby — the *live* member
  (``cluster.probe_liveness`` over ``<dir>/members/``) with the lowest
  ``(process_id, pid, host)``.  The winner takes an ``O_EXCL``
  takeover lock, replays the journal (``replay_journal`` — a pure
  function ``scripts/check_resilience.py`` lints for exactness),
  re-derives the lease table and epoch fencing, rebases lease clocks,
  resumes serving at its own ``/sched/*`` address and re-advertises.
  Losers spin on rediscovery (``CoordinatorLostError`` is transient;
  the backoff rides ``ShardRetrier``) instead of raising.  Shards
  finished before the crash are served from the shared ``ReadLedger``;
  leases in flight at the crash expire and requeue exactly like a
  worker death.
- **Write-direction leasing.**  ``run_write_stage`` stage tasks lease
  through the same coordinator (run key suffixed ``#write``; lease
  docs carry ``dir=write``) with ``StageManifest`` as the durable
  side: a SIGKILL'd writer's staged parts survive in the shared
  manifest, its unfinished write shards requeue to survivors, and the
  multi-host sorted write rides the same membership/steal machinery
  as reads (``scheduled_write_stage``).
- **Multi-run fairness.**  When several runs share one coordinator,
  each lease grant is capped at the run's weighted max-min share of
  in-flight leases (``DisqOptions.sched_run_weight``), so an
  interactive run cannot be starved by a saturating batch pass:
  every run can always hold at least one lease, and surplus capacity
  still flows to whoever asks (``sched.quota.{granted,deferred}``).

Zero overhead when disabled (the default): ``client_for_storage``
returns ``None``, ``scheduled_map_ordered`` falls straight through to
``map_ordered_resumable``, and no coordinator object, thread or socket
exists (``scripts/check_overhead.py`` guards this structurally —
including that failover-off means no journal file and no standby
thread).

Knobs (``DisqOptions`` fields / env — env wins for the ``sched_*``
tuning knobs so subprocess workers are configured by their launcher):

- ``scheduler`` / ``DISQ_TPU_SCHED``: ``None`` off (default);
  ``"serve"`` host the coordinator in-process (on the introspection
  endpoint) and work; ``"host:port"`` join that coordinator.
- ``sched_lease_n`` / ``DISQ_TPU_SCHED_LEASE_N``: shards per lease
  round (default 2 — small batches are what makes stealing effective).
- ``sched_lease_s`` / ``DISQ_TPU_SCHED_LEASE_S``: lease expiry seconds
  (default 10; the crash-detection latency).
- ``sched_steal`` / ``DISQ_TPU_SCHED_STEAL``: enable stealing
  (default on).
- ``DISQ_TPU_SCHED_HOST``: host identity override (default
  ``p<process_id()>``).
- ``DISQ_TPU_SCHED_STATIC=k,N``: bench/compare mode — lease only
  shards ``≡ k (mod N)`` and exit when that class drains: a *static*
  split expressed through the same machinery, so scheduler-vs-static
  comparisons pay identical RPC overhead.
- ``DISQ_TPU_SCHED_SALT``: appended to the run key so repeated reads
  of the same input register as distinct runs (bench reps).
- ``sched_run_weight`` / ``DISQ_TPU_SCHED_WEIGHT``: this run's fairness
  weight (default 1.0) — its max-min share of in-flight leases when
  runs contend.
- ``sched_failover_dir`` / ``DISQ_TPU_SCHED_FAILOVER``: shared
  directory arming coordinator failover (journal + address file +
  member registry).  ``scheduler="auto"`` discovers the coordinator
  address from this directory instead of naming it.
"""

from __future__ import annotations

import bisect
import http.client
import json
import math
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from disq_tpu.runtime import flightrec
from disq_tpu.runtime.tracing import (
    REGISTRY,
    counter,
    inject_trace_headers,
    observe_gauge,
    record_span,
    span,
)

DEFAULT_LEASE_N = 2
DEFAULT_LEASE_S = 10.0
_IDLE_SLEEP_MIN_S = 0.02
_IDLE_SLEEP_MAX_S = 0.25
_RPC_RETRIES = 3
_RPC_BACKOFF_S = 0.05


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------


class _Run:
    """One registered read's queue state on the coordinator."""

    def __init__(self, key: str, path: str,
                 ranges: Dict[int, Optional[Tuple[int, int]]],
                 weight: float = 1.0, direction: str = "read") -> None:
        self.key = key
        self.path = path
        self.ranges = ranges
        self.weight = max(1e-9, float(weight))  # fairness share weight
        self.direction = direction  # "read" | "write" lease direction
        self.joined: set = set()  # hosts that joined THIS pass
        self.epoch = 1            # pass number for this run key
        self.pending: List[int] = sorted(ranges)   # ascending shard ids
        self.leases: Dict[int, Tuple[str, float]] = {}  # shard -> (host, since)
        self.done: Dict[int, str] = {}             # shard -> winning host
        self.requeued: List[int] = []              # expiry-reclaimed shards
        self.stolen: List[int] = []                # steal-reassigned shards
        self.locality_hits = 0
        self.locality_misses = 0

    @property
    def finished(self) -> bool:
        return len(self.done) == len(self.ranges)


class ShardCoordinator:
    """The shared work-queue process 0 serves (see module docstring).

    All state mutations run under one lock; expiry sweeps piggyback on
    every request (no dedicated thread — the scheduler-off path must
    stay thread-free, and the scheduler-on path is request-driven
    anyway).  ``clock`` is injectable for deterministic expiry tests.
    """

    def __init__(self, lease_s: float = DEFAULT_LEASE_S,
                 steal_after_s: Optional[float] = None,
                 clock=time.monotonic, journal=None) -> None:
        self.lease_s = float(lease_s)
        self.steal_after_s = (float(steal_after_s) if steal_after_s
                              is not None else self.lease_s / 3.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._runs: Dict[str, _Run] = {}
        self._hosts: Dict[str, float] = {}  # host -> last_seen
        self._epochs: Dict[str, int] = {}   # run key -> last pass number
        # Failover replication log (manifest.SchedJournal) — None is
        # the zero-overhead default: no journal object, no file.
        self._journal = journal

    def attach_journal(self, journal) -> None:
        self._journal = journal

    def _journal_locked(self, op: str, **fields: Any) -> None:
        """Record one state transition.  Called under the coordinator
        lock so the journal's record order IS the mutation order —
        what makes ``replay_journal`` exact."""
        if self._journal is not None:
            self._journal.append(op, **fields)

    # -- sweeps -------------------------------------------------------------

    def _sweep_locked(self, now: float) -> None:
        """Reclaim expired leases and drop silent members.  Caller
        holds the lock."""
        expired: List[Tuple[str, str, int]] = []
        for run in self._runs.values():
            for shard, (host, since) in list(run.leases.items()):
                if now - since >= self.lease_s:
                    del run.leases[shard]
                    bisect.insort(run.pending, shard)
                    run.requeued.append(shard)
                    expired.append((run.key, host, shard))
                    self._journal_locked("expire", key=run.key,
                                         host=host, shard=shard, t=now)
        for host, seen in list(self._hosts.items()):
            if now - seen < 2.0 * self.lease_s:
                continue
            if any(host == h for run in self._runs.values()
                   for h, _s in run.leases.values()):
                continue
            del self._hosts[host]
            self._journal_locked("member_lost", host=host, t=now)
            flightrec.record_event("sched_member_lost", host=host)
        if expired:
            observe_gauge("sched.members", len(self._hosts))
        for key, host, shard in expired:
            counter("sched.lease_expired").inc()
            flightrec.record_event("sched_lease_expired", host=host,
                                   shard=shard, run=key)

    # -- requests -----------------------------------------------------------

    def join(self, host: str, run: Optional[Dict[str, Any]] = None,
             rejoin: bool = False) -> Dict[str, Any]:
        """Register ``host`` (idempotent) and, when ``run`` carries a
        shard table, register the run (first registration wins; all
        workers compute the identical table from the same input).

        ``rejoin`` marks a failover re-registration: the worker is
        recovering its membership after a coordinator handoff, not
        starting a new read — a rejoin must NEVER restart a finished
        pass (a standby that replayed a completed journal would
        otherwise re-decode every shard)."""
        now = self._clock()
        with self._lock:
            fresh = host not in self._hosts
            self._hosts[host] = now
            registered = False
            if run and run.get("key"):
                key = str(run["key"])
                existing = self._runs.get(key)
                if (existing is not None and existing.finished
                        and host in existing.joined and not rejoin):
                    # A host that participated in the (now finished)
                    # pass is registering the same input again: that is
                    # a NEW read, not a late same-pass joiner — start a
                    # fresh pass.  A host joining a finished run it
                    # never participated in arrived after the work was
                    # done and correctly emits nothing.
                    del self._runs[key]
                    existing = None
                if existing is None:
                    ranges = {
                        int(sid): (tuple(rng) if rng else None)
                        for sid, rng in (run.get("shards") or {}).items()
                    }
                    fresh_run = _Run(key, str(run.get("path", "")),
                                     ranges,
                                     weight=float(run.get("weight")
                                                  or 1.0),
                                     direction=str(run.get("dir")
                                                   or "read"))
                    fresh_run.epoch = self._epochs.get(key, 0) + 1
                    self._epochs[key] = fresh_run.epoch
                    self._runs[key] = fresh_run
                    registered = True
                    self._journal_locked(
                        "run", key=key, path=fresh_run.path,
                        shards={str(s): (list(r) if r else None)
                                for s, r in ranges.items()},
                        epoch=fresh_run.epoch, weight=fresh_run.weight,
                        dir=fresh_run.direction, host=host, t=now)
                self._runs[key].joined.add(host)
                epoch = self._runs[key].epoch
            else:
                key = None
                epoch = None
            self._journal_locked("join", host=host, key=key, t=now)
            members = len(self._hosts)
        observe_gauge("sched.members", members)
        if fresh:
            flightrec.record_event("sched_join", host=host)
        return {"host": host, "registered": registered, "members": members,
                "epoch": epoch}

    @staticmethod
    def _locality_score(rng: Optional[Tuple[int, int]],
                        blocks: frozenset, block_size: int) -> int:
        if rng is None or not blocks or block_size <= 0:
            return 0
        lo, hi = rng
        if hi <= lo:
            return 0
        first, last = lo // block_size, (hi - 1) // block_size
        # walk whichever side is smaller: shard spans are a few blocks
        # wide normally, but a tiny reported block_size must not turn
        # one score into a giant range scan
        if last - first + 1 > len(blocks):
            return sum(1 for b in blocks if first <= b <= last)
        return sum(1 for b in range(first, last + 1) if b in blocks)

    def _quota_locked(self, run: _Run, want: int) -> Tuple[int, int]:
        """Weighted max-min fairness cap: when another unfinished run
        has pending work, ``run`` may only grow its in-flight leases to
        its weighted share of the total (its weight over the sum of
        contending runs' weights), never below one — every run always
        progresses; a run alone on the coordinator is never throttled.
        Returns ``(granted_cap, deferred)``; deferred == 0 means the
        quota didn't engage or didn't bind."""
        contending = [r for r in self._runs.values()
                      if not r.finished and (r.pending or r.leases)]
        others_waiting = any(r is not run and r.pending
                             for r in contending)
        if not others_waiting:
            return want, 0
        total_weight = sum(r.weight for r in contending) or run.weight
        in_flight = sum(len(r.leases) for r in contending)
        share = max(1, math.ceil(
            (in_flight + want) * run.weight / total_weight))
        cap = max(0, share - len(run.leases))
        return min(want, cap), max(0, want - cap)

    def lease(self, host: str, key: str, want: int = DEFAULT_LEASE_N,
              block_size: Optional[int] = None,
              blocks: Optional[Sequence[int]] = None,
              static_of: Optional[Tuple[int, int]] = None,
              epoch: Optional[int] = None,
              direction: Optional[str] = None) -> Dict[str, Any]:
        """Hand ``host`` up to ``want`` pending shards: locality-scored
        picks first (shards whose byte range overlaps the host's cached
        blocks), then FIFO ascending.  ``static_of=(k, N)`` restricts
        eligibility to ``shard % N == k`` — the static-split compare
        mode.  ``direction`` (``dir=`` on the wire) must match the
        run's registered lease direction when given — a read loop
        leasing a write run's key is a caller bug worth failing."""
        now = self._clock()
        want = max(1, int(want))
        cached = frozenset(int(b) for b in blocks) if blocks else frozenset()
        with self._lock:
            self._hosts[host] = now
            self._sweep_locked(now)
            run = self._runs.get(key)
            if run is None:
                return {"error": f"unknown run {key!r}", "shards": []}
            if direction is not None and direction != run.direction:
                return {"error": f"run {key!r} leases dir="
                                 f"{run.direction}, not dir={direction}",
                        "shards": []}
            if epoch is not None and epoch != run.epoch:
                # the caller belongs to a previous pass of this key —
                # its pass is over; it must not drain the new pass
                return {"shards": [], "finished": True, "stale": True}
            want, deferred = self._quota_locked(run, want)
            eligible = [s for s in run.pending
                        if static_of is None
                        or s % static_of[1] == static_of[0]] if want else []
            picked: List[int] = []
            hits = 0
            if cached and block_size:
                scored = sorted(
                    ((self._locality_score(run.ranges.get(s), cached,
                                           int(block_size)), s)
                     for s in eligible),
                    key=lambda t: (-t[0], t[1]))
                for score, s in scored:
                    if len(picked) >= want or score <= 0:
                        break
                    picked.append(s)
                    hits += 1
            for s in eligible:
                if len(picked) >= want:
                    break
                if s not in picked:
                    picked.append(s)
            for s in picked:
                run.pending.remove(s)
                run.leases[s] = (host, now)
            if picked:
                self._journal_locked("lease", key=key, host=host,
                                     shards=list(picked), t=now)
            run.locality_hits += hits
            run.locality_misses += len(picked) - hits
            pending_n = len(run.pending)
            outstanding = len(run.leases)
            finished = run.finished
        if picked:
            counter("sched.leases").inc(len(picked), host=host)
            if hits:
                counter("sched.locality").inc(hits, result="hit")
            if len(picked) - hits:
                counter("sched.locality").inc(len(picked) - hits,
                                              result="miss")
        if deferred:
            # the fairness quota engaged and bound this grant
            counter("sched.quota.deferred").inc(deferred)
            if picked:
                counter("sched.quota.granted").inc(len(picked))
        observe_gauge("sched.queue_depth", pending_n)
        return {"shards": sorted(picked), "pending": pending_n,
                "outstanding": outstanding, "finished": finished}

    def done(self, host: str, key: str, shard: int,
             epoch: Optional[int] = None) -> Dict[str, Any]:
        """Mark one shard complete.  First completion wins; a losing
        (stolen-race) completion returns ``won=False`` so the worker
        drops its duplicate result."""
        now = self._clock()
        shard = int(shard)
        with self._lock:
            self._hosts[host] = now
            run = self._runs.get(key)
            if run is None:
                return {"error": f"unknown run {key!r}", "won": False}
            if epoch is not None and epoch != run.epoch:
                # a straggler completion from a previous pass: that
                # pass already finished (reset requires finished), so
                # every shard it could report was already won there
                return {"won": False, "finished": True, "stale": True}
            newly = shard not in run.done
            if newly:
                run.done[shard] = host
                run.leases.pop(shard, None)
                # a late completion of a shard that expired back into
                # the queue still wins — retract the duplicate work
                if shard in run.pending:
                    run.pending.remove(shard)
                self._journal_locked("done", key=key, host=host,
                                     shard=shard, t=now)
            # Idempotent for the WINNER: the client retries a done POST
            # whose response was lost — telling the true winner
            # won=False would make it drop the only copy of the shard's
            # result.  Only a DIFFERENT host's completion is a lost
            # race.
            won = run.done[shard] == host
            by_host = sum(1 for h in run.done.values() if h == host)
            finished = run.finished
        if newly:
            REGISTRY.gauge("sched.shards").observe(by_host, host=host)
        elif not won:
            counter("sched.dup_done").inc()
        return {"won": won, "finished": finished}

    def steal(self, host: str, key: str,
              epoch: Optional[int] = None) -> Dict[str, Any]:
        """Reassign to ``host`` the oldest lease held past
        ``steal_after_s`` by the most-loaded *other* host — the
        idle-worker path when the queue is dry but stragglers still
        hold work."""
        now = self._clock()
        with self._lock:
            self._hosts[host] = now
            self._sweep_locked(now)
            run = self._runs.get(key)
            if run is None:
                return {"error": f"unknown run {key!r}", "shards": []}
            if epoch is not None and epoch != run.epoch:
                return {"shards": [], "finished": True, "stale": True}
            stale: Dict[str, List[Tuple[float, int]]] = {}
            for shard, (holder, since) in run.leases.items():
                if holder != host and now - since >= self.steal_after_s:
                    stale.setdefault(holder, []).append((since, shard))
            victim = max(stale, key=lambda h: len(stale[h])) if stale else None
            if victim is None:
                return {"shards": [], "pending": len(run.pending),
                        "outstanding": len(run.leases),
                        "finished": run.finished}
            _since, shard = min(stale[victim])
            run.leases[shard] = (host, now)
            run.stolen.append(shard)
            self._journal_locked("steal", key=key, host=host,
                                 victim=victim, shard=shard, t=now)
            finished = run.finished
        counter("sched.steals").inc(victim=victim)
        flightrec.record_event("sched_steal", thief=host, victim=victim,
                               shard=shard, run=key)
        return {"shards": [shard], "victim": victim, "finished": finished}

    def stats(self, key: Optional[str] = None) -> Dict[str, Any]:
        """Observability snapshot (``GET /sched/stats``) — what the
        bench and the handoff tests assert on."""
        with self._lock:
            self._sweep_locked(self._clock())
            runs = {}
            for k, run in self._runs.items():
                if key is not None and k != key:
                    continue
                total_local = run.locality_hits + run.locality_misses
                runs[k] = {
                    "path": run.path,
                    "epoch": run.epoch,
                    "dir": run.direction,
                    "weight": run.weight,
                    "shards": len(run.ranges),
                    "pending": list(run.pending),
                    "leases": {str(s): {"host": h, "age_s": round(
                        self._clock() - since, 3)}
                        for s, (h, since) in run.leases.items()},
                    "done": {str(s): h for s, h in run.done.items()},
                    "requeued": list(run.requeued),
                    "stolen": list(run.stolen),
                    "locality_hits": run.locality_hits,
                    "locality_misses": run.locality_misses,
                    "locality_hit_rate": round(
                        run.locality_hits / total_local, 3)
                    if total_local else 0.0,
                    "finished": run.finished,
                }
            return {"members": sorted(self._hosts), "runs": runs}

    # -- failover -----------------------------------------------------------

    def state_fingerprint(self) -> Dict[str, Any]:
        """The canonical queue state — epoch fencing plus every run's
        full lease table (pending / leases-with-timestamps / done /
        requeued / stolen).  ``replay_journal`` over a coordinator's
        journal must reproduce this EXACTLY (``check_resilience.py``
        lints the invariant); telemetry-only fields (locality counts,
        host heartbeats) are deliberately excluded."""
        with self._lock:
            runs: Dict[str, Any] = {}
            for k, run in self._runs.items():
                runs[k] = {
                    "path": run.path,
                    "dir": run.direction,
                    "weight": run.weight,
                    "epoch": run.epoch,
                    "joined": sorted(run.joined),
                    "ranges": {str(s): (list(r) if r else None)
                               for s, r in sorted(run.ranges.items())},
                    "pending": list(run.pending),
                    "leases": {str(s): [h, t] for s, (h, t)
                               in sorted(run.leases.items())},
                    "done": {str(s): h for s, h
                             in sorted(run.done.items())},
                    "requeued": list(run.requeued),
                    "stolen": list(run.stolen),
                }
            return {"epochs": dict(self._epochs), "runs": runs}

    def rebase_clock(self, clock=time.monotonic) -> None:
        """Shift replayed lease/heartbeat timestamps into THIS
        process's monotonic timebase (the journal's ``t`` values come
        from the dead coordinator's clock, which shares no origin with
        ours).  The newest replayed timestamp maps to "now", so
        relative lease ages are preserved: leases the dead coordinator
        believed fresh get a full ``lease_s`` to complete or expire
        back into the queue — the same fencing a worker death gets."""
        with self._lock:
            last = 0.0
            for run in self._runs.values():
                for _h, since in run.leases.values():
                    last = max(last, since)
            for seen in self._hosts.values():
                last = max(last, seen)
            delta = clock() - last
            for run in self._runs.values():
                run.leases = {s: (h, since + delta)
                              for s, (h, since) in run.leases.items()}
            self._hosts = {h: seen + delta
                           for h, seen in self._hosts.items()}
            self._clock = clock


def replay_journal(records: Sequence[Dict[str, Any]],
                   lease_s: float = DEFAULT_LEASE_S,
                   steal_after_s: Optional[float] = None
                   ) -> ShardCoordinator:
    """Rebuild a coordinator from its ``SchedJournal`` records — the
    standby's takeover path, and a PURE function of the record list:
    no clock reads, no I/O, no journaling.  Records are applied in
    order exactly as the dead coordinator's locked mutations ran, so
    ``replayed.state_fingerprint() == dead.state_fingerprint()``
    (``scripts/check_resilience.py`` lints this).  The caller rebases
    the clock (``rebase_clock``) before serving."""
    last_t = 0.0
    coord = ShardCoordinator(lease_s, steal_after_s,
                             clock=lambda: last_t)
    runs = coord._runs
    for rec in records:
        op = rec.get("op")
        t = float(rec.get("t") or 0.0)
        last_t = max(last_t, t)
        key = rec.get("key")
        run = runs.get(key) if key is not None else None
        if op == "run":
            ranges = {int(s): (tuple(r) if r else None)
                      for s, r in (rec.get("shards") or {}).items()}
            fresh = _Run(str(key), str(rec.get("path", "")), ranges,
                         weight=float(rec.get("weight") or 1.0),
                         direction=str(rec.get("dir") or "read"))
            fresh.epoch = int(rec.get("epoch") or 1)
            coord._epochs[str(key)] = fresh.epoch
            runs[str(key)] = fresh
            fresh.joined.add(str(rec.get("host", "")))
        elif op == "join":
            coord._hosts[str(rec.get("host", ""))] = t
            if run is not None:
                run.joined.add(str(rec.get("host", "")))
        elif op == "lease" and run is not None:
            host = str(rec.get("host", ""))
            coord._hosts[host] = t
            for s in rec.get("shards") or []:
                s = int(s)
                if s in run.pending:
                    run.pending.remove(s)
                run.leases[s] = (host, t)
        elif op == "done" and run is not None:
            host = str(rec.get("host", ""))
            coord._hosts[host] = t
            shard = int(rec["shard"])
            if shard not in run.done:
                run.done[shard] = host
                run.leases.pop(shard, None)
                if shard in run.pending:
                    run.pending.remove(shard)
        elif op == "steal" and run is not None:
            host = str(rec.get("host", ""))
            coord._hosts[host] = t
            shard = int(rec["shard"])
            run.leases[shard] = (host, t)
            run.stolen.append(shard)
        elif op == "expire" and run is not None:
            shard = int(rec["shard"])
            if run.leases.pop(shard, None) is not None:
                bisect.insort(run.pending, shard)
            run.requeued.append(shard)
        elif op == "member_lost":
            coord._hosts.pop(str(rec.get("host", "")), None)
        # "takeover" and unknown future ops: membership/provenance
        # markers, no queue effect
    return coord


# ---------------------------------------------------------------------------
# Module coordinator lifecycle + HTTP dispatch (runtime/introspect.py
# routes /sched/* here only when a request arrives — zero overhead off)
# ---------------------------------------------------------------------------

_COORD_LOCK = threading.Lock()
_COORDINATOR: Optional[ShardCoordinator] = None
_JOURNAL = None  # manifest.SchedJournal when failover is armed


def active_coordinator() -> Optional[ShardCoordinator]:
    return _COORDINATOR


def active_journal():
    """The coordinator's failover journal, or None (the default —
    ``check_overhead.py`` asserts failover-off keeps this None and
    writes no journal file)."""
    return _JOURNAL


# -- failover directory layout ----------------------------------------------
#
# <failover_dir>/
#   journal.jsonl      SchedJournal — the coordinator's replication log
#   coordinator.addr   JSON {address, host, pid, process_id} (atomic)
#   members/<host>.json  one per worker: {host, process_id, pid, endpoint}
#   takeover.lock      O_EXCL election guard (owner pid inside)


def _failover_paths(failover_dir: str) -> Dict[str, str]:
    return {
        "journal": os.path.join(failover_dir, "journal.jsonl"),
        "addr": os.path.join(failover_dir, "coordinator.addr"),
        "members": os.path.join(failover_dir, "members"),
        "lock": os.path.join(failover_dir, "takeover.lock"),
    }


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    import tempfile

    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".addr-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, "r") as f:
            doc = json.load(f)
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def advertise_coordinator(failover_dir: str, address: str) -> None:
    """Publish the live coordinator's address (atomic rename — a
    reader sees the old or the new document, never a torn one)."""
    from disq_tpu.runtime.multihost import process_id

    _atomic_write_json(_failover_paths(failover_dir)["addr"], {
        "address": address,
        "pid": os.getpid(),
        "process_id": process_id(),
    })


def discover_coordinator(failover_dir: str,
                         wait_s: float = 10.0) -> str:
    """Resolve the coordinator address from the failover directory
    (``scheduler="auto"``), waiting up to ``wait_s`` for the
    coordinator to advertise on a cold start."""
    addr_path = _failover_paths(failover_dir)["addr"]
    deadline = time.monotonic() + wait_s
    while True:
        doc = _read_json(addr_path)
        if doc and doc.get("address"):
            return str(doc["address"])
        if time.monotonic() >= deadline:
            raise IOError(
                f"no scheduler coordinator advertised in "
                f"{failover_dir!r} after {wait_s:.1f}s")
        time.sleep(0.05)


def register_member(failover_dir: str, host: str, endpoint: str) -> None:
    """Enroll this process in the standby electorate: its liveness
    endpoint and election key (process_id, pid)."""
    from disq_tpu.runtime.multihost import process_id

    members = _failover_paths(failover_dir)["members"]
    _atomic_write_json(os.path.join(members, f"{host}.json"), {
        "host": host,
        "process_id": process_id(),
        "pid": os.getpid(),
        "endpoint": endpoint,
    })


def _list_members(failover_dir: str) -> List[Dict[str, Any]]:
    members_dir = _failover_paths(failover_dir)["members"]
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(members_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        doc = _read_json(os.path.join(members_dir, name))
        if doc and doc.get("endpoint"):
            out.append(doc)
    return out


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError):
        return False
    return True


def _acquire_takeover_lock(failover_dir: str, host: str) -> bool:
    """One standby wins the right to replay: ``O_EXCL`` create; a lock
    whose recorded owner pid is dead is stale and reclaimed (the
    winning standby crashed mid-takeover)."""
    lock_path = _failover_paths(failover_dir)["lock"]
    payload = json.dumps({"host": host, "pid": os.getpid()})
    for _attempt in (0, 1):
        try:
            fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            return True
        except FileExistsError:
            owner = _read_json(lock_path)
            if owner is not None and _pid_alive(owner.get("pid", -1)):
                return False
            try:  # stale lock: owner died mid-takeover — reclaim
                os.unlink(lock_path)
            except OSError:
                return False
    return False


def _release_takeover_lock(failover_dir: str) -> None:
    try:
        os.unlink(_failover_paths(failover_dir)["lock"])
    except OSError:
        pass


def serve_coordinator(lease_s: float = DEFAULT_LEASE_S,
                      steal_after_s: Optional[float] = None,
                      port: int = 0,
                      failover_dir: Optional[str] = None) -> str:
    """Host the coordinator in this process on the introspection
    endpoint (started if needed); idempotent.  Returns ``host:port``.
    With ``failover_dir`` the coordinator journals every transition
    there and advertises its address for standby rediscovery."""
    global _COORDINATOR, _JOURNAL
    from disq_tpu.runtime.introspect import start_introspect_server

    with _COORD_LOCK:
        if _COORDINATOR is None:
            journal = None
            if failover_dir:
                from disq_tpu.runtime.manifest import SchedJournal

                journal = SchedJournal(
                    _failover_paths(failover_dir)["journal"])
            _COORDINATOR = ShardCoordinator(lease_s, steal_after_s,
                                            journal=journal)
            _JOURNAL = journal
    address = start_introspect_server(port)
    if failover_dir and _JOURNAL is not None:
        advertise_coordinator(failover_dir, address)
    return address


def adopt_coordinator(coord: ShardCoordinator, journal=None,
                      port: int = 0) -> str:
    """Install a REPLAYED coordinator in this process (the standby's
    takeover) and serve it on this process's introspection endpoint.
    Returns the address to advertise."""
    global _COORDINATOR, _JOURNAL
    from disq_tpu.runtime.introspect import start_introspect_server

    with _COORD_LOCK:
        if journal is not None:
            coord.attach_journal(journal)
        _COORDINATOR = coord
        _JOURNAL = journal
    return start_introspect_server(port)


def stop_coordinator() -> None:
    """Test hook: forget the coordinator (the introspection server, if
    any, keeps running — ``reset_introspection`` owns that)."""
    global _COORDINATOR, _JOURNAL
    with _COORD_LOCK:
        _COORDINATOR = None
        journal, _JOURNAL = _JOURNAL, None
    if journal is not None:
        journal.close()


def handle_http(method: str, path: str,
                doc: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
    """Dispatch one ``/sched/*`` request from the introspection server.
    Returns ``(status, json_doc)``."""
    coord = _COORDINATOR
    if coord is None:
        return 409, {"error": "no scheduler coordinator in this process "
                              "(DisqOptions.scheduler='serve' arms one)"}
    op = path[len("/sched/"):]
    try:
        if op == "stats" and method == "GET":
            return 200, coord.stats(doc.get("run"))
        host = str(doc.get("host", ""))
        if not host:
            return 400, {"error": "missing host"}
        if op == "join":
            return 200, coord.join(host, doc.get("run"),
                                   rejoin=bool(doc.get("rejoin")))
        epoch = doc.get("epoch")
        if epoch is not None:
            epoch = int(epoch)
        if op == "lease":
            static_of = doc.get("static_of")
            direction = doc.get("dir")
            return 200, coord.lease(
                host, str(doc.get("run", "")),
                want=int(doc.get("want", DEFAULT_LEASE_N)),
                block_size=doc.get("block_size"),
                blocks=doc.get("blocks"),
                static_of=(tuple(int(x) for x in static_of)
                           if static_of else None),
                epoch=epoch,
                direction=(str(direction) if direction else None))
        if op == "done":
            return 200, coord.done(host, str(doc.get("run", "")),
                                   int(doc["shard"]), epoch=epoch)
        if op == "steal":
            return 200, coord.steal(host, str(doc.get("run", "")),
                                    epoch=epoch)
    except (KeyError, TypeError, ValueError) as e:
        return 400, {"error": f"bad request: {type(e).__name__}: {e}"}
    return 404, {"error": f"unknown scheduler path {path!r}",
                 "endpoints": ["/sched/join", "/sched/lease",
                               "/sched/done", "/sched/steal",
                               "/sched/stats"]}


# ---------------------------------------------------------------------------
# Worker client
# ---------------------------------------------------------------------------


class SchedulerClient:
    """Worker-side JSON-over-HTTP client for the coordinator plane.

    With ``failover_dir`` set, an RPC that exhausts its in-call retries
    does NOT raise: the client rediscovers the coordinator — re-read
    the advertised address, or (when this process is the lowest live
    member) take over by replaying the journal — and retries the call
    on a ``ShardRetrier`` backoff (``resilience.rediscovery_retrier``),
    raising the transient ``CoordinatorLostError`` only when the whole
    rediscovery budget drains."""

    def __init__(self, address: str, host: str,
                 lease_n: int = DEFAULT_LEASE_N, steal: bool = True,
                 static_of: Optional[Tuple[int, int]] = None,
                 serves: bool = False, timeout_s: float = 10.0,
                 weight: float = 1.0,
                 failover_dir: Optional[str] = None,
                 lease_s: float = DEFAULT_LEASE_S,
                 direction: str = "read") -> None:
        self.address = address
        self.host = host
        self.lease_n = max(1, int(lease_n))
        self.steal = bool(steal)
        self.static_of = static_of
        self.serves = serves  # this process hosts the coordinator
        self.timeout_s = timeout_s
        self.weight = float(weight)
        self.failover_dir = failover_dir
        self.lease_s = float(lease_s)  # replay parameter on takeover
        self.direction = direction
        self.run_key: Optional[str] = None
        self.epoch: Optional[int] = None  # pass number, set by join()
        self._run_doc: Optional[Dict[str, Any]] = None  # for rejoin

    def _call_once(self, op: str, doc: Dict[str, Any]) -> Dict[str, Any]:
        url = f"http://{self.address}/sched/{op}"
        body = json.dumps(doc).encode()
        last: Optional[Exception] = None
        with span("sched.rpc", op=op):
            for attempt in range(_RPC_RETRIES):
                try:
                    req = urllib.request.Request(
                        url, data=body,
                        headers=inject_trace_headers(
                            {"Content-Type": "application/json"}))
                    with urllib.request.urlopen(
                            req, timeout=self.timeout_s) as resp:
                        return json.loads(resp.read())
                except urllib.error.HTTPError as e:
                    # coordinator answered: surface its error verbatim
                    # (a death mid-error-body still lands in failover)
                    try:
                        return json.loads(e.read())
                    except (ValueError, OSError,
                            http.client.HTTPException):
                        raise IOError(
                            f"scheduler {op} failed: HTTP {e.code}") from e
                except (urllib.error.URLError, OSError, ValueError,
                        http.client.HTTPException) as e:
                    # HTTPException covers IncompleteRead: a coordinator
                    # SIGKILLed mid-response-body raises it from
                    # resp.read(), and it is NOT an OSError — it must
                    # still land in the retry/failover ladder, not kill
                    # the worker.
                    last = e
                    time.sleep(_RPC_BACKOFF_S * (attempt + 1))
        raise IOError(
            f"scheduler coordinator at {self.address} unreachable "
            f"({op}): {last}") from last

    def _call(self, op: str, doc: Dict[str, Any]) -> Dict[str, Any]:
        try:
            return self._call_once(op, doc)
        except IOError:
            if not self.failover_dir:
                raise  # failover off: PR 12's fail-loudly contract
            return self._call_failover(op, doc)

    # -- failover: rediscovery + standby election ---------------------------

    def _call_failover(self, op: str,
                       doc: Dict[str, Any]) -> Dict[str, Any]:
        from disq_tpu.runtime.errors import CoordinatorLostError
        from disq_tpu.runtime.resilience import rediscovery_retrier

        flightrec.record_event("sched_coordinator_lost",
                               address=self.address, op=op)

        def attempt() -> Dict[str, Any]:
            self._rediscover()
            # a rejoin during rediscovery may have moved the epoch
            fresh = dict(doc)
            if "epoch" in fresh:
                fresh["epoch"] = self.epoch
            try:
                return self._call_once(op, fresh)
            except IOError as e:
                raise CoordinatorLostError(
                    "scheduler coordinator lost",
                    address=self.address, op=op) from e

        return rediscovery_retrier().call(attempt, what="sched")

    def _rediscover(self) -> None:
        """One rediscovery step: prefer the advertised address (some
        standby already took over), else run the election and take
        over ourselves if we are the lowest live member."""
        paths = _failover_paths(self.failover_dir)
        info = _read_json(paths["addr"])
        advertised = str(info.get("address", "")) if info else ""
        if advertised and advertised != self.address:
            self.address = advertised
            counter("sched.failover.rediscoveries").inc()
            flightrec.record_event("sched_rediscovered",
                                   address=advertised, host=self.host)
            self._rejoin()
            return
        if self.serves and active_coordinator() is not None:
            return  # we ARE the (possibly just-adopted) coordinator
        self._maybe_takeover()

    def _election_key(self, member: Dict[str, Any]) -> Tuple:
        return (int(member.get("process_id") or 0),
                int(member.get("pid") or 0),
                str(member.get("host") or ""))

    def _maybe_takeover(self) -> None:
        from disq_tpu.runtime.cluster import probe_liveness

        members = _list_members(self.failover_dir)
        if not members:
            return
        alive = probe_liveness([m["endpoint"] for m in members],
                               timeout_s=1.0)
        live = sorted((m for m in members if alive.get(m["endpoint"])),
                      key=self._election_key)
        if not live:
            return
        winner = live[0]
        if (str(winner.get("host")) != self.host
                or int(winner.get("pid") or -1) != os.getpid()):
            return  # a lower-ranked live member owns the takeover
        if not _acquire_takeover_lock(self.failover_dir, self.host):
            return
        try:
            # Re-check under the lock: another standby may have won a
            # previous election and already be serving.
            info = _read_json(_failover_paths(self.failover_dir)["addr"])
            if (info and str(info.get("address", "")) != self.address
                    and _pid_alive(info.get("pid", -1))):
                self.address = str(info["address"])
                self._rejoin()
                return
            self._take_over_locked()
        finally:
            _release_takeover_lock(self.failover_dir)

    def _take_over_locked(self) -> None:
        """Replay the journal and become the coordinator (the standby
        promotion path; the takeover lock is held)."""
        from disq_tpu.runtime.manifest import SchedJournal

        paths = _failover_paths(self.failover_dir)
        records = SchedJournal.load(paths["journal"])
        coord = replay_journal(records, lease_s=self.lease_s)
        coord.rebase_clock()
        journal = SchedJournal(paths["journal"])
        address = adopt_coordinator(coord, journal)
        journal.append("takeover", host=self.host, pid=os.getpid())
        advertise_coordinator(self.failover_dir, address)
        self.address = address
        self.serves = True
        counter("sched.failover.takeovers").inc(host=self.host)
        flightrec.record_event("sched_takeover", host=self.host,
                               address=address,
                               replayed=len(records))
        self._rejoin()

    def _rejoin(self) -> None:
        """Re-register with the (new) coordinator using the join doc
        cached at join() — replay preserved the run, so this is a
        heartbeat that refreshes our epoch."""
        if self._run_doc is None:
            return
        try:
            resp = self._call_once("join", {"host": self.host,
                                            "run": self._run_doc,
                                            "rejoin": True})
        except IOError:
            return  # the next retrier attempt rediscovers again
        if resp.get("epoch") is not None:
            self.epoch = resp.get("epoch")

    def _with_rejoin(self, op: str,
                     doc: Dict[str, Any]) -> Dict[str, Any]:
        """Absorb an ``unknown run`` error by rejoining and retrying
        once: a coordinator restarted between our join and this call
        (the failover window) must not abort an otherwise-healthy
        worker."""
        resp = self._call(op, doc)
        err = resp.get("error")
        if (isinstance(err, str) and "unknown run" in err
                and self._run_doc is not None):
            jr = self._call("join", {"host": self.host,
                                     "run": self._run_doc,
                                     "rejoin": True})
            if jr.get("epoch") is not None:
                self.epoch = jr.get("epoch")
            doc = dict(doc)
            if "epoch" in doc:
                doc["epoch"] = self.epoch
            flightrec.record_event("sched_rejoin", host=self.host,
                                   op=op, run=self.run_key)
            resp = self._call(op, doc)
        return resp

    # -- the four RPCs ------------------------------------------------------

    def join(self, run_doc: Dict[str, Any]) -> Dict[str, Any]:
        self.run_key = str(run_doc["key"])
        run_doc.setdefault("weight", self.weight)
        if self.direction != "read":
            run_doc.setdefault("dir", self.direction)
        self._run_doc = run_doc
        resp = self._call("join", {"host": self.host, "run": run_doc})
        self.epoch = resp.get("epoch")
        return resp

    def lease(self, cache: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"host": self.host, "run": self.run_key,
                               "want": self.lease_n, "epoch": self.epoch}
        if cache:
            doc.update(cache)
        if self.static_of is not None:
            doc["static_of"] = list(self.static_of)
        if self.direction != "read":
            doc["dir"] = self.direction
        return self._with_rejoin("lease", doc)

    def steal_once(self) -> Dict[str, Any]:
        return self._with_rejoin("steal", {"host": self.host,
                                           "run": self.run_key,
                                           "epoch": self.epoch})

    def done(self, shard: int) -> Dict[str, Any]:
        return self._with_rejoin("done",
                                 {"host": self.host, "run": self.run_key,
                                  "shard": int(shard),
                                  "epoch": self.epoch})


def _env_number(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return cast(raw)
    except ValueError:
        return default


def client_for_storage(storage,
                       direction: str = "read"
                       ) -> Optional[SchedulerClient]:
    """The scheduler client for one read (or, with
    ``direction="write"``, one write stage), or None when the scheduler
    is off (the default — ``scheduled_map_ordered`` then falls through
    to the static path with zero extra work)."""
    from disq_tpu.runtime.errors import DisqOptions
    from disq_tpu.runtime.multihost import process_id

    opts = getattr(storage, "_options", None) or DisqOptions()
    mode = getattr(opts, "scheduler", None)
    if mode is None:
        mode = os.environ.get("DISQ_TPU_SCHED") or None
    if not mode:
        return None
    lease_n = _env_number("DISQ_TPU_SCHED_LEASE_N",
                          getattr(opts, "sched_lease_n", DEFAULT_LEASE_N),
                          int)
    lease_s = _env_number("DISQ_TPU_SCHED_LEASE_S",
                          getattr(opts, "sched_lease_s", DEFAULT_LEASE_S),
                          float)
    steal = bool(_env_number("DISQ_TPU_SCHED_STEAL",
                             1 if getattr(opts, "sched_steal", True) else 0,
                             int))
    weight = _env_number("DISQ_TPU_SCHED_WEIGHT",
                         getattr(opts, "sched_run_weight", 1.0), float)
    failover_dir = (os.environ.get("DISQ_TPU_SCHED_FAILOVER")
                    or getattr(opts, "sched_failover_dir", None))
    static_raw = os.environ.get("DISQ_TPU_SCHED_STATIC")
    static_of = None
    if static_raw:
        try:
            k, n = static_raw.replace("/", ",").split(",")
            static_of = (int(k), int(n))
        except ValueError:
            static_of = None
    serves = mode in ("serve", "1", "coordinator")
    host = os.environ.get("DISQ_TPU_SCHED_HOST") or f"p{process_id()}"
    if serves:
        port = getattr(opts, "introspect_port", None)
        address = serve_coordinator(lease_s=lease_s, port=port or 0,
                                    failover_dir=failover_dir)
        if failover_dir:
            register_member(failover_dir, host, address)
    elif mode == "auto":
        if not failover_dir:
            raise ValueError(
                "scheduler='auto' discovers the coordinator through "
                "the failover directory — set "
                "DisqOptions.sched_failover_dir or "
                "DISQ_TPU_SCHED_FAILOVER")
        address = discover_coordinator(failover_dir)
    else:
        address = mode
    if failover_dir and not serves:
        # Enroll in the standby electorate: this worker must be
        # liveness-probeable (and able to host an adopted coordinator),
        # so it serves the introspection plane too.
        from disq_tpu.runtime.introspect import start_introspect_server

        endpoint = start_introspect_server(0)
        register_member(failover_dir, host, endpoint)
    return SchedulerClient(address, host, lease_n=lease_n, steal=steal,
                           static_of=static_of, serves=serves,
                           weight=weight, failover_dir=failover_dir,
                           lease_s=lease_s, direction=direction)


# ---------------------------------------------------------------------------
# The scheduled split loop — what the sources call instead of
# map_ordered_resumable
# ---------------------------------------------------------------------------


def _cache_hints(fs, path: str) -> Optional[Dict[str, Any]]:
    """The worker's locality hint: its HTTP block-cache occupancy for
    ``path`` (None for non-HTTP filesystems — every pick then scores as
    a locality miss, which is the truth)."""
    from disq_tpu.fsw.http import HttpFileSystemWrapper

    inner = fs
    if hasattr(fs, "inner"):  # FaultInjectingFileSystemWrapper et al.
        if hasattr(fs, "_strip"):
            path = fs._strip(path)
        inner = fs.inner
    if not isinstance(inner, HttpFileSystemWrapper):
        return None
    return {"block_size": inner.block_size,
            "blocks": inner.cached_block_indices(path)}


def run_key_for(path: str, n_shards: int,
                direction: str = "read") -> str:
    salt = os.environ.get("DISQ_TPU_SCHED_SALT", "")
    key = f"{path}#{n_shards}" + (f"#{salt}" if salt else "")
    # the write stage of a sorted save shares the coordinator with the
    # read that feeds it — distinct keys keep the queues distinct
    return key + "#write" if direction == "write" else key


def scheduled_map_ordered(storage, fs, path: str, executor, tasks,
                          ledger=None) -> Iterator:
    """``map_ordered_resumable`` behind the shard scheduler: with the
    scheduler off (default) this IS ``map_ordered_resumable`` — same
    iterator, same inline path.  With it on, this worker joins the
    run's shared queue and yields exactly the shards it wins, in
    ascending shard order per lease batch (a single-worker scheduled
    run therefore emits the identical sequence the static path does).

    Handoff contract: with a shared ``ledger`` every emitted shard is
    spilled *before* its ``/sched/done``, so a shard completed by a
    host that later dies never re-decodes — a successor leasing a
    spilled shard serves it from the ledger."""
    from disq_tpu.runtime.executor import map_ordered_resumable

    client = client_for_storage(storage)
    if client is None:
        return map_ordered_resumable(executor, tasks, ledger)
    return _scheduled_iter(client, storage, fs, path, executor,
                           list(tasks), ledger)


def _scheduled_iter(client: SchedulerClient, storage, fs, path: str,
                    executor, tasks: List, ledger) -> Iterator:
    from disq_tpu.runtime.executor import ShardResult, executor_for_storage

    by_id = {t.shard_id: t for t in tasks}
    run_doc = {
        "key": run_key_for(path, len(tasks)),
        "path": path,
        "shards": {
            str(t.shard_id): (list(t.byte_range)
                              if getattr(t, "byte_range", None) else None)
            for t in tasks
        },
    }
    client.join(run_doc)
    # The executor's resilience manager (hedge pool, deadlines) is
    # single-use: its close() runs when one map_ordered exhausts.  The
    # scheduled loop runs one map_ordered PER LEASE BATCH, so with
    # resilience armed each batch gets a fresh executor; without it the
    # one executor is reusable (pools are per-run, health tokens too).
    single_use = getattr(executor, "_resilience", None) is not None
    current = executor
    idle = _IDLE_SLEEP_MIN_S
    finished = False
    while True:
        resp = client.lease(_cache_hints(fs, path))
        if resp.get("error"):
            # coordinator restarted / forgot the run: fail the read
            # loudly — spinning here would hang the caller forever
            raise IOError(f"scheduler lease failed: {resp['error']}")
        ids = list(resp.get("shards") or [])
        if not ids and client.steal and client.static_of is None:
            with span("sched.steal"):
                sresp = client.steal_once()
            if sresp.get("error"):
                raise IOError(f"scheduler steal failed: {sresp['error']}")
            ids = list(sresp.get("shards") or [])
            if not ids and sresp.get("finished"):
                finished = True
        if not ids:
            if resp.get("finished") or finished:
                finished = True
                break
            if client.static_of is not None:
                # static-compare mode: this host's residue class is
                # drained — exit like a static split would
                break
            record_span("sched.wait", idle)
            time.sleep(idle)
            idle = min(_IDLE_SLEEP_MAX_S, idle * 1.7)
            continue
        idle = _IDLE_SLEEP_MIN_S
        ids.sort()
        cached = {i for i in ids
                  if ledger is not None and ledger.is_done(i)}
        if current is None:
            current = executor_for_storage(storage)
        fresh_iter = current.map_ordered(
            [by_id[i] for i in ids if i not in cached])
        for i in ids:
            if i in cached:
                res = ShardResult(i, ledger.load(i))
            else:
                res = next(fresh_iter)
                if ledger is not None:
                    # spill BEFORE done: once the coordinator believes
                    # the shard complete its bytes must be recoverable
                    ledger.record(res.shard_id, res.value)
            if client.done(res.shard_id).get("won", True):
                yield res
            # a lost steal race: the thief already emitted this shard —
            # drop the duplicate so exactly one host owns it
        # exhaust the batch iterator so its close-out (resilience /
        # stage-pool teardown) runs now, not at GC time
        if next(fresh_iter, None) is not None:
            raise RuntimeError("scheduled batch emitted extra shards")
        if single_use:
            current = None
    if finished and client.serves and ledger is not None:
        # the coordinator host observed completion: commit the shared
        # ledger (spills dropped) exactly like the static path's finish
        ledger.finish()


# ---------------------------------------------------------------------------
# Write-direction leasing — what run_write_stage routes through when
# the scheduler is armed and a StageManifest provides the durable side
# ---------------------------------------------------------------------------


def write_leasing_armed(storage) -> bool:
    """Whether write stages should lease through the coordinator —
    the same mode check ``client_for_storage`` makes, without building
    a client (so the off path allocates nothing:
    ``scripts/check_overhead.py`` asserts this stays False by
    default)."""
    opts = getattr(storage, "_options", None)
    mode = getattr(opts, "scheduler", None) if opts is not None else None
    if mode is None:
        mode = os.environ.get("DISQ_TPU_SCHED") or None
    return bool(mode)


def scheduled_write_stage(storage, path: str, pipeline, n_shards: int,
                          make_task, manifest,
                          stage_name: str = "write.parts",
                          retries: int = 1, fs=None) -> List[Any]:
    """``run_write_stage`` behind the shard scheduler: the write
    stage's shards lease through the same coordinator as reads (run
    key suffixed ``#write``, lease docs carry ``dir=write``) with the
    shared ``StageManifest`` as the durable side.

    Durability contract: every completed shard is ``mark_done``'d as
    its part lands and the manifest is flushed (merge + atomic rename
    + fsync) once per lease batch BEFORE the batch's ``/sched/done``
    calls — so any shard the coordinator believes complete has a
    durable manifest record, and a SIGKILL'd writer loses at most the
    in-flight batch, whose shards expire back to survivors.  Stealing
    is disabled in the write direction: a stolen write would stage the
    same part twice concurrently; crash recovery goes through lease
    expiry alone.  Returns the per-shard info list in shard order,
    assembling other hosts' infos from the shared manifest.

    Locality: tasks carrying a ``byte_range`` (the sink's estimated
    output byte range per part) register it in the run doc, and each
    lease ships the worker's block-cache occupancy for ``path``
    (``fs`` permitting) — write leases then route through the exact
    locality scoring read leases use, so contiguous parts land on the
    host already holding neighboring output blocks instead of pure
    FIFO.  Range-less tasks keep the FIFO behavior."""
    from dataclasses import replace

    from disq_tpu.runtime.executor import _retrying, run_write_stage

    client = client_for_storage(storage, direction="write")
    if client is None:
        return run_write_stage(pipeline, n_shards, make_task,
                               manifest=manifest, stage_name=stage_name,
                               retries=retries)
    # several processes mark into one manifest file: merge-on-flush,
    # and batch the rewrite+fsync behind a small interval
    manifest.mark_shared(flush_interval_s=0.05)
    # one task build per shard (the closures are cheap): the byte
    # ranges go into the run doc now, the same objects serve the lease
    # loop below
    raw_tasks = {k: make_task(k) for k in range(n_shards)}
    client.join({
        "key": run_key_for(path, n_shards, direction="write"),
        "path": path,
        "shards": {
            str(k): (list(t.byte_range)
                     if getattr(t, "byte_range", None) else None)
            for k, t in raw_tasks.items()
        },
        "dir": "write",
    })
    # resume: report manifest-recorded shards done so they never lease
    for k in range(n_shards):
        if manifest.is_done(stage_name, k):
            client.done(k)

    def task_for(k: int):
        task = raw_tasks[k]
        inner = _retrying(task.stage, retries)

        def marked(payload, _inner=inner, _k=k):
            info = _inner(payload) if _inner is not None else payload
            manifest.mark_done(stage_name, _k, info)
            return info

        return replace(task, encode=_retrying(task.encode, retries),
                       deflate=_retrying(task.deflate, retries),
                       stage=marked)

    idle = _IDLE_SLEEP_MIN_S
    while True:
        resp = client.lease(_cache_hints(fs, path)
                            if fs is not None else None)
        if resp.get("error"):
            raise IOError(
                f"scheduler write lease failed: {resp['error']}")
        ids = sorted(resp.get("shards") or [])
        if not ids:
            if resp.get("finished"):
                break
            record_span("sched.wait", idle)
            time.sleep(idle)
            idle = min(_IDLE_SLEEP_MAX_S, idle * 1.7)
            continue
        idle = _IDLE_SLEEP_MIN_S
        fresh = [k for k in ids
                 if not manifest.is_done(stage_name, k)]
        for _res in pipeline.map_ordered([task_for(k) for k in fresh]):
            pass  # infos are assembled from the manifest below
        manifest.flush()  # durable BEFORE the coordinator learns
        for k in ids:
            client.done(k)
    manifest.flush()
    # other hosts' shard infos live only in the shared file
    manifest.reload()
    return [manifest.shard_info(stage_name, k) for k in range(n_shards)]
