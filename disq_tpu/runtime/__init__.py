"""Runtime auxiliary subsystems (SURVEY.md §5).

The reference delegates failure handling to Spark (task retry, lineage
re-execution) and contributes only the idempotent temp-dir write
protocol. JAX has no task retry, so the equivalents here are:

- ``manifest`` — a deterministic, restartable *stage manifest* on disk:
  which shard ranges have been decoded/sorted/written, with shard-level
  re-execution on restart and the same temp-dir commit protocol; plus
  the ``QuarantineManifest`` sidecar ledger for corrupt blocks.
- ``errors`` — the read-path error policy: ``ShardRetrier`` (bounded
  backoff retry of transient faults), ``ErrorPolicy``
  (strict/skip/quarantine dispatch of corrupt blocks), and
  ``CorruptBlockError`` with full (path, shard, block, voffset)
  coordinates.
- ``executor`` — the shard-pipeline executor: a bounded three-stage
  fetch → decode → ordered-emit pipeline shared by every format
  source, overlapping range-reads, inflate and record decode across
  splits (``DisqOptions.executor_workers`` / ``prefetch_shards``);
  plus its write-direction twin ``ShardWritePipeline`` (encode →
  deflate → stage, ``DisqOptions.writer_workers``) shared by every
  format sink.
- ``counters`` — per-shard counters (records, blocks, bytes,
  compression ratio) returned per shard and reduced.
- ``tracing`` — the structured telemetry layer: a labeled
  ``MetricsRegistry`` (counters / gauges / histograms, Prometheus
  ``metrics_text()``), per-shard ``span`` timelines with a bounded
  ring + JSONL sink (``DISQ_TPU_TRACE_JSONL``, Chrome/Perfetto
  export), and the ``jax.profiler`` bridge (``trace_phase``,
  ``DISQ_TPU_TRACE_DIR``).
- ``resilience`` — adaptive, closed-loop fault handling layered on
  ``errors``/``executor``: hedged shard fetches from a rolling latency
  quantile (``DisqOptions.hedge_quantile``), per-shard deadlines with
  a retry → hedge → quarantine escalation ladder
  (``shard_deadline_s``), a process-wide retry token bucket
  (``retry_budget_tokens``) and per-filesystem circuit breakers
  (``breaker_window``) that fail fast during fault storms — all free
  when disabled.
- ``introspect`` — the live half of observability: an opt-in
  in-process HTTP endpoint (``/metrics`` / ``/healthz`` /
  ``/progress`` / ``/spans``; ``DisqOptions.introspect_port`` /
  ``DISQ_TPU_INTROSPECT_PORT``), a heartbeat watchdog flagging shards
  whose active pipeline stage went silent past
  ``DisqOptions.watchdog_stall_s`` (policy ``warn`` | ``abort``), and
  a progress/ETA reporter with an optional periodic JSONL log
  (``DisqOptions.progress_log``).
- ``flightrec`` — the postmortem half of observability: a bounded
  event ring of recent decisions (retries, hedges, breaker
  transitions, watchdog stalls, quarantines) and, on any abort path,
  a postmortem bundle directory (thread stacks, metrics snapshot,
  span tail, event ring, ledger tails, resolved options;
  ``DisqOptions.postmortem_dir`` / ``DISQ_TPU_POSTMORTEM_DIR``) that
  ``scripts/trace_report.py --postmortem`` renders; plus
  ``faulthandler`` wiring for native crashes.
- ``profiler`` — the in-process sampling profiler: folded stacks
  keyed by the canonical ``disq-*`` thread names attribute CPU per
  pipeline stage, exported as collapsed-stack / speedscope
  (``DisqOptions.profile_hz`` / ``DISQ_TPU_PROFILE_HZ``, the
  ``/debug/profile`` endpoint, ``trace_report.py --flame``).
- ``cluster`` — the cross-host half of observability: a
  ``ClusterAggregator`` scraping N processes' introspection endpoints
  and serving a merged ``/metrics`` / ``/progress`` / ``/healthz``
  rollup with per-process labels, plus fleet-wide ``/debug/stacks`` /
  ``/debug/profile`` collection (CLI:
  ``scripts/metrics_aggregate.py``).
- ``multihost`` — multi-process jax scaffold: axis planning, the
  global (dcn, shards) mesh, and the ``process_id()`` identity every
  introspection endpoint labels its output with.
- ``debug`` — a debug mode (``DISQ_TPU_DEBUG=1``) asserting
  shard-boundary invariants (record counts, offset monotonicity)
  after each phase.
- ``device_service`` — the cross-shard device decode service
  (``DISQ_TPU_DEVICE_SERVICE=1``): one dispatcher owning the device
  queue, coalescing concurrently-decoding shards' BGZF/rANS blocks
  into full 128-lane SIMD launches with per-shard error isolation
  and zero-copy array-native unpack; nothing exists when disabled.
"""

from disq_tpu.runtime.counters import (  # noqa: F401
    PipelineCounters,
    ShardCounters,
    reduce_counters,
)
from disq_tpu.runtime.errors import (  # noqa: F401
    BreakerOpenError,
    CoordinatorLostError,
    CorruptBlockError,
    DeadlineExceededError,
    DisqOptions,
    ErrorPolicy,
    ShardErrorContext,
    ShardRetrier,
    TransientIOError,
    TruncatedReadError,
    WatchdogStallError,
    context_for_storage,
    is_transient,
)
from disq_tpu.runtime.executor import (  # noqa: F401
    ExecutorStats,
    ShardPipelineExecutor,
    ShardResult,
    ShardTask,
    ShardWritePipeline,
    WriteShardResult,
    WriteShardTask,
    WriterStats,
    executor_for_storage,
    map_ordered_resumable,
    read_ledger_for_storage,
    run_write_stage,
    write_retrier_for_storage,
    writer_for_storage,
)
from disq_tpu.runtime.resilience import (  # noqa: F401
    CircuitBreaker,
    HedgeController,
    ResilienceManager,
    RetryBudget,
    ShardDeadline,
    resilience_for_options,
    reset_resilience,
)
from disq_tpu.runtime.cluster import (  # noqa: F401
    ClusterAggregator,
    parse_metrics_text,
)
from disq_tpu.runtime.multihost import (  # noqa: F401
    process_count,
    process_id,
)
from disq_tpu.runtime.scheduler import (  # noqa: F401
    SchedulerClient,
    ShardCoordinator,
    client_for_storage,
    scheduled_map_ordered,
    serve_coordinator,
)
from disq_tpu.runtime.introspect import (  # noqa: F401
    HEALTH,
    PipelineHealth,
    introspect_address,
    note_shard_counters,
    start_introspect_server,
    start_progress_log,
    stop_introspect_server,
    stop_progress_log,
)
from disq_tpu.runtime.flightrec import (  # noqa: F401
    FlightRecorder,
    record_event,
    reset_flightrec,
    thread_stacks_text,
)
from disq_tpu.runtime.profiler import (  # noqa: F401
    SamplingProfiler,
    active_profiler,
    profile_for,
    reset_profiler,
    start_profiler,
    stop_profiler,
)
from disq_tpu.runtime.columnar import (  # noqa: F401
    ColumnarBatch,
    as_read_batch,
    concat_batches,
    resident_decode_enabled,
)
from disq_tpu.runtime.manifest import (  # noqa: F401
    QuarantineManifest,
    ReadLedger,
    StageManifest,
)
from disq_tpu.runtime.tracing import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
    chrome_trace_events,
    count_transfer,
    counter,
    device_span,
    export_chrome_trace,
    gauge,
    hbm_resident,
    synced_timer,
    track_hbm,
    gauge_report,
    histogram,
    metrics_text,
    observe_gauge,
    phase_report,
    record_span,
    reset_telemetry,
    span,
    spans,
    start_span_log,
    stop_span_log,
    telemetry_snapshot,
    telemetry_summary,
    trace_phase,
    wrap_span,
)
from disq_tpu.runtime.debug import (  # noqa: F401
    debug_enabled,
    check_read_batch,
    check_voffsets,
)
