"""Runtime auxiliary subsystems (SURVEY.md §5).

The reference delegates failure handling to Spark (task retry, lineage
re-execution) and contributes only the idempotent temp-dir write
protocol. JAX has no task retry, so the equivalents here are:

- ``manifest`` — a deterministic, restartable *stage manifest* on disk:
  which shard ranges have been decoded/sorted/written, with shard-level
  re-execution on restart and the same temp-dir commit protocol.
- ``counters`` — per-shard counters (records, blocks, bytes,
  compression ratio) returned per shard and reduced.
- ``tracing`` — phase wrappers around ``jax.profiler`` traces plus
  wall-clock structured logs (``DISQ_TPU_TRACE_DIR`` emits perfetto
  traces).
- ``debug`` — a debug mode (``DISQ_TPU_DEBUG=1``) asserting
  shard-boundary invariants (record counts, offset monotonicity)
  after each phase.
"""

from disq_tpu.runtime.counters import (  # noqa: F401
    PipelineCounters,
    ShardCounters,
    reduce_counters,
)
from disq_tpu.runtime.manifest import StageManifest  # noqa: F401
from disq_tpu.runtime.tracing import trace_phase, phase_report  # noqa: F401
from disq_tpu.runtime.debug import (  # noqa: F401
    debug_enabled,
    check_read_batch,
    check_voffsets,
)
