"""Serving plane — a long-lived multi-tenant interval-query daemon
(ROADMAP item 2).

Everything before this module is batch-job shaped: one process, one
read, one write. The workload this system reproduces is fundamentally
*many concurrent region queries over shared indexed files*, so this
module composes the pieces PRs 5–12 already shipped into a serving
path measured in p50/p99 latency under concurrency:

- **Endpoints** ride the existing introspection HTTP plane
  (``runtime/introspect.py``): ``POST /query/reads``,
  ``POST /query/variants``, ``POST /query/stats``, the operator-suite
  queries ``POST /query/markdup-stats`` / ``POST /query/pileup`` /
  ``POST /query/filtered-count`` (``ops/markdup.py`` / ``ops/pileup.py``
  / ``ops/rfilter.py`` over the parsed tier),
  ``GET /serve/stats``, ``GET /serve/cachemap`` (the cache-locality
  digest the fleet router in ``runtime/fleet.py`` consumes) and
  ``POST /serve/register`` all funnel through
  :func:`handle_http`, resolved lazily by the handler so the serve-off
  path imports and allocates nothing.
- **Cross-request device batching**: every cache-missing BGZF block a
  request needs is submitted to the device decode service
  (``runtime/device_service.py``) in one ``submit_inflate`` batch, so
  concurrent tenants' independent requests coalesce into full 128-lane
  inflate launches — the cross-shard coalescing the service already
  does within one run, applied across requests.
- **Shared hot-block cache**: a process-wide two-tier LRU keyed
  ``(path, coffset)`` — tier "compressed" holds raw BGZF block bytes
  (saves the storage round-trip), tier "decoded" holds inflated
  payloads (saves the inflate) — with per-tenant byte accounting, so a
  hot region never pays inflate twice no matter which tenant warmed it.
- **Per-tenant QoS**: admission control in the spirit of
  ``runtime/resilience.py``'s RetryBudget/CircuitBreaker — each tenant
  gets a fixed number of concurrency slots plus a bounded wait queue;
  past that, requests are shed with HTTP 429 so one abusive tenant
  cannot blow up everyone else's p99.
- **Index/header LRU**: parsed headers and BAI/TBI indexes are cached
  per path, keyed by ``(path, size, mtime)`` so a rewritten file
  invalidates naturally.

Zero-overhead-when-off contract (guarded by
``scripts/check_overhead.py``): no daemon, no cache, no admission
state and no thread exists until :func:`start_serve` runs;
:func:`serve_if_running` NEVER creates, and :func:`handle_http`
answers 503 without allocating when the daemon is down. The daemon
itself owns no threads — requests execute on the introspect server's
request threads.
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from disq_tpu.runtime.flightrec import record_event
from disq_tpu.runtime.tracing import (
    TraceContext, activate_trace, counter, current_trace,
    deactivate_trace, gauge, histogram, mint_trace, record_span,
    trace_requests_enabled)

DEFAULT_TENANT = "anon"

# Two-tier cache defaults (bytes). Decoded payloads are ~3-4x the
# compressed blocks for genomic data, so the decoded tier gets more.
DEFAULT_COMPRESSED_CACHE_MB = 64
DEFAULT_DECODED_CACHE_MB = 128
DEFAULT_PARSED_CACHE_MB = 128
DEFAULT_TENANT_SLOTS = 4
DEFAULT_TENANT_QUEUE = 16
DEFAULT_INDEX_CACHE_ENTRIES = 16

_BGZF_FOOTER = 8

# Cache-locality digest granularity: one bucket per 64 KiB of
# compressed-file offset. BGZF blocks are <= 64 KiB, so every cached
# block lands in one or two buckets — coarse enough that a replica's
# digest stays a few hundred ints, fine enough that the fleet router's
# overlap score mirrors the shard scheduler's block-locality signal.
DIGEST_BUCKET_BITS = 16
# Bounded op log backing incremental /serve/cachemap refresh; a router
# whose `since` has scrolled off gets a full map instead.
DIGEST_LOG_CAP = 4096


def digest_buckets(cb: int, ce: int) -> Tuple[int, ...]:
    """Digest buckets covered by virtual-offset chunk ``[cb, ce)`` —
    shared by the cache (put/evict accounting) and the fleet router
    (scoring a query's chunks against replica digests) so both sides
    key ``(path, coffset range)`` with identical math."""
    lo = (cb >> 16) >> DIGEST_BUCKET_BITS
    hi = max(lo, ((ce >> 16) >> DIGEST_BUCKET_BITS))
    return tuple(range(lo, hi + 1))


class AdmissionShed(Exception):
    """Request shed by per-tenant admission control (HTTP 429)."""

    def __init__(self, tenant: str, reason: str) -> None:
        super().__init__(f"tenant {tenant!r} shed: {reason}")
        self.tenant = tenant
        self.reason = reason


class TenantAdmission:
    """Per-tenant concurrency slots + bounded wait queue.

    A tenant holds at most ``slots`` requests in flight; up to
    ``queue_depth`` more may wait for a slot; anything beyond that is
    shed immediately (the caller maps :class:`AdmissionShed` to 429).
    Queue wait is booked as a ``serve.admission.wait`` span so
    ``trace_report --analyze`` can attribute p99 to queuing.
    """

    def __init__(self, slots: int = DEFAULT_TENANT_SLOTS,
                 queue_depth: int = DEFAULT_TENANT_QUEUE,
                 wait_timeout_s: float = 30.0) -> None:
        if slots < 1:
            raise ValueError(f"tenant slots must be >= 1, got {slots}")
        if queue_depth < 0:
            raise ValueError(
                f"tenant queue depth must be >= 0, got {queue_depth}")
        self.slots = slots
        self.queue_depth = queue_depth
        self.wait_timeout_s = wait_timeout_s
        self._cond = threading.Condition()
        self._active: Dict[str, int] = {}
        self._queued: Dict[str, int] = {}
        # tenant -> enqueue timestamps of waiters still in the queue,
        # so /serve/stats can report head-of-line blocking (oldest
        # waiter age) before a wait timeout fires
        self._waiting: Dict[str, List[float]] = {}

    def acquire(self, tenant: str) -> None:
        adm = counter("serve.admission")
        with self._cond:
            if self._active.get(tenant, 0) < self.slots:
                self._active[tenant] = self._active.get(tenant, 0) + 1
                adm.inc(result="admit", tenant=tenant)
                return
            if self._queued.get(tenant, 0) >= self.queue_depth:
                adm.inc(result="shed", tenant=tenant)
                raise AdmissionShed(
                    tenant,
                    f"{self._active.get(tenant, 0)} active, "
                    f"{self._queued.get(tenant, 0)} queued")
            t0 = time.perf_counter()
            self._queued[tenant] = self._queued.get(tenant, 0) + 1
            self._waiting.setdefault(tenant, []).append(t0)
            adm.inc(result="queued", tenant=tenant)
            deadline = t0 + self.wait_timeout_s
            try:
                while self._active.get(tenant, 0) >= self.slots:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        adm.inc(result="shed", tenant=tenant)
                        raise AdmissionShed(tenant, "queue wait timeout")
                    self._cond.wait(remaining)
                self._active[tenant] = self._active.get(tenant, 0) + 1
            finally:
                self._queued[tenant] -= 1
                self._waiting[tenant].remove(t0)
                record_span("serve.admission.wait",
                            time.perf_counter() - t0, tenant=tenant)

    def release(self, tenant: str) -> None:
        with self._cond:
            self._active[tenant] = max(0, self._active.get(tenant, 0) - 1)
            self._cond.notify_all()

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            now = time.perf_counter()
            tenants = sorted(set(self._active) | set(self._queued))
            return {
                "slots": self.slots,
                "queue_depth": self.queue_depth,
                "tenants": {
                    t: {"active": self._active.get(t, 0),
                        "queued": self._queued.get(t, 0),
                        "oldest_wait_s": (
                            round(now - min(self._waiting[t]), 6)
                            if self._waiting.get(t) else 0.0)}
                    for t in tenants
                },
            }


class HotBlockCache:
    """Process-wide block/chunk LRU shared by every tenant.

    Tier ``compressed`` maps ``(path, coffset)`` to the raw BGZF block
    bytes (header + deflate payload + footer); tier ``decoded`` maps
    the same key to ``(csize, payload)`` — the inflated payload plus
    the compressed size needed to advance a block walk without
    re-reading the file. Tier ``parsed`` sits above both, keyed
    ``(path, (chunk_begin, chunk_end))`` by virtual-offset chunk, and
    holds the fully decoded columnar batch (plus its precomputed
    alignment ends for reads) — a hot repeated region skips inflate
    AND record decode AND the cigar walk, leaving only the per-query
    interval filter. Eviction is LRU per tier under a byte budget;
    per-tenant resident bytes are accounted so ``/serve/stats`` can
    show who owns the working set (the cache itself is shared — a hit
    is a hit regardless of who inserted the block).
    """

    TIERS = ("compressed", "decoded", "parsed")

    def __init__(self,
                 compressed_bytes: int = DEFAULT_COMPRESSED_CACHE_MB << 20,
                 decoded_bytes: int = DEFAULT_DECODED_CACHE_MB << 20,
                 parsed_bytes: int = DEFAULT_PARSED_CACHE_MB << 20) -> None:
        self._lock = threading.Lock()
        self._cap = {"compressed": int(compressed_bytes),
                     "decoded": int(decoded_bytes),
                     "parsed": int(parsed_bytes)}
        self._lru: Dict[str, OrderedDict] = {
            t: OrderedDict() for t in self.TIERS}
        self._bytes = {t: 0 for t in self.TIERS}
        self._tenant_bytes: Dict[Tuple[str, str], int] = {}
        # Cache-locality digest: path -> {bucket -> refcount}. The
        # refcount spans tiers — the digest answers "which file regions
        # are warm here", not "which tier holds them". Every 0<->1
        # transition is journaled so /serve/cachemap can answer a
        # router's incremental `since=` refresh from the log.
        self._digest: Dict[str, Dict[int, int]] = {}
        self._digest_seq = 0
        self._digest_log: deque = deque(maxlen=DIGEST_LOG_CAP)

    def get(self, tier: str, path: str, coffset: int,
            tenant: str) -> Optional[Any]:
        with self._lock:
            ent = self._lru[tier].get((path, coffset))
            if ent is None:
                counter("serve.cache.misses").inc(tier=tier, tenant=tenant)
                return None
            self._lru[tier].move_to_end((path, coffset))
            counter("serve.cache.hits").inc(tier=tier, tenant=tenant)
            return ent[0]

    def put(self, tier: str, path: str, coffset: int, value: Any,
            nbytes: int, tenant: str) -> None:
        cap = self._cap[tier]
        if nbytes > cap:
            return
        buckets = (digest_buckets(*coffset) if isinstance(coffset, tuple)
                   else (coffset >> DIGEST_BUCKET_BITS,))
        with self._lock:
            lru = self._lru[tier]
            key = (path, coffset)
            if key in lru:
                lru.move_to_end(key)
                return
            lru[key] = (value, nbytes, tenant, buckets)
            self._digest_add(path, buckets)
            self._bytes[tier] += nbytes
            tk = (tier, tenant)
            self._tenant_bytes[tk] = self._tenant_bytes.get(tk, 0) + nbytes
            while self._bytes[tier] > cap and lru:
                ev_key, (_, ev_bytes, ev_tenant, ev_buckets) = lru.popitem(
                    last=False)
                self._bytes[tier] -= ev_bytes
                ek = (tier, ev_tenant)
                self._tenant_bytes[ek] = max(
                    0, self._tenant_bytes.get(ek, 0) - ev_bytes)
                self._digest_del(ev_key[0], ev_buckets)
                counter("serve.cache.evictions").inc(tier=tier)
                record_event("serve_cache_evict", tier=tier,
                             tenant=ev_tenant, nbytes=ev_bytes)
            gauge("serve.cache.bytes").observe(self._bytes[tier], tier=tier)

    def clear(self) -> None:
        with self._lock:
            for t in self.TIERS:
                self._lru[t].clear()
                self._bytes[t] = 0
            self._tenant_bytes.clear()
            # digest goes cold with the cache; bump seq with the log
            # emptied so any router's `since` falls back to a full map
            self._digest.clear()
            self._digest_seq += 1
            self._digest_log.clear()

    # -- cache-locality digest (fleet routing signal) ----------------------

    def _digest_add(self, path: str, buckets: Tuple[int, ...]) -> None:
        refs = self._digest.setdefault(path, {})
        for b in buckets:
            n = refs.get(b, 0)
            refs[b] = n + 1
            if n == 0:
                self._digest_seq += 1
                self._digest_log.append((self._digest_seq, "add", path, b))

    def _digest_del(self, path: str, buckets: Tuple[int, ...]) -> None:
        refs = self._digest.get(path)
        if refs is None:
            return
        for b in buckets:
            n = refs.get(b, 0)
            if n <= 1:
                refs.pop(b, None)
                self._digest_seq += 1
                self._digest_log.append((self._digest_seq, "del", path, b))
            else:
                refs[b] = n - 1
        if not refs:
            self._digest.pop(path, None)

    def invalidate_path(self, path: str) -> int:
        """Drop every cached entry of ``path`` across all tiers — the
        cache side of dataset-epoch invalidation: a re-register fans
        out here so replicas shed stale ``(path, coffset)`` entries."""
        dropped = 0
        with self._lock:
            for tier in self.TIERS:
                lru = self._lru[tier]
                stale = [k for k in lru if k[0] == path]
                for k in stale:
                    _, ev_bytes, ev_tenant, ev_buckets = lru.pop(k)
                    self._bytes[tier] -= ev_bytes
                    ek = (tier, ev_tenant)
                    self._tenant_bytes[ek] = max(
                        0, self._tenant_bytes.get(ek, 0) - ev_bytes)
                    self._digest_del(path, ev_buckets)
                if stale:
                    counter("serve.cache.invalidations").inc(
                        len(stale), tier=tier)
                    gauge("serve.cache.bytes").observe(
                        self._bytes[tier], tier=tier)
                dropped += len(stale)
        if dropped:
            record_event("serve_cache_invalidate", path=path,
                         entries=dropped)
        return dropped

    def cachemap(self, since: Optional[int] = None) -> Dict[str, Any]:
        """Compact digest of which ``(path, 64 KiB bucket)`` regions
        are warm in any tier. With ``since`` set to a previously
        returned ``seq``, answers the incremental delta while the
        bounded op log still covers it; otherwise the full map."""
        with self._lock:
            doc: Dict[str, Any] = {"seq": self._digest_seq,
                                   "bucket_bits": DIGEST_BUCKET_BITS}
            if since is not None and 0 <= since <= self._digest_seq:
                if since == self._digest_seq:
                    doc["delta"] = []
                    return doc
                log = self._digest_log
                if log and log[0][0] <= since + 1:
                    doc["delta"] = [[op, path, bucket]
                                    for seq, op, path, bucket in log
                                    if seq > since]
                    return doc
            doc["paths"] = {p: sorted(refs)
                            for p, refs in self._digest.items() if refs}
            return doc

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                tier: {
                    "blocks": len(self._lru[tier]),
                    "bytes": self._bytes[tier],
                    "capacity_bytes": self._cap[tier],
                    "tenant_bytes": {
                        tenant: n
                        for (t, tenant), n in sorted(
                            self._tenant_bytes.items())
                        if t == tier and n > 0
                    },
                }
                for tier in self.TIERS
            }


class IndexCache:
    """Parsed header + index LRU keyed ``(path, size, mtime)``.

    Before this cache every interval read re-fetched and re-parsed the
    BAI/TBI; a daemon answering thousands of region queries against a
    handful of registered files must parse each index once. The key
    carries the file's ``(size, mtime_ns)`` stat so a rewritten file
    invalidates on its next query (non-posix backends fall back to
    size-only, which still catches every rewrite that changes length).
    """

    def __init__(self, entries: int = DEFAULT_INDEX_CACHE_ENTRIES) -> None:
        self._lock = threading.Lock()
        self._entries = int(entries)
        self._lru: OrderedDict = OrderedDict()

    @staticmethod
    def _stat(fs, path: str) -> Tuple[int, int]:
        try:
            st = os.stat(path)
            return int(st.st_size), int(st.st_mtime_ns)
        except OSError:
            return int(fs.get_file_length(path)), -1

    def get(self, fs, path: str, build):
        """Cached ``build(fs, path)`` result, invalidated on stat
        change of ``path`` (the builder may parse sidecars too — their
        rewrite accompanies the data file's in every supported
        writer)."""
        key = (path,) + self._stat(fs, path)
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)
                counter("serve.index_cache.hits").inc()
                return self._lru[key]
        counter("serve.index_cache.misses").inc()
        value = build(fs, path)
        with self._lock:
            # drop stale generations of the same path, then LRU-bound
            for stale in [k for k in self._lru if k[0] == path]:
                del self._lru[stale]
            self._lru[key] = value
            while len(self._lru) > self._entries:
                self._lru.popitem(last=False)
        return value

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._lru),
                "capacity": self._entries,
                "hits": counter("serve.index_cache.hits").total(),
                "misses": counter("serve.index_cache.misses").total(),
            }


class _Dataset:
    """One registered dataset: resolved filesystem + kind."""

    __slots__ = ("name", "path", "kind", "fs")

    def __init__(self, name: str, path: str, kind: str, fs) -> None:
        self.name = name
        self.path = path
        self.kind = kind
        self.fs = fs


def _parse_raw_block(raw: bytes) -> Tuple[bytes, int]:
    """(deflate payload, usize) of one raw BGZF block."""
    xlen = struct.unpack_from("<H", raw, 10)[0]
    usize = struct.unpack_from("<I", raw, len(raw) - 4)[0]
    return raw[12 + xlen: len(raw) - _BGZF_FOOTER], usize


def _sniff_kind(path: str) -> str:
    low = path.lower()
    if low.endswith((".vcf.gz", ".vcf.bgz", ".vcf")):
        return "variants"
    return "reads"


class ServeDaemon:
    """Registry + query engine behind the ``/query/*`` endpoints.

    Holds no threads: requests run on the introspect HTTP server's
    request threads, synchronized only through the cache/admission
    locks above.
    """

    def __init__(self, *, options=None,
                 compressed_cache_mb: int = DEFAULT_COMPRESSED_CACHE_MB,
                 decoded_cache_mb: int = DEFAULT_DECODED_CACHE_MB,
                 parsed_cache_mb: int = DEFAULT_PARSED_CACHE_MB,
                 tenant_slots: int = DEFAULT_TENANT_SLOTS,
                 tenant_queue: int = DEFAULT_TENANT_QUEUE) -> None:
        from disq_tpu.runtime.errors import DisqOptions, ShardRetrier

        self._options = options or DisqOptions()
        self.cache = HotBlockCache(compressed_cache_mb << 20,
                                   decoded_cache_mb << 20,
                                   parsed_cache_mb << 20)
        self.indexes = IndexCache()
        self.admission = TenantAdmission(tenant_slots, tenant_queue)
        self._retrier = ShardRetrier(self._options.max_retries,
                                     self._options.retry_backoff_s)
        quantile = getattr(self._options, "hedge_quantile", None)
        if quantile is not None:
            from disq_tpu.runtime.resilience import HedgeController

            self._hedge: Optional[HedgeController] = HedgeController(
                quantile, getattr(self._options, "hedge_min_s", 0.05))
        else:
            self._hedge = None
        self._datasets: Dict[str, _Dataset] = {}
        # resolved path -> dataset epoch; bumped on every re-register
        # so the fleet tier can invalidate stale digests and caches
        self._epochs: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- registry ----------------------------------------------------------

    def register(self, name: str, path: str,
                 kind: Optional[str] = None) -> Dict[str, Any]:
        from disq_tpu.fsw.filesystem import resolve_path

        kind = kind or _sniff_kind(path)
        if kind not in ("reads", "variants"):
            raise ValueError(f"unknown dataset kind {kind!r}")
        fs, fs_path = resolve_path(path)
        if not fs.exists(fs_path):
            raise FileNotFoundError(path)
        ds = _Dataset(name, fs_path, kind, fs)
        with self._lock:
            epoch = self._epochs.get(fs_path, 0) + 1
            self._epochs[fs_path] = epoch
            self._datasets[name] = ds
            gauge("serve.datasets").observe(len(self._datasets))
        if epoch > 1:
            # re-register: the file may have been rewritten under the
            # same path — shed every cached (path, coffset) entry and
            # let /serve/cachemap's epoch map tell routers to do the same
            dropped = self.cache.invalidate_path(fs_path)
            record_event("serve_register_epoch", name=name,
                         path=fs_path, epoch=epoch, dropped=dropped)
        return {"name": name, "path": path, "kind": kind, "epoch": epoch}

    def _dataset(self, doc: Dict[str, Any], kind: str) -> _Dataset:
        name = doc.get("dataset")
        if name is not None:
            with self._lock:
                ds = self._datasets.get(name)
            if ds is None:
                # 404, not 400 — the request is well-formed, the
                # resource isn't there
                raise FileNotFoundError(
                    f"dataset {name!r} not registered")
            return ds
        path = doc.get("path")
        if not path:
            raise ValueError("request needs 'dataset' or 'path'")
        # by-path queries auto-register under the path itself
        with self._lock:
            ds = self._datasets.get(path)
        if ds is None:
            self.register(path, path, kind)
            with self._lock:
                ds = self._datasets[path]
        return ds

    # -- cached index resolution ------------------------------------------

    @staticmethod
    def _build_bam_meta(fs, path: str):
        from disq_tpu.bam.source import read_header
        from disq_tpu.traversal.bai_query import _resolve_bai

        header, first_vo = read_header(fs, path)
        return header, first_vo, _resolve_bai(fs, path)

    @staticmethod
    def _build_vcf_meta(fs, path: str):
        from disq_tpu.index.tbi import TbiIndex
        from disq_tpu.vcf.header import read_vcf_header

        header = read_vcf_header(fs, path)
        tbi = TbiIndex.from_bytes(fs.read_all(path + ".tbi"))
        return header, tbi

    # -- the cached + batched block pipeline ------------------------------

    def _chunk_blob(self, ds: _Dataset, cb: int, ce: int,
                    tenant: str) -> bytes:
        """Decoded bytes of virtual-offset chunk [cb, ce) — the serving
        analogue of ``BamSource._fetch_range`` + inflate, with two
        differences: every block goes through the shared two-tier
        cache, and every cache-missing block of the request is
        inflated in ONE device-service submission so concurrent
        requests coalesce into full 128-lane launches."""
        lo_block, lo_u = cb >> 16, cb & 0xFFFF
        hi_block, hi_u = ce >> 16, ce & 0xFFFF
        want_end = max(hi_block + (1 if hi_u > 0 else 0), lo_block + 1)
        length = ds.fs.get_file_length(ds.path)

        order: List[int] = []          # coffsets in file order
        payloads: Dict[int, bytes] = {}  # coffset -> decoded payload
        csizes: Dict[int, int] = {}
        pending: List[Tuple[int, bytes, int]] = []  # (coffset, comp, usize)
        pos = lo_block
        while pos < want_end and pos < length:
            ent = self.cache.get("decoded", ds.path, pos, tenant)
            if ent is not None:
                csize, payload = ent
                order.append(pos)
                payloads[pos] = payload
                csizes[pos] = csize
                pos += csize
                continue
            raw = self.cache.get("compressed", ds.path, pos, tenant)
            if raw is not None:
                comp, usize = _parse_raw_block(raw)
                order.append(pos)
                csizes[pos] = len(raw)
                pending.append((pos, comp, usize))
                pos += len(raw)
                continue
            # miss: walk+stage the rest of the chunk in one range read
            # (retried through the shard retrier — transient storage
            # faults must not 500 a tenant)
            blocks, data = self._fetch(ds, pos, want_end, length)
            if not blocks:
                break
            base = blocks[0].pos
            for b in blocks:
                raw_b = data[b.pos - base: b.end - base]
                self.cache.put("compressed", ds.path, b.pos, raw_b,
                               len(raw_b), tenant)
                comp, _ = _parse_raw_block(raw_b)
                order.append(b.pos)
                csizes[b.pos] = b.csize
                pending.append((b.pos, comp, b.usize))
            pos = blocks[-1].end
        if pending:
            self._inflate_pending(ds, pending, payloads, csizes, tenant)
        blob = b"".join(payloads[co] for co in order)
        if hi_u > 0:
            acc_before_hi = sum(
                len(payloads[co]) for co in order if co < hi_block)
            end_u = acc_before_hi + hi_u
        else:
            end_u = len(blob)
        return blob[lo_u:end_u]

    @staticmethod
    def _walk(fs, path, pos, want_end, length):
        from disq_tpu.bgzf.guesser import _walk_blocks_collect

        return _walk_blocks_collect(
            fs, path, pos, max(want_end, pos + 1), length)

    def _fetch(self, ds: _Dataset, pos: int, want_end: int, length: int):
        """One retried — and, when ``DisqOptions.hedge_quantile`` is
        set, hedged — range fetch+walk: the query path's analogue of
        the executor's hedged fetch stage, so a tail-latency storage
        read races a duplicate and lands in the flight recorder as a
        ``hedge_launched`` event on the serving plane."""
        def call():
            return self._retrier.call(
                self._walk, ds.fs, ds.path, pos, want_end, length,
                what="serve.fetch")

        if self._hedge is None:
            return call()
        return self._hedge.call(call)

    def _inflate_pending(self, ds: _Dataset, pending, payloads, csizes,
                         tenant: str) -> None:
        """Inflate every cache-missing block of one request in a
        single batch: through the device service when enabled (the
        dispatcher coalesces lanes ACROSS concurrent requests), host
        zlib otherwise. Decoded payloads land in the hot tier."""
        from disq_tpu.runtime import device_service

        if device_service.enabled():
            sub = device_service.get_service().submit_inflate(
                [comp for _, comp, _ in pending],
                [usize for _, _, usize in pending])
            blob, offsets = sub.result()
            raw = blob.tobytes()
            decoded = [
                raw[int(offsets[i]): int(offsets[i + 1])]
                for i in range(len(pending))
            ]
        else:
            decoded = [
                zlib.decompress(comp, -15, usize or 1)
                for _, comp, usize in pending
            ]
        for (coffset, _comp, _usize), payload in zip(pending, decoded):
            payloads[coffset] = payload
            self.cache.put("decoded", ds.path, coffset,
                           (csizes[coffset], payload), len(payload),
                           tenant)

    # -- query execution ---------------------------------------------------

    @staticmethod
    def _parse_intervals(doc: Dict[str, Any]):
        from disq_tpu.api import Interval

        raw = doc.get("intervals")
        if not isinstance(raw, list) or not raw:
            raise ValueError(
                "request needs 'intervals': [{contig, start, end}, …]")
        out = []
        for iv in raw:
            if not isinstance(iv, dict):
                raise ValueError("each interval must be an object")
            try:
                out.append(Interval(str(iv["contig"]), int(iv["start"]),
                                    int(iv["end"])))
            except KeyError as e:
                raise ValueError(f"interval missing {e.args[0]!r}")
        return out

    @staticmethod
    def _batch_nbytes(batch, *extra) -> int:
        return sum(v.nbytes for v in vars(batch).values()
                   if hasattr(v, "nbytes")) \
            + sum(a.nbytes for a in extra)

    def _parsed_chunk(self, ds: _Dataset, header, cb: int, ce: int,
                      tenant: str):
        """(batch, alignment_ends) of one virtual-offset chunk through
        the parsed tier — decode and the cigar walk are paid once per
        chunk, not per request."""
        from disq_tpu.bam.codec import decode_records, scan_record_offsets

        ent = self.cache.get("parsed", ds.path, (cb, ce), tenant)
        if ent is None:
            record_bytes = self._chunk_blob(ds, cb, ce, tenant)
            if not record_bytes:
                return None
            offsets = scan_record_offsets(record_bytes)
            sub = decode_records(record_bytes, offsets, n_ref=header.n_ref)
            ends = sub.alignment_ends()
            ent = (sub, ends)
            self.cache.put("parsed", ds.path, (cb, ce), ent,
                           self._batch_nbytes(sub, ends), tenant)
        return ent

    def _read_batch(self, ds: _Dataset, intervals, tenant: str,
                    materialize: bool = True):
        """(header, filtered ReadBatch or None, count) covering
        ``intervals`` — the cached, batched serving analogue of
        ``read_with_traversal``. With ``materialize=False`` (count-only
        queries: ``limit`` 0 and no digest) the per-request work is
        just the vectorized overlap mask — no column copies, no
        concat."""
        from disq_tpu.bam.columnar import ReadBatch
        from disq_tpu.traversal.bai_query import (
            chunks_for_intervals, overlap_mask)

        header, _first_vo, bai = self.indexes.get(
            ds.fs, ds.path, self._build_bam_meta)
        batches = []
        count = 0
        for cb, ce in chunks_for_intervals(header, bai, intervals):
            ent = self._parsed_chunk(ds, header, cb, ce, tenant)
            if ent is None:
                continue
            sub, ends = ent
            mask = overlap_mask(sub, header, intervals, ends=ends)
            if materialize:
                batches.append(sub.filter(mask))
            else:
                count += int(mask.sum())
        if not materialize:
            return header, None, count
        batch = (ReadBatch.concat(batches) if batches
                 else ReadBatch.empty())
        return header, batch, int(batch.count)

    @staticmethod
    def _batch_digest(batch) -> str:
        h = hashlib.sha1()
        for col in (batch.refid, batch.pos, batch.flag, batch.mapq,
                    batch.tlen):
            h.update(col.tobytes())
        h.update(batch.names.tobytes())
        h.update(batch.cigars.tobytes())
        h.update(batch.seqs.tobytes())
        h.update(batch.quals.tobytes())
        return h.hexdigest()

    def _q_reads(self, doc: Dict[str, Any], tenant: str) -> Dict[str, Any]:
        ds = self._dataset(doc, "reads")
        if ds.kind != "reads":
            raise ValueError(f"dataset {ds.name!r} holds variants")
        intervals = self._parse_intervals(doc)
        limit = int(doc.get("limit", 100))
        want_digest = bool(doc.get("digest", True))
        # Count-only queries (limit 0, no digest) skip batch
        # materialization: the answer is a mask sum per cached chunk.
        header, batch, count = self._read_batch(
            ds, intervals, tenant,
            materialize=want_digest or limit > 0)
        names = [s.name for s in header.sequences]
        records = [
            {
                "name": batch.name(i),
                "contig": (names[int(batch.refid[i])]
                           if 0 <= int(batch.refid[i]) < len(names)
                           else None),
                "pos": int(batch.pos[i]) + 1,
                "flag": int(batch.flag[i]),
                "mapq": int(batch.mapq[i]),
            }
            for i in range(min(count, max(0, limit)))
        ] if batch is not None else []
        out = {
            "dataset": ds.name,
            "count": count,
            "records": records,
        }
        # sha1 over every column is the cross-client identity check;
        # latency-sensitive callers opt out with "digest": false
        if want_digest:
            out["digest"] = self._batch_digest(batch)
        return out

    def _q_variants(self, doc: Dict[str, Any],
                    tenant: str) -> Dict[str, Any]:
        from disq_tpu.vcf.columnar import VariantBatch, parse_vcf_lines
        from disq_tpu.vcf.source import VcfSource

        ds = self._dataset(doc, "variants")
        if ds.kind != "variants":
            raise ValueError(f"dataset {ds.name!r} holds reads")
        intervals = self._parse_intervals(doc)
        header, tbi = self.indexes.get(ds.fs, ds.path,
                                       self._build_vcf_meta)
        chunks = []
        for iv in intervals:
            chunks += tbi.chunks_for_interval(iv.contig, iv.start - 1,
                                              iv.end)
        chunks.sort()
        merged: List[Tuple[int, int]] = []
        for cb, ce in chunks:
            if merged and cb <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], ce))
            else:
                merged.append((cb, ce))
        batches = []
        for cb, ce in merged:
            sub = self.cache.get("parsed", ds.path, (cb, ce), tenant)
            if sub is None:
                blob = self._chunk_blob(ds, cb, ce, tenant)
                lines = [
                    ln for ln in blob.split(b"\n")
                    if ln and not ln.startswith(b"#")
                    and ln.count(b"\t") >= 7
                ]
                sub = parse_vcf_lines(lines, header.contig_names)
                self.cache.put("parsed", ds.path, (cb, ce), sub,
                               self._batch_nbytes(sub), tenant)
            batches.append(sub)
        batch = (VariantBatch.concat(batches) if batches
                 else VariantBatch.empty(header.contig_names))
        batch = batch.filter(VcfSource._overlap_mask(batch, intervals))
        limit = int(doc.get("limit", 100))
        out = {
            "dataset": ds.name,
            "count": int(batch.count),
            "records": [batch.line(i)
                        for i in range(min(int(batch.count),
                                           max(0, limit)))],
        }
        if doc.get("digest", True):
            h = hashlib.sha1()
            h.update(batch.chrom.tobytes())
            h.update(batch.pos.tobytes())
            h.update(batch.lines.tobytes())
            out["digest"] = h.hexdigest()
        return out

    def _q_stats(self, doc: Dict[str, Any], tenant: str) -> Dict[str, Any]:
        ds = self._dataset(doc, "reads")
        if ds.kind != "reads":
            raise ValueError("/query/stats serves reads datasets")
        intervals = self._parse_intervals(doc)
        header, batch, _count = self._read_batch(ds, intervals, tenant)
        from disq_tpu.api import ReadsDataset

        view = ReadsDataset(header=header, reads=batch)
        out: Dict[str, Any] = {"dataset": ds.name,
                               "count": int(batch.count)}
        which = doc.get("stat", "flagstat")
        if which not in ("flagstat", "depth"):
            raise ValueError(f"unknown stat {which!r}")
        if which == "flagstat":
            out["flagstat"] = {k: int(v)
                               for k, v in view.flagstat().items()}
        else:
            window = int(doc.get("window", 1024))
            depth = view.depth(window=window)
            names = [s.name for s in header.sequences]
            out["depth"] = {
                "window": window,
                "refs": {
                    (names[int(refid)]
                     if 0 <= int(refid) < len(names) else str(refid)): {
                        "windows": int(len(arr)),
                        "max": int(arr.max()) if len(arr) else 0,
                        "total": int(arr.sum()) if len(arr) else 0,
                    }
                    for refid, arr in depth.items()
                },
            }
        return out

    # -- operator-suite queries (ops/*.py on the parsed tier) --------------

    def _q_markdup_stats(self, doc: Dict[str, Any],
                         tenant: str) -> Dict[str, Any]:
        """``POST /query/markdup-stats`` — duplicate-marking stats for
        the reads overlapping ``intervals`` (``ops/markdup.py`` on the
        hot-block cache's parsed tier; the batch under the query is one
        coordinate scan, so no seam merge is needed). Optional
        ``"rgstats": true`` adds the per-read-group breakdown of the
        marked batch."""
        from disq_tpu.ops.markdup import markdup_batch

        ds = self._dataset(doc, "reads")
        if ds.kind != "reads":
            raise ValueError("/query/markdup-stats serves reads datasets")
        intervals = self._parse_intervals(doc)
        _header, batch, count = self._read_batch(ds, intervals, tenant)
        batch, res = markdup_batch(batch)
        out: Dict[str, Any] = {"dataset": ds.name, "count": count,
                               "markdup": res.stats()}
        if doc.get("rgstats"):
            from disq_tpu.ops.rgstats import read_group_stats

            out["rgstats"] = read_group_stats(batch)
        return out

    def _q_pileup(self, doc: Dict[str, Any], tenant: str) -> Dict[str, Any]:
        """``POST /query/pileup`` — per-base coverage over ONE interval
        (``ops/pileup.py``). The full base vector is returned up to
        ``max_bases`` (default 16384) positions; wider regions get the
        summary only."""
        from disq_tpu.ops.pileup import region_pileup

        ds = self._dataset(doc, "reads")
        if ds.kind != "reads":
            raise ValueError("/query/pileup serves reads datasets")
        intervals = self._parse_intervals(doc)
        if len(intervals) != 1:
            raise ValueError("/query/pileup wants exactly one interval")
        iv = intervals[0]
        header, batch, _count = self._read_batch(ds, [iv], tenant)
        names = [s.name for s in header.sequences]
        if iv.contig not in names:
            raise ValueError(f"unknown contig {iv.contig!r}")
        start, end = int(iv.start) - 1, int(iv.end)
        cov = region_pileup(batch, names.index(iv.contig), start, end)
        out: Dict[str, Any] = {
            "dataset": ds.name, "contig": iv.contig,
            "start": int(iv.start), "end": int(iv.end),
            "max": int(cov.max()) if len(cov) else 0,
            "mean": round(float(cov.mean()), 4) if len(cov) else 0.0,
            "nonzero": int((cov > 0).sum()),
        }
        if len(cov) <= int(doc.get("max_bases", 16384)):
            out["coverage"] = cov.astype(int).tolist()
        return out

    def _q_filtered_count(self, doc: Dict[str, Any],
                          tenant: str) -> Dict[str, Any]:
        """``POST /query/filtered-count`` — how many reads in
        ``intervals`` pass a ``samtools view``-grammar ``"filter"``
        spec (``ops/rfilter.py``), without materializing records into
        the response."""
        import numpy as np

        from disq_tpu.ops.rfilter import (
            host_mask, name_hashes_from_columns, parse_read_filter)

        ds = self._dataset(doc, "reads")
        if ds.kind != "reads":
            raise ValueError("/query/filtered-count serves reads datasets")
        rf = parse_read_filter(str(doc.get("filter", "")))
        intervals = self._parse_intervals(doc)
        _header, batch, count = self._read_batch(ds, intervals, tenant)
        nh = None
        if rf.needs_name_hash:
            nh = name_hashes_from_columns(
                np.asarray(batch.names), np.asarray(batch.name_offsets))
        mask = host_mask(rf, np.asarray(batch.flag),
                         np.asarray(batch.mapq), nh)
        return {"dataset": ds.name, "count": count,
                "matched": int(mask.sum())}

    # -- stats + HTTP ------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        lat = histogram("serve.request")
        with self._lock:
            datasets = [
                {"name": d.name, "path": d.path, "kind": d.kind}
                for d in self._datasets.values()
            ]
        return {
            "datasets": datasets,
            "cache": self.cache.stats(),
            "index_cache": self.indexes.stats(),
            "admission": self.admission.stats(),
            "latency": {
                "p50_ms": lat.percentile(50) * 1e3,
                "p99_ms": lat.percentile(99) * 1e3,
                "p999_ms": lat.percentile(99.9) * 1e3,
                "max_ms": lat.percentile(100) * 1e3,
            },
        }

    _QUERIES = {
        "/query/reads": "_q_reads",
        "/query/variants": "_q_variants",
        "/query/stats": "_q_stats",
        "/query/markdup-stats": "_q_markdup_stats",
        "/query/pileup": "_q_pileup",
        "/query/filtered-count": "_q_filtered_count",
    }

    def cachemap(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """``GET /serve/cachemap[?since=N]`` — the replica's advertised
        cache digest plus its dataset epochs, consumed by the fleet
        router's incremental refresh."""
        since = doc.get("since")
        try:
            since = int(since) if since is not None else None
        except (TypeError, ValueError):
            since = None
        out = self.cache.cachemap(since)
        with self._lock:
            out["epochs"] = dict(self._epochs)
        return out

    def handle(self, method: str, path: str,
               doc: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        if method == "GET" and path == "/serve/stats":
            return 200, self.stats()
        if method == "GET" and path == "/serve/cachemap":
            return 200, self.cachemap(doc)
        if method != "POST":
            return 405, {"error": f"{path} expects POST"}
        if path == "/serve/register":
            try:
                return 200, self.register(
                    str(doc.get("name") or doc.get("path") or ""),
                    str(doc["path"]), doc.get("kind"))
            except KeyError:
                return 400, {"error": "register needs 'path'"}
            except (ValueError, FileNotFoundError) as e:
                return 400, {"error": str(e)}
        fn_name = self._QUERIES.get(path)
        if fn_name is None:
            return 404, {"error": f"unknown serve path {path}",
                         "endpoints": sorted(self._QUERIES)
                         + ["/serve/register", "/serve/stats"]}
        tenant = str(doc.get("tenant") or DEFAULT_TENANT)
        t0 = time.perf_counter()
        endpoint = path.rsplit("/", 1)[-1]
        # Request-scoped causality: adopt the client's context (already
        # activated from X-Disq-Trace-* by the introspection handler),
        # or mint a root one when DISQ_TPU_TRACE_REQUESTS is set.  The
        # tenant rides the body, not the headers, so an adopted context
        # is rebound to the body's tenant for per-tenant attribution.
        ctx = current_trace()
        token = None
        if ctx is None:
            if trace_requests_enabled():
                ctx = mint_trace(tenant)
                token = activate_trace(ctx)
        elif ctx.tenant != tenant:
            ctx = TraceContext(ctx.trace_id, ctx.span_id, tenant)
            token = activate_trace(ctx)
        try:
            try:
                self.admission.acquire(tenant)
            except AdmissionShed as e:
                record_event("serve_shed", tenant=tenant,
                             endpoint=endpoint, reason=e.reason)
                if ctx is not None:
                    record_span("serve.request.trace",
                                time.perf_counter() - t0,
                                endpoint=endpoint, tenant=tenant,
                                status=429)
                return 429, {"error": str(e), "tenant": tenant}
            status = 500
            try:
                body = getattr(self, fn_name)(doc, tenant)
                status = 200
                return 200, body
            except (KeyError, ValueError) as e:
                status = 400
                return 400, {"error": str(e)}
            except FileNotFoundError as e:
                status = 404
                return 404, {"error": f"not found: {e}"}
            except Exception as e:  # noqa: BLE001 — surfaced as HTTP 500
                return 500, {"error": f"{type(e).__name__}: {e}"}
            finally:
                self.admission.release(tenant)
                dur = time.perf_counter() - t0
                histogram("serve.request").observe(
                    dur, endpoint=endpoint, tenant=tenant)
                if status >= 500:
                    counter("serve.request.errors").inc(
                        endpoint=endpoint, tenant=tenant)
                if ctx is not None:
                    # the stitched waterfall's root on this process
                    record_span("serve.request.trace", dur,
                                endpoint=endpoint, tenant=tenant,
                                status=status)
        finally:
            if token is not None:
                deactivate_trace(token)


# -- module-level daemon lifecycle ----------------------------------------

_LOCK = threading.RLock()
_DAEMON: Optional[ServeDaemon] = None


def serve_if_running() -> Optional[ServeDaemon]:
    """The live daemon, or None. NEVER creates one — the overhead
    guard (``scripts/check_overhead.py``) calls this to prove the
    serve-off path allocates nothing."""
    return _DAEMON


def start_serve(port: int = 0, **daemon_kwargs: Any) -> str:
    """Create the daemon (idempotent) and return the ``host:port`` of
    the introspection HTTP server now also answering ``/query/*`` and
    ``/serve/*``. Keyword args feed :class:`ServeDaemon` on first
    start and are ignored on an already-running daemon."""
    global _DAEMON
    with _LOCK:
        if _DAEMON is None:
            _DAEMON = ServeDaemon(**daemon_kwargs)
    # The daemon is the serving edge the SLO layer watches, so it also
    # arms the evaluator from DISQ_TPU_SLO — a bare start_serve() never
    # passes through the DisqOptions storage funnel.  No-op (and no
    # thread) when the env knob is unset.
    from disq_tpu.runtime import slo as _slo

    _slo.configure_from_env()
    from disq_tpu.runtime.introspect import start_introspect_server

    return start_introspect_server(port)


def stop_serve() -> None:
    """Drop the daemon (registry, caches, admission state). The
    introspection server is shared with the rest of the telemetry
    plane, so the caller that started it stops it."""
    global _DAEMON
    with _LOCK:
        daemon, _DAEMON = _DAEMON, None
    if daemon is not None and daemon._hedge is not None:
        daemon._hedge.close()


def handle_http(method: str, path: str,
                doc: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
    """Route one serve-plane request; 503 (allocating nothing) when
    no daemon is running."""
    daemon = _DAEMON
    if daemon is None:
        return 503, {
            "error": "serving plane not started — call "
                     "disq_tpu.api.serve() or scripts/serve.py"}
    return daemon.handle(method, path, doc)
