"""Symmetric device write path — resident encode + fused SIMD deflate.

The write-side mirror of ``runtime/device_pipeline.py`` (ROADMAP open
item 5): the read path fuses inflate → parse → columnar so decoded
bytes never round-trip d2h; this module fuses the inverse, sort →
encode → deflate, so *encoded* bytes never round-trip h2d↔d2h between
the stages.  Compressed blocks are the only thing small enough to be
worth moving (the Compressed-Resident direction, PAPERS.md
arxiv 2606.18900), so compression happens where the data already
lives:

- ``ResidentShardEncoder`` uploads a ``ColumnarBatch``'s record blob
  as device words ONCE per write (shards share the array — jax arrays
  are immutable, so the write pipeline's workers slice it
  concurrently), and ``encode_shard`` gathers each shard's records —
  in the sort permutation's order — into a block-aligned device word
  blob.  BAM encode of an unmodified record is byte-identical to its
  source bytes (``bam/codec.py``'s encode∘decode identity), so the
  permuted-record gather IS the record encode, as one device launch.
- ``EncodedShard.deflate`` feeds that still-resident blob straight
  into ``ops/deflate.py``'s 128-lane entropy coder: each chunk's
  (cw, 128) word columns are built by an on-device reshape/transpose
  (no staging arena, no payload re-upload — h2d per chunk is the
  (1,128) byte counts plus the once-per-table LUTs), and d2h carries
  ONLY the occupied compressed prefix + end-bit row.  The per-block
  csizes flow back for the voffset/BAI arithmetic exactly as the host
  path's do.

The host keeps what it already owns: the pre-encode record blob (the
decode path holds it for CRC verification and ragged columns), from
which block CRC32/ISIZE footers and the rare expanded-lane host-zlib
fallback are served — no device bytes cross d2h for either.

Enablement: ``DisqOptions.device_deflate`` / env
``DISQ_TPU_DEVICE_DEFLATE`` + a sorted device-backed batch
(``ColumnarBatch.permuted``).  Disabled, this module is never imported
and allocates nothing (``scripts/check_overhead.py``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

from disq_tpu.bgzf.block import BGZF_MAX_PAYLOAD

#: BGZF payload blocking in LE u32 words — BGZF_MAX_PAYLOAD (65280) is
#: 4-aligned, so every block of a block-aligned blob starts word-aligned
#: and a chunk's (cw, 128) columns are a pure reshape/transpose.
BLOCK_WORDS = BGZF_MAX_PAYLOAD // 4


@functools.lru_cache(maxsize=1)
def _jax():
    import jax
    import jax.numpy as jnp

    return jax, jnp


@functools.lru_cache(maxsize=32)
def _gather_compiled(total_words: int):
    """Per-output-byte record gather: out byte ``b`` belongs to the
    record found by searchsorted over the destination offsets and reads
    the source blob at that record's start plus the within-record
    offset — the exact inverse of ``device_pipeline.
    assemble_device_words``'s per-byte compaction."""
    jax, jnp = _jax()

    def gather(blob_words, src_starts, dst_offsets):
        total = dst_offsets[-1]

        def byte_at(b):
            i = jnp.searchsorted(dst_offsets, b, side="right") - 1
            i = jnp.clip(i, 0, src_starts.shape[0] - 1)
            src = src_starts[i] + (b - dst_offsets[i])
            w = blob_words[jnp.clip(src >> 2, 0, blob_words.shape[0] - 1)]
            byte = (w >> (((src & 3) << 3).astype(jnp.uint32))) \
                & jnp.uint32(0xFF)
            return jnp.where(b < total, byte, jnp.uint32(0))

        w_iota = jnp.arange(total_words, dtype=jnp.int32) << 2
        out = byte_at(w_iota)
        out = out | (byte_at(w_iota + 1) << 8)
        out = out | (byte_at(w_iota + 2) << 16)
        out = out | (byte_at(w_iota + 3) << 24)
        return out

    return jax.jit(gather)


class EncodedShard:
    """One shard's permuted records as a device-resident, block-aligned
    word blob — the unit the fused deflate consumes."""

    def __init__(self, encoder: "ResidentShardEncoder", lo: int, hi: int,
                 words, nbytes: int,
                 record_offsets: np.ndarray) -> None:
        self._encoder = encoder
        self._lo, self._hi = lo, hi
        self._words = words
        self.nbytes = nbytes
        #: (n+1,) shard-local uncompressed record offsets — the
        #: voffset/index arithmetic input (mirrors
        #: ``encode_records_with_offsets``'s second return)
        self.record_offsets = record_offsets
        self.n_blocks = max(0, -(-nbytes // BGZF_MAX_PAYLOAD))
        self._host: Optional[np.ndarray] = None
        self._hbm = int(words.size) * 4 if words is not None else 0
        if self._hbm:
            from disq_tpu.runtime.tracing import track_hbm

            track_hbm(self._hbm)

    # -- host mirror (CRC/ISIZE footers + expanded-lane fallback) -----------

    def host_payload(self) -> np.ndarray:
        """The shard's encoded bytes gathered from the HOST record blob
        the batch already holds (the read path's CRC/ragged copy) —
        serves the BGZF footers and the host-zlib fallback with zero
        d2h."""
        if self._host is None:
            from disq_tpu.bam.codec import _ragged_gather

            enc = self._encoder
            starts = enc._src_starts[self._lo: self._hi]
            lens = enc._lens[self._lo: self._hi]
            self._host, _ = _ragged_gather(enc._blob_u8, starts, lens)
        return self._host

    # -- fused deflate -------------------------------------------------------

    def deflate(self) -> Tuple[bytes, np.ndarray]:
        """Device deflate of the resident blob: (compressed bytes,
        per-block csizes) — the ``deflate_blob`` contract, with the
        encode → deflate handoff entirely in HBM.  Launches ride the
        shared adaptive dispatch window (chunk ``c+1`` is in flight
        while chunk ``c``'s compressed prefix fetches and finalizes on
        host), and the per-lane finalize/fallback/accounting is the
        one shared ``ops/deflate.finalize_chunk`` every route uses."""
        from disq_tpu.ops import deflate as DF
        from disq_tpu.ops import inflate_simd as IS
        from disq_tpu.runtime.tracing import span
        from disq_tpu.util import bucket_pow2

        _jax_mod, jnp = _jax()
        if self.nbytes == 0:
            return b"", np.zeros(0, dtype=np.int64)
        host = self.host_payload()
        n_blocks = self.n_blocks
        table = DF.DeflateTable(
            np.bincount(host, minlength=256).astype(np.int64), n_blocks)
        cw = bucket_pow2(BLOCK_WORDS)
        chunk_geom = [(c0, min(DF.LANES, n_blocks - c0))
                      for c0 in range(0, n_blocks, DF.LANES)]
        chunk_bytes = cw * DF.LANES * 4 + table.out_bytes * DF.LANES
        window = IS.dispatch_window(len(chunk_geom), chunk_bytes)

        def launch(ci: int):
            c0, nl = chunk_geom[ci]
            clen = np.zeros((1, DF.LANES), np.int32)
            for j in range(nl):
                b = c0 + j
                clen[0, j] = (min((b + 1) * BGZF_MAX_PAYLOAD,
                                  self.nbytes) - b * BGZF_MAX_PAYLOAD)
            seg = self._words[c0 * BLOCK_WORDS: (c0 + nl) * BLOCK_WORDS]
            cols = jnp.transpose(seg.reshape(nl, BLOCK_WORDS))
            cols = jnp.pad(
                cols, ((0, cw - BLOCK_WORDS), (0, DF.LANES - nl)))
            return DF.launch_resident(cols, clen, table, cw), clen

        blocks: list = [None] * n_blocks
        launched: list = [launch(ci)
                          for ci in range(min(window, len(chunk_geom)))]
        for ci, (c0, nl) in enumerate(chunk_geom):
            handle, clen = launched[ci]
            launched[ci] = None
            with span("device.deflate.encode", blocks=nl):
                bodies, end = DF.fetch_chunk(handle, table, nl)
                if ci + window < len(chunk_geom):
                    launched.append(launch(ci + window))
                payloads = [
                    host[(c0 + j) * BGZF_MAX_PAYLOAD:
                         (c0 + j) * BGZF_MAX_PAYLOAD + int(clen[0, j])]
                    for j in range(nl)
                ]
                # expanded lanes reroute inline: the writer pipeline
                # already overlaps shards, so this worker IS the
                # shard's own thread (no dispatcher to unblock)
                DF.finalize_chunk(
                    bodies, end, table, payloads,
                    lambda j, blk, c0=c0: blocks.__setitem__(
                        c0 + j, blk),
                    lambda flagged, c0=c0, payloads=payloads: [
                        blocks.__setitem__(
                            c0 + j, DF.host_block(payloads[j]))
                        for j in flagged])
        out = bytearray()
        sizes = np.empty(n_blocks, dtype=np.int64)
        for i in range(n_blocks):
            sizes[i] = len(blocks[i])
            out += blocks[i]
        self.release()
        return bytes(out), sizes

    def release(self) -> None:
        if self._hbm:
            from disq_tpu.runtime.tracing import track_hbm

            track_hbm(-self._hbm)
            self._hbm = 0
        self._words = None

    def __del__(self) -> None:  # pragma: no cover - GC timing
        try:
            self.release()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass


class ResidentShardEncoder:
    """Per-write driver of the resident encode: one record-blob upload,
    then a per-shard device gather of the (sorted) record bytes.

    Built from a ``ColumnarBatch`` whose ``encode_source()`` is
    available — i.e. a fused-decode batch, optionally ``permuted()`` by
    the coordinate sort.  Thread-safe for the write pipeline: shards
    only read the shared immutable device blob."""

    def __init__(self, batch) -> None:
        from disq_tpu.runtime.device_pipeline import upload_blob_words
        from disq_tpu.runtime.tracing import count_transfer, span, track_hbm

        src = batch.encode_source()
        if src is None:
            raise ValueError(
                "batch holds no host record blob — resident encode "
                "needs a fused-decode ColumnarBatch")
        blob, offsets, order = src
        blob = np.asarray(blob, dtype=np.uint8)
        offsets = np.asarray(offsets, dtype=np.int64)
        if int(offsets[-1]) >= 2 ** 31:
            raise ValueError(
                f"record blob is {int(offsets[-1])} bytes; the device "
                "write path indexes with i32 — split below 2 GiB")
        self._blob_u8 = blob
        lens = np.diff(offsets)
        if order is not None:
            self._src_starts = offsets[:-1][order]
            self._lens = lens[order]
        else:
            self._src_starts = offsets[:-1].copy()
            self._lens = lens
        n = len(self._lens)
        self._perm_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(self._lens, out=self._perm_off[1:])
        with span("device.transfer", direction="h2d"):
            self._words, up = upload_blob_words(blob)
        count_transfer("h2d", up)
        self._hbm = up
        track_hbm(up)

    @property
    def count(self) -> int:
        return len(self._lens)

    def encode_shard(self, lo: int, hi: int) -> EncodedShard:
        """Gather records [lo, hi) of the (permuted) batch into a
        block-aligned device word blob — the resident record encode.
        Only the small per-record index vectors cross h2d."""
        from disq_tpu.runtime.device_pipeline import _pad_quantum
        from disq_tpu.runtime.tracing import count_transfer, device_span
        from disq_tpu.util import bucket_pow2

        jax, jnp = _jax()
        n = hi - lo
        local_off = self._perm_off[lo: hi + 1] - self._perm_off[lo]
        nbytes = int(local_off[-1])
        if n <= 0 or nbytes == 0:
            return EncodedShard(self, lo, hi, None, 0,
                                np.zeros(1, dtype=np.int64))
        n_blocks = -(-nbytes // BGZF_MAX_PAYLOAD)
        total_words = _pad_quantum(n_blocks * BLOCK_WORDS)
        # bucket-padded index uploads (pads repeat the end so padded
        # output bytes read a real record and compile shapes quantize)
        nb_pad = bucket_pow2(max(1, n))
        starts_pad = np.empty(nb_pad, np.int32)
        starts_pad[:n] = self._src_starts[lo:hi]
        starts_pad[n:] = self._src_starts[hi - 1]
        dst_pad = np.empty(nb_pad + 1, np.int32)
        dst_pad[: n + 1] = local_off
        dst_pad[n + 1:] = nbytes
        count_transfer("h2d", starts_pad.nbytes + dst_pad.nbytes)
        starts_dev = jnp.asarray(starts_pad)
        dst_dev = jnp.asarray(dst_pad)
        with device_span("device.kernel", kernel="encode_resident",
                         records=n) as fence:
            with jax.transfer_guard("disallow"):
                words = _gather_compiled(total_words)(
                    self._words, starts_dev, dst_dev)
                jax.block_until_ready(words)
            fence.sync(words)
        # the deflate chunking below reads exactly the block span
        words = words[: n_blocks * BLOCK_WORDS]
        return EncodedShard(self, lo, hi, words, nbytes, local_off)

    def release(self) -> None:
        if self._hbm:
            from disq_tpu.runtime.tracing import track_hbm

            track_hbm(-self._hbm)
            self._hbm = 0
        self._words = None

    def __del__(self) -> None:  # pragma: no cover - GC timing
        try:
            self.release()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass


def resident_encoder_for(storage, batch) -> Optional[ResidentShardEncoder]:
    """The encoder for one sink write, or None when the device write
    path is off or the batch cannot encode resident (no host record
    blob — e.g. a plain host ``ReadBatch``).  The sink then falls back
    to host encode with (still service-routable) deflate."""
    from disq_tpu.bgzf.codec import device_deflate_enabled
    from disq_tpu.runtime.columnar import ColumnarBatch

    if not device_deflate_enabled(storage):
        return None
    if not isinstance(batch, ColumnarBatch):
        return None
    if batch.encode_source() is None:
        return None
    try:
        return ResidentShardEncoder(batch)
    except ValueError:
        # e.g. a concatenated record blob past the i32 indexing bound:
        # exactly the "cannot encode resident" case — host encode (with
        # routed deflate) handles any size
        return None
