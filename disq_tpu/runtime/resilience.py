"""Adaptive resilience — closed-loop fault handling for the pipelines.

PR 1 gave every shard bounded retry and PR 5 a stall watchdog, but both
only *observe* a slow or failing shard: nothing acts on a tail-latency
fetch before the watchdog's blunt warn/abort, and nothing stops N
workers from mounting a synchronized retry storm against an already
degraded object store.  This module turns that observability into
control, four mechanisms sharing one design rule — **disabled is free**
(no knob configured ⇒ no object, no thread, no timer, byte-identical
behavior):

- **Hedged fetches** (``HedgeController``): the executor's fetch stage
  arms a hedge threshold from a rolling per-run latency quantile
  (``DisqOptions.hedge_quantile`` / ``hedge_min_s``).  A range-read that
  outlives it gets a duplicate fetch; first result wins, the loser is
  cancelled or discarded.  Booked as ``hedge.launched`` /
  ``hedge.won{winner=}`` / ``hedge.wasted_bytes``, with the duplicate
  itself traced as a ``hedge.fetch`` span and the loser's burned time
  as ``hedge.waste``.
- **Per-shard deadlines** (``ShardDeadline``): ``shard_deadline_s``
  gives each shard a wall-clock budget that *escalates* — normal retry
  while young, forced hedging past half the budget
  (``deadline.hedge_forced``), and a certain, non-transient
  ``DeadlineExceededError`` once the budget is gone
  (``deadline.exceeded``), which sources under skip/quarantine policy
  convert into a quarantined empty shard instead of an aborted run.
- **Shared retry budget** (``RetryBudget``): a process-wide token
  bucket consulted by every ``ShardRetrier.call`` — each retry spends a
  token (``budget.spent``), each *success* refills proportionally, and
  an empty bucket denies the retry (``budget.denied``) so a fault storm
  degrades into fast failures instead of a synchronized stampede.
- **Circuit breaker** (``CircuitBreaker``): per-filesystem
  closed→open→half-open state machine.  ``breaker_window`` consecutive
  transient failures open it; while open every call fails fast with
  ``BreakerOpenError`` (``breaker.rejected``); after
  ``breaker_cooldown_s`` one half-open probe decides whether to reclose
  (``breaker.transitions{to=}``, ``breaker.state`` gauge, the open /
  half-open windows traced as ``breaker.open`` / ``breaker.half_open``
  spans for ``trace_report``'s shaded bands).

Budget and breakers are process-wide (they model the *store*, which
every run shares); hedging and deadlines are per-run (they model this
run's latency distribution).  ``scripts/check_resilience.py`` guards
the invariants: breaker transitions are total, every hedge launch is
accounted won-or-wasted, and the disabled path creates zero
threads/timers and stays byte-identical to seed behavior.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional

from disq_tpu.runtime import flightrec
from disq_tpu.runtime.errors import (
    BreakerOpenError,
    DeadlineExceededError,
    DisqOptions,
)
from disq_tpu.runtime.tracing import counter, observe_gauge, record_span, span

# ---------------------------------------------------------------------------
# Coordinator rediscovery backoff (scheduler failover)
# ---------------------------------------------------------------------------

# How long a worker hunts for a new coordinator before its
# CoordinatorLostError surfaces: 8 retries on a 0.25 s decorrelated-
# jitter base gives a standby several seconds to probe liveness,
# replay the journal and re-advertise, without a dead failover
# directory wedging the read for minutes.
REDISCOVERY_RETRIES = 8
REDISCOVERY_BACKOFF_S = 0.25


def rediscovery_retrier():
    """The retrier behind ``SchedulerClient`` rediscovery — a plain
    ``ShardRetrier`` so failover waits ride the same decorrelated
    jitter, retry-budget accounting and telemetry as every other
    transient-fault retry in the runtime."""
    from disq_tpu.runtime.errors import ShardRetrier

    return ShardRetrier(REDISCOVERY_RETRIES, REDISCOVERY_BACKOFF_S)


# ---------------------------------------------------------------------------
# Shared retry budget — the anti-stampede token bucket
# ---------------------------------------------------------------------------


class RetryBudget:
    """Process-wide token bucket bounding the *total* retry rate.

    Every ``ShardRetrier.call`` retry spends one token; every
    successful call refills ``refill_per_success`` tokens (capped at
    ``capacity``) — so a healthy store earns back retry headroom and a
    degraded one drains it, after which retries are denied and the
    original error surfaces immediately.  The refill-on-success
    coupling is what prevents the synchronized-stampede failure mode:
    when *nothing* succeeds, the whole process stops retrying together.
    """

    def __init__(self, capacity: int, refill_per_success: float = 0.1
                 ) -> None:
        if capacity < 1:
            raise ValueError(f"budget capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.refill_per_success = float(refill_per_success)
        self._tokens = float(capacity)
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def try_spend(self, what: str = "retry") -> bool:
        """Consume one token for a retry; False = denied (bucket dry)."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                tokens = self._tokens
                ok = True
            else:
                tokens = self._tokens
                ok = False
        if ok:
            counter("budget.spent").inc()
        else:
            counter("budget.denied").inc(what=what)
        observe_gauge("budget.tokens", tokens)
        return ok

    def on_success(self) -> None:
        """A call succeeded: earn back retry headroom."""
        if self.refill_per_success <= 0:
            return
        with self._lock:
            self._tokens = min(float(self.capacity),
                               self._tokens + self.refill_per_success)
            tokens = self._tokens
        observe_gauge("budget.tokens", tokens)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "capacity": self.capacity,
                    "refill_per_success": self.refill_per_success}


_budget_lock = threading.Lock()
_BUDGET: Optional[RetryBudget] = None


def configure_budget(capacity: Optional[int],
                     refill_per_success: float = 0.1
                     ) -> Optional[RetryBudget]:
    """Install (or clear, with ``capacity=None``) the process-wide
    retry budget.  Idempotent for an unchanged capacity — repeated runs
    with the same options share one bucket rather than refilling it."""
    global _BUDGET
    with _budget_lock:
        if capacity is None:
            _BUDGET = None
        elif (_BUDGET is None or _BUDGET.capacity != int(capacity)
              or _BUDGET.refill_per_success != float(refill_per_success)):
            _BUDGET = RetryBudget(capacity, refill_per_success)
        return _BUDGET


def active_budget() -> Optional[RetryBudget]:
    return _BUDGET


# ---------------------------------------------------------------------------
# Circuit breaker — per-filesystem closed→open→half-open
# ---------------------------------------------------------------------------

_STATE_VALUE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class CircuitBreaker:
    """Fail-fast guard for one backing store (one filesystem/scheme).

    - ``closed``: calls flow; ``window`` *consecutive* transient
      failures trip to ``open`` (any success resets the count).
    - ``open``: every call is rejected immediately with
      ``BreakerOpenError`` until ``cooldown_s`` has elapsed.
    - ``half_open``: exactly one probe call is admitted; its success
      recloses the breaker, its failure re-opens (fresh cooldown).
      Concurrent callers during the probe stay rejected.

    The transition set is total — every ``(state, event)`` pair has a
    defined successor — which ``scripts/check_resilience.py`` asserts
    by exhaustive enumeration.  ``clock`` is injectable so tests drive
    the cooldown with a fake clock.
    """

    def __init__(self, key: str, window: int = 5, cooldown_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if window < 1:
            raise ValueError(f"breaker window must be >= 1, got {window}")
        self.key = key
        self.window = int(window)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._state_since = clock()
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to: str, now: float) -> None:
        # caller holds self._lock
        prev, since = self._state, self._state_since
        if prev == to:
            return
        self._state = to
        self._state_since = now
        counter("breaker.transitions").inc(key=self.key, to=to)
        flightrec.record_event("breaker_transition", key=self.key,
                               to=to, window_s=round(now - since, 3))
        observe_gauge("breaker.state", _STATE_VALUE[to], key=self.key)
        # The window just left renders as a shaded band in trace_report:
        # open/half-open spans carry the window's real duration.
        if prev == "open":
            record_span("breaker.open", now - since, key=self.key)
        elif prev == "half_open":
            record_span("breaker.half_open", now - since, key=self.key)

    def before_call(self) -> None:
        """Gate one call: raises ``BreakerOpenError`` while open (and
        while a half-open probe is already in flight)."""
        with self._lock:
            now = self._clock()
            if self._state == "open":
                if now - self._opened_at < self.cooldown_s:
                    counter("breaker.rejected").inc(key=self.key)
                    raise BreakerOpenError(
                        "circuit breaker open — failing fast",
                        key=self.key,
                        retry_after_s=self.cooldown_s
                        - (now - self._opened_at),
                    )
                self._transition("half_open", now)
                self._probing = True
                return
            if self._state == "half_open":
                if (self._probing
                        and now - self._state_since < self.cooldown_s):
                    counter("breaker.rejected").inc(key=self.key)
                    raise BreakerOpenError(
                        "circuit breaker half-open — probe in flight",
                        key=self.key, retry_after_s=self.cooldown_s)
                # Either the previous probe resolved without an event
                # (released below) or it has been silent a whole
                # cooldown — a probe that died without reporting must
                # not wedge the breaker in half_open forever.
                self._probing = True

    def release_probe(self) -> None:
        """The admitted call ended without a success/failure verdict
        for the *store* (a non-transient error — corrupt data, a 404 —
        says nothing about the fault storm that opened the breaker):
        free the probe slot so the next caller can probe."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            now = self._clock()
            self._failures = 0
            if self._state == "open":
                # A success observed while open is stale (the call was
                # admitted before the trip): the breaker may only
                # reclose through a half-open probe.
                return
            self._probing = False
            # Recloses a probing breaker; in closed state a no-op.
            self._transition("closed", now)

    def record_failure(self) -> None:
        with self._lock:
            now = self._clock()
            self._probing = False
            if self._state == "half_open":
                self._opened_at = now
                self._transition("open", now)
                return
            self._failures += 1
            if self._failures >= self.window:
                self._failures = 0
                self._opened_at = now
                self._transition("open", now)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "window": self.window,
                "cooldown_s": self.cooldown_s,
                "state_age_s": round(self._clock() - self._state_since, 3),
            }


_breaker_lock = threading.Lock()
_BREAKERS: Dict[str, CircuitBreaker] = {}
_breaker_config: Optional[Dict[str, float]] = None


def configure_breakers(window: Optional[int],
                       cooldown_s: float = 1.0) -> None:
    """Enable (or disable, ``window=None``) per-filesystem breakers.
    Existing breaker instances keep their state — reconfiguring only
    changes what ``breaker_for`` builds next."""
    global _breaker_config
    with _breaker_lock:
        if window is None:
            _breaker_config = None
        else:
            _breaker_config = {"window": int(window),
                               "cooldown_s": float(cooldown_s)}


def breaker_for(path: str) -> Optional[CircuitBreaker]:
    """The breaker guarding ``path``'s filesystem (keyed by URI scheme,
    ``local`` for scheme-less paths), or None when breakers are off."""
    with _breaker_lock:
        cfg = _breaker_config
        if cfg is None:
            return None
        key = path.split("://", 1)[0] if "://" in path else "local"
        br = _BREAKERS.get(key)
        if br is None:
            br = _BREAKERS[key] = CircuitBreaker(
                key, window=int(cfg["window"]),
                cooldown_s=cfg["cooldown_s"])
        return br


def breakers_snapshot() -> Dict[str, Dict[str, Any]]:
    with _breaker_lock:
        return {k: b.snapshot() for k, b in sorted(_BREAKERS.items())}


# ---------------------------------------------------------------------------
# Per-shard deadline — the escalation ladder's clock
# ---------------------------------------------------------------------------

# Fraction of the deadline after which hedging is forced (a shard past
# half its budget cannot afford to wait for the hedge quantile).
HEDGE_ESCALATE_FRACTION = 0.5


class ShardDeadline:
    """Wall-clock budget for one shard's whole pipeline life (armed at
    the first stage it is checked in, spanning every retry).  The
    escalation ladder reads it at three points: the retrier denies
    further retries once exceeded, the hedge controller forces an
    immediate duplicate past ``HEDGE_ESCALATE_FRACTION``, and the
    executor's stage boundaries raise ``DeadlineExceededError``."""

    __slots__ = ("deadline_s", "shard_id", "_clock", "_start")

    def __init__(self, deadline_s: float, shard_id: int = -1,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.deadline_s = float(deadline_s)
        self.shard_id = shard_id
        self._clock = clock
        self._start: Optional[float] = None

    def arm(self) -> None:
        if self._start is None:
            self._start = self._clock()

    def elapsed(self) -> float:
        if self._start is None:
            return 0.0
        return self._clock() - self._start

    def exceeded(self) -> bool:
        self.arm()
        return self.elapsed() >= self.deadline_s

    def should_force_hedge(self) -> bool:
        self.arm()
        return self.elapsed() >= HEDGE_ESCALATE_FRACTION * self.deadline_s

    def check(self, what: str = "shard") -> None:
        """Raise (and book) once the budget is gone."""
        if self.exceeded():
            counter("deadline.exceeded").inc(what=what)
            flightrec.record_event(
                "deadline_exceeded", what=what, shard=self.shard_id,
                elapsed_s=round(self.elapsed(), 3),
                deadline_s=self.deadline_s)
            raise DeadlineExceededError(
                "shard exceeded its deadline",
                shard_id=self.shard_id,
                elapsed_s=self.elapsed(),
                deadline_s=self.deadline_s,
            )


# ---------------------------------------------------------------------------
# Hedged fetches — first-result-wins duplicate reads
# ---------------------------------------------------------------------------


def _payload_nbytes(value: Any) -> int:
    """Best-effort byte size of a discarded fetch payload (the
    ``hedge.wasted_bytes`` booking): bytes-likes report their length,
    staged tuples (the sources' fetch payloads carry the compressed
    range as one bytes element) sum their bytes-like elements."""
    if value is None:
        return 0
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, tuple):
        return sum(len(v) for v in value
                   if isinstance(v, (bytes, bytearray, memoryview)))
    nbytes = getattr(value, "nbytes", None)
    return int(nbytes) if isinstance(nbytes, int) else 0


class HedgeController:
    """Per-run hedged-fetch machinery: tracks a rolling window of fetch
    latencies and races a duplicate against any fetch that outlives the
    configured quantile of that window (never below ``min_s`` — a warm
    run must not hedge every fetch because the window is fast).

    The worker pool is created lazily on the first hedge launch, so a
    run whose fetches all beat the threshold costs one ``wait()``
    timeout per fetch and zero threads.  ``close()`` cancels any
    pending duplicates — the executor calls it from the same ``finally``
    that shuts the stage pools down, so an aborted run leaves no
    orphaned hedge futures behind."""

    WINDOW = 128

    def __init__(self, quantile: float, min_s: float,
                 max_workers: int = 4) -> None:
        if not 0.0 < quantile < 1.0:
            raise ValueError(
                f"hedge_quantile must be in (0, 1), got {quantile}")
        self.quantile = float(quantile)
        self.min_s = float(min_s)
        self._max_workers = max(1, int(max_workers))
        self._lock = threading.Lock()
        self._latencies: "deque[float]" = deque(maxlen=self.WINDOW)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._closed = False

    # -- latency window -----------------------------------------------------

    def record(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)

    def threshold(self) -> float:
        """Current hedge delay: the rolling ``quantile`` of observed
        fetch latencies, floored at ``min_s`` (which is also the cold
        answer while the window is empty)."""
        with self._lock:
            lats = sorted(self._latencies)
        if not lats:
            return self.min_s
        k = min(len(lats) - 1, int(self.quantile * len(lats)))
        return max(self.min_s, lats[k])

    # -- pool lifecycle -----------------------------------------------------

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._closed:
                raise RuntimeError("hedge controller already closed")
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._max_workers,
                    thread_name_prefix="disq-hedge")
            return self._pool

    def close(self) -> None:
        """Tear the hedge pool down, cancelling queued duplicates (a
        duplicate already running finishes its I/O and is discarded by
        its done-callback)."""
        with self._lock:
            pool, self._pool = self._pool, None
            self._closed = True
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    # -- the hedged call ----------------------------------------------------

    def call(self, fn: Callable[[], Any], shard_id: int = -1,
             deadline: Optional[ShardDeadline] = None,
             on_outcome: Optional[Callable[[str, bool], None]] = None) -> Any:
        """Run ``fn`` with hedging: if it outlives the rolling-quantile
        threshold, launch a duplicate and take whichever finishes first
        (first *success* wins; if one side fails while the other is in
        flight, the survivor's outcome decides).  With a deadline past
        its escalation point the duplicate launches immediately.

        ``on_outcome(winner, hedged)`` — when given — fires once per
        resolved call with ``winner`` in ``{"primary", "hedge",
        "neither"}`` and whether a duplicate was launched, so callers
        like the fleet router can book their own hedge accounting
        (``fleet.hedge.*``) without re-deriving the race result."""
        delay = self.threshold()
        if deadline is not None and deadline.should_force_hedge():
            counter("deadline.hedge_forced").inc()
            flightrec.record_event("hedge_forced", shard=shard_id,
                                   elapsed_s=round(deadline.elapsed(), 3))
            delay = 0.0
        pool = self._ensure_pool()
        t0 = time.perf_counter()
        primary = pool.submit(fn)
        done, _ = wait([primary], timeout=delay)
        if primary in done:
            if primary.exception() is None:
                self.record(time.perf_counter() - t0)
                if on_outcome is not None:
                    on_outcome("primary", False)
            return primary.result()

        counter("hedge.launched").inc()
        flightrec.record_event("hedge_launched", shard=shard_id,
                               delay_s=round(delay, 4))
        h0 = time.perf_counter()

        def duplicate() -> Any:
            with span("hedge.fetch", shard=shard_id):
                return fn()

        secondary = pool.submit(duplicate)
        futures = {primary: "primary", secondary: "hedge"}
        winner = None
        first_error: Optional[BaseException] = None
        pending = set(futures)
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            ok = [f for f in done if f.exception() is None]
            if ok:
                winner = ok[0]
                break
            for f in done:
                if first_error is None:
                    first_error = f.exception()
        if winner is None:
            # Both sides failed: surface the first failure (the retrier
            # above classifies and may retry the whole hedged call).
            # Booked as winner="neither" so launched == won stays an
            # exact invariant (check_resilience.py asserts it).
            counter("hedge.won").inc(winner="neither")
            if on_outcome is not None:
                on_outcome("neither", True)
            raise first_error  # type: ignore[misc]
        loser = secondary if winner is primary else primary
        loser_started = t0 if loser is primary else h0
        counter("hedge.won").inc(winner=futures[winner])
        if on_outcome is not None:
            on_outcome(futures[winner], True)
        if winner is primary:
            self.record(time.perf_counter() - t0)
        if not loser.cancel():
            # Still running: discard its payload when it lands, booking
            # the bytes and time the lost race burned.
            def _discard(f, started=loser_started, shard=shard_id) -> None:
                burned = time.perf_counter() - started
                record_span("hedge.waste", burned, shard=shard)
                if f.cancelled() or f.exception() is not None:
                    return
                counter("hedge.wasted_bytes").inc(
                    _payload_nbytes(f.result()))

            loser.add_done_callback(_discard)
        return winner.result()


# ---------------------------------------------------------------------------
# Per-run manager + options plumbing
# ---------------------------------------------------------------------------


class ResilienceManager:
    """One run's resilience bundle, built by ``resilience_for_options``:
    the hedge controller (if hedging is on) and the deadline factory
    (if deadlines are on).  The executor owns its lifecycle — ``close``
    runs in the same ``finally`` as the stage-pool shutdown."""

    def __init__(self, hedge: Optional[HedgeController] = None,
                 deadline_s: Optional[float] = None) -> None:
        self.hedge = hedge
        self.deadline_s = deadline_s

    def new_deadline(self, shard_id: int) -> Optional[ShardDeadline]:
        if self.deadline_s is None:
            return None
        return ShardDeadline(self.deadline_s, shard_id=shard_id)

    def fetch(self, fn: Callable[[], Any], shard_id: int = -1,
              deadline: Optional[ShardDeadline] = None) -> Any:
        if self.hedge is None:
            return fn()
        return self.hedge.call(fn, shard_id=shard_id, deadline=deadline)

    def close(self) -> None:
        if self.hedge is not None:
            self.hedge.close()


def configure_globals_from_options(opts) -> None:
    """Install the process-wide budget/breaker configuration from one
    ``DisqOptions`` — the single chokepoint every entry path
    (``context_for_storage``, ``write_retrier_for_storage``,
    ``resilience_for_options``) funnels through.  Budget/breakers are
    process-wide (they model the shared store): a run that sets the
    knobs installs them; a run that doesn't leaves another run's
    protection alone (clear via ``reset_resilience``)."""
    if getattr(opts, "retry_budget_tokens", None) is not None:
        configure_budget(opts.retry_budget_tokens,
                         getattr(opts, "retry_budget_refill", 0.1))
    if getattr(opts, "breaker_window", None) is not None:
        configure_breakers(opts.breaker_window,
                           getattr(opts, "breaker_cooldown_s", 1.0))


def resilience_for_options(opts: Optional[DisqOptions]
                           ) -> Optional[ResilienceManager]:
    """Resolve one ``DisqOptions``' resilience knobs.  Also installs
    the process-wide budget/breaker configuration (they are consulted
    by every ``ShardRetrier``, not just this run's pipeline).  Returns
    None on the default path — the executor then never touches this
    module per shard."""
    if opts is None:
        return None
    configure_globals_from_options(opts)
    quantile = getattr(opts, "hedge_quantile", None)
    deadline_s = getattr(opts, "shard_deadline_s", None)
    if quantile is None and deadline_s is None:
        return None
    hedge = None
    if quantile is not None:
        # Primaries AND duplicates share the hedge pool: size it at
        # 2 × the fetch concurrency so a correlated slow tail hitting
        # every worker at once (exactly what hedging exists for) still
        # leaves a free slot for each duplicate — W primaries must
        # never queue out their own hedges.
        workers = max(1, int(getattr(opts, "executor_workers", 1)))
        hedge = HedgeController(
            quantile, getattr(opts, "hedge_min_s", 0.05),
            max_workers=2 * workers)
    return ResilienceManager(hedge=hedge, deadline_s=deadline_s)


def snapshot() -> Dict[str, Any]:
    """Resilience state for ``/healthz``: the budget's fill level and
    every breaker's state machine.  Empty dict when nothing is
    configured (the endpoint then omits the section)."""
    out: Dict[str, Any] = {}
    budget = _BUDGET
    if budget is not None:
        out["budget"] = budget.snapshot()
    breakers = breakers_snapshot()
    if breakers:
        out["breakers"] = breakers
    return out


def reset_resilience() -> None:
    """Test hook: drop the budget, every breaker, and their config."""
    global _BUDGET, _breaker_config
    with _budget_lock:
        _BUDGET = None
    with _breaker_lock:
        _BREAKERS.clear()
        _breaker_config = None
