"""Per-shard counters, returned per shard and reduced (SURVEY.md §5).

The reference surfaces record counts only through user-level Spark
accumulators; disq_tpu makes them first-class: every source/sink shard
can fill a ``ShardCounters``, and ``reduce_counters`` folds them into
pipeline totals (records, blocks, bytes in/out, compression ratio).
On-device reductions (e.g. flagstat's psum) remain separate — these are
host-side bookkeeping for observability, not data-path state.
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Dict, Iterable


@dataclass
class ShardCounters:
    shard_id: int = -1
    records: int = 0
    blocks: int = 0
    bytes_compressed: int = 0
    bytes_uncompressed: int = 0
    wall_seconds: float = 0.0
    # Error-policy observability (runtime/errors.py): how many corrupt
    # blocks this shard dropped / copied aside, and how many transient
    # read failures were absorbed by retry.
    skipped_blocks: int = 0
    quarantined_blocks: int = 0
    retried_reads: int = 0

    def as_dict(self) -> Dict[str, float]:
        return {f.name: getattr(self, f.name) for f in fields(self)}


@dataclass
class PipelineCounters:
    shards: int = 0
    records: int = 0
    blocks: int = 0
    bytes_compressed: int = 0
    bytes_uncompressed: int = 0
    wall_seconds: float = 0.0
    skipped_blocks: int = 0
    quarantined_blocks: int = 0
    retried_reads: int = 0

    @property
    def compression_ratio(self) -> float:
        if self.bytes_compressed == 0:
            return 0.0
        return self.bytes_uncompressed / self.bytes_compressed

    def as_dict(self) -> Dict[str, float]:
        d = {f.name: getattr(self, f.name) for f in fields(self)}
        d["compression_ratio"] = round(self.compression_ratio, 4)
        return d


def reduce_counters(shard_counters: Iterable[ShardCounters]) -> PipelineCounters:
    # Field-wise sum over every ShardCounters field except shard_id, so a
    # counter added to both dataclasses folds without touching this code.
    summed = [f.name for f in fields(ShardCounters) if f.name != "shard_id"]
    total = PipelineCounters()
    for c in shard_counters:
        total.shards += 1
        for name in summed:
            setattr(total, name, getattr(total, name) + getattr(c, name))
    return total
