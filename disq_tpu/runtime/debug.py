"""Debug invariant mode (SURVEY.md §5, "race detection" row).

The reference gets safety from architecture (share-nothing tasks,
driver-serial merge); disq_tpu keeps that shape — cross-chip interaction
is only collective ops, race-free by construction — and adds a debug
mode asserting shard-boundary invariants after each phase: consistent
column lengths, monotone ragged offsets, strictly increasing virtual
file offsets. Enabled by ``DISQ_TPU_DEBUG=1`` (checks are O(N) numpy
passes on host; off by default in the hot path).
"""

from __future__ import annotations

import os

import numpy as np


def env_flag(name: str, default: str = "0") -> bool:
    """Shared boolean env-var semantics: unset⇒``default``; ""/0/false/
    off ⇒ False."""
    return os.environ.get(name, default).lower() not in (
        "", "0", "false", "off")


def debug_enabled() -> bool:
    return env_flag("DISQ_TPU_DEBUG")


def _check_offsets(name: str, offsets: np.ndarray, n: int, data_len: int) -> None:
    if offsets.shape != (n + 1,):
        raise AssertionError(
            f"{name}_offsets shape {offsets.shape} != ({n + 1},)"
        )
    if n >= 0 and len(offsets):
        if offsets[0] != 0:
            raise AssertionError(f"{name}_offsets[0] = {offsets[0]} != 0")
        if np.any(np.diff(offsets) < 0):
            raise AssertionError(f"{name}_offsets not monotone")
        if offsets[-1] != data_len:
            raise AssertionError(
                f"{name}_offsets[-1] = {offsets[-1]} != len = {data_len}"
            )


def check_read_batch(batch, n_ref: int = None) -> None:
    """Assert columnar invariants on a ReadBatch (shard-boundary check)."""
    n = batch.count
    for col in ("pos", "mapq", "bin", "flag", "next_refid", "next_pos", "tlen"):
        arr = getattr(batch, col)
        if len(arr) != n:
            raise AssertionError(f"column {col} length {len(arr)} != {n}")
    _check_offsets("name", batch.name_offsets, n, len(batch.names))
    _check_offsets("cigar", batch.cigar_offsets, n, len(batch.cigars))
    _check_offsets("seq", batch.seq_offsets, n, len(batch.seqs))
    _check_offsets("tag", batch.tag_offsets, n, len(batch.tags))
    if len(batch.quals) != len(batch.seqs):
        raise AssertionError("quals length != seqs length")
    if n_ref is not None and n:
        rid = np.asarray(batch.refid)
        if rid.min(initial=0) < -1 or rid.max(initial=-1) >= n_ref:
            raise AssertionError(f"refid outside [-1, {n_ref})")


def check_voffsets(voffsets: np.ndarray) -> None:
    """Virtual file offsets of successive records must strictly increase."""
    v = np.asarray(voffsets, dtype=np.uint64)
    if len(v) > 1 and np.any(v[1:] <= v[:-1]):
        bad = int(np.argmax(v[1:] <= v[:-1]))
        raise AssertionError(
            f"virtual offsets not strictly increasing at record {bad + 1}"
        )
