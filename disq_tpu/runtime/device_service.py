"""Cross-shard device decode service — one dispatcher owns the device
queue and feeds the 128-lane SIMD codecs at full lane utilization.

Why: the SIMD kernels decode 128 independent streams per launch, but
the per-shard dispatch in ``bgzf/codec.py`` / ``cram/rans.py`` submits
one shard's blocks at a time — a shard with 40 BGZF blocks launches a
40/128-full chunk, and N executor decode workers each do so
*concurrently*, so the device sees N partial launches instead of the
few full ones the work actually needs (TPU_KERNELS.json: 54.2 MB/s
kernel-only vs 17.96 MB/s end-to-end — the whole gap is host packing,
per-chunk allocation and partial lanes).

This module inverts the ownership, the way "Extending TensorFlow's
Semantics with Pipelined Execution" overlaps producer/consumer stages:
executor decode stages submit their shard's block batch
(``submit_inflate`` / ``submit_rans``) — and, since the symmetric
write path, write-pipeline deflate stages submit their shard's
uncompressed BGZF block payloads (``submit_deflate``) — and get a
future back; ONE
dispatcher thread coalesces blocks *across* in-flight shards into full
128-lane chunks (flushing on full, on an oldest-lane timeout, or at
drain), keeps an adaptive window of launches in flight
(``inflate_simd.dispatch_window``), packs into pooled staging arenas,
and writes each decoded lane straight from the kernel's transposed
output into the owning submission's preallocated blob — zero
intermediate ``bytes`` objects on the device path.

Multi-chip (the mesh-native pipeline, ``runtime/mesh.py``): when the
mesh knob is armed at service creation, each codec keeps one sub-queue
PER DEVICE and the single dispatcher feeds them all — a submission's
lanes land on the least-loaded device, each launch runs under
``jax.default_device(dev)`` (const tables and staging land on that
chip, ``inflate_simd._device_const_tables`` is device-keyed), and the
in-flight window scales by the device count so every chip keeps a full
pipeline instead of device 0 taking all launches.  Mesh off, the
device list is ``[None]`` and every code path below degenerates to the
exact single-queue behavior it had before.

Error isolation is strict per submission: a lane the kernel flags is
re-inflated on host; if the host also fails (truly corrupt input) only
the OWNER shard's future raises — lanes co-batched from other shards
are delivered regardless.  Oversize payloads never enter the queue:
they decode on the submitting shard's own thread, exactly like the
per-shard dispatch did.

Telemetry: ``device.lane_fill`` (lanes per launch / 128),
``device.queue_depth``, ``device.batch.flush{reason=full|timeout|drain}``,
``device.service.wait`` (oldest-lane queue wait per flushed chunk) and
the arena pool's ``device.arena_bytes``.

Enablement: ``DISQ_TPU_DEVICE_SERVICE=1`` — checked by the codec entry
points alongside ``DISQ_TPU_DEVICE_INFLATE`` / ``DISQ_TPU_DEVICE_RANS``.
Disabled (the default), no thread, queue or arena exists and the
per-shard dispatch runs exactly as before — the zero-overhead contract
``scripts/check_overhead.py`` guards.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from disq_tpu.runtime import flightrec as _flightrec
from disq_tpu.runtime.tracing import (
    counter as _counter,
    current_trace as _current_trace,
    observe_gauge as _observe_gauge,
    record_span as _record_span,
    trace_scope as _trace_scope,
)

LANES = 128  # mirrors ops/inflate_simd.LANES (not imported: keep this
#              module importable without pulling jax in)


class _Lane:
    """One block/stream queued for a kernel lane."""

    __slots__ = ("sub", "index", "payload", "expect", "ts", "trace")

    def __init__(self, sub: "Submission", index: int, payload: Any,
                 expect: int, ts: float, trace: Any = None) -> None:
        self.sub = sub
        self.index = index
        self.payload = payload
        self.expect = expect
        self.ts = ts
        # the submitting request's TraceContext (or None): rides the
        # thread hop into the dispatcher so a coalesced launch can book
        # each owner request's share of queue wait + launch time
        self.trace = trace


class Submission:
    """Future for one shard's submitted batch.

    Inflate submissions carry a preallocated ``blob`` + ``offsets``
    (usizes are always known for BGZF) that lanes are written into as
    they materialize; rANS submissions collect per-stream ``parts``.
    The first failing owner lane records the error and releases the
    waiter — late lanes of a failed submission are dropped."""

    __slots__ = ("_event", "_lock", "_pending", "_error", "blob",
                 "offsets", "parts")

    def __init__(self, blob: Optional[np.ndarray] = None,
                 offsets: Optional[np.ndarray] = None,
                 parts_n: Optional[int] = None) -> None:
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.blob = blob
        self.offsets = offsets
        self.parts: Optional[List[Optional[bytes]]] = (
            [None] * parts_n if parts_n is not None else None)
        self._pending = (parts_n if parts_n is not None
                         else len(offsets) - 1)
        self._error: Optional[BaseException] = None
        if self._pending == 0:
            self._event.set()

    def _store(self, index: int, value: Any) -> None:
        if self.parts is not None:
            self.parts[index] = (value if isinstance(value, bytes)
                                 else bytes(value))
        else:
            lo = int(self.offsets[index])
            hi = int(self.offsets[index + 1])
            if isinstance(value, np.ndarray):
                self.blob[lo:hi] = value
            else:
                self.blob[lo:hi] = np.frombuffer(value, dtype=np.uint8)

    def deliver_local(self, index: int, value: Any) -> None:
        """Pre-enqueue delivery on the submitting thread (oversize /
        empty lanes) — no lock needed, the dispatcher can't see the
        submission yet."""
        self._store(index, value)
        self._pending -= 1

    def deliver(self, index: int, value: Any) -> None:
        with self._lock:
            if self._error is None:
                self._store(index, value)
            self._pending -= 1
            if self._pending <= 0:
                self._event.set()

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = exc
            self._pending -= 1
            self._event.set()

    def result(self, timeout: Optional[float] = None):
        """Block until every lane landed (or the first owner-lane
        error); returns ``(blob, offsets)`` for inflate submissions,
        the parts list for rANS ones."""
        if not self._event.wait(timeout):
            raise TimeoutError("device decode service result timed out")
        if self._error is not None:
            raise self._error
        if self.parts is not None:
            return list(self.parts)
        return self.blob, self.offsets


class _InflateEngine:
    """Launch/finalize hooks for BGZF raw-DEFLATE lanes, built on the
    refactored ops/inflate_simd dispatch helpers (shared arenas,
    device-resident const tables, transposed+donated compile).

    ``host_map`` (from the owning service) fans multi-lane host-zlib
    fallbacks out over the service's host pool so a degraded shard's
    re-inflates don't serialize on the dispatcher thread and stall
    every co-batched shard's queue."""

    kind = "inflate"

    def __init__(self, interpret: bool, host_map) -> None:
        self._interpret = bool(interpret)
        self._host_map = host_map

    def launch(self, lanes: Sequence[_Lane]):
        import jax.numpy as jnp

        from disq_tpu.ops import inflate_simd as IS

        payloads = [l.payload for l in lanes]
        cw, ow = IS.buckets_for(
            payloads, max(l.expect for l in lanes))
        arena = IS.ARENAS.acquire(
            ("inflate", cw), lambda: IS._PackArena(cw))
        try:
            comp, clen = IS._pack_chunk(payloads, cw, arena)
            IS._count_transfer("h2d", comp.nbytes + clen.nbytes)
            fn = IS._compiled(cw, ow, self._interpret, True, True)
            out = fn(jnp.asarray(comp), jnp.asarray(clen),
                     *IS._device_const_tables())
        except BaseException:
            IS.ARENAS.release(("inflate", cw), arena)
            raise
        return out, arena, cw

    def finalize(self, handle, lanes: Sequence[_Lane]) -> None:
        from disq_tpu.ops import inflate_simd as IS

        out, arena, cw = handle
        try:
            lanes_u8, meta = IS._fetch_chunk(out, len(lanes))
        finally:
            IS.ARENAS.release(("inflate", cw), arena)
        flagged: List[_Lane] = []
        for j, lane in enumerate(lanes):
            n, status = int(meta[0, j]), int(meta[1, j])
            if status != 0 or n != lane.expect:
                IS.last_stats["host_fallback"] += 1
                _counter("device.host_fallback_blocks").inc(
                    reason="flagged")
                flagged.append(lane)
            else:
                IS.last_stats["device_lanes"] += 1
                lane.sub.deliver(lane.index, lanes_u8[j, :n])
        if flagged:
            self._host_map(
                flagged,
                lambda lane: IS.host_inflate(lane.payload, lane.expect))


class _RansEngine:
    """Launch/finalize hooks for CRAM order-0 rANS lanes; a lane's
    payload is ``(stream bytes, parsed meta)`` — the host-side table
    parse already happened on the submitting thread (and raised there
    for a corrupt header: owner-only by construction)."""

    kind = "rans"

    def __init__(self, interpret: bool, host_map) -> None:
        self._interpret = bool(interpret)
        self._host_map = host_map

    def launch(self, lanes: Sequence[_Lane]):
        import jax.numpy as jnp

        from disq_tpu.ops import inflate_simd as IS
        from disq_tpu.ops import rans_simd as RS

        metas = [l.payload[1] for l in lanes]
        cw, ow = RS.kernel_geometry(metas)
        arena = IS.ARENAS.acquire(("rans", cw),
                                  lambda: RS._rans_arena(cw))
        try:
            args = RS.pack_lane_tables(metas, cw, arena)
            IS._count_transfer("h2d", sum(a.nbytes for a in args))
            fn = RS._compiled(cw, ow, self._interpret, True, True)
            out = fn(*(jnp.asarray(a) for a in args))
        except BaseException:
            IS.ARENAS.release(("rans", cw), arena)
            raise
        return out, arena, cw

    def finalize(self, handle, lanes: Sequence[_Lane]) -> None:
        from disq_tpu.ops import inflate_simd as IS
        from disq_tpu.ops import rans_simd as RS

        out, arena, cw = handle
        try:
            lanes_u8, meta = RS._fetch_chunk(out, len(lanes))
        finally:
            IS.ARENAS.release(("rans", cw), arena)
        flagged: List[_Lane] = []
        for j, lane in enumerate(lanes):
            if int(meta[1, j]) != 0:
                RS.last_stats["host_fallback"] += 1
                _counter("device.host_fallback_blocks").inc(
                    reason="flagged")
                flagged.append(lane)
            else:
                RS.last_stats["device_lanes"] += 1
                lane.sub.deliver(lane.index, lanes_u8[j, : lane.expect])
        if flagged:
            self._host_map(
                flagged, lambda lane: RS._host_decode0(lane.payload[0]))


class _DeflateEngine:
    """Launch/finalize hooks for BGZF DEFLATE *encode* lanes — the
    write-side mirror of ``_InflateEngine`` (ops/deflate's 128-lane
    batched entropy coder on the shared arena/packer layout).

    A lane's payload is ``(block payload bytes <= 65280, its 256-bin
    histogram)`` — the histogram was computed on the SUBMITTING thread
    so this dispatcher only sums small vectors; its delivery is the
    complete framed BGZF block.  Each flushed chunk builds ONE shared
    Huffman table from its lanes' combined histogram (blocks
    co-batched from different shards share the table — bit-valid for
    every lane; the table is part of each block's own dynamic header
    so shards stay independent).  Lanes the entropy coder expanded
    reroute to host zlib over the service's host pool, off the
    dispatcher thread."""

    kind = "deflate"

    def __init__(self, interpret: bool, host_map) -> None:
        # the encoder is plain jitted XLA (no Pallas): interpret is
        # accepted for engine-construction symmetry but unused
        self._host_map = host_map

    def launch(self, lanes: Sequence[_Lane]):
        from disq_tpu.ops import deflate as DF

        payloads = [l.payload[0] for l in lanes]
        freq = np.zeros(256, np.int64)
        for l in lanes:
            freq += l.payload[1]
        table = DF.DeflateTable(freq, len(lanes))
        handle = DF.launch_chunk(payloads, table)
        return handle, table

    def finalize(self, handle, lanes: Sequence[_Lane]) -> None:
        from disq_tpu.ops import deflate as DF

        chunk_handle, table = handle
        try:
            bodies, end = DF.fetch_chunk(chunk_handle, table, len(lanes))
        finally:
            DF.release_chunk_arena(chunk_handle)
        # shared per-lane finalize: identical framing + accounting on
        # every route; expanded lanes fan out over the service's host
        # pool, off this dispatcher thread
        DF.finalize_chunk(
            bodies, end, table, [l.payload[0] for l in lanes],
            lambda j, blk: lanes[j].sub.deliver(lanes[j].index, blk),
            lambda flagged: self._host_map(
                [lanes[j] for j in flagged],
                lambda lane: DF.host_block(lane.payload[0])))


class DeviceDecodeService:
    """The dispatcher that owns the device queue (module docstring)."""

    def __init__(self, flush_timeout_s: Optional[float] = None,
                 interpret: Optional[bool] = None) -> None:
        import os

        if flush_timeout_s is None:
            flush_timeout_s = float(
                os.environ.get("DISQ_TPU_SERVICE_FLUSH_MS", "2")) / 1e3
        self.flush_timeout_s = flush_timeout_s
        if interpret is None:
            import jax

            interpret = jax.default_backend() != "tpu"
        # outstanding fire-and-forget host-fallback lanes (drained at
        # close so shutdown never strands a waiter); the pool itself is
        # the process-wide disq_tpu.util.shared_host_pool
        self._fallback_pending = 0
        self._engines = {
            "inflate": _InflateEngine(interpret, self._host_map),
            "rans": _RansEngine(interpret, self._host_map),
            "deflate": _DeflateEngine(interpret, self._host_map),
        }
        self._cond = threading.Condition()
        # dispatch targets, snapshotted once: [None] (default-device
        # semantics) unless the mesh knob was armed before service
        # start — then one sub-queue per mesh device (module docstring)
        from disq_tpu.runtime.mesh import service_devices

        self._devices = service_devices()
        n_dev = len(self._devices)
        self._queues: Dict[str, List[Deque[_Lane]]] = {
            k: [deque() for _ in range(n_dev)]
            for k in ("inflate", "rans", "deflate")}
        self._inflight: Deque[Tuple[str, Any, List[_Lane]]] = deque()
        self._closed = False
        # window sized for the standard full-BGZF geometry; the env
        # knobs in dispatch_window apply here too.  Scaled by the
        # device count: the window bounds launches IN FLIGHT, and with
        # n chips each wants its own pipeline of them
        from disq_tpu.ops.inflate_simd import dispatch_window

        self._window = dispatch_window(4, 16 << 20) * n_dev
        self._thread = threading.Thread(
            target=self._run, name="disq-device-dispatch", daemon=True)
        self._thread.start()

    # -- submission ---------------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._closed

    def submit_inflate(self, payloads: Sequence,
                       usizes: Sequence[int]) -> Submission:
        """Submit one shard's raw-DEFLATE block batch; the result is
        ``(blob, offsets)`` — decoded bytes of every block, contiguous
        in submission order.  Oversize blocks decode on THIS thread
        (host zlib), exactly like the per-shard dispatch."""
        from disq_tpu.ops import inflate_simd as IS

        n = len(payloads)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(np.asarray([int(u) for u in usizes], np.int64),
                  out=offsets[1:])
        sub = Submission(blob=np.empty(int(offsets[-1]), np.uint8),
                         offsets=offsets)
        ctx = _current_trace()
        lanes: List[_Lane] = []
        for i, p in enumerate(payloads):
            if len(p) > IS.MAX_DEVICE_CSIZE:
                IS.last_stats["host_big"] += 1
                _counter("device.host_fallback_blocks").inc(
                    reason="oversize")
                sub.deliver_local(i, IS.host_inflate(p, int(usizes[i])))
            else:
                # ts stamped at enqueue (see _enqueue)
                lanes.append(_Lane(sub, i, p, int(usizes[i]), 0.0, ctx))
        self._enqueue("inflate", lanes, sub)
        return sub

    def submit_rans(self, streams: Sequence[bytes]) -> Submission:
        """Submit order-0 rANS streams; the result is the per-stream
        decoded bytes list.  Header parse / oversize fallbacks run on
        THIS thread (owner-only errors by construction)."""
        from disq_tpu.ops import rans_simd as RS

        n = len(streams)
        sub = Submission(parts_n=n)
        ctx = _current_trace()
        lanes: List[_Lane] = []
        for k, s in enumerate(streams):
            meta = RS._parse_stream(k, s)
            if meta is None:
                sub.deliver_local(k, b"")
                continue
            if (len(meta[1]) > RS.MAX_DEVICE_CSIZE
                    or meta[0] > RS.MAX_DEVICE_RAW):
                RS.last_stats["host_big"] += 1
                _counter("device.host_fallback_blocks").inc(
                    reason="oversize")
                sub.deliver_local(k, RS._host_decode0(s))
                continue
            lanes.append(_Lane(sub, k, (s, meta), meta[0], 0.0, ctx))
        self._enqueue("rans", lanes, sub)
        return sub

    def submit_deflate(self, payloads: Sequence) -> Submission:
        """Submit one write shard's uncompressed BGZF block payloads
        (each <= 65280 bytes, the canonical blocking ``deflate_blob``
        applies); the result is the per-block framed BGZF block bytes
        list, in submission order.  The dispatcher coalesces blocks
        ACROSS in-flight write shards into full 128-lane encode
        launches — the write-side mirror of ``submit_inflate``.
        A payload over the BGZF bound raises HERE (no encode can frame
        it as one block — ``deflate_block``'s contract); each lane's
        byte histogram is computed on THIS thread so the dispatcher
        only sums them per chunk instead of rescanning up to ~8 MB of
        payload while every other queue waits."""
        from disq_tpu.bgzf.block import BGZF_MAX_PAYLOAD

        n = len(payloads)
        sub = Submission(parts_n=n)
        ctx = _current_trace()
        lanes: List[_Lane] = []
        for i, p in enumerate(payloads):
            if len(p) > BGZF_MAX_PAYLOAD:
                raise ValueError(
                    f"payload too large for one BGZF block: {len(p)}")
            if len(p) == 0:
                sub.deliver_local(i, b"")
            else:
                hist = np.bincount(
                    np.frombuffer(p, np.uint8),
                    minlength=256).astype(np.int64)
                lanes.append(_Lane(sub, i, (p, hist), len(p), 0.0, ctx))
        self._enqueue("deflate", lanes, sub)
        return sub

    def _enqueue(self, kind: str, lanes: List[_Lane],
                 sub: Submission) -> None:
        # stamp the flush clock HERE, not at submission start: oversize
        # host decode / rANS table parsing on the submitting thread can
        # take longer than the flush timeout, and pre-aged lanes would
        # flush immediately at partial fill — defeating the coalescing
        # this queue exists for
        now = time.perf_counter()
        for lane in lanes:
            lane.ts = now
        with self._cond:
            if self._closed:
                raise RuntimeError("device decode service is closed")
            # least-loaded device sub-queue takes the whole batch (one
            # submission's lanes stay together — they share pack
            # geometry and error scope); with one device this is the
            # old single-queue append
            subqs = self._queues[kind]
            subqs[min(range(len(subqs)),
                      key=lambda i: len(subqs[i]))].extend(lanes)
            depth = sum(
                len(q) for qs in self._queues.values() for q in qs)
            if sub._pending <= 0:
                sub._event.set()
            self._cond.notify_all()
        _observe_gauge("device.queue_depth", depth)

    def _host_map(self, lanes: List[_Lane], fn) -> None:
        """Deliver host-fallback lanes, fanning multi-lane work over
        the process-wide host pool so a degraded shard's re-decodes
        don't serialize the dispatcher (and stall co-batched shards); a
        host failure fails ONLY the owner submission."""

        def one(lane: _Lane) -> None:
            try:
                val = fn(lane)
            except Exception as e:  # noqa: BLE001 — owner-only
                lane.sub.fail(e)
            else:
                lane.sub.deliver(lane.index, val)

        if len(lanes) <= 1:
            for lane in lanes:
                one(lane)
            return
        from disq_tpu.util import shared_host_pool

        def tracked(lane: _Lane) -> None:
            try:
                one(lane)
            finally:
                with self._cond:
                    self._fallback_pending -= 1
                    self._cond.notify_all()

        # fire-and-forget: each lane delivers (or fails its owner) from
        # the pool; the dispatcher goes straight back to launching
        with self._cond:
            self._fallback_pending += len(lanes)
        pool = shared_host_pool()
        for lane in lanes:
            pool.submit(tracked, lane)

    def close(self, timeout: float = 30.0) -> None:
        """Drain the queue (remaining partial chunks flush with
        ``reason=drain``), wait out any in-flight host-fallback lanes,
        and stop the dispatcher."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        with self._cond:
            self._cond.wait_for(
                lambda: self._fallback_pending <= 0, timeout)

    # -- dispatcher ---------------------------------------------------------

    def _run(self) -> None:
        try:
            self._loop()
        except BaseException as e:  # noqa: BLE001 — fail pending, not hang
            self._abort_all(e)

    def _loop(self) -> None:
        while True:
            chunk = None
            with self._cond:
                while True:
                    chunk = self._take_chunk_locked()
                    if chunk is not None:
                        break
                    if self._inflight:
                        break  # overlap the wait with a materialize
                    if self._closed:
                        return
                    self._cond.wait(self._wait_s_locked())
            if chunk is not None:
                kind, dev_i, lanes, reason = chunk
                try:
                    entry = self._launch(kind, dev_i, lanes, reason)
                except BaseException as e:
                    # the chunk is already out of the queues, so
                    # _abort_all can't see it — fail its owners here
                    # or they wait forever
                    for lane in lanes:
                        lane.sub.fail(e)
                    raise
                if entry is not None:
                    self._inflight.append(entry)
            if self._inflight and (chunk is None
                                   or len(self._inflight) >= self._window):
                self._materialize(self._inflight.popleft())

    def _take_chunk_locked(self):
        now = time.perf_counter()
        # oldest-lane-first across (kind, device) sub-queues: a
        # sustained full-chunk burst on one codec or chip must not
        # starve another queue's lanes past their flush deadline
        ready = sorted(
            ((k, i) for k, qs in self._queues.items()
             for i, q in enumerate(qs) if q),
            key=lambda ki: self._queues[ki[0]][ki[1]][0].ts)
        for kind, i in ready:
            q = self._queues[kind][i]
            if len(q) >= LANES:
                lanes = [q.popleft() for _ in range(LANES)]
                reason = "full"
            elif self._closed or (now - q[0].ts) >= self.flush_timeout_s:
                lanes = list(q)
                q.clear()
                reason = "drain" if self._closed else "timeout"
            else:
                continue
            return kind, i, lanes, reason
        return None

    def _wait_s_locked(self) -> Optional[float]:
        now = time.perf_counter()
        waits = [
            self.flush_timeout_s - (now - q[0].ts)
            for qs in self._queues.values() for q in qs if q
        ]
        if not waits:
            return None  # nothing queued: sleep until a notify
        return max(1e-3, min(waits))

    def _launch(self, kind: str, dev_i: int, lanes: List[_Lane],
                reason: str):
        dev = self._devices[dev_i]
        _counter("device.batch.flush").inc(reason=reason)
        _flightrec.record_event("device_flush", codec=kind,
                                reason=reason, lanes=len(lanes))
        # mesh-off ([None]) keeps the historic unlabeled gauge; a real
        # device list labels fill per chip so partial lanes on one
        # device are visible, not averaged away
        if dev is None:
            _observe_gauge("device.lane_fill", len(lanes) / LANES)
        else:
            _observe_gauge("device.lane_fill", len(lanes) / LANES,
                           device=str(dev_i))
        _observe_gauge(
            "device.queue_depth",
            sum(len(q) for qs in self._queues.values() for q in qs))
        _record_span("device.service.wait",
                     time.perf_counter() - min(l.ts for l in lanes),
                     kind=kind, lanes=len(lanes))
        # group lanes by owning request context (None = untraced): a
        # coalesced launch serves n distinct requests, and each owner
        # inherits its share of queue wait + launch time below
        owners: Dict[Tuple[str, str, str], List[_Lane]] = {}
        for lane in lanes:
            if lane.trace is not None:
                owners.setdefault(
                    (lane.trace.trace_id, lane.trace.span_id,
                     lane.trace.tenant), []).append(lane)
        if owners:
            # label is "requests", not "n" — Counter.inc's first
            # positional is the increment amount named n, so a label
            # called n would collide with it
            _counter("device.batch.requests").inc(
                requests=str(len(owners)))
        t_launch = time.perf_counter()
        try:
            if dev is None:
                handle = self._engines[kind].launch(lanes)
            else:
                import jax

                with jax.default_device(dev):
                    handle = self._engines[kind].launch(lanes)
        except BaseException as e:  # noqa: BLE001 — owners, not the loop
            for lane in lanes:
                lane.sub.fail(e)
            return None
        if owners:
            launch_s = time.perf_counter() - t_launch
            for own_lanes in owners.values():
                share = launch_s * len(own_lanes) / len(lanes)
                wait = t_launch - min(l.ts for l in own_lanes)
                with _trace_scope(own_lanes[0].trace):
                    _record_span("device.batch.share",
                                 max(0.0, wait) + share, kind=kind,
                                 lanes=len(own_lanes),
                                 batch_lanes=len(lanes))
        return kind, handle, lanes

    def _materialize(self, entry) -> None:
        kind, handle, lanes = entry
        try:
            self._engines[kind].finalize(handle, lanes)
        except BaseException as e:  # noqa: BLE001 — owners, not the loop
            for lane in lanes:
                lane.sub.fail(e)

    def _abort_all(self, exc: BaseException) -> None:
        with self._cond:
            self._closed = True
            pending = [
                l for qs in self._queues.values() for q in qs for l in q]
            for qs in self._queues.values():
                for q in qs:
                    q.clear()
            inflight = list(self._inflight)
            self._inflight.clear()
        for _kind, _handle, lanes in inflight:
            pending.extend(lanes)
        for lane in pending:
            lane.sub.fail(exc)


# ---------------------------------------------------------------------------
# Process-wide singleton (lazy — the disabled path touches none of this)
# ---------------------------------------------------------------------------

_SERVICE: Optional[DeviceDecodeService] = None
_SERVICE_LOCK = threading.Lock()


def enabled() -> bool:
    """True when ``DISQ_TPU_DEVICE_SERVICE`` is set truthy — the codec
    entry points then route device decode through the shared service."""
    from disq_tpu.runtime.debug import env_flag

    return env_flag("DISQ_TPU_DEVICE_SERVICE")


def get_service() -> DeviceDecodeService:
    """The process-wide service, created on first use."""
    global _SERVICE
    with _SERVICE_LOCK:
        if _SERVICE is None or not _SERVICE.alive:
            _SERVICE = DeviceDecodeService()
        return _SERVICE


def service_if_running() -> Optional[DeviceDecodeService]:
    """The live service or None — NEVER creates one (the overhead
    guard asserts this stays None on the default path)."""
    return _SERVICE


def shutdown_service() -> None:
    global _SERVICE
    with _SERVICE_LOCK:
        service, _SERVICE = _SERVICE, None
    if service is not None:
        service.close()
