"""Device-resident read pipeline: decoded bytes → parse → sort keys →
flagstat, all as jax Arrays with no host numpy between stages.

VERDICT r4 item 4 / BASELINE.json north star ("HBM-resident shard
buffers ... bypassing per-record htsjdk object allocation"): the host
inflate/stage step puts a shard's decoded BGZF bytes on device ONCE;
everything after — record-prefix gather, the Pallas fixed-field parse
kernel, coordinate-key construction, the sort, flag filtering, the
flagstat histogram — runs on device arrays inside a single jit.

Residency is PROVEN, not claimed: the jitted steps execute under
``jax.transfer_guard("disallow")``, which raises on any implicit
device↔host copy. The only transfers in the whole flow are the
explicit up-front blob/offset uploads and the final (tiny, LAZY)
results fetch. Record *offsets* are planning metadata (the shard
manifest), computed during the decode walk like split bounds — the
record columns themselves never round-trip through the host.

Three entry points:

- ``run_device_pipeline`` — the parse→sort→flagstat chain; returns a
  ``DevicePipelineResult`` whose keys / order / stats fetch d2h
  **lazily on attribute access** (tuple unpacking materializes all
  three under one transfer span, exactly the old behavior), so a
  caller that only wants ``stats`` never moves the key vectors.
- ``parse_columns_resident`` — the fused-decode half: upload (or reuse
  a device-assembled blob from the SIMD inflate kernels) + one parse
  launch, returning the raw device column dict for
  ``runtime/columnar.ColumnarBatch``.
- ``assemble_device_words`` — compaction of the 128-lane inflate
  kernel's *still-resident* transposed output chunks into one
  contiguous device word blob (per-byte searchsorted gather, host
  fallback lanes patched from a small upload), so the parse chain
  reads the decoded bytes where the inflate kernel left them instead
  of round-tripping them through host and back.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


from disq_tpu.util import bucket_pow2 as _bucket


def _pad_quantum(n: int) -> int:
    """Compile-shape quantization with bounded waste: power-of-two
    below 64K units (cheap), then 1/16-octave steps — retraces stay a
    handful per octave while zero-pad overhead is capped at ~6%
    (plain power-of-two would zero-fill and upload up to 2x the blob,
    defeating the transfer win the resident path exists for)."""
    if n <= 1 << 16:
        return _bucket(n)
    step = 1 << max((n - 1).bit_length() - 5, 0)
    return -(-n // step) * step


def gather_record_words(blob_words: jax.Array,
                        starts: jax.Array) -> jax.Array:
    """Record-prefix gather (jit-traceable): 9 consecutive u32 words
    per record from a device word blob. BAM records are 4-byte aligned
    only at the word level of their own offsets, so unaligned words are
    assembled from adjacent pairs."""
    from disq_tpu.ops.parse import N_WORDS

    w0 = starts >> 2
    sh = ((starts & 3) << 3).astype(jnp.uint32)
    idx = w0[:, None] + jnp.arange(N_WORDS + 1)[None, :]
    raw = blob_words[jnp.clip(idx, 0, blob_words.shape[0] - 1)]
    lo = raw[:, :N_WORDS].astype(jnp.uint32)
    hi = raw[:, 1:].astype(jnp.uint32)
    shv = sh[:, None]
    return jnp.where(
        shv == 0, lo,
        (lo >> shv) | (hi << ((jnp.uint32(32) - shv) & jnp.uint32(31))),
    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pipeline(blob_words: jax.Array, starts: jax.Array,
              interpret: bool = False):
    """blob_words: decoded shard bytes as LE u32 words (device);
    starts: per-record byte offsets of the block_size word (device).
    Returns (sorted u32-pair keys, sort permutation, flagstat vector) —
    all device arrays."""
    from disq_tpu.ops.flagstat import _flagstat_single
    from disq_tpu.ops.parse import parse_fixed_words_pallas

    words = gather_record_words(blob_words, starts)
    cols = parse_fixed_words_pallas(words, interpret=interpret)
    refid, pos, flag = cols["refid"], cols["pos"], cols["flag"]

    # coordinate keys as u32 pairs (no x64): unmapped after everything
    hi_k = jnp.where(refid < 0, jnp.uint32(0x7FFFFFFF),
                     refid.astype(jnp.uint32))
    lo_k = (pos + 1).astype(jnp.uint32)
    order = jnp.lexsort((lo_k, hi_k))
    # flagstat is permutation-invariant: no need to gather by order
    fs = _flagstat_single(flag.astype(jnp.int32))
    return hi_k[order], lo_k[order], order.astype(jnp.int32), fs


@functools.partial(jax.jit, static_argnames=("interpret",))
def _parse_columns(blob_words: jax.Array, starts: jax.Array,
                   interpret: bool = False) -> Dict[str, jax.Array]:
    """Fused gather + Pallas fixed-field parse over a device word blob;
    ``starts`` may be bucket-padded (pads duplicate a valid start) —
    the caller slices columns back to the true record count."""
    from disq_tpu.ops.parse import parse_fixed_words_pallas

    words = gather_record_words(blob_words, starts)
    return parse_fixed_words_pallas(words, interpret=interpret)


@functools.lru_cache(maxsize=8)
def _mesh_parse_compiled(mesh, interpret: bool):
    """shard_map'd gather+parse over the batch mesh axis: the word
    blob is replicated (every record's prefix may straddle any byte),
    the bucket-padded starts shard over ``batch``, and each device
    runs the SAME local gather + Pallas parse the single-device jit
    runs — out columns come back 1-D and batch-sharded, exactly the
    ``ColumnarBatch`` column shape."""
    from disq_tpu.runtime.mesh import MESH_AXIS
    from disq_tpu.ops.parse import parse_fixed_words_pallas
    from disq_tpu.sort.sharded import _shard_map
    from jax.sharding import PartitionSpec as P

    def body(blob_words, starts):
        words = gather_record_words(blob_words, starts)
        return parse_fixed_words_pallas(words, interpret=interpret)

    # check_rep=False: shard_map has no replication rule for
    # pallas_call; the body is per-device-local by construction
    return jax.jit(_shard_map()(
        body, mesh=mesh, in_specs=(P(None), P(MESH_AXIS)),
        out_specs=P(MESH_AXIS), check_rep=False))


def upload_blob_words(blob: np.ndarray) -> Tuple[jax.Array, int]:
    """Word-align a decoded byte blob with ONE preallocated buffer +
    tail write and upload it; returns (device u32 words, bytes moved).
    Transfer accounting is the caller's (some callers batch it with
    the starts upload under one span)."""
    pad = (-len(blob)) % 4
    if pad:
        padded = np.empty(len(blob) + pad, np.uint8)
        padded[: len(blob)] = blob
        padded[len(blob):] = 0
        blob = padded
    words_host = np.ascontiguousarray(blob).view("<u4")
    return jax.device_put(jnp.asarray(words_host)), words_host.nbytes


def pad_starts(offsets: np.ndarray, origin: int = 0) -> np.ndarray:
    """Record starts as bucket-padded i32 (pads repeat the last valid
    start so padded lanes parse a real record and compile shapes
    quantize to a handful of buckets instead of one per shard)."""
    starts = offsets[:-1].astype(np.int64) + origin
    n = len(starts)
    padded = np.empty(_bucket(max(1, n)), np.int32)
    padded[:n] = starts
    padded[n:] = starts[-1] if n else 0
    return padded


# ---------------------------------------------------------------------------
# Device blob assembly: inflate-kernel chunks -> one contiguous word blob
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("total_words",))
def _assemble_words_for(flat_lanes: jax.Array, offsets: jax.Array,
                        lane_of: jax.Array, patch_flat: jax.Array,
                        patch_base: jax.Array,
                        total_words: int) -> jax.Array:
    """Compact per-lane decoded bytes (still device-resident from the
    128-lane inflate kernel, lanes-major words) into one contiguous LE
    u32 word blob, entirely on device.

    ``flat_lanes``: (n_lanes, ow) u32 — stacked transposed chunk
    outputs. ``offsets``: (nblocks_padded + 1,) i32 cumulative usizes
    (pads repeat the total). ``lane_of``: flat lane index per block, or
    0 for host-patched blocks. ``patch_flat``/``patch_base``: bytes of
    host-fallback blocks (oversize / kernel-flagged lanes), gathered
    when ``patch_base[i] >= 0``.

    Per output byte: block via searchsorted, byte via one lane gather —
    O(blob) work with log(nblocks) index math, no host round-trip."""
    ow = flat_lanes.shape[1]
    total = jnp.int32(offsets[-1])

    def byte_at(b):
        # b: (total_words,) i32 byte positions
        i = jnp.searchsorted(offsets, b, side="right") - 1
        i = jnp.clip(i, 0, lane_of.shape[0] - 1)
        within = b - offsets[i]
        lane = lane_of[i]
        w = flat_lanes[lane, jnp.clip(within >> 2, 0, ow - 1)]
        dev_b = (w >> ((within.astype(jnp.uint32) & 3) << 3)) & 0xFF
        pb = patch_base[i]
        pidx = jnp.clip(pb + within, 0, patch_flat.shape[0] - 1)
        host_b = patch_flat[pidx].astype(jnp.uint32)
        byte = jnp.where(pb >= 0, host_b, dev_b)
        return jnp.where(b < total, byte, jnp.uint32(0))

    w_iota = jnp.arange(total_words, dtype=jnp.int32) << 2
    out = byte_at(w_iota)
    out = out | (byte_at(w_iota + 1) << 8)
    out = out | (byte_at(w_iota + 2) << 16)
    out = out | (byte_at(w_iota + 3) << 24)
    return out


def assemble_device_words(chunks, lane_of: np.ndarray,
                          offsets: np.ndarray,
                          patches) -> Tuple[jax.Array, int]:
    """Host driver for ``_assemble_words_for``: uploads only the small
    per-block index arrays (and any host-fallback patch bytes), stacks
    the still-resident chunk outputs, and returns (device word blob,
    bytes of the index uploads). The decoded payload bytes themselves
    never leave the device."""
    from disq_tpu.runtime.tracing import count_transfer

    total = int(offsets[-1])
    if total >= 2 ** 31:
        # the gather indexes (and the offsets upload) are i32 — refuse
        # here rather than let the int32 cast below wrap silently
        raise ValueError(
            f"decoded blob is {total} bytes; device assembly indexes "
            "with i32 — split the shard below 2 GiB")
    # quantum-padded like the upload path: a plain power-of-two bucket
    # would run the 4 per-word gathers (and hold HBM) over up to 2x the
    # real data on large shards
    total_words = _pad_quantum(max(1, (total + 3) // 4))
    nb = len(offsets) - 1
    nb_pad = _bucket(max(1, nb))
    off_pad = np.empty(nb_pad + 1, np.int32)
    off_pad[: nb + 1] = offsets
    off_pad[nb + 1:] = total
    lane_pad = np.zeros(nb_pad, np.int32)
    lane_pad[:nb] = np.where(lane_of[:nb] >= 0, lane_of[:nb], 0)
    patch_base = np.full(nb_pad, -1, np.int64)
    parts = []
    acc = 0
    for i, data in patches:
        patch_base[i] = acc
        parts.append(np.frombuffer(data, np.uint8)
                     if not isinstance(data, np.ndarray) else data)
        acc += len(parts[-1])
    patch_flat = (np.concatenate(parts) if parts
                  else np.zeros(1, np.uint8))
    flat = jnp.concatenate([jnp.reshape(c, (c.shape[0], -1))
                            for c in chunks], axis=0)
    up = off_pad.nbytes + lane_pad.nbytes + patch_flat.nbytes \
        + patch_base.nbytes
    count_transfer("h2d", up)
    words = _assemble_words_for(
        flat, jnp.asarray(off_pad), jnp.asarray(lane_pad),
        jnp.asarray(patch_flat), jnp.asarray(patch_base.astype(np.int32)),
        total_words=total_words)
    return words, up


# ---------------------------------------------------------------------------
# Fused columnar parse (the ColumnarBatch build step)
# ---------------------------------------------------------------------------


def parse_columns_resident(
    blob: Optional[np.ndarray],
    offsets: np.ndarray,
    words_dev: Optional[jax.Array] = None,
    origin: int = 0,
    interpret: bool = False,
    mesh=None,
) -> Tuple[Dict[str, jax.Array], int, int]:
    """One fused upload(+)gather(+)parse launch chain producing the raw
    device column dict (bucket-padded; callers slice to ``n``).

    ``words_dev`` (from ``assemble_device_words``) skips the blob
    upload entirely — the parse reads the inflate kernel's output where
    it already lives in HBM; ``origin`` rebases record offsets into
    that blob. Returns (cols, resident word bytes, record count).

    With ``mesh`` (runtime/mesh.py batch-axis mesh) the parse runs as
    ONE sharded program: the word blob replicates to every device (h2d
    and HBM booked per copy — accounting stays per-device-correct),
    the bucket-padded starts shard over ``batch`` (power-of-two bucket
    sizes always divide the power-of-two axis), and the returned
    columns are batch-sharded device arrays."""
    from disq_tpu.runtime.tracing import (
        count_transfer, counter, device_span, span)

    n = len(offsets) - 1
    if int(offsets[-1]) + origin >= 2 ** 31:
        raise ValueError(
            f"decoded shard is {int(offsets[-1]) + origin} bytes; the "
            "device pipeline indexes with i32 — split the shard below "
            "2 GiB")
    n_dev = 1
    if mesh is not None:
        from disq_tpu.runtime.mesh import (
            batch_sharding, mesh_put, replicated, shard_count)

        n_dev = shard_count(mesh)
    starts_host = pad_starts(offsets, origin)
    if words_dev is None:
        # quantum-pad the blob like the starts: shard blob sizes vary
        # per split, and an exact-shape upload would retrace the parse
        # jit once per shard — quantized shapes keep compiles to a
        # handful per run at <=~6% pad overhead on big shards
        nwords = _pad_quantum(max(1, (len(blob) + 3) // 4))
        padded = np.empty(nwords * 4, np.uint8)
        padded[: len(blob)] = blob
        padded[len(blob):] = 0
        with span("device.transfer", direction="h2d"):
            if mesh is None:
                words_dev = jax.device_put(jnp.asarray(padded.view("<u4")))
                starts_dev = jax.device_put(jnp.asarray(starts_host))
            else:
                words_dev = jax.device_put(
                    jnp.asarray(padded.view("<u4")), replicated(mesh))
                starts_dev = jax.device_put(
                    jnp.asarray(starts_host), batch_sharding(mesh))
        # the replicated blob lands on every device: book each copy
        count_transfer("h2d", padded.nbytes * n_dev + starts_host.nbytes)
        word_bytes = padded.nbytes * n_dev
    else:
        with span("device.transfer", direction="h2d"):
            if mesh is None:
                starts_dev = jax.device_put(jnp.asarray(starts_host))
            else:
                # the inflate chain left the blob on one device —
                # replicate it over ICI (mesh_put books the fan-out
                # into device.mesh.reshard_bytes, not h2d: it never
                # crosses the host)
                words_dev = mesh_put(words_dev, mesh, batch=False)
                starts_dev = jax.device_put(
                    jnp.asarray(starts_host), batch_sharding(mesh))
        count_transfer("h2d", starts_host.nbytes)
        word_bytes = int(words_dev.size) * 4 * n_dev
    # bind the compiled fn OUTSIDE the guard: its first construction
    # imports sort/sharded, whose module constants are device puts
    parse_fn = (_parse_columns if mesh is None
                else _mesh_parse_compiled(mesh, interpret))
    with device_span("device.kernel", kernel="columnar_parse",
                     records=n, devices=n_dev) as fence:
        with jax.transfer_guard("disallow"):
            if mesh is None:
                cols = parse_fn(words_dev, starts_dev,
                                interpret=interpret)
            else:
                cols = parse_fn(words_dev, starts_dev)
            jax.block_until_ready(cols["pos"])
        fence.sync(cols["pos"])
    if mesh is not None:
        counter("device.mesh.batches").inc()
    return cols, word_bytes + starts_host.nbytes, n


# ---------------------------------------------------------------------------
# run_device_pipeline with a lazy result fetch
# ---------------------------------------------------------------------------


class DevicePipelineResult:
    """Lazy handle over the pipeline's device outputs.

    Tuple unpacking (``keys, order, stats = run_device_pipeline(...)``)
    materializes all three under ONE d2h transfer span — the historical
    behavior. Attribute access (``res.stats``) fetches only that piece,
    once: repeated access returns the cache, so ``device.transfer``
    bytes are never double-booked on the fused path. ``release()``
    (also ``__del__``) books never-fetched results into
    ``device.d2h_avoided_bytes`` and returns the HBM estimate."""

    __slots__ = ("_dev", "_np", "_hbm", "_released", "__weakref__")

    def __init__(self, hi=None, lo=None, order=None, fs=None,
                 hbm_bytes: int = 0,
                 host: Optional[Dict[str, np.ndarray]] = None) -> None:
        self._dev = (None if host is not None
                     else {"hi": hi, "lo": lo, "order": order, "fs": fs})
        self._np: Dict[str, np.ndarray] = dict(host or {})
        self._hbm = hbm_bytes
        self._released = False

    @classmethod
    def empty(cls) -> "DevicePipelineResult":
        from disq_tpu.ops.flagstat import FLAGSTAT_FIELDS

        return cls(host={
            "hi": np.zeros(0, np.uint32), "lo": np.zeros(0, np.uint32),
            "order": np.zeros(0, np.int32),
            "fs": np.zeros(len(FLAGSTAT_FIELDS), np.int32),
        })

    def _fetch(self, *names: str) -> None:
        from disq_tpu.runtime.tracing import count_transfer, span

        if self._dev is None:
            if any(m not in self._np for m in names):
                raise RuntimeError(
                    "result accessed after release() — fetch before "
                    "releasing the DevicePipelineResult")
            return
        missing = [m for m in names if m not in self._np]
        if not missing:
            return
        with span("device.transfer", direction="d2h"):
            got = {m: np.asarray(self._dev[m]) for m in missing}
        count_transfer("d2h", sum(a.nbytes for a in got.values()))
        self._np.update(got)
        if all(k in self._np for k in ("hi", "lo", "order", "fs")):
            self._release_hbm()

    def _release_hbm(self) -> None:
        if self._hbm:
            from disq_tpu.runtime.tracing import track_hbm

            track_hbm(-self._hbm)
            self._hbm = 0
        self._dev = None

    @property
    def keys(self) -> np.ndarray:
        """Sorted u64 coordinate keys (fetches the u32 key pair)."""
        self._fetch("hi", "lo")
        return (self._np["hi"].astype(np.uint64) << np.uint64(32)) | \
            self._np["lo"].astype(np.uint64)

    @property
    def order(self) -> np.ndarray:
        self._fetch("order")
        return self._np["order"]

    @property
    def stats(self) -> Dict[str, int]:
        from disq_tpu.ops.flagstat import FLAGSTAT_FIELDS

        self._fetch("fs")
        return {k: int(v)
                for k, v in zip(FLAGSTAT_FIELDS, self._np["fs"])}

    def release(self) -> None:
        """Drop device results; columns never fetched are booked into
        ``device.d2h_avoided_bytes`` — the d2h the lazy fetch skipped."""
        if self._released:
            return
        self._released = True
        if self._dev is not None:
            avoided = sum(
                int(np.prod(self._dev[m].shape)) * self._dev[m].dtype.itemsize
                for m in ("hi", "lo", "order", "fs")
                if m not in self._np and self._dev.get(m) is not None)
            if avoided:
                from disq_tpu.runtime.tracing import counter

                counter("device.d2h_avoided_bytes").inc(avoided)
        self._release_hbm()

    def __del__(self) -> None:  # pragma: no cover - GC timing
        try:
            self.release()
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def __iter__(self) -> Iterator:
        """Back-compat tuple protocol: one bulk fetch, then
        (keys, order, stats)."""
        self._fetch("hi", "lo", "order", "fs")
        yield self.keys
        yield self.order
        yield self.stats


def run_device_pipeline(
    blob: np.ndarray, offsets: np.ndarray, interpret: bool = False,
) -> DevicePipelineResult:
    """Upload a decoded shard once, run the device-resident step under a
    transfer guard, and hand back a LAZY result: d2h happens per result
    on first access (or all at once under tuple unpacking).

    blob: decoded BGZF payload bytes (u8). offsets: (n+1,) record byte
    offsets (the decode-walk manifest)."""
    from disq_tpu.runtime.tracing import (
        count_transfer, device_span, span, track_hbm)

    if len(offsets) <= 1:
        return DevicePipelineResult.empty()
    if int(offsets[-1]) >= 2 ** 31:
        raise ValueError(
            f"decoded shard is {int(offsets[-1])} bytes; the device "
            "pipeline indexes with i32 — split the shard below 2 GiB")
    starts_host = np.ascontiguousarray(offsets[:-1].astype(np.int32))
    # explicit uploads — the ONLY host->device transfers in the flow.
    # Upload accounting covers what actually moves: the word-aligned
    # blob (pad bytes included) plus the starts vector.
    with span("device.transfer", direction="h2d"):
        blob_dev, blob_bytes = upload_blob_words(blob)
        starts_dev = jax.device_put(jnp.asarray(starts_host))
    up_bytes = blob_bytes + starts_host.nbytes
    count_transfer("h2d", up_bytes)
    track_hbm(up_bytes)
    try:
        # device_span's close materializes a sentinel of fs — the true
        # sync PROBES.md requires (block_until_ready alone does not
        # block on this platform); the sentinel fetch happens outside
        # the transfer guard, like the lazy results fetch.
        with device_span("device.kernel", kernel="device_pipeline") as fence:
            with jax.transfer_guard("disallow"):
                hi_k, lo_k, order, fs = _pipeline(
                    blob_dev, starts_dev, interpret=interpret)
                jax.block_until_ready(fs)
            fence.sync(fs)
    except BaseException:
        track_hbm(-up_bytes)
        raise
    # the uploaded blob/starts die with this frame — from here on only
    # the (small) result vectors are resident, so the gauge must carry
    # their footprint, not the upload's, for the result's lifetime
    res_bytes = sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for a in (hi_k, lo_k, order, fs))
    track_hbm(res_bytes - up_bytes)
    return DevicePipelineResult(hi_k, lo_k, order, fs,
                                hbm_bytes=res_bytes)
