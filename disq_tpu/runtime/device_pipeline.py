"""Device-resident read pipeline: decoded bytes → parse → sort keys →
flagstat, all as jax Arrays with no host numpy between stages.

VERDICT r4 item 4 / BASELINE.json north star ("HBM-resident shard
buffers ... bypassing per-record htsjdk object allocation"): the host
inflate/stage step puts a shard's decoded BGZF bytes on device ONCE;
everything after — record-prefix gather, the Pallas fixed-field parse
kernel, coordinate-key construction, the sort, flag filtering, the
flagstat histogram — runs on device arrays inside a single jit.

Residency is PROVEN, not claimed: ``run_device_pipeline`` executes the
jitted step under ``jax.transfer_guard("disallow")``, which raises on
any implicit device↔host copy. The only transfers in the whole flow
are the explicit up-front blob/offset uploads and the final (tiny)
results fetch. Record *offsets* are planning metadata (the shard
manifest), computed during the decode walk like split bounds — the
record columns themselves never round-trip through the host.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pipeline(blob_words: jax.Array, starts: jax.Array,
              interpret: bool = False):
    """blob_words: decoded shard bytes as LE u32 words (device);
    starts: per-record byte offsets of the block_size word (device).
    Returns (sorted u32-pair keys, sort permutation, flagstat vector) —
    all device arrays."""
    from disq_tpu.ops.flagstat import _flagstat_single
    from disq_tpu.ops.parse import N_WORDS, parse_fixed_words_pallas

    # record-prefix gather: 9 consecutive u32 words per record. BAM
    # records are 4-byte aligned only at the word level of their own
    # offsets, so assemble unaligned words from adjacent pairs.
    w0 = starts >> 2
    sh = ((starts & 3) << 3).astype(jnp.uint32)
    idx = w0[:, None] + jnp.arange(N_WORDS + 1)[None, :]
    raw = blob_words[jnp.clip(idx, 0, blob_words.shape[0] - 1)]
    lo = raw[:, :N_WORDS].astype(jnp.uint32)
    hi = raw[:, 1:].astype(jnp.uint32)
    shv = sh[:, None]
    words = jnp.where(
        shv == 0, lo,
        (lo >> shv) | (hi << ((jnp.uint32(32) - shv) & jnp.uint32(31))),
    ).astype(jnp.int32)

    cols = parse_fixed_words_pallas(words, interpret=interpret)
    refid, pos, flag = cols["refid"], cols["pos"], cols["flag"]

    # coordinate keys as u32 pairs (no x64): unmapped after everything
    hi_k = jnp.where(refid < 0, jnp.uint32(0x7FFFFFFF),
                     refid.astype(jnp.uint32))
    lo_k = (pos + 1).astype(jnp.uint32)
    order = jnp.lexsort((lo_k, hi_k))
    # flagstat is permutation-invariant: no need to gather by order
    fs = _flagstat_single(flag.astype(jnp.int32))
    return hi_k[order], lo_k[order], order.astype(jnp.int32), fs


def run_device_pipeline(
    blob: np.ndarray, offsets: np.ndarray, interpret: bool = False,
) -> Tuple[np.ndarray, np.ndarray, Dict[str, int]]:
    """Upload a decoded shard once, run the device-resident step under a
    transfer guard, fetch the (small) results.

    blob: decoded BGZF payload bytes (u8). offsets: (n+1,) record byte
    offsets (the decode-walk manifest). Returns (sorted u64 keys,
    permutation, flagstat dict).
    """
    from disq_tpu.ops.flagstat import FLAGSTAT_FIELDS
    from disq_tpu.runtime.tracing import (
        count_transfer, device_span, hbm_resident, span)

    if len(offsets) <= 1:
        return (np.zeros(0, np.uint64), np.zeros(0, np.int32),
                {k: 0 for k in FLAGSTAT_FIELDS})
    if int(offsets[-1]) >= 2 ** 31:
        raise ValueError(
            f"decoded shard is {int(offsets[-1])} bytes; the device "
            "pipeline indexes with i32 — split the shard below 2 GiB")
    pad = (-len(blob)) % 4
    if pad:
        # Word-align with ONE preallocated buffer + tail write (the old
        # np.concatenate built a temp list and a second full copy).
        padded = np.empty(len(blob) + pad, np.uint8)
        padded[: len(blob)] = blob
        padded[len(blob):] = 0
        blob = padded
    words_host = np.ascontiguousarray(blob).view("<u4")
    starts_host = np.ascontiguousarray(offsets[:-1].astype(np.int32))
    # Upload accounting covers what actually moves: the word-aligned
    # blob (pad bytes included) plus the starts vector.
    up_bytes = words_host.nbytes + starts_host.nbytes
    count_transfer("h2d", up_bytes)
    with hbm_resident(up_bytes):
        # explicit uploads — the ONLY host->device transfers in the flow
        with span("device.transfer", direction="h2d", bytes=up_bytes):
            blob_dev = jax.device_put(jnp.asarray(words_host))
            starts_dev = jax.device_put(jnp.asarray(starts_host))
        # device_span's close materializes a sentinel of fs — the true
        # sync PROBES.md requires (block_until_ready alone does not
        # block on this platform); the sentinel fetch happens outside
        # the transfer guard, like the results fetch below.
        with device_span("device.kernel", kernel="device_pipeline") as fence:
            with jax.transfer_guard("disallow"):
                hi_k, lo_k, order, fs = _pipeline(
                    blob_dev, starts_dev, interpret=interpret)
                jax.block_until_ready(fs)
            fence.sync(fs)
        # explicit results fetch
        with span("device.transfer", direction="d2h"):
            hi_np = np.asarray(hi_k)
            lo_np = np.asarray(lo_k)
            order_np = np.asarray(order)
            fs_np = np.asarray(fs)
        count_transfer("d2h", hi_np.nbytes + lo_np.nbytes
                       + order_np.nbytes + fs_np.nbytes)
    keys = (hi_np.astype(np.uint64) << np.uint64(32)) | \
        lo_np.astype(np.uint64)
    stats = {k: int(v) for k, v in zip(FLAGSTAT_FIELDS, fs_np)}
    return keys, order_np, stats
