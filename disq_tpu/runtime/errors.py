"""Shard-level error policy — retry / skip / quarantine (SURVEY.md §5).

The reference inherits fault tolerance from Spark: a task that dies on a
flaky range-read is simply re-executed, and a corrupt input kills the
job with a stack trace pointing at nothing. disq_tpu replaces both with
explicit, observable machinery:

- **Transient faults** (network blips, stalled connections, truncated
  range reads) are retried per shard with bounded exponential backoff
  (``ShardRetrier`` — the Spark-task-retry analogue). Every retry is
  counted (``ShardCounters.retried_reads`` plus the labeled
  ``retry.attempts`` telemetry counter) and its backoff sleep traced
  as a ``retry.backoff`` span labeled with what was being retried.
- **Corrupt data** (failed CRC, bad DEFLATE bits, impossible record
  framing) is *not* retried — re-reading corrupt bytes yields the same
  corrupt bytes. It is governed by an ``ErrorPolicy``:

  - ``STRICT`` (default): raise ``CorruptBlockError`` carrying the full
    coordinates (path, shard, compressed block offset, virtual offset).
  - ``SKIP``: drop the corrupt block, count it
    (``ShardCounters.skipped_blocks``), decode everything else.
  - ``QUARANTINE``: as SKIP, but additionally copy the corrupt
    compressed bytes to a sidecar file recorded in a
    ``QuarantineManifest`` (``runtime/manifest.py``) for offline
    forensics / re-processing.

The classification boundary is ``is_transient``: OSError-family errors
(minus the definitive ones like ``FileNotFoundError``) and truncated
reads are transient; ``ValueError``-family codec errors are corrupt.
"""

from __future__ import annotations

import enum
import random
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional, TypeVar

from disq_tpu.runtime import flightrec

T = TypeVar("T")


class ErrorPolicy(enum.Enum):
    """What to do with a shard's corrupt (non-transient) block."""

    STRICT = "strict"
    SKIP = "skip"
    QUARANTINE = "quarantine"

    @classmethod
    def coerce(cls, value: "ErrorPolicy | str") -> "ErrorPolicy":
        if isinstance(value, cls):
            return value
        try:
            return cls(str(value).lower())
        except ValueError:
            raise ValueError(
                f"unknown error policy {value!r}; expected one of "
                f"{[p.value for p in cls]}"
            ) from None


@dataclass(frozen=True)
class DisqOptions:
    """Read-path runtime knobs, attached to the storage builders
    (``ReadsStorage.error_policy(...)`` / ``VariantsStorage``).

    ``quarantine_dir`` defaults to ``<input path> + ".quarantine"`` on
    the local filesystem; remote (read-only) inputs must set it
    explicitly.

    ``executor_workers`` / ``prefetch_shards`` size the shard-pipeline
    executor (``runtime/executor.py``): 1 worker (the default) is the
    sequential-compatible inline path; N>1 overlaps range-reads,
    inflate and record decode across splits with at most
    ``prefetch_shards`` splits in flight past the emit frontier
    (None ⇒ ``2 × executor_workers``).

    ``writer_workers`` / ``writer_prefetch_shards`` are the write-side
    mirror: they size the ``ShardWritePipeline`` every sink runs its
    shards through, overlapping record encode, BGZF deflate and part
    staging across shards. Output is byte-identical at any width; 1
    (the default) is the inline sequential path.

    ``span_log`` points the *process-wide* JSONL span sink at the
    given path when a read through this storage starts (per-shard
    fetch/decode, retries, quarantine writes — the file
    ``scripts/trace_report.py`` replays).  Equivalent to setting
    ``DISQ_TPU_TRACE_JSONL`` at read time: there is one sink per
    process, so the storage that most recently started a read wins,
    and the sink keeps collecting until ``stop_span_log()`` (each
    run's spans carry its ``run_id``, so appended runs stay
    separable).

    Live introspection (``runtime/introspect.py``):

    - ``introspect_port`` starts the process-wide 127.0.0.1 HTTP
      endpoint (``/metrics`` / ``/healthz`` / ``/progress`` /
      ``/spans``) the first time a pipeline built from these options
      runs; 0 binds an ephemeral port (also: env
      ``DISQ_TPU_INTROSPECT_PORT``). None (the default) never creates
      a thread or socket.
    - ``watchdog_stall_s`` arms the heartbeat watchdog: any shard
      whose active pipeline stage has been silent that many seconds is
      flagged (``watchdog.stalled_shards`` counter, ``watchdog.stall``
      span, one rate-limited stderr line, ``/healthz`` degraded).
      ``watchdog_policy`` decides what happens next: ``"warn"`` (the
      default) keeps running; ``"abort"`` cancels the run through the
      pipeline's first-error-abort path with a ``WatchdogStallError``.
    - ``progress_log`` appends a periodic JSONL progress line
      (shards done / in flight / total, records, rolling records/sec,
      ETA) that ``scripts/trace_report.py --progress`` replays.

    Adaptive resilience (``runtime/resilience.py`` — every knob None
    or default keeps the zero-overhead seed behavior):

    - ``hedge_quantile`` arms hedged fetches: a shard fetch outliving
      that rolling quantile of this run's fetch latencies (never less
      than ``hedge_min_s``) races a duplicate, first result wins.
    - ``shard_deadline_s`` gives each shard a wall-clock budget with
      an escalation ladder: retry while young → forced hedge past half
      the budget → ``DeadlineExceededError`` (quarantined under
      skip/quarantine policy) once it is gone.
    - ``retry_budget_tokens`` installs the process-wide retry token
      bucket every ``ShardRetrier`` consults (a retry spends a token,
      a success refills ``retry_budget_refill``); an empty bucket
      denies retries so a fault storm cannot stampede the store.
    - ``breaker_window`` arms the per-filesystem circuit breaker:
      that many consecutive transient failures open it, calls then
      fail fast with ``BreakerOpenError`` until a successful probe
      after ``breaker_cooldown_s`` recloses it.
    - ``read_ledger`` points the crash-resumable *read* ledger at a
      directory: each decoded shard is spilled there as it emits, and
      a killed process re-runs only unfinished shards on restart.

    Postmortem & profiling (``runtime/flightrec.py`` /
    ``runtime/profiler.py`` — both off by default, zero threads and
    zero per-shard work until armed):

    - ``postmortem_dir`` turns the flight recorder on: recent events
      (retries, hedges, breaker transitions, watchdog stalls,
      quarantines) are kept in a bounded ring, and any abort path —
      pipeline first-error-abort, watchdog abort, breaker storm, or an
      explicit ``flightrec.dump()`` — writes a postmortem bundle
      directory (thread stacks, metrics snapshot, span tail, event
      ring, healthz/progress, ledger tails, resolved options) that
      ``scripts/trace_report.py --postmortem`` renders.  Also wires
      ``faulthandler`` into the dir for native crashes.  Env
      equivalent: ``DISQ_TPU_POSTMORTEM_DIR``.
    - ``profile_hz`` starts the in-process sampling profiler at that
      rate: folded stacks keyed by the canonical ``disq-*`` thread
      names attribute CPU per pipeline stage, exported as
      collapsed-stack / speedscope (``profile.samples{thread_role=}``
      / ``profile.dropped``).  Env equivalent:
      ``DISQ_TPU_PROFILE_HZ``.
    """

    error_policy: ErrorPolicy = ErrorPolicy.STRICT
    max_retries: int = 3
    retry_backoff_s: float = 0.05
    quarantine_dir: Optional[str] = None
    executor_workers: int = 1
    prefetch_shards: Optional[int] = None
    writer_workers: int = 1
    writer_prefetch_shards: Optional[int] = None
    span_log: Optional[str] = None
    introspect_port: Optional[int] = None
    watchdog_stall_s: Optional[float] = None
    watchdog_policy: str = "warn"
    progress_log: Optional[str] = None
    hedge_quantile: Optional[float] = None
    hedge_min_s: float = 0.05
    shard_deadline_s: Optional[float] = None
    retry_budget_tokens: Optional[int] = None
    retry_budget_refill: float = 0.1
    breaker_window: Optional[int] = None
    breaker_cooldown_s: float = 1.0
    read_ledger: Optional[str] = None
    postmortem_dir: Optional[str] = None
    profile_hz: Optional[float] = None
    # HBM-resident fused decode (runtime/columnar.py): sources parse
    # each shard's decoded blob into a device-backed ColumnarBatch in
    # the same launch chain as the device codecs — fixed columns stay
    # resident, d2h happens lazily per column. Env equivalent:
    # DISQ_TPU_RESIDENT_DECODE. Off (default) ⇒ plain host ReadBatch
    # and zero device allocations (check_overhead-guarded).
    resident_decode: bool = False
    # Symmetric device write path (ops/deflate + runtime/device_write):
    # every sink's BGZF deflate routes through the 128-lane SIMD
    # encoder (service-coalesced across write shards when the device
    # service is up), and a sorted device-backed ColumnarBatch encodes
    # its records on device so sort → encode → deflate never
    # materializes host records — only compressed blocks cross d2h.
    # Output is byte-VALID BGZF but not byte-identical to the host
    # zlib pin. Env equivalent: DISQ_TPU_DEVICE_DEFLATE. Off (default)
    # ⇒ canonical host zlib and zero device allocations
    # (check_overhead-guarded).
    device_deflate: bool = False
    # Mesh-native device pipeline (runtime/mesh.py): None (default)
    # keeps every device stage on the single-device dispatch and
    # builds no Mesh object (check_overhead-guarded); 0 shards the
    # resident parse/sort/reduce chain over ALL local devices on a
    # batch axis; n >= 1 uses the first n devices (rounded down to a
    # power of two; 1 ⇒ the off path). Env equivalent: DISQ_TPU_MESH
    # (unset/0/off ⇒ off, all/auto ⇒ all devices, integer ⇒ first n).
    mesh: Optional[int] = None
    # Cross-host shard scheduler (runtime/scheduler.py): None (default)
    # keeps the static split loops with zero coordinator threads or
    # sockets; "serve" hosts the coordinator on this process's
    # introspection endpoint and works; "host:port" joins that
    # coordinator as a worker. sched_lease_n shards per lease round,
    # sched_lease_s lease expiry (crash-detection latency), sched_steal
    # arms idle-worker stealing. Env equivalents: DISQ_TPU_SCHED,
    # DISQ_TPU_SCHED_LEASE_N/_LEASE_S/_STEAL (env wins for the tuning
    # knobs so subprocess workers inherit their launcher's settings).
    # sched_run_weight is this run's share weight in the coordinator's
    # weighted max-min lease quota (multi-run fairness — an interactive
    # run outweighing a batch pass cannot be starved by it); env
    # DISQ_TPU_SCHED_WEIGHT. sched_failover_dir arms coordinator
    # failover: the coordinator journals every state transition to
    # <dir>/journal.jsonl and advertises its address in
    # <dir>/coordinator.addr, workers register member files there, and
    # on coordinator death the lowest live process id replays the
    # journal and resumes the pass; env DISQ_TPU_SCHED_FAILOVER. None
    # (default) keeps PR 12's guarantee: no journal file, no standby,
    # no extra state (check_overhead-guarded).
    scheduler: Optional[str] = None
    sched_lease_n: int = 2
    sched_lease_s: float = 10.0
    sched_steal: bool = True
    sched_run_weight: float = 1.0
    sched_failover_dir: Optional[str] = None
    # HTTP block-LRU capacity (fsw/http.py) — None keeps the built-in
    # default (32 blocks, or DISQ_TPU_HTTP_CACHE_BLOCKS); the locality
    # scorer reads occupancy off the fsw.http.cache.blocks gauge.
    http_cache_blocks: Optional[int] = None
    # Per-tenant SLO spec (runtime/slo.py): comma-separated
    # "tenant:latency_ms:target_pct[:availability_pct]" clauses ("*" =
    # wildcard tenant). Arms the multi-window burn-rate evaluator whose
    # snapshot /slo serves and /healthz merges (fast burn ⇒ degraded).
    # Env equivalent: DISQ_TPU_SLO. None (default) starts no evaluator
    # thread and touches nothing (check_overhead-guarded).
    slo: Optional[str] = None
    # Resident read filter (ops/rfilter.py): a ``samtools view``-style
    # spec ("-f INT -F INT -q INT -s SEED.FRAC") pushed into the
    # decode — the mask builds on device from the resident flag/mapq
    # columns and compacts each shard BEFORE any d2h or host record
    # parse. Env equivalent: DISQ_TPU_READ_FILTER. None (default)
    # builds no mask and imports no operator module
    # (check_overhead-guarded).
    read_filter: Optional[str] = None

    def with_policy(self, policy: "ErrorPolicy | str") -> "DisqOptions":
        return replace(self, error_policy=ErrorPolicy.coerce(policy))

    def with_executor(self, workers: int,
                      prefetch_shards: Optional[int] = None) -> "DisqOptions":
        if workers < 1:
            raise ValueError(f"executor_workers must be >= 1, got {workers}")
        return replace(self, executor_workers=int(workers),
                       prefetch_shards=prefetch_shards)

    def with_writer(self, workers: int,
                    prefetch_shards: Optional[int] = None) -> "DisqOptions":
        if workers < 1:
            raise ValueError(f"writer_workers must be >= 1, got {workers}")
        return replace(self, writer_workers=int(workers),
                       writer_prefetch_shards=prefetch_shards)

    def with_watchdog(self, stall_s: float,
                      policy: str = "warn") -> "DisqOptions":
        if stall_s <= 0:
            raise ValueError(
                f"watchdog_stall_s must be > 0, got {stall_s}")
        if policy not in ("warn", "abort"):
            raise ValueError(
                f"watchdog_policy must be 'warn' or 'abort', got {policy!r}")
        return replace(self, watchdog_stall_s=float(stall_s),
                       watchdog_policy=policy)

    def with_hedging(self, quantile: float,
                     min_s: float = 0.05) -> "DisqOptions":
        if not 0.0 < quantile < 1.0:
            raise ValueError(
                f"hedge_quantile must be in (0, 1), got {quantile}")
        if min_s < 0:
            raise ValueError(f"hedge_min_s must be >= 0, got {min_s}")
        return replace(self, hedge_quantile=float(quantile),
                       hedge_min_s=float(min_s))

    def with_shard_deadline(self, deadline_s: float) -> "DisqOptions":
        if deadline_s <= 0:
            raise ValueError(
                f"shard_deadline_s must be > 0, got {deadline_s}")
        return replace(self, shard_deadline_s=float(deadline_s))

    def with_retry_budget(self, tokens: int,
                          refill_per_success: float = 0.1) -> "DisqOptions":
        if tokens < 1:
            raise ValueError(
                f"retry_budget_tokens must be >= 1, got {tokens}")
        return replace(self, retry_budget_tokens=int(tokens),
                       retry_budget_refill=float(refill_per_success))

    def with_breaker(self, window: int,
                     cooldown_s: float = 1.0) -> "DisqOptions":
        if window < 1:
            raise ValueError(f"breaker_window must be >= 1, got {window}")
        if cooldown_s <= 0:
            raise ValueError(
                f"breaker_cooldown_s must be > 0, got {cooldown_s}")
        return replace(self, breaker_window=int(window),
                       breaker_cooldown_s=float(cooldown_s))

    def with_read_ledger(self, path: str) -> "DisqOptions":
        return replace(self, read_ledger=path)

    def with_postmortem(self, path: str) -> "DisqOptions":
        if not path:
            raise ValueError("postmortem_dir must be a non-empty path")
        return replace(self, postmortem_dir=path)

    def with_profile(self, hz: float) -> "DisqOptions":
        if hz <= 0:
            raise ValueError(f"profile_hz must be > 0, got {hz}")
        return replace(self, profile_hz=float(hz))

    def with_scheduler(self, mode: str, lease_n: int = 2,
                       lease_s: float = 10.0,
                       steal: bool = True,
                       run_weight: float = 1.0,
                       failover_dir: Optional[str] = None
                       ) -> "DisqOptions":
        if not mode:
            raise ValueError(
                "scheduler mode must be 'serve', 'auto' or 'host:port'")
        if lease_n < 1:
            raise ValueError(f"sched_lease_n must be >= 1, got {lease_n}")
        if lease_s <= 0:
            raise ValueError(f"sched_lease_s must be > 0, got {lease_s}")
        if run_weight <= 0:
            raise ValueError(
                f"sched_run_weight must be > 0, got {run_weight}")
        return replace(self, scheduler=str(mode),
                       sched_lease_n=int(lease_n),
                       sched_lease_s=float(lease_s),
                       sched_steal=bool(steal),
                       sched_run_weight=float(run_weight),
                       sched_failover_dir=(str(failover_dir)
                                           if failover_dir else None))

    def with_http_cache_blocks(self, n: int) -> "DisqOptions":
        if n < 1:
            raise ValueError(f"http_cache_blocks must be >= 1, got {n}")
        return replace(self, http_cache_blocks=int(n))

    def with_slo(self, spec: str) -> "DisqOptions":
        """Attach a per-tenant SLO spec (validated eagerly so a typo
        fails at options-build time, not mid-serve)."""
        from disq_tpu.runtime.slo import parse_slo_spec

        parse_slo_spec(spec)  # raises ValueError on a malformed spec
        return replace(self, slo=str(spec))

    def with_resident_decode(self, enable: bool = True) -> "DisqOptions":
        return replace(self, resident_decode=bool(enable))

    def with_device_deflate(self, enable: bool = True) -> "DisqOptions":
        return replace(self, device_deflate=bool(enable))

    def with_read_filter(self, spec: str) -> "DisqOptions":
        """Push a ``samtools view``-grammar read filter into the
        decode (validated eagerly so a typo fails at options-build
        time, not per shard)."""
        from disq_tpu.ops.rfilter import parse_read_filter

        parse_read_filter(spec)  # raises ValueError on a malformed spec
        return replace(self, read_filter=str(spec))

    def with_mesh(self, devices: int = 0) -> "DisqOptions":
        """Arm the mesh-native pipeline: 0 = all local devices, n = the
        first n (power-of-two floor; resolving to 1 device keeps the
        plain single-device dispatch)."""
        if devices < 0:
            raise ValueError(f"mesh devices must be >= 0, got {devices}")
        return replace(self, mesh=int(devices))


class CorruptBlockError(ValueError):
    """A compressed block failed decode *with certainty* (CRC mismatch,
    invalid DEFLATE bits, impossible container framing) — carrying the
    coordinates every layer above needs to act on it."""

    def __init__(
        self,
        message: str,
        *,
        path: str = "",
        shard_id: int = -1,
        block_offset: int = -1,
        virtual_offset: Optional[int] = None,
    ) -> None:
        detail = (
            f"{message} [path={path!r} shard={shard_id} "
            f"block_offset={block_offset}"
            + (f" voffset={virtual_offset:#x}" if virtual_offset is not None else "")
            + "]"
        )
        super().__init__(detail)
        self.path = path
        self.shard_id = shard_id
        self.block_offset = block_offset
        self.virtual_offset = virtual_offset


class TransientIOError(IOError):
    """Marker for errors known to be transient (used by the fault
    injector and by wrappers that can prove transience)."""


class CoordinatorLostError(TransientIOError):
    """The shard-scheduler coordinator became unreachable mid-run
    (``runtime/scheduler.py``).  Transient by inheritance: with
    failover armed (``DISQ_TPU_SCHED_FAILOVER`` / a standby replaying
    the ``SchedJournal``) the worker rediscovers the new coordinator
    address and retries; without failover the worker's rediscovery
    budget drains and this error surfaces as the read's failure."""

    def __init__(self, message: str, *, address: str = "",
                 op: str = "") -> None:
        super().__init__(
            f"{message} [address={address or '?'} op={op or '?'}]")
        self.address = address
        self.op = op


class MissingReferenceError(ValueError):
    """Reference FASTA absent/wrong for reference-compressed CRAM — a
    *configuration* error: never retried, and never treated as data
    corruption by skip/quarantine (silently dropping every container
    because the user forgot ``reference_source_path`` would be a
    catastrophe, not fault tolerance)."""


class WatchdogStallError(RuntimeError):
    """The heartbeat watchdog (``runtime/introspect.py``) flagged a
    shard as stalled past ``DisqOptions.watchdog_stall_s`` under
    ``watchdog_policy="abort"``: the pipeline run is cancelled through
    its first-error-abort path. Deliberately NOT transient — retrying
    the very work the watchdog just declared wedged would mask the
    hang it exists to surface."""

    def __init__(self, message: str, *, shard_id: int = -1,
                 stage: str = "", age_s: float = 0.0,
                 direction: str = "") -> None:
        detail = (f"{message} [direction={direction or '?'} "
                  f"shard={shard_id} stage={stage or '?'} "
                  f"silent_for={age_s:.3f}s]")
        super().__init__(detail)
        self.shard_id = shard_id
        self.stage = stage
        self.age_s = age_s
        self.direction = direction


class DeadlineExceededError(RuntimeError):
    """A shard exhausted its ``DisqOptions.shard_deadline_s`` budget —
    the terminal rung of the resilience escalation ladder (retry →
    hedge → this).  A *certain*, non-transient kind: retrying work the
    deadline already declared over-budget would defeat the deadline.
    Under skip/quarantine policy the sources convert it into a
    quarantined empty shard instead of aborting the run."""

    def __init__(self, message: str, *, shard_id: int = -1,
                 elapsed_s: float = 0.0, deadline_s: float = 0.0) -> None:
        detail = (f"{message} [shard={shard_id} "
                  f"elapsed={elapsed_s:.3f}s deadline={deadline_s:.3f}s]")
        super().__init__(detail)
        self.shard_id = shard_id
        self.elapsed_s = elapsed_s
        self.deadline_s = deadline_s


class BreakerOpenError(RuntimeError):
    """The circuit breaker guarding a filesystem is open: the call was
    rejected *before* touching the store (``runtime/resilience.py``).
    Non-transient by classification — the breaker exists precisely to
    stop retry loops from hammering a store it has declared degraded;
    callers should surface the failure (or wait ``retry_after_s``)."""

    def __init__(self, message: str, *, key: str = "",
                 retry_after_s: float = 0.0) -> None:
        super().__init__(
            f"{message} [filesystem={key or '?'} "
            f"retry_after={retry_after_s:.3f}s]")
        self.key = key
        self.retry_after_s = retry_after_s


class TruncatedReadError(OSError, ValueError):
    """A range read returned fewer bytes than the on-disk structure
    requires. Subclasses ``OSError`` (it is an I/O symptom — a flaky
    remote can truncate a body, so it is *retryable*) and ``ValueError``
    (compat: callers of the block walk historically catch ValueError)."""


# OSError subclasses that are definitive, not worth retrying.
_PERMANENT_OS_ERRORS = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
    FileExistsError,
)


def is_transient(exc: BaseException) -> bool:
    """Transient (retryable) vs. permanent/corrupt classification."""
    if isinstance(exc, TransientIOError):
        return True
    if isinstance(exc, (CorruptBlockError, WatchdogStallError,
                        DeadlineExceededError, BreakerOpenError)):
        return False
    if isinstance(exc, _PERMANENT_OS_ERRORS):
        return False
    if isinstance(exc, (TimeoutError, ConnectionError, TruncatedReadError)):
        return True
    try:
        import urllib.error

        if isinstance(exc, urllib.error.HTTPError):
            return exc.code >= 500
        if isinstance(exc, urllib.error.URLError):
            return True
    except ImportError:  # pragma: no cover
        pass
    try:
        import http.client

        # IncompleteRead / RemoteDisconnected and friends: wire-level
        # symptoms a re-request can fix.
        if isinstance(exc, http.client.HTTPException):
            return True
    except ImportError:  # pragma: no cover
        pass
    return isinstance(exc, OSError)


# Shared fallback RNG for backoff jitter: module-wide so concurrent
# retriers draw *different* sleeps even when none injects its own.
_JITTER_RNG = random.Random()

_resilience = None  # lazily bound module ref (avoids an import cycle)


def _resilience_mod():
    global _resilience
    if _resilience is None:
        from disq_tpu.runtime import resilience

        _resilience = resilience
    return _resilience


class ShardRetrier:
    """Bounded retry with decorrelated-jitter backoff for transient
    faults — the analogue of Spark task retry, scoped to one shard's
    work.

    ``call(fn, ...)`` runs ``fn`` up to ``1 + max_retries`` times,
    retrying only when ``is_transient`` says the failure is worth it.
    Retries are counted in ``.retried`` and traced as ``retry.<what>``
    phases so a flaky store is visible in ``phase_report()``.

    Backoff uses *decorrelated jitter* (``sleep = uniform(base, 3 ×
    prev)``, capped at ``base × 2^max_retries``) instead of bare
    exponential doubling: N parallel workers that all failed in the
    same instant must not come back in lockstep against the very store
    that just dropped them.  ``rng`` is injectable (seeded) so tests
    stay deterministic; the default draws from a process-shared RNG so
    sibling shards decorrelate.

    The retrier is also the resilience layer's choke point
    (``runtime/resilience.py``; every hook below is a no-op until the
    matching ``DisqOptions`` knob configures it):

    - the process-wide ``RetryBudget`` is consulted before every
      retry — a dry bucket denies it and the original error surfaces;
    - an attached per-filesystem ``CircuitBreaker`` gates each attempt
      (``BreakerOpenError`` while open) and is fed every transient
      outcome;
    - an attached ``ShardDeadline`` ends retrying with a
      ``DeadlineExceededError`` once the shard's budget is spent.
    """

    def __init__(
        self,
        max_retries: int = 3,
        backoff_s: float = 0.05,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        breaker=None,
    ) -> None:
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self._sleep = sleep
        self._rng = rng if rng is not None else _JITTER_RNG
        self.retried = 0
        # Resilience attachments (None = the zero-overhead default).
        self.breaker = breaker
        self.deadline = None

    def _next_backoff(self, prev: float) -> float:
        """Decorrelated jitter: uniform in [base, 3 × prev], capped at
        the old schedule's terminal value so worst-case total sleep
        stays the same order as before."""
        base = self.backoff_s
        if base <= 0:
            return 0.0
        cap = base * (2 ** max(1, self.max_retries))
        return min(cap, self._rng.uniform(base, max(base, prev * 3)))

    def call(self, fn: Callable[..., T], *args: Any,
             what: str = "read", **kwargs: Any) -> T:
        from disq_tpu.runtime.tracing import counter, span

        attempt = 0
        prev_sleep = self.backoff_s
        if self.deadline is not None:
            # The shard's wall-clock budget starts with its first
            # attempt, not with its first failure.
            self.deadline.arm()
        while True:
            if self.breaker is not None:
                self.breaker.before_call()
            try:
                result = fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — classified below
                transient = is_transient(e)
                if self.breaker is not None:
                    if transient:
                        self.breaker.record_failure()
                    else:
                        # Not a store fault (corrupt data, 404, config
                        # error): no state-machine event, but a
                        # half-open probe slot must be released or the
                        # breaker wedges in half_open.
                        self.breaker.release_probe()
                if not transient or attempt >= self.max_retries:
                    raise
                if self.deadline is not None:
                    # Escalation ladder terminal: no more retries once
                    # the shard's wall-clock budget is gone.
                    try:
                        self.deadline.check(what=what)
                    except Exception as deadline_exc:
                        raise deadline_exc from e
                budget = _resilience_mod().active_budget()
                if budget is not None and not budget.try_spend(what=what):
                    raise  # bucket dry: the storm must not stampede
                attempt += 1
                self.retried += 1
                counter("retry.attempts").inc(what=what)
                flightrec.record_event(
                    "retry", what=what, attempt=attempt,
                    error=f"{type(e).__name__}: {e}")
                prev_sleep = self._next_backoff(prev_sleep)
                with span("retry.backoff", what=what, attempt=attempt):
                    self._sleep(prev_sleep)
            else:
                if self.breaker is not None:
                    self.breaker.record_success()
                budget = _resilience_mod().active_budget()
                if budget is not None:
                    budget.on_success()
                return result


@dataclass
class ShardErrorContext:
    """Per-shard bundle: the policy, the retrier, and the corrupt-block
    bookkeeping, threaded through a source's shard loop."""

    policy: ErrorPolicy
    path: str
    shard_id: int = -1
    retrier: ShardRetrier = field(default_factory=ShardRetrier)
    quarantine: Optional["QuarantineManifest"] = None  # noqa: F821
    quarantine_dir: Optional[str] = None
    skipped_blocks: int = 0
    quarantined_blocks: int = 0

    def for_shard(self, shard_id: int) -> "ShardErrorContext":
        """A fresh per-shard view (own retrier + counters) sharing the
        policy and the quarantine sink."""
        ctx = ShardErrorContext(
            policy=self.policy,
            path=self.path,
            shard_id=shard_id,
            retrier=ShardRetrier(
                self.retrier.max_retries, self.retrier.backoff_s,
                self.retrier._sleep, rng=self.retrier._rng,
                breaker=self.retrier.breaker,
            ),
            quarantine=self.quarantine,
            quarantine_dir=self.quarantine_dir,
        )
        ctx._parent = self  # type: ignore[attr-defined]
        return ctx

    # -- corrupt-block dispatch -------------------------------------------

    def handle_corrupt_block(
        self,
        error: BaseException,
        *,
        block_offset: int,
        raw: bytes = b"",
        virtual_offset: Optional[int] = None,
        kind: str = "block",
    ) -> None:
        """Apply the policy to one corrupt block. STRICT raises a
        ``CorruptBlockError`` with full coordinates; SKIP counts;
        QUARANTINE additionally copies ``raw`` to the sidecar.  Counted
        outcomes are also booked as labeled telemetry counters
        (``errors.skipped_blocks`` / ``quarantine.blocks``) unless this
        is a ``silent()`` non-owner view."""
        from disq_tpu.runtime.tracing import counter

        if self.policy is ErrorPolicy.STRICT:
            raise CorruptBlockError(
                f"corrupt {kind}: {error}",
                path=self.path,
                shard_id=self.shard_id,
                block_offset=block_offset,
                virtual_offset=virtual_offset,
            ) from error
        silent = getattr(self, "_is_silent", False)
        if self.policy is ErrorPolicy.QUARANTINE:
            self._quarantine_sink().quarantine(
                self.path,
                block_offset,
                raw,
                shard_id=self.shard_id,
                virtual_offset=virtual_offset,
                error=str(error),
                kind=kind,
            )
            self.quarantined_blocks += 1
            if not silent:
                counter("quarantine.blocks").inc(kind=kind)
                flightrec.record_event(
                    "quarantine", block_kind=kind, path=self.path,
                    shard=self.shard_id, block_offset=block_offset,
                    error=str(error))
        else:
            self.skipped_blocks += 1
            if not silent:
                counter("errors.skipped_blocks").inc(kind=kind)
                flightrec.record_event(
                    "skipped_block", block_kind=kind, path=self.path,
                    shard=self.shard_id, block_offset=block_offset,
                    error=str(error))

    def silent(self) -> "ShardErrorContext":
        """A non-counting view for blocks this shard reads but does NOT
        own (split-boundary straddle blocks, boundary-guess windows,
        straddling-line extensions): the owning shard does the counting
        and quarantining, so handling them here would double-book one
        corrupt block across two shards. STRICT still raises — failing
        at first sight is identical to failing when the owner decodes."""
        if self.policy is ErrorPolicy.STRICT:
            return self
        ctx = ShardErrorContext(
            policy=ErrorPolicy.SKIP, path=self.path, shard_id=self.shard_id
        )
        # Non-owner views never book telemetry counters either — the
        # owning shard's context does (same one-owner rule as the
        # ShardCounters bookkeeping).
        ctx._is_silent = True  # type: ignore[attr-defined]
        return ctx

    # Sink creation races under the parallel shard executor: two shards
    # hitting their first corrupt block concurrently must share ONE
    # manifest (two instances would tear the JSONL ledger header).
    _sink_lock = threading.Lock()

    def _quarantine_sink(self) -> "QuarantineManifest":  # noqa: F821
        if self.quarantine is None:
            from disq_tpu.runtime.manifest import QuarantineManifest

            parent = getattr(self, "_parent", None)
            with ShardErrorContext._sink_lock:
                if parent is not None and parent.quarantine is not None:
                    self.quarantine = parent.quarantine
                    return self.quarantine
                base = self.quarantine_dir
                if base is None:
                    if "://" in self.path:
                        raise ValueError(
                            "ErrorPolicy.QUARANTINE on remote input "
                            f"{self.path!r} requires an explicit "
                            "DisqOptions.quarantine_dir — the default "
                            "sidecar location <input>.quarantine only "
                            "exists for local files"
                        )
                    base = self.path + ".quarantine"
                self.quarantine = QuarantineManifest(base)
                if parent is not None:
                    parent.quarantine = self.quarantine
        return self.quarantine


def context_for_storage(storage, path: str) -> ShardErrorContext:
    """Build the read-path error context from a storage builder's
    ``DisqOptions`` (absent/None ⇒ defaults: STRICT, 3 retries).
    Every source funnels through here, so this is also where the
    ``span_log`` knob turns on the JSONL span sink for the read."""
    opts = getattr(storage, "_options", None) or DisqOptions()
    if getattr(opts, "span_log", None):
        from disq_tpu.runtime.tracing import start_span_log

        start_span_log(opts.span_log)
    # Arm the flight recorder before any shard work starts, so even a
    # fault in split planning happens with the event ring live.
    flightrec.configure_from_options(opts)
    if getattr(opts, "slo", None):
        from disq_tpu.runtime import slo as _slo

        _slo.configure_from_options(opts)
    breaker = None
    if (getattr(opts, "retry_budget_tokens", None) is not None
            or getattr(opts, "breaker_window", None) is not None):
        res = _resilience_mod()
        res.configure_globals_from_options(opts)
        breaker = res.breaker_for(path)
    return ShardErrorContext(
        policy=ErrorPolicy.coerce(opts.error_policy),
        path=path,
        retrier=ShardRetrier(opts.max_retries, opts.retry_backoff_s,
                             breaker=breaker),
        quarantine_dir=opts.quarantine_dir,
    )


def deadline_fallback_for(opts, shard_ctx,
                          make_empty: Callable[[], T]
                          ) -> Optional[Callable[[], T]]:
    """Build a ``ShardTask.deadline_fallback`` for one shard: under
    skip/quarantine policy with ``shard_deadline_s`` armed, a shard
    whose deadline expires is booked through the shard's existing
    corrupt-block machinery (counted, and under QUARANTINE recorded in
    the manifest with ``kind="shard deadline"``) and replaced by
    ``make_empty()``'s stand-in value.  STRICT — or no deadline — gets
    None: the ``DeadlineExceededError`` then aborts the run, which is
    exactly the strict contract."""
    if getattr(opts, "shard_deadline_s", None) is None:
        return None
    if shard_ctx is None or shard_ctx.policy is ErrorPolicy.STRICT:
        return None

    def fallback() -> T:
        shard_ctx.handle_corrupt_block(
            DeadlineExceededError(
                "shard deadline exceeded — shard set aside",
                shard_id=shard_ctx.shard_id,
                deadline_s=float(opts.shard_deadline_s)),
            block_offset=-1,
            kind="shard deadline",
        )
        return make_empty()

    return fallback


# -- BGZF salvage ----------------------------------------------------------


def inflate_blocks_salvage(data, blocks, base: int, ctx: ShardErrorContext,
                           owned_until: Optional[int] = None):
    """Per-block inflate applying ``ctx``'s policy: returns a list of
    per-block payloads with ``None`` holes where a corrupt block was
    skipped/quarantined (STRICT raises on the first corrupt block).

    Blocks at file offset >= ``owned_until`` (the boundary straddle this
    shard reads but its successor owns) are salvaged with the silent,
    non-counting view of ``ctx`` so one corrupt block is never booked by
    two shards.

    This is the slow path behind the batched ``inflate_blocks`` — used
    only once a batch inflate has already failed, so the common fault-free
    decode pays nothing.
    """
    from disq_tpu.bgzf.block import make_virtual_offset
    from disq_tpu.bgzf.codec import inflate_block

    silent = ctx.silent()
    payloads = []
    for b in blocks:
        off = b.pos - base
        try:
            payloads.append(inflate_block(data, off))
        except ValueError as e:
            target = (
                silent if owned_until is not None and b.pos >= owned_until
                else ctx
            )
            target.handle_corrupt_block(
                e,
                block_offset=b.pos,
                raw=bytes(data[off: off + b.csize]),
                virtual_offset=make_virtual_offset(b.pos, 0),
                kind="BGZF block",
            )
            payloads.append(None)
    return payloads
