"""Per-tenant SLO layer — multi-window burn rates over the metrics
registry (``runtime/tracing.py``), surfaced on ``/slo`` and merged
fleet-wide by ``runtime/cluster.py``.

An SLO spec (``DisqOptions.slo`` / ``DISQ_TPU_SLO``) is a comma-
separated list of per-tenant objectives::

    tenant:latency_ms:target_pct[:availability_pct]

    t0:250:99          # 99% of t0's requests under 250 ms
    *:500:95:99.9      # default for every other tenant: 95% under
                       # 500 ms AND 99.9% of requests not 5xx

``*`` is the wildcard objective applied to any tenant without an
explicit clause.  The evaluator samples the existing ``serve.request``
latency histogram (per-tenant labelsets, summed across endpoints) and
the ``serve.request.errors`` counter on a periodic tick, keeps a
bounded ring of timestamped snapshots, and computes the classic
burn-rate family over several windows:

    burn = observed_error_rate / error_budget     (budget = 1 - target)

A burn of 1.0 spends the budget exactly at the sustainable rate; the
fast-burn threshold (default 14.4 — the one-hour-page point for a
30-day budget) over the two shortest windows flips ``/healthz`` to
degraded via ``introspect.PipelineHealth``.  Latency goodness is read
off the histogram's cumulative buckets, so a threshold is rounded UP
to the nearest bucket boundary (documented, deterministic).

Zero-overhead contract (``scripts/check_overhead.py``): nothing here
runs until ``configure(...)`` / the ``DISQ_TPU_SLO`` env knob / the
``DisqOptions.slo`` funnel arms it — ``evaluator_if_running()`` stays
None, no ``disq-slo`` thread exists, and the serving hot path never
calls into this module.

Telemetry: ``slo.burn_rate{tenant,window,objective}`` (gauge),
``slo.fast_burn{tenant}`` (gauge, 0/1), ``slo.evaluations`` (counter).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from disq_tpu.runtime.tracing import (
    REGISTRY, RUN_ID, counter as _counter, gauge as _gauge)

# Default burn windows (seconds): short/mid/long.  The fast-burn page
# condition requires the threshold over BOTH of the two shortest
# windows, so a single spike can't flip healthz but a sustained burn
# does within one short window.
DEFAULT_WINDOWS: Tuple[float, ...] = (60.0, 300.0, 3600.0)
DEFAULT_FAST_BURN = 14.4

LATENCY_HISTOGRAM = "serve.request"
ERROR_COUNTER = "serve.request.errors"
WILDCARD = "*"


class SloObjective:
    """One tenant's objectives: latency (required) and availability
    (optional)."""

    __slots__ = ("tenant", "latency_s", "target", "availability")

    def __init__(self, tenant: str, latency_s: float, target: float,
                 availability: Optional[float] = None) -> None:
        self.tenant = tenant
        self.latency_s = latency_s
        self.target = target
        self.availability = availability

    def as_doc(self) -> Dict[str, Any]:
        return {
            "latency_ms": round(self.latency_s * 1e3, 3),
            "target": self.target,
            "availability": self.availability,
        }


def parse_slo_spec(spec: str) -> Dict[str, SloObjective]:
    """Parse the spec grammar above; raises ``ValueError`` with the
    offending clause on any malformed input."""
    objectives: Dict[str, SloObjective] = {}
    for clause in str(spec).split(","):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"bad SLO clause {clause!r}: expected "
                "tenant:latency_ms:target_pct[:availability_pct]")
        tenant = parts[0].strip()
        if not tenant:
            raise ValueError(f"bad SLO clause {clause!r}: empty tenant")
        try:
            latency_ms = float(parts[1])
            target_pct = float(parts[2])
            avail_pct = float(parts[3]) if len(parts) == 4 else None
        except ValueError:
            raise ValueError(
                f"bad SLO clause {clause!r}: non-numeric field") from None
        if latency_ms <= 0:
            raise ValueError(
                f"bad SLO clause {clause!r}: latency_ms must be > 0")
        for pct in (target_pct,) + (
                (avail_pct,) if avail_pct is not None else ()):
            if not 0.0 < pct < 100.0:
                raise ValueError(
                    f"bad SLO clause {clause!r}: percent targets must "
                    "be in (0, 100)")
        objectives[tenant] = SloObjective(
            tenant, latency_ms / 1e3, target_pct / 100.0,
            avail_pct / 100.0 if avail_pct is not None else None)
    if not objectives:
        raise ValueError(f"empty SLO spec {spec!r}")
    return objectives


def _tenant_samples() -> Dict[str, Tuple[int, int, float]]:
    """Per-tenant ``(total, errors, latency_sum_by_bucket…)`` sampled
    from the registry: returns ``{tenant: (cum_bucket_counts, total,
    errors)}`` with bucket counts CUMULATIVE (so goodness at a
    threshold is one index lookup) and summed across endpoints."""
    hist = REGISTRY.histogram(LATENCY_HISTOGRAM)
    err = REGISTRY.counter(ERROR_COUNTER)
    out: Dict[str, Any] = {}
    with REGISTRY._lock:
        nb = len(hist.buckets) + 1
        for key, bucket_counts in hist._counts.items():
            tenant = dict(key).get("tenant")
            if tenant is None:
                continue
            entry = out.setdefault(str(tenant), [[0] * nb, 0, 0])
            for i, n in enumerate(bucket_counts):
                entry[0][i] += n
            entry[1] += hist._stats[key]["count"]
        for key, v in err._values.items():
            tenant = dict(key).get("tenant")
            if tenant is None:
                continue
            entry = out.setdefault(str(tenant),
                                   [[0] * nb, 0, 0])
            entry[2] += int(v)
    # cumulative buckets
    result: Dict[str, Tuple[List[int], int, int]] = {}
    for tenant, (buckets, total, errors) in out.items():
        cum, acc = [], 0
        for n in buckets:
            acc += n
            cum.append(acc)
        result[tenant] = (cum, int(total), int(errors))
    return result


class SloEvaluator:
    """The periodic evaluator: one daemon thread, a bounded snapshot
    ring, per-tenant multi-window burn rates, and the fast-burn flag
    ``/healthz`` merges."""

    def __init__(self, objectives: Dict[str, SloObjective],
                 windows: Tuple[float, ...] = DEFAULT_WINDOWS,
                 interval_s: float = 5.0,
                 fast_burn: float = DEFAULT_FAST_BURN,
                 clock=time.monotonic) -> None:
        self.objectives = dict(objectives)
        self.windows = tuple(sorted(windows))
        self.interval_s = float(interval_s)
        self.fast_burn = float(fast_burn)
        self._clock = clock
        self._lock = threading.Lock()
        self._snaps: Deque[Tuple[float, Dict[str, Any]]] = deque()
        self._latest: Dict[str, Any] = {}
        self._stop = threading.Event()
        # baseline snapshot so the first evaluation has a delta anchor
        self._snaps.append((self._clock(), _tenant_samples()))
        self._thread = threading.Thread(
            target=self._loop, name="disq-slo", daemon=True)
        self._thread.start()

    # -- evaluation ---------------------------------------------------------

    def _objective_for(self, tenant: str) -> Optional[SloObjective]:
        return self.objectives.get(tenant) or self.objectives.get(WILDCARD)

    @staticmethod
    def _good_index(buckets: Tuple[float, ...], latency_s: float) -> int:
        """Index into cumulative counts whose boundary is the threshold
        rounded UP to the nearest bucket edge (+Inf if beyond all)."""
        for i, b in enumerate(buckets):
            if latency_s <= b:
                return i
        return len(buckets)

    def _window_delta(self, now: float, window: float,
                      current: Dict[str, Any], tenant: str
                      ) -> Tuple[List[int], int, int, float]:
        """(bucket_delta, total_delta, error_delta, span_s) for one
        tenant over one window — against the newest snapshot at least
        ``window`` old, else the oldest we have (partial window)."""
        base_t, base = self._snaps[0]
        for t, snap in reversed(self._snaps):
            if now - t >= window:
                base_t, base = t, snap
                break
        cur = current.get(tenant)
        if cur is None:
            return [], 0, 0, max(now - base_t, 1e-9)
        cum, total, errors = cur
        b = base.get(tenant)
        if b is None:
            return list(cum), total, errors, max(now - base_t, 1e-9)
        bcum, btotal, berrors = b
        delta = [c - p for c, p in zip(cum, bcum)]
        return (delta, total - btotal, errors - berrors,
                max(now - base_t, 1e-9))

    def evaluate_now(self) -> Dict[str, Any]:
        """One evaluation tick: sample the registry, compute per-tenant
        burn over every window, book the slo.* metrics, store + return
        the snapshot doc.  Called by the loop and by tests that need a
        deterministic tick."""
        now = self._clock()
        current = _tenant_samples()
        hist_buckets = REGISTRY.histogram(LATENCY_HISTOGRAM).buckets
        tenants: Dict[str, Any] = {}
        with self._lock:
            for tenant in sorted(current):
                obj = self._objective_for(tenant)
                if obj is None:
                    continue
                gi = self._good_index(hist_buckets, obj.latency_s)
                budget = max(1e-9, 1.0 - obj.target)
                avail_budget = (max(1e-9, 1.0 - obj.availability)
                                if obj.availability is not None else None)
                wdocs: Dict[str, Any] = {}
                burns: List[float] = []
                for w in self.windows:
                    delta, total, errors, span = self._window_delta(
                        now, w, current, tenant)
                    good = delta[gi] if delta else 0
                    bad = max(0, total - good)
                    burn = (bad / total / budget) if total > 0 else 0.0
                    avail_burn = None
                    if avail_budget is not None:
                        avail_burn = (errors / total / avail_budget
                                      if total > 0 else 0.0)
                    wdocs[str(int(w))] = {
                        "total": total, "good": good, "errors": errors,
                        "burn": round(burn, 4),
                        "availability_burn": (
                            round(avail_burn, 4)
                            if avail_burn is not None else None),
                        "span_s": round(span, 3),
                    }
                    worst = max(burn, avail_burn or 0.0)
                    burns.append(worst)
                    _gauge("slo.burn_rate").observe(
                        worst, tenant=tenant, window=str(int(w)))
                fast = (len(burns) >= 2
                        and burns[0] >= self.fast_burn
                        and burns[1] >= self.fast_burn)
                _gauge("slo.fast_burn").observe(
                    1.0 if fast else 0.0, tenant=tenant)
                tenants[tenant] = dict(
                    objective=obj.as_doc(), windows=wdocs,
                    fast_burn=fast)
            self._snaps.append((now, current))
            horizon = now - (self.windows[-1] + 2 * self.interval_s)
            while len(self._snaps) > 2 and self._snaps[1][0] < horizon:
                self._snaps.popleft()
            self._latest = {
                "enabled": True, "run_id": RUN_ID,
                "windows": [int(w) for w in self.windows],
                "fast_burn_threshold": self.fast_burn,
                "tenants": tenants,
            }
            _counter("slo.evaluations").inc()
            return dict(self._latest)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_now()
            except Exception:  # noqa: BLE001 — the evaluator must survive
                pass

    # -- read side ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The latest evaluation doc (evaluating once if the loop has
        not ticked yet) — what ``/slo`` serves."""
        with self._lock:
            latest = dict(self._latest)
        return latest if latest else self.evaluate_now()

    def fast_burn_tenants(self) -> List[str]:
        with self._lock:
            return sorted(
                t for t, doc in self._latest.get("tenants", {}).items()
                if doc.get("fast_burn"))

    def health_fragment(self) -> Dict[str, Any]:
        """The compact fragment ``/healthz`` merges: fast-burn tenants
        plus each tenant's worst current burn."""
        with self._lock:
            tenants = self._latest.get("tenants", {})
            return {
                "fast_burn_tenants": sorted(
                    t for t, d in tenants.items() if d.get("fast_burn")),
                "worst_burn": {
                    t: max((w["burn"] for w in d["windows"].values()),
                           default=0.0)
                    for t, d in tenants.items()
                },
            }

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout)


# ---------------------------------------------------------------------------
# Process-wide singleton (lazy — the unconfigured path touches none of
# this module beyond an is-None test)
# ---------------------------------------------------------------------------

_EVALUATOR: Optional[SloEvaluator] = None
_LOCK = threading.Lock()


def configure(spec: str, **kwargs: Any) -> SloEvaluator:
    """Arm (or re-arm with a new spec) the process-wide evaluator."""
    global _EVALUATOR
    objectives = parse_slo_spec(spec)
    with _LOCK:
        if _EVALUATOR is not None:
            _EVALUATOR.stop()
        _EVALUATOR = SloEvaluator(objectives, **kwargs)
        return _EVALUATOR


def configure_from_env() -> Optional[SloEvaluator]:
    """Arm from ``DISQ_TPU_SLO`` if set (idempotent: an evaluator that
    is already running is kept)."""
    spec = os.environ.get("DISQ_TPU_SLO")
    if not spec:
        return None
    with _LOCK:
        if _EVALUATOR is not None:
            return _EVALUATOR
    return configure(spec)


def configure_from_options(options: Any) -> Optional[SloEvaluator]:
    """The ``DisqOptions.slo`` funnel (``context_for_storage``)."""
    spec = getattr(options, "slo", None)
    if not spec:
        return configure_from_env()
    return configure(spec)


def evaluator_if_running() -> Optional[SloEvaluator]:
    """The live evaluator or None — NEVER creates one (the overhead
    guard asserts this stays None on the default path)."""
    return _EVALUATOR


def slo_doc() -> Dict[str, Any]:
    """What ``/slo`` serves: the evaluator's snapshot, or a disabled
    stub when nothing is configured."""
    ev = _EVALUATOR
    if ev is None:
        return {"enabled": False, "run_id": RUN_ID, "tenants": {}}
    return ev.snapshot()


def reset_slo() -> None:
    """Test hook: stop and forget the evaluator."""
    global _EVALUATOR
    with _LOCK:
        if _EVALUATOR is not None:
            _EVALUATOR.stop()
        _EVALUATOR = None
