"""Cross-host metric aggregation — joining N processes' introspection.

A multi-process run (``runtime/multihost.py``: one process per host)
leaves N separate ``runtime/introspect.py`` endpoints, which is N
browser tabs and no cluster answer to "how far along is the job".
This module is the rollup: a :class:`ClusterAggregator` scrapes every
worker's ``/metrics`` + ``/progress`` + ``/healthz``, merges them, and
serves (or returns) the cluster view:

- **Metrics.** Each worker's Prometheus exposition is parsed and
  re-emitted with a ``process="<id>"`` label on every series (the id
  comes from the ``disq_tpu_process_info`` series each endpoint
  exposes, sourced from ``multihost.process_id()``), plus one
  **rollup series per metric without the ``process`` label whose value
  is the sum across processes** — counters sum to cluster totals,
  histogram ``_bucket``/``_sum``/``_count`` series sum to cluster
  histograms, gauges sum to cluster-wide levels (in-flight shards,
  HBM bytes).
- **Progress.** Per-direction shard/record/byte totals summed across
  workers, rolling rates summed, ETA recomputed from the cluster
  remaining/rate, with the per-process views kept under
  ``"processes"``.
- **Health.** ``ok`` only when every worker is reachable and ``ok``;
  any degraded or unreachable worker degrades the cluster verdict and
  is named.

Everything is stdlib (``urllib`` + ``http.server``) and CPU-only
testable: point it at N subprocess workers' ephemeral endpoints.
The scrape itself is telemetry too: ``cluster.scrape`` spans (labeled
with the endpoint), ``cluster.scrape_errors`` and the
``cluster.processes`` reachable-worker gauge.

CLI: ``scripts/metrics_aggregate.py``.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

from disq_tpu.runtime.tracing import REGISTRY, inject_trace_headers, span

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

PROCESS_INFO_SERIES = "disq_tpu_process_info"


def parse_metrics_text(text: str) -> Tuple[
        Dict[str, str], List[Tuple[str, Tuple[Tuple[str, str], ...], float]]]:
    """Parse a Prometheus text exposition into
    ``({series_base_name: kind}, [(sample_name, labels, value), ...])``.

    Handles exactly the exposition this framework emits (``# TYPE``
    comments + plain samples; histogram samples appear as
    ``name_bucket`` / ``name_sum`` / ``name_count`` under a ``# TYPE
    name histogram``)."""
    kinds: Dict[str, str] = {}
    samples: List[Tuple[str, Tuple[Tuple[str, str], ...], float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, _, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = tuple(
            (k, v.replace('\\"', '"').replace("\\\\", "\\"))
            for k, v in _LABEL_RE.findall(raw_labels or ""))
        samples.append((name, labels, value))
    return kinds, samples


def _kind_of(sample_name: str, kinds: Dict[str, str]) -> str:
    """The TYPE of one sample series, resolving histogram suffixes."""
    if sample_name in kinds:
        return kinds[sample_name]
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if kinds.get(base) == "histogram":
                return "histogram"
    return "untyped"


def probe_liveness(endpoints: Sequence[str],
                   timeout_s: float = 1.0) -> Dict[str, bool]:
    """One-shot reachability probe of introspection endpoints — the
    scheduler failover election's view of "live" (``scheduler.py``
    standby election picks the lowest live member).  Hits ``/healthz``
    with a short timeout; a 503 (degraded) still counts as *alive* —
    election needs "is the process up", not "is it healthy"."""
    alive: Dict[str, bool] = {}
    for endpoint in endpoints:
        base = endpoint if "://" in endpoint else "http://" + endpoint
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=timeout_s):
                alive[endpoint] = True
        except urllib.error.HTTPError:
            alive[endpoint] = True  # answered — the process is up
        except Exception:  # noqa: BLE001 — reachability verdict
            alive[endpoint] = False
    return alive


class WorkerState:
    """One scraped worker: reachability, identity, parsed payloads."""

    def __init__(self, endpoint: str) -> None:
        self.endpoint = endpoint          # "host:port"
        self.ok = False
        self.error: Optional[str] = None
        self.process_id: Optional[int] = None
        self.run_id: Optional[str] = None
        self.kinds: Dict[str, str] = {}
        self.samples: List[Tuple[str, Tuple[Tuple[str, str], ...],
                                 float]] = []
        self.progress: Dict[str, Any] = {}
        self.healthz: Dict[str, Any] = {}
        self.slo: Dict[str, Any] = {}
        self.serve_stats: Dict[str, Any] = {}


class ClusterAggregator:
    """Scrape N introspection endpoints and merge (see module doc).

    ``endpoints`` are ``host:port`` strings (scheme optional).
    ``scrape()`` refreshes every worker synchronously and returns the
    worker list; the ``metrics_text`` / ``progress`` / ``healthz``
    views render the most recent scrape.  ``serve(port)`` starts an
    HTTP server exposing the same three paths, scraping on demand
    (throttled to at most one scrape per ``min_scrape_interval_s``).
    """

    def __init__(self, endpoints: Sequence[str], timeout_s: float = 5.0,
                 min_scrape_interval_s: float = 0.2) -> None:
        if not endpoints:
            raise ValueError("at least one worker endpoint required")
        self.endpoints = [e.strip() for e in endpoints if e.strip()]
        self.timeout_s = timeout_s
        self.min_scrape_interval_s = min_scrape_interval_s
        self._lock = threading.Lock()
        self._workers: List[WorkerState] = [
            WorkerState(e) for e in self.endpoints]
        self._last_scrape = 0.0
        self._server: Optional[ThreadingHTTPServer] = None
        self._server_thread: Optional[threading.Thread] = None
        self._address: Optional[str] = None

    # -- scraping -----------------------------------------------------------

    def _get(self, endpoint: str, path: str,
             timeout_s: Optional[float] = None) -> bytes:
        base = endpoint
        if "://" not in base:
            base = "http://" + base
        req = urllib.request.Request(
            base + path, headers=inject_trace_headers({}))
        with urllib.request.urlopen(
                req,
                timeout=self.timeout_s if timeout_s is None
                else timeout_s) as resp:
            return resp.read()

    def _scrape_one(self, worker: WorkerState) -> None:
        with span("cluster.scrape", endpoint=worker.endpoint):
            try:
                metrics_raw = self._get(worker.endpoint,
                                        "/metrics").decode()
                progress_raw = self._get(worker.endpoint, "/progress")
                try:
                    healthz_raw = self._get(worker.endpoint, "/healthz")
                except urllib.error.HTTPError as e:
                    # /healthz answers 503 when degraded — that IS the
                    # payload, not a scrape failure.
                    healthz_raw = e.read()
            except Exception as e:  # noqa: BLE001 — reachability verdict
                worker.ok = False
                worker.error = f"{type(e).__name__}: {e}"
                REGISTRY.counter("cluster.scrape_errors").inc(
                    endpoint=worker.endpoint)
                return
            # /slo is newer than /metrics — a worker without the route
            # (or with SLOs unconfigured) must still scrape clean.
            try:
                slo_raw = self._get(worker.endpoint, "/slo")
            except Exception:  # noqa: BLE001 — optional endpoint
                slo_raw = b"{}"
            # /serve/stats likewise: older replicas 404 it and
            # non-serving workers answer 503 — both scrape clean.
            try:
                serve_raw = self._get(worker.endpoint, "/serve/stats")
            except Exception:  # noqa: BLE001 — optional endpoint
                serve_raw = b"{}"
        worker.kinds, worker.samples = parse_metrics_text(metrics_raw)
        try:
            worker.slo = json.loads(slo_raw)
            if not isinstance(worker.slo, dict):
                worker.slo = {}
        except ValueError:
            worker.slo = {}
        try:
            worker.serve_stats = json.loads(serve_raw)
            if not isinstance(worker.serve_stats, dict):
                worker.serve_stats = {}
        except ValueError:
            worker.serve_stats = {}
        try:
            worker.progress = json.loads(progress_raw)
        except ValueError:
            worker.progress = {}
        try:
            worker.healthz = json.loads(healthz_raw)
        except ValueError:
            worker.healthz = {}
        worker.process_id = self._identity(worker)
        worker.run_id = worker.progress.get("run_id") \
            or worker.healthz.get("run_id")
        worker.ok = True
        worker.error = None

    @staticmethod
    def _identity(worker: WorkerState) -> int:
        """Worker process id: the process_info series first, then the
        JSON endpoints, then the scrape-list position."""
        for name, labels, _value in worker.samples:
            if name == PROCESS_INFO_SERIES:
                for k, v in labels:
                    if k == "process_id":
                        try:
                            return int(v)
                        except ValueError:
                            break
        for doc in (worker.progress, worker.healthz):
            pid = doc.get("process_id")
            if isinstance(pid, int):
                return pid
        return -1

    def scrape(self) -> List[WorkerState]:
        """Refresh every worker (concurrently — a dead worker's timeout
        must not serialize the healthy ones) and return the states."""
        with self._lock:
            workers = [WorkerState(e) for e in self.endpoints]
            threads = [
                threading.Thread(target=self._scrape_one, args=(w,),
                                 name=f"disq-cluster-scrape-{i}",
                                 daemon=True)
                for i, w in enumerate(workers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # Every worker ends up with a UNIQUE id: a reported id is
            # kept first-come; duplicates (N workers all reporting
            # jax.process_index()==0), missing ids and unreachable
            # workers fall back to unused integers — otherwise two
            # same-id workers would overwrite each other's process-
            # labeled series and break the rollup-equals-sum contract.
            taken = set()
            for w in workers:
                if (w.ok and isinstance(w.process_id, int)
                        and w.process_id >= 0
                        and w.process_id not in taken):
                    taken.add(w.process_id)
                else:
                    w.process_id = None
            next_free = 0
            for w in workers:
                if w.process_id is None:
                    while next_free in taken:
                        next_free += 1
                    w.process_id = next_free
                    taken.add(next_free)
            self._workers = workers
            self._last_scrape = time.perf_counter()
            REGISTRY.gauge("cluster.processes").observe(
                sum(1 for w in workers if w.ok))
            return workers

    def _fresh(self) -> List[WorkerState]:
        with self._lock:
            age = time.perf_counter() - self._last_scrape
            if self._last_scrape and age < self.min_scrape_interval_s:
                return self._workers
        return self.scrape()

    # -- merged views -------------------------------------------------------

    def metrics_text(self, workers: Optional[List[WorkerState]] = None
                     ) -> str:
        """Merged Prometheus exposition: every worker series re-labeled
        ``process="<id>"`` plus, for each (name, labels) series, one
        rollup sample WITHOUT the process label holding the sum across
        processes."""
        if workers is None:
            workers = self._fresh()
        kinds: Dict[str, str] = {}
        # sample name -> labelset(with process) -> value, and rollups
        per_process: Dict[str, Dict[Tuple[Tuple[str, str], ...],
                                    float]] = defaultdict(dict)
        rollup: Dict[str, Dict[Tuple[Tuple[str, str], ...],
                               float]] = defaultdict(lambda:
                                                     defaultdict(float))
        for w in workers:
            if not w.ok:
                continue
            kinds.update(w.kinds)
            for name, labels, value in w.samples:
                if name == PROCESS_INFO_SERIES:
                    continue
                labeled = tuple(sorted(
                    labels + (("process", str(w.process_id)),)))
                per_process[name][labeled] = value
                rollup[name][labels] += value

        def fmt(labels: Tuple[Tuple[str, str], ...]) -> str:
            if not labels:
                return ""
            body = ",".join(
                '%s="%s"' % (k, v.replace("\\", "\\\\").replace(
                    '"', '\\"')) for k, v in labels)
            return "{" + body + "}"

        def fmt_val(v: float) -> str:
            return repr(round(v, 9)) if v != int(v) else str(int(v))

        lines: List[str] = [
            "# TYPE disq_tpu_cluster_workers gauge",
            "disq_tpu_cluster_workers{state=\"ok\"} %d"
            % sum(1 for w in workers if w.ok),
            "disq_tpu_cluster_workers{state=\"unreachable\"} %d"
            % sum(1 for w in workers if not w.ok),
        ]
        typed_done = set()
        for name in sorted(per_process):
            base_kind = _kind_of(name, kinds)
            type_name = name
            for suffix in ("_bucket", "_sum", "_count"):
                if (base_kind == "histogram"
                        and name.endswith(suffix)):
                    type_name = name[: -len(suffix)]
            if type_name not in typed_done and base_kind != "untyped":
                lines.append(f"# TYPE {type_name} {base_kind}")
                typed_done.add(type_name)
            for labels in sorted(rollup[name]):
                lines.append(
                    f"{name}{fmt(labels)} "
                    f"{fmt_val(rollup[name][labels])}")
            for labels in sorted(per_process[name]):
                lines.append(
                    f"{name}{fmt(labels)} "
                    f"{fmt_val(per_process[name][labels])}")
        return "\n".join(lines) + "\n"

    def progress(self, workers: Optional[List[WorkerState]] = None
                 ) -> Dict[str, Any]:
        """Cluster progress: per-direction totals summed across
        workers, rates summed, ETA recomputed from cluster
        remaining/rate; per-process views preserved."""
        if workers is None:
            workers = self._fresh()
        directions: Dict[str, Dict[str, Any]] = {}
        processes: Dict[str, Any] = {}
        for w in workers:
            key = str(w.process_id if w.process_id is not None else -1)
            if not w.ok:
                processes[key] = {"endpoint": w.endpoint,
                                  "ok": False, "error": w.error}
                continue
            processes[key] = {"endpoint": w.endpoint, "ok": True,
                              "run_id": w.run_id,
                              "directions": w.progress.get(
                                  "directions", {})}
            for direction, view in (w.progress.get("directions")
                                    or {}).items():
                agg = directions.setdefault(direction, {
                    "active": False, "shards_total": 0, "shards_done": 0,
                    "in_flight": 0, "records": 0, "bytes_compressed": 0,
                    "bytes_uncompressed": 0, "records_per_sec": 0.0,
                    "shards_per_sec": 0.0, "elapsed_s": 0.0,
                    "eta_s": None,
                })
                agg["active"] = agg["active"] or bool(view.get("active"))
                for k in ("shards_total", "shards_done", "in_flight",
                          "records", "bytes_compressed",
                          "bytes_uncompressed"):
                    agg[k] += int(view.get(k) or 0)
                for k in ("records_per_sec", "shards_per_sec"):
                    agg[k] = round(agg[k] + float(view.get(k) or 0.0), 3)
                agg["elapsed_s"] = max(agg["elapsed_s"],
                                       float(view.get("elapsed_s")
                                             or 0.0))
        for view in directions.values():
            remaining = max(0, view["shards_total"] - view["shards_done"])
            rate = view["shards_per_sec"]
            if not remaining:
                view["eta_s"] = 0.0
            elif view["active"] and rate > 0:
                view["eta_s"] = round(remaining / rate, 3)
        return {
            "cluster": True,
            "workers_ok": sum(1 for w in workers if w.ok),
            "workers_total": len(workers),
            "directions": directions,
            "processes": processes,
        }

    def healthz(self, workers: Optional[List[WorkerState]] = None
                ) -> Dict[str, Any]:
        """Cluster liveness: ok only when every worker is reachable and
        itself ok; degraded/unreachable workers are named."""
        if workers is None:
            workers = self._fresh()
        problems = []
        for w in workers:
            if not w.ok:
                problems.append({"endpoint": w.endpoint,
                                 "process_id": w.process_id,
                                 "status": "unreachable",
                                 "error": w.error})
            elif w.healthz.get("status") not in (None, "ok"):
                problems.append({"endpoint": w.endpoint,
                                 "process_id": w.process_id,
                                 "status": w.healthz.get("status"),
                                 "stalls": w.healthz.get("stalls", [])})
        return {
            "status": "ok" if not problems else "degraded",
            "cluster": True,
            "workers_ok": sum(1 for w in workers if w.ok),
            "workers_total": len(workers),
            "problems": problems,
        }

    def slo(self, workers: Optional[List[WorkerState]] = None
            ) -> Dict[str, Any]:
        """Fleet SLO verdict: per-tenant worst burn across workers
        (max — one hot replica pages, it does not average away), the
        union of fast-burn tenants, per-process docs preserved."""
        if workers is None:
            workers = self._fresh()
        tenants: Dict[str, Dict[str, Any]] = {}
        processes: Dict[str, Any] = {}
        enabled = False
        for w in workers:
            key = str(w.process_id if w.process_id is not None else -1)
            if not w.ok:
                processes[key] = {"endpoint": w.endpoint, "ok": False,
                                  "error": w.error}
                continue
            doc = w.slo or {}
            processes[key] = {"endpoint": w.endpoint, "ok": True,
                              "slo": doc}
            if not doc.get("enabled"):
                continue
            enabled = True
            for tenant, tdoc in (doc.get("tenants") or {}).items():
                agg = tenants.setdefault(str(tenant), {
                    "worst_burn": 0.0, "fast_burn": False,
                    "processes": [],
                })
                worst = 0.0
                for wdoc in (tdoc.get("windows") or {}).values():
                    worst = max(worst,
                                float(wdoc.get("burn") or 0.0),
                                float(wdoc.get("availability_burn")
                                      or 0.0))
                agg["worst_burn"] = round(
                    max(agg["worst_burn"], worst), 4)
                if tdoc.get("fast_burn"):
                    agg["fast_burn"] = True
                agg["processes"].append(key)
        return {
            "cluster": True,
            "enabled": enabled,
            "workers_ok": sum(1 for w in workers if w.ok),
            "workers_total": len(workers),
            "fast_burn_tenants": sorted(
                t for t, d in tenants.items() if d["fast_burn"]),
            "tenants": tenants,
            "processes": processes,
        }

    def serve_stats(self, workers: Optional[List[WorkerState]] = None
                    ) -> Dict[str, Any]:
        """Fleet-wide serving-plane view: per-tenant admission usage
        summed across replicas (active/queued add — they are fleet
        capacity consumption), head-of-line blocking as the max
        ``oldest_wait_s`` (one stuck replica pages), aggregate
        slots/queue as the fleet's admission ceiling, per-process docs
        preserved under ``process=`` keys. Workers whose ``/serve/stats``
        404d or 503d (older build, serving off) contribute nothing but
        do not poison the merge — same tolerance as the ``/slo`` view.
        """
        if workers is None:
            workers = self._fresh()
        tenants: Dict[str, Dict[str, Any]] = {}
        processes: Dict[str, Any] = {}
        slots = queue_depth = serving = 0
        for w in workers:
            key = str(w.process_id if w.process_id is not None else -1)
            if not w.ok:
                processes[key] = {"endpoint": w.endpoint, "ok": False,
                                  "error": w.error}
                continue
            doc = w.serve_stats or {}
            processes[key] = {"endpoint": w.endpoint, "ok": True,
                              "serve": doc}
            adm = doc.get("admission") or {}
            if not adm:
                continue
            serving += 1
            slots += int(adm.get("slots") or 0)
            queue_depth += int(adm.get("queue_depth") or 0)
            for tenant, tdoc in (adm.get("tenants") or {}).items():
                agg = tenants.setdefault(str(tenant), {
                    "active": 0, "queued": 0, "oldest_wait_s": 0.0,
                    "processes": [],
                })
                agg["active"] += int(tdoc.get("active") or 0)
                agg["queued"] += int(tdoc.get("queued") or 0)
                agg["oldest_wait_s"] = round(
                    max(agg["oldest_wait_s"],
                        float(tdoc.get("oldest_wait_s") or 0.0)), 6)
                agg["processes"].append(key)
        return {
            "cluster": True,
            "serving": serving,
            "workers_ok": sum(1 for w in workers if w.ok),
            "workers_total": len(workers),
            "slots": slots,
            "queue_depth": queue_depth,
            "tenants": tenants,
            "processes": processes,
        }

    # -- fleet debug collection ---------------------------------------------

    def _collect_debug(self, path: str,
                       workers: Optional[List[WorkerState]] = None,
                       extra_timeout_s: float = 0.0
                       ) -> Dict[int, Dict[str, Any]]:
        """Fetch one ``/debug/*`` path from every reachable worker
        concurrently; ``{process_id: {"endpoint", "ok", "body"|"error"}}``.
        Debug fetches are deliberately scrape-independent: a wedged
        worker that no longer answers ``/metrics`` may still answer
        ``/debug/stacks`` (the whole point of collecting stacks).
        ``extra_timeout_s`` stretches the per-fetch timeout for paths
        that legitimately block (a ``/debug/profile`` holds its
        response for the whole sampling window)."""
        if workers is None:
            workers = self._fresh()
        out: Dict[int, Dict[str, Any]] = {}
        lock = threading.Lock()
        timeout_s = self.timeout_s + extra_timeout_s

        def fetch(worker: WorkerState, idx: int) -> None:
            # scrape() guarantees unique ids, but externally-built
            # WorkerStates may carry None — fall back to a unique
            # negative slot so two unidentified workers never clobber
            # each other's debug output.
            pid = (worker.process_id if worker.process_id is not None
                   else -(idx + 1))
            try:
                body = self._get(worker.endpoint, path,
                                 timeout_s=timeout_s).decode()
                doc = {"endpoint": worker.endpoint, "ok": True,
                       "body": body}
            except Exception as e:  # noqa: BLE001 — reachability verdict
                doc = {"endpoint": worker.endpoint, "ok": False,
                       "error": f"{type(e).__name__}: {e}"}
            with lock:
                out[pid] = doc

        threads = [
            threading.Thread(target=fetch, args=(w, i),
                             name=f"disq-cluster-debug-{i}", daemon=True)
            for i, w in enumerate(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    def debug_stacks(self, workers: Optional[List[WorkerState]] = None
                     ) -> Dict[str, Any]:
        """Every worker's all-thread stack dump, keyed by process id —
        the cluster answer to "what is each process doing right now"."""
        collected = self._collect_debug("/debug/stacks", workers)
        return {
            "cluster": True,
            "processes": {
                str(pid): doc for pid, doc in sorted(collected.items())
            },
        }

    def debug_profile(self, seconds: float = 2.0,
                      workers: Optional[List[WorkerState]] = None) -> str:
        """Sample every worker for ``seconds`` concurrently and merge
        the collapsed stacks into one document, each stack rooted at a
        ``process=<id>`` frame — one flamegraph for the whole fleet,
        split by process then thread role."""
        seconds = max(0.05, min(60.0, float(seconds)))
        collected = self._collect_debug(
            "/debug/profile?seconds=%g" % seconds, workers,
            extra_timeout_s=seconds)
        lines: List[str] = []
        for pid, doc in sorted(collected.items()):
            if not doc.get("ok"):
                continue
            for line in doc["body"].splitlines():
                if line.strip():
                    lines.append(f"process={pid};{line}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- serving ------------------------------------------------------------

    def serve(self, port: int = 0) -> str:
        """Serve the merged ``/metrics`` / ``/progress`` / ``/healthz``
        on 127.0.0.1 (``port`` 0 = ephemeral); each request scrapes on
        demand (throttled).  Returns the bound ``host:port``."""
        if self._server is not None:
            return self._address  # type: ignore[return-value]
        aggregator = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = "disq-tpu-cluster/1"

            def log_message(self, *args: Any) -> None:
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802
                path, _, query = self.path.partition("?")
                workers = aggregator._fresh()
                if path == "/metrics":
                    self._send(
                        200, aggregator.metrics_text(workers).encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/progress":
                    self._send(
                        200,
                        json.dumps(aggregator.progress(workers),
                                   default=str).encode(),
                        "application/json")
                elif path == "/healthz":
                    doc = aggregator.healthz(workers)
                    self._send(
                        200 if doc["status"] == "ok" else 503,
                        json.dumps(doc, default=str).encode(),
                        "application/json")
                elif path == "/slo":
                    self._send(
                        200,
                        json.dumps(aggregator.slo(workers),
                                   default=str).encode(),
                        "application/json")
                elif path == "/serve/stats":
                    self._send(
                        200,
                        json.dumps(aggregator.serve_stats(workers),
                                   default=str).encode(),
                        "application/json")
                elif path == "/debug/stacks":
                    self._send(
                        200,
                        json.dumps(aggregator.debug_stacks(workers),
                                   default=str).encode(),
                        "application/json")
                elif path == "/debug/profile":
                    seconds = 2.0
                    for part in query.split("&"):
                        if part.startswith("seconds="):
                            try:
                                seconds = float(part[len("seconds="):])
                            except ValueError:
                                pass
                    self._send(
                        200,
                        aggregator.debug_profile(seconds,
                                                 workers).encode(),
                        "text/plain; charset=utf-8")
                else:
                    self._send(404, json.dumps({
                        "error": "unknown path",
                        "endpoints": ["/metrics", "/progress",
                                      "/healthz", "/slo",
                                      "/debug/stacks",
                                      "/debug/profile"]}).encode(),
                        "application/json")

        class _NamedServer(ThreadingHTTPServer):
            # named request threads: profiler/py-spy attribution
            def process_request_thread(self, request, client_address):
                threading.current_thread().name = "disq-cluster-req"
                super().process_request_thread(request, client_address)

        srv = _NamedServer(("127.0.0.1", int(port)), _Handler)
        srv.daemon_threads = True
        self._server = srv
        self._address = "127.0.0.1:%d" % srv.server_address[1]
        self._server_thread = threading.Thread(
            target=srv.serve_forever, name="disq-cluster", daemon=True)
        self._server_thread.start()
        return self._address

    def close(self) -> None:
        srv, thread = self._server, self._server_thread
        self._server = None
        self._server_thread = None
        self._address = None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if thread is not None:
            thread.join(timeout=5)

    @property
    def address(self) -> Optional[str]:
        return self._address
