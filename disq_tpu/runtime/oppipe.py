"""OpPipeline — composable resident operator chains (ROADMAP item 4's
sam2bam shape: decode → filter → markdup → sort → stat as ONE
pipeline on the columnar currency).

An ``OpPipeline`` is an ordered list of operators applied shard-wise
between decode and sink/reduce. Every transform speaks
``ColumnarBatch`` in and out, so a chain over resident shards never
materializes host records: ``filter`` compacts on device, ``sort``
returns a ``permuted()`` resident batch, ``markdup`` patches flag
bits in HBM *and* in the record blob bytes, and the reductions
(``pileup`` / ``rgstats``) only move their result rows d2h. Host
``ReadBatch`` shards run the same operators through their host paths
— identical outputs, different residency.

Operators with cross-shard semantics finalize after the per-shard
pass: ``markdup`` runs the driver-side boundary-key merge
(``ops/markdup.merge_boundary_duplicates``) so duplicate clusters
straddling shard seams elect one global representative.

This module imports none of the operator modules at import time and
is itself only imported by ``ReadsDataset.pipeline`` / direct users —
the suite-off zero-work guard (``scripts/check_overhead.py``) holds
``disq_tpu.runtime.oppipe`` out of ``sys.modules`` entirely.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np


class _Op:
    """One pipeline stage: ``apply`` maps a shard batch to a shard
    batch (identity for reductions); ``finalize`` sees every shard
    once and returns the op's merged stats (or None)."""

    name = "op"

    def apply(self, batch, shard: int):
        return batch

    def finalize(self, batches: List) -> Optional[Dict]:
        return None


class FilterOp(_Op):
    """Predicate filter + seeded subsample (``ops/rfilter`` grammar)."""

    name = "filter"

    def __init__(self, spec):
        from disq_tpu.ops.rfilter import ReadFilter, parse_read_filter

        self.rf = spec if isinstance(spec, ReadFilter) \
            else parse_read_filter(spec)

    def apply(self, batch, shard: int):
        from disq_tpu.ops.rfilter import apply_read_filter

        return apply_read_filter(batch, self.rf)


class SortOp(_Op):
    """Coordinate sort, resident when the batch is (``permuted()``
    keeps the device columns + blob for the write path). Within-shard:
    a coordinate-sorted input's shards cover disjoint coordinate
    ranges, so per-shard sorting preserves the global order."""

    name = "sort"

    def apply(self, batch, shard: int):
        from disq_tpu.sort.coordinate import coordinate_sort_batch

        return coordinate_sort_batch(batch, keep_resident=True)


class MarkdupOp(_Op):
    """Duplicate marking + the cross-shard boundary-key merge."""

    name = "markdup"

    def __init__(self, boundary_bp: Optional[int] = None):
        from disq_tpu.ops.markdup import DEFAULT_BOUNDARY_BP

        self.boundary_bp = (DEFAULT_BOUNDARY_BP if boundary_bp is None
                            else int(boundary_bp))
        self._results: List = []

    def apply(self, batch, shard: int):
        from disq_tpu.ops.markdup import markdup_batch

        batch, res = markdup_batch(batch, boundary_bp=self.boundary_bp)
        self._results.append((batch, res))
        return batch

    def finalize(self, batches: List) -> Dict:
        from disq_tpu.ops.markdup import merge_boundary_duplicates

        merge_boundary_duplicates(self._results)
        out = {"examined": 0, "duplicates": 0, "boundary_flips": 0}
        for _b, res in self._results:
            for k, v in res.stats().items():
                out[k] += v
        self._results = []
        return out


class PileupOp(_Op):
    """Per-base coverage over one region, summed across shards
    (disjoint shards contribute disjoint alignments; integer adds)."""

    name = "pileup"

    def __init__(self, refid: int, start: int, end: int):
        self.refid, self.start, self.end = int(refid), int(start), int(end)
        self._cov: Optional[np.ndarray] = None

    def apply(self, batch, shard: int):
        from disq_tpu.ops.pileup import region_pileup

        cov = region_pileup(batch, self.refid, self.start, self.end)
        self._cov = cov if self._cov is None \
            else (self._cov + cov).astype(np.int32)
        return batch

    def finalize(self, batches: List) -> Dict:
        cov = self._cov if self._cov is not None else np.zeros(
            max(0, self.end - self.start), np.int32)
        self._cov = None
        return {"refid": self.refid, "start": self.start,
                "end": self.end, "coverage": cov}


class RgStatsOp(_Op):
    """Per-read-group reduction, histogram-merged across shards."""

    name = "rgstats"

    def __init__(self):
        self._acc: Dict[str, Dict] = {}

    def apply(self, batch, shard: int):
        from disq_tpu.ops.rgstats import read_group_stats

        for name, st in read_group_stats(batch).items():
            acc = self._acc.setdefault(name, {
                "reads": 0, "duplicates": 0,
                "mapq_hist": np.zeros(256, np.int64)})
            acc["reads"] += st["reads"]
            acc["duplicates"] += st["duplicates"]
            acc["mapq_hist"] += np.asarray(st["mapq_hist"])
        return batch

    def finalize(self, batches: List) -> Dict:
        out: Dict[str, Dict] = {}
        mq = np.arange(256)
        for name, acc in self._acc.items():
            reads, d = int(acc["reads"]), int(acc["duplicates"])
            h = acc["mapq_hist"]
            out[name] = {
                "reads": reads, "duplicates": d,
                "dup_rate": round(d / reads, 6) if reads else 0.0,
                "mean_mapq": round(float((h * mq).sum() / reads), 3)
                if reads else 0.0,
                "mapq_hist": h.astype(int).tolist(),
            }
        self._acc = {}
        return out


_OP_BY_NAME = {
    "filter": FilterOp, "sort": SortOp, "markdup": MarkdupOp,
    "pileup": PileupOp, "rgstats": RgStatsOp,
}


@dataclass
class PipelineResult:
    """Per-shard output batches + each op's merged stats."""

    batches: List
    stats: Dict[str, object] = field(default_factory=dict)

    def concat(self):
        """One batch (consuming — resident shards fold into a resident
        result, see ``ColumnarBatch.concat``)."""
        from disq_tpu.runtime.columnar import concat_batches

        return concat_batches(self.batches)


def make_op(spec) -> _Op:
    """Resolve one op spec: an ``_Op`` instance passes through; a name
    (``"sort"``) or ``(name, *args)`` tuple constructs one."""
    if isinstance(spec, _Op):
        return spec
    if isinstance(spec, str):
        name, args = spec, ()
    elif isinstance(spec, (tuple, list)) and spec:
        name, args = spec[0], tuple(spec[1:])
    else:
        raise TypeError(f"not an operator spec: {spec!r}")
    cls = _OP_BY_NAME.get(name)
    if cls is None:
        raise ValueError(
            f"unknown operator {name!r}; have {sorted(_OP_BY_NAME)}")
    return cls(*args)


class OpPipeline:
    """``OpPipeline(FilterOp("-q 30"), MarkdupOp(), RgStatsOp())`` —
    or by name: ``OpPipeline("filter -q 30" and friends via specs:
    ("filter", "-q 30"), "sort", "markdup", "rgstats")``. ``run``
    takes the decoded shard batches (one concatenated dataset batch
    counts as a single shard) and applies every op in order,
    shard-wise, then finalizes."""

    def __init__(self, *ops):
        self.ops = [make_op(op) for op in ops]

    def run(self, batches: Sequence) -> PipelineResult:
        from disq_tpu.runtime.tracing import span

        batches = list(batches)
        result = PipelineResult(batches=batches)
        with span("ops.pipeline.run",
                  ops=",".join(op.name for op in self.ops),
                  shards=len(batches)):
            for op in self.ops:
                batches = [op.apply(b, i) for i, b in enumerate(batches)]
                st = op.finalize(batches)
                if st is not None:
                    result.stats[op.name] = st
            result.batches = batches
        return result
