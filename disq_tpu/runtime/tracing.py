"""Structured telemetry — labeled metrics registry + per-shard span timelines.

The reference's only observability is the Spark UI plus slf4j loggers
(SURVEY.md §5).  disq_tpu replaces both with a process-local telemetry
layer shared by every subsystem:

- **Metrics registry** (``MetricsRegistry`` / module-level ``REGISTRY``):
  labeled ``Counter`` / ``Gauge`` (min/max/last/mean) / fixed-bucket
  ``Histogram`` handles, thread-safe and resettable.  Exported as
  Prometheus text exposition via ``metrics_text()`` and as a plain dict
  via ``telemetry_snapshot()`` / ``telemetry_summary()``.
- **Span timeline**: ``span(name, shard=…)`` context managers emit
  ``{ts, dur, name, labels}`` events with a process-wide ``run_id`` and
  monotonic timestamps into a bounded in-memory ring (default 64k
  events; overflow drops the oldest and counts
  ``telemetry.dropped_spans``) plus an optional JSONL sink
  (``DISQ_TPU_TRACE_JSONL`` or ``start_span_log(path)`` /
  ``DisqOptions.span_log``).  A whole BAM read becomes a replayable
  per-shard timeline (``scripts/trace_report.py``) instead of a sum.
- **Exporters**: Chrome/Perfetto ``trace_event`` JSON
  (``chrome_trace_events`` / ``export_chrome_trace``) and Prometheus
  text (``metrics_text``).
- **jax.profiler bridge**: ``trace_phase(name)`` additionally opens a
  ``jax.profiler.TraceAnnotation`` so phases appear on the XLA
  timeline, and ``DISQ_TPU_TRACE_DIR`` (or ``start_trace(dir)``)
  captures a perfetto/tensorboard trace of everything between the
  first phase entered and process exit (or ``stop_trace()``).

Metric taxonomy (dotted names, linted by ``scripts/check_metrics.py``
against the README table):

- ``executor.*``  — shard-pipeline executor: per-shard ``executor.fetch``
  / ``executor.decode`` spans + latency histograms, the
  ``executor.emit.stall`` ordered-emit stall histogram, and the
  ``executor.in_flight`` window-depth gauge.
- ``retry.*``     — transient-fault machinery: ``retry.attempts``
  (counter, labeled ``what=``) and ``retry.backoff`` (sleep spans).
- ``errors.*`` / ``quarantine.*`` — corrupt-block policy outcomes:
  ``errors.skipped_blocks``, ``quarantine.blocks`` (counters, labeled
  ``kind=``) and ``quarantine.write`` sidecar-write spans.
- ``fsw.http.*``  — remote I/O: ``fsw.http.range_get`` latency
  spans/histogram and the block-LRU efficacy counters
  ``fsw.http.cache.hits`` / ``fsw.http.cache.misses`` /
  ``fsw.http.cache.evictions``.
- ``codec.*``     — codec batch work: ``codec.inflate.batch`` spans.
- ``bam.*`` / ``vcf.*`` / ``bcf.*`` / ``cram.*`` — format phases
  (``bam.read.header`` …) and per-split ``<fmt>.split.fetch`` /
  ``<fmt>.split.decode`` spans carrying shard id + byte range.
- ``device.*`` — the device-resident pipeline and Pallas kernels:
  ``device.bytes_to_device`` / ``device.bytes_to_host`` transfer
  counters, ``device.kernel_launches{kernel=}``,
  ``device.host_fallback_blocks{reason=}``, the ``device.hbm_bytes``
  live-footprint gauge, and ``device.kernel`` / ``device.transfer``
  spans.  Device spans are timed by ``device_span`` /
  ``synced_timer``, which **materialize a sentinel element** of the
  kernel's output before closing — PROBES.md: ``block_until_ready``
  does not sync on this platform, so an unmaterialized timing
  under-reports arbitrarily.
- ``telemetry.*`` — self-observation (``telemetry.dropped_spans``).

Back-compat: ``trace_phase`` / ``record_phase`` / ``phase_report`` /
``observe_gauge`` / ``gauge_report`` are thin views over the registry —
phases are unlabeled duration histograms, so ``phase_report()`` keeps
returning ``{name: {calls, total_s}}``.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger("disq_tpu.tracing")

# Process-wide run id: every span carries it, so timelines from
# different runs/processes appended to one JSONL stay separable.
RUN_ID = f"{os.getpid():x}-{time.time_ns() & 0xFFFFFFFF:08x}"

# Default latency buckets (seconds): spans are I/O + decode phases that
# range from sub-millisecond (cache hit) to tens of seconds (cold
# remote shard).  Fixed buckets keep observe() O(len(buckets)) with no
# allocation.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_LabelKey = Tuple[Tuple[str, Any], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted(labels.items()))


def _label_str(key: _LabelKey) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


class Counter:
    """Monotonic labeled counter handle: ``inc(n, **labels)``."""

    kind = "counter"

    def __init__(self, name: str, registry: "MetricsRegistry") -> None:
        self.name = name
        self._registry = registry
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, n: float = 1, **labels: Any) -> None:
        key = _label_key(labels)
        with self._registry._lock:
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels: Any) -> float:
        """Value for one exact labelset (no labels ⇒ the unlabeled
        series)."""
        with self._registry._lock:
            return self._values.get(_label_key(labels), 0)

    def total(self) -> float:
        """Sum across every labelset."""
        with self._registry._lock:
            return sum(self._values.values())

    def _reset(self) -> None:
        self._values.clear()

    def _snapshot(self) -> Dict[str, float]:
        return {_label_str(k): v for k, v in sorted(self._values.items())}


class Gauge:
    """Level-style labeled quantity (queue depth, in-flight shards):
    keeps min / max / last / mean per labelset — gauges are states, not
    durations."""

    kind = "gauge"

    def __init__(self, name: str, registry: "MetricsRegistry") -> None:
        self.name = name
        self._registry = registry
        self._states: Dict[_LabelKey, Dict[str, float]] = {}

    def observe(self, value: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._registry._lock:
            g = self._states.get(key)
            if g is None:
                self._states[key] = {
                    "min": value, "max": value, "last": value,
                    "sum": value, "samples": 1,
                }
            else:
                g["min"] = min(g["min"], value)
                g["max"] = max(g["max"], value)
                g["last"] = value
                g["sum"] += value
                g["samples"] += 1

    def state(self, **labels: Any) -> Optional[Dict[str, float]]:
        with self._registry._lock:
            g = self._states.get(_label_key(labels))
            return None if g is None else self._view(g)

    @staticmethod
    def _view(g: Dict[str, float]) -> Dict[str, float]:
        out = {k: g[k] for k in ("min", "max", "last", "samples")}
        out["mean"] = g["sum"] / g["samples"] if g["samples"] else 0.0
        return out

    def _reset(self) -> None:
        self._states.clear()

    def _snapshot(self) -> Dict[str, Dict[str, float]]:
        return {
            _label_str(k): self._view(g)
            for k, g in sorted(self._states.items())
        }


class Histogram:
    """Fixed-bucket labeled histogram with percentile estimation.

    ``observe(seconds)`` is O(len(buckets)); ``percentile(p)`` linearly
    interpolates inside the winning bucket, clamped to the observed
    min/max so a single sample reports itself exactly."""

    kind = "histogram"

    def __init__(self, name: str, registry: "MetricsRegistry",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 unit: str = "seconds") -> None:
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self.unit = unit
        self._registry = registry
        # labelset -> [bucket counts... , +Inf count]
        self._counts: Dict[_LabelKey, List[int]] = {}
        self._stats: Dict[_LabelKey, Dict[str, float]] = {}

    def observe(self, value: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._registry._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
                self._stats[key] = {"count": 0, "sum": 0.0,
                                    "min": value, "max": value}
            i = 0
            for i, b in enumerate(self.buckets):  # noqa: B007
                if value <= b:
                    break
            else:
                i = len(self.buckets)
            counts[i] += 1
            st = self._stats[key]
            st["count"] += 1
            st["sum"] += value
            st["min"] = min(st["min"], value)
            st["max"] = max(st["max"], value)

    # -- read side ---------------------------------------------------------

    def _merged(self) -> Tuple[List[int], Dict[str, float]]:
        """Aggregate counts+stats across every labelset (caller holds
        the registry lock)."""
        counts = [0] * (len(self.buckets) + 1)
        stats = {"count": 0, "sum": 0.0, "min": float("inf"), "max": 0.0}
        for key, c in self._counts.items():
            for i, n in enumerate(c):
                counts[i] += n
            st = self._stats[key]
            stats["count"] += st["count"]
            stats["sum"] += st["sum"]
            stats["min"] = min(stats["min"], st["min"])
            stats["max"] = max(stats["max"], st["max"])
        if stats["count"] == 0:
            stats["min"] = 0.0
        return counts, stats

    @property
    def count(self) -> int:
        with self._registry._lock:
            return self._merged()[1]["count"]

    @property
    def sum(self) -> float:
        with self._registry._lock:
            return self._merged()[1]["sum"]

    def percentile(self, p: float) -> float:
        """Estimate the p-th percentile (p in [0, 100]) across all
        labelsets from the bucket counts."""
        with self._registry._lock:
            counts, stats = self._merged()
        total = stats["count"]
        if total == 0:
            return 0.0
        rank = p / 100.0 * total
        cum = 0
        lo = stats["min"]
        for i, n in enumerate(counts):
            if n == 0:
                continue
            hi = (self.buckets[i] if i < len(self.buckets)
                  else stats["max"])
            if cum + n >= rank:
                frac = (rank - cum) / n
                est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                return max(stats["min"], min(stats["max"], est))
            cum += n
            lo = hi
        return stats["max"]

    def _reset(self) -> None:
        self._counts.clear()
        self._stats.clear()

    def _snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key in sorted(self._counts):
            counts = self._counts[key]
            st = self._stats[key]
            out[_label_str(key)] = {
                "count": st["count"],
                "sum": round(st["sum"], 6),
                "min": round(st["min"], 6),
                "max": round(st["max"], 6),
                "buckets": {
                    ("+Inf" if i == len(self.buckets)
                     else repr(self.buckets[i])): n
                    for i, n in enumerate(counts) if n
                },
            }
        return out


class MetricsRegistry:
    """Thread-safe named-metric registry.  ``counter`` / ``gauge`` /
    ``histogram`` create-or-return handles; registering one name as two
    different kinds raises (the metric-name lint makes that a CI
    failure before it is a runtime one)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, Any] = {}

    def _get(self, name: str, factory: Callable[[], Any], kind: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, lambda: Counter(name, self), "counter")

    def gauge(self, name: str) -> Gauge:
        return self._get(name, lambda: Gauge(name, self), "gauge")

    def histogram(self, name: str,
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                  unit: str = "seconds") -> Histogram:
        return self._get(
            name, lambda: Histogram(name, self, buckets, unit), "histogram")

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._metrics)

    def reset(self) -> None:
        """Zero every metric (handles stay registered, so references
        held by long-lived objects keep working)."""
        with self._lock:
            for m in self._metrics.values():
                m._reset()

    # -- exporters ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Full registry state as a JSON-serializable dict:
        ``{"counters": …, "gauges": …, "histograms": …}``, each keyed
        by metric name then labelset string (``""`` = unlabeled)."""
        out: Dict[str, Dict[str, Any]] = {
            "counters": {}, "gauges": {}, "histograms": {},
        }
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                snap = m._snapshot()
                if snap:
                    out[m.kind + "s"][name] = snap
        return out

    def summary(self) -> Dict[str, Any]:
        """Compact one-level summary (what ``bench.py`` embeds):
        counters as cross-label totals, gauges as last/max, histograms
        as calls/total/p50/p99.  The lock (re-entrant) is held across
        the whole walk so concurrent first-observations of a labelset
        can't mutate a state dict mid-iteration."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "phases": {}}
        with self._lock:
            items = sorted(self._metrics.items())
            for name, m in items:
                self._summarize_one(name, m, out)
        return out

    def _summarize_one(self, name: str, m, out: Dict[str, Any]) -> None:
        # caller holds self._lock
        if m.kind == "counter":
            total = m.total()
            if total:
                out["counters"][name] = total
        elif m.kind == "gauge":
            snap = m._snapshot()
            if snap:
                merged = list(snap.values())
                out["gauges"][name] = {
                    "last": merged[-1]["last"],
                    "max": max(g["max"] for g in merged),
                }
        else:
            if m.count:
                out["phases"][name] = {
                    "calls": m.count,
                    "total_s": round(m.sum, 6),
                    "p50_s": round(m.percentile(50), 6),
                    "p99_s": round(m.percentile(99), 6),
                }

    def metrics_text(self) -> str:
        """Prometheus text exposition.  Dotted names become
        ``disq_tpu_``-prefixed underscore names; histograms get the
        conventional ``_bucket``/``_sum``/``_count`` series with
        cumulative ``le`` labels."""
        def prom_name(name: str) -> str:
            return "disq_tpu_" + name.replace(".", "_")

        def esc(v: Any) -> str:
            return str(v).replace("\\", "\\\\").replace('"', '\\"')

        def fmt_labels(key: _LabelKey, extra: str = "") -> str:
            parts = ['%s="%s"' % (k, esc(v)) for k, v in key]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        def fmt_val(v: float) -> str:
            return repr(round(v, 9)) if isinstance(v, float) else str(v)

        lines: List[str] = []
        with self._lock:
            items = sorted(self._metrics.items())
            for name, m in items:
                pn = prom_name(name)
                if m.kind == "counter":
                    if not m._values:
                        continue
                    lines.append(f"# TYPE {pn} counter")
                    for key, v in sorted(m._values.items()):
                        lines.append(f"{pn}{fmt_labels(key)} {fmt_val(v)}")
                elif m.kind == "gauge":
                    if not m._states:
                        continue
                    lines.append(f"# TYPE {pn} gauge")
                    for key, g in sorted(m._states.items()):
                        lines.append(
                            f"{pn}{fmt_labels(key)} {fmt_val(g['last'])}")
                else:
                    if not m._counts:
                        continue
                    hn = pn + ("_" + m.unit if m.unit else "")
                    lines.append(f"# TYPE {hn} histogram")
                    for key in sorted(m._counts):
                        counts = m._counts[key]
                        st = m._stats[key]
                        cum = 0
                        for i, n in enumerate(counts):
                            cum += n
                            le = ("+Inf" if i == len(m.buckets)
                                  else repr(m.buckets[i]))
                            lines.append(
                                "%s_bucket%s %d" % (
                                    hn, fmt_labels(key, 'le="%s"' % le), cum))
                        lines.append(
                            f"{hn}_sum{fmt_labels(key)} "
                            f"{fmt_val(st['sum'])}")
                        lines.append(
                            f"{hn}_count{fmt_labels(key)} "
                            f"{int(st['count'])}")
        return "\n".join(lines) + ("\n" if lines else "")


REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str,
              buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, buckets)


def metrics_text() -> str:
    return REGISTRY.metrics_text()


def telemetry_snapshot() -> Dict[str, Any]:
    return REGISTRY.snapshot()


def telemetry_summary() -> Dict[str, Any]:
    return REGISTRY.summary()


# ---------------------------------------------------------------------------
# Request-scoped trace context: causal identity across threads/processes
# ---------------------------------------------------------------------------
#
# A TraceContext is minted ONCE at the serving edge (or adopted from a
# client's X-Disq-Trace-* headers) and carried via contextvars so every
# span and flight-recorder event emitted under it — on this thread, or
# on a hop the caller explicitly propagated to — is stamped with the
# request's trace id.  Propagation is explicit and cheap:
#
# - HTTP hop (scheduler RPCs, fsw ranged GETs, cluster scrapes):
#   ``inject_trace_headers(headers)`` adds the three headers when a
#   context is active, and the receiving introspection handler re-
#   activates it via ``trace_from_headers(self.headers)``.
# - Thread hop (device-service submissions): the submitting thread's
#   context rides on each queued lane and the dispatcher re-activates
#   it per owner via ``trace_scope`` when booking that owner's share.
#
# Zero-overhead contract (scripts/check_overhead.py): with no context
# active and DISQ_TPU_TRACE_REQUESTS unset, ``current_trace()`` is one
# ContextVar read, ``inject_trace_headers`` adds nothing, and no trace
# id is ever minted (``trace_ids_minted()`` stays 0).

TRACE_ID_HEADER = "X-Disq-Trace-Id"
TRACE_PARENT_HEADER = "X-Disq-Trace-Parent"
TRACE_TENANT_HEADER = "X-Disq-Trace-Tenant"


class TraceContext:
    """Immutable causal identity of one request: the trace id shared by
    every hop, the parent span/hop id that reached here, the tenant."""

    __slots__ = ("trace_id", "span_id", "tenant")

    def __init__(self, trace_id: str, span_id: str, tenant: str) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.tenant = tenant

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, tenant={self.tenant!r})")


_trace_var: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("disq_tpu_trace", default=None))
_trace_mint_lock = threading.Lock()
_trace_ids_minted = 0
_trace_span_seq = 0
_trace_env_resolved = False
_trace_requests = False


def trace_requests_enabled() -> bool:
    """True when ``DISQ_TPU_TRACE_REQUESTS`` is set truthy — the
    serving edge then mints a trace for requests that arrive without
    one.  Resolved once per process (explicit headers always win)."""
    global _trace_env_resolved, _trace_requests
    if not _trace_env_resolved:
        with _trace_mint_lock:
            if not _trace_env_resolved:
                _trace_requests = os.environ.get(
                    "DISQ_TPU_TRACE_REQUESTS", "").lower() not in (
                        "", "0", "false", "off")
                _trace_env_resolved = True
    return _trace_requests


def current_trace() -> Optional[TraceContext]:
    """The active request context, or None (the common, free case)."""
    return _trace_var.get()


def _mint_id(nbytes: int = 8) -> str:
    global _trace_ids_minted
    with _trace_mint_lock:
        _trace_ids_minted += 1
    return os.urandom(nbytes).hex()


def trace_ids_minted() -> int:
    """How many trace/span ids this process has minted — the overhead
    guard asserts this stays 0 on the tracing-off path."""
    with _trace_mint_lock:
        return _trace_ids_minted


def mint_trace(tenant: str) -> TraceContext:
    """Mint a fresh root context at the serving edge."""
    return TraceContext(_mint_id(8), _mint_id(4), str(tenant))


def child_context(ctx: TraceContext) -> TraceContext:
    """A hop-local context under ``ctx``'s trace: same trace id and
    tenant, a fresh span/hop id (cheap sequence, not entropy — hop ids
    only need uniqueness within one process's trace participation)."""
    global _trace_span_seq
    with _trace_mint_lock:
        _trace_span_seq += 1
        seq = _trace_span_seq
    return TraceContext(ctx.trace_id, f"{RUN_ID}-{seq:x}", ctx.tenant)


def activate_trace(ctx: TraceContext) -> "contextvars.Token":
    """Make ``ctx`` the active context on this thread; returns the
    token for ``deactivate_trace``."""
    return _trace_var.set(ctx)


def deactivate_trace(token: "contextvars.Token") -> None:
    _trace_var.reset(token)


@contextlib.contextmanager
def trace_scope(ctx: Optional[TraceContext]) -> Iterator[None]:
    """Scope ``ctx`` (None = no-op) over a block — used by the device
    dispatcher to book each owner's share under its own trace."""
    if ctx is None:
        yield
        return
    token = _trace_var.set(ctx)
    try:
        yield
    finally:
        _trace_var.reset(token)


def inject_trace_headers(headers: Dict[str, str]) -> Dict[str, str]:
    """Add ``X-Disq-Trace-*`` to an outbound header dict when a context
    is active; with none active this is one ContextVar read and the
    dict is returned untouched."""
    ctx = _trace_var.get()
    if ctx is not None:
        headers[TRACE_ID_HEADER] = ctx.trace_id
        headers[TRACE_PARENT_HEADER] = ctx.span_id
        headers[TRACE_TENANT_HEADER] = ctx.tenant
    return headers


def trace_from_headers(headers: Any) -> Optional[TraceContext]:
    """Parse an inbound context from HTTP headers (any mapping with
    ``.get``, including ``http.client.HTTPMessage``); None when the
    trace-id header is absent — one dict lookup on the off path."""
    trace_id = headers.get(TRACE_ID_HEADER)
    if not trace_id:
        return None
    return TraceContext(
        str(trace_id),
        str(headers.get(TRACE_PARENT_HEADER) or ""),
        str(headers.get(TRACE_TENANT_HEADER) or "anon"))


def reset_trace_state() -> None:
    """Test hook: forget the env resolution and zero the mint counter
    (any active context on the calling thread is left alone)."""
    global _trace_env_resolved, _trace_requests, _trace_ids_minted
    global _trace_span_seq
    with _trace_mint_lock:
        _trace_env_resolved = False
        _trace_requests = False
        _trace_ids_minted = 0
        _trace_span_seq = 0


# ---------------------------------------------------------------------------
# Span timeline: bounded ring + optional JSONL sink
# ---------------------------------------------------------------------------

DEFAULT_SPAN_RING = 65536

_span_lock = threading.Lock()
_span_ring: "deque[Dict[str, Any]]" = deque(maxlen=DEFAULT_SPAN_RING)
_span_sink = None            # open file object, or None
_span_sink_path: Optional[str] = None
_span_writes = 0             # lines since the last explicit flush
_sink_dropped_base = 0.0     # telemetry.dropped_spans total when this
                             # sink opened — the stop trailer reports
                             # only drops during the sink's lifetime
_SINK_FLUSH_EVERY = 64       # amortize flushes: a synchronous flush per
                             # span would serialize every worker thread
                             # on trace-disk latency (close() flushes
                             # the tail, so at most this many spans are
                             # lost to a hard crash)
_env_resolved = False        # DISQ_TPU_TRACE_JSONL honored at first use


def _resolve_span_env() -> None:
    global _env_resolved
    if _env_resolved:
        return
    with _span_lock:
        if _env_resolved:
            return
        _env_resolved = True
        path = os.environ.get("DISQ_TPU_TRACE_JSONL")
    if path and _span_sink is None:
        start_span_log(path)


def start_span_log(path: str) -> None:
    """Start (or re-point) the JSONL span sink.  Each emitted span is
    appended as one JSON line; a meta line maps this run's monotonic
    clock to the epoch so timelines from multiple runs stay
    separable."""
    global _span_sink, _span_sink_path, _env_resolved, _sink_dropped_base
    dropped_now = REGISTRY.counter("telemetry.dropped_spans").total()
    with _span_lock:
        _env_resolved = True  # explicit call wins over the env knob
        if _span_sink is not None:
            if _span_sink_path == path:
                return
            _span_sink.close()
        _sink_dropped_base = dropped_now
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _span_sink = open(path, "a")
        _span_sink_path = path
        _span_sink.write(json.dumps({
            "meta": 1, "run_id": RUN_ID, "pid": os.getpid(),
            "epoch": time.time(), "mono": time.perf_counter(),
        }) + "\n")
        _span_sink.flush()
        atexit.register(stop_span_log)


def stop_span_log() -> None:
    global _span_sink, _span_sink_path, _span_writes
    total = REGISTRY.counter("telemetry.dropped_spans").total()
    with _span_lock:
        if _span_sink is not None:
            dropped = int(total - _sink_dropped_base)
            if dropped > 0:
                # Trailer meta line: the in-memory ring overflowed
                # during this sink's lifetime, so any ring-derived view
                # (/spans, chrome export) is truncated even though the
                # JSONL itself is complete — trace_report surfaces it
                # as a banner instead of silently rendering a partial
                # waterfall.
                _span_sink.write(json.dumps({
                    "meta": 1, "run_id": RUN_ID,
                    "dropped_spans": dropped,
                }) + "\n")
            _span_sink.close()  # flushes any buffered tail
            _span_sink = None
            _span_sink_path = None
            _span_writes = 0


def span_log_path() -> Optional[str]:
    with _span_lock:
        return _span_sink_path


def set_span_ring_capacity(n: int) -> None:
    """Resize the in-memory span ring (keeps the most recent spans)."""
    global _span_ring
    with _span_lock:
        _span_ring = deque(_span_ring, maxlen=max(1, int(n)))


def spans() -> List[Dict[str, Any]]:
    """Snapshot of the in-memory span ring, oldest first."""
    with _span_lock:
        return list(_span_ring)


def reset_spans() -> None:
    with _span_lock:
        _span_ring.clear()


def _emit_span(name: str, ts: float, dur: float,
               labels: Dict[str, Any]) -> None:
    global _span_writes
    REGISTRY.histogram(name).observe(dur)
    rec = {"ts": round(ts, 6), "dur": round(dur, 6), "name": name,
           "run": RUN_ID, "labels": labels}
    ctx = _trace_var.get()
    if ctx is not None:
        rec["trace"] = ctx.trace_id
        rec["parent"] = ctx.span_id
        rec["tenant"] = ctx.tenant
    # Serialize outside the lock (unlocked sink check is benign: worst
    # case one wasted dumps around a concurrent start/stop).
    line = (json.dumps(rec, default=str) + "\n"
            if _span_sink is not None else None)
    with _span_lock:
        dropped = len(_span_ring) == _span_ring.maxlen
        _span_ring.append(rec)
        if _span_sink is not None:
            if line is None:
                line = json.dumps(rec, default=str) + "\n"
            _span_sink.write(line)
            _span_writes += 1
            if _span_writes >= _SINK_FLUSH_EVERY:
                _span_sink.flush()
                _span_writes = 0
    if dropped:
        REGISTRY.counter("telemetry.dropped_spans").inc()
    logger.debug("span %s: %.4fs %s", name, dur, labels)


@contextlib.contextmanager
def span(name: str, **labels: Any) -> Iterator[None]:
    """Timeline span: emits a ``{ts, dur, name, labels}`` event into the
    ring/JSONL and books the duration in the ``name`` histogram (so
    ``phase_report()`` and percentiles see it)."""
    _resolve_span_env()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _emit_span(name, t0, time.perf_counter() - t0, labels)


def record_span(name: str, seconds: float, **labels: Any) -> None:
    """Book an already-measured duration as a span ending now (for
    waits timed inline — e.g. the executor's ordered-emit stall — where
    a context manager would nest a lock inside a condition wait)."""
    _resolve_span_env()
    now = time.perf_counter()
    _emit_span(name, now - seconds, seconds, labels)


def wrap_span(name: str, fn: Callable, **labels: Any) -> Callable:
    """``fn`` wrapped in ``span(name, **labels)`` — for handing staged
    callables (executor ``ShardTask.fetch``/``decode``) a per-shard
    span without changing their signatures."""
    def wrapped(*args: Any, **kwargs: Any):
        with span(name, **labels):
            return fn(*args, **kwargs)
    return wrapped


# ---------------------------------------------------------------------------
# Device telemetry: synced kernel spans, transfer counters, HBM gauge
# ---------------------------------------------------------------------------

def _materialize_sentinel(value: Any) -> None:
    """Truly wait for every jax array in ``value`` (a pytree) by
    materializing ONE element of each.  ``block_until_ready`` does not
    block on this platform (PROBES.md measurement caveats) — only
    ``np.asarray`` syncs — so a sentinel fetch is the cheapest honest
    fence: a one-element slice dispatches after the producing kernel
    and costs a few bytes of D2H, not the whole result."""
    try:
        import jax
        from jax.core import Tracer
        import numpy as _np
    except ImportError:  # host-only deployment: nothing to sync
        return
    for leaf in jax.tree_util.tree_leaves(value):
        if isinstance(leaf, jax.Array) and not isinstance(leaf, Tracer):
            _np.asarray(leaf.ravel()[:1] if leaf.ndim else leaf)


class _DeviceSync:
    """Handle yielded by ``device_span``: the body registers its device
    outputs with ``sync(...)``; span close materializes one sentinel
    element of each so the recorded duration covers real execution."""

    __slots__ = ("_values",)

    def __init__(self) -> None:
        self._values: List[Any] = []

    def sync(self, *values: Any):
        """Register device arrays (or pytrees of them) to fence on at
        span close.  Returns the single value (or the tuple) so call
        sites can wrap an expression in place."""
        self._values.extend(values)
        return values[0] if len(values) == 1 else values

    def materialize(self) -> None:
        for v in self._values:
            _materialize_sentinel(v)
        self._values.clear()


@contextlib.contextmanager
def device_span(name: str, **labels: Any) -> Iterator[_DeviceSync]:
    """Span over device work whose close is a true sync point: the body
    hands its output arrays to ``.sync(...)`` and span exit
    materializes a one-element sentinel of each before taking the end
    timestamp (the PROBES.md caveat: unmaterialized device timings
    under-report arbitrarily).  Also books one
    ``device.kernel_launches`` increment when a ``kernel=`` label is
    present, so every synced kernel span is a counted launch."""
    _resolve_span_env()
    if "kernel" in labels:
        REGISTRY.counter("device.kernel_launches").inc(
            kernel=labels["kernel"])
    handle = _DeviceSync()
    t0 = time.perf_counter()
    try:
        yield handle
    finally:
        handle.materialize()
        _emit_span(name, t0, time.perf_counter() - t0, labels)


def synced_timer(name: str, **labels: Any) -> Callable:
    """Decorator form of ``device_span``: times the wrapped function
    and materializes a sentinel of its return value before the span
    closes — for ops entry points whose return IS the device output."""
    def deco(fn: Callable) -> Callable:
        def wrapped(*args: Any, **kwargs: Any):
            with device_span(name, **labels) as fence:
                return fence.sync(fn(*args, **kwargs))
        return wrapped
    return deco


def count_transfer(direction: str, nbytes: int) -> None:
    """Book one explicit host↔device transfer (``direction`` ``"h2d"``
    or ``"d2h"``) in the ``device.bytes_*`` counters."""
    if direction == "h2d":
        REGISTRY.counter("device.bytes_to_device").inc(int(nbytes))
    else:
        REGISTRY.counter("device.bytes_to_host").inc(int(nbytes))


_hbm_lock = threading.Lock()
_hbm_live = 0


def track_hbm(nbytes: int) -> int:
    """Adjust the live-HBM-footprint estimate (negative to release) and
    observe the ``device.hbm_bytes`` gauge; returns the new estimate.
    The estimate is array-size arithmetic, not an allocator query — it
    tracks what the framework *put* on device, which is exactly the
    number a shard-sizing decision needs."""
    global _hbm_live
    with _hbm_lock:
        _hbm_live = max(0, _hbm_live + int(nbytes))
        live = _hbm_live
    REGISTRY.gauge("device.hbm_bytes").observe(live)
    return live


def hbm_live_bytes() -> int:
    with _hbm_lock:
        return _hbm_live


@contextlib.contextmanager
def hbm_resident(nbytes: int) -> Iterator[None]:
    """Scope one call's device residency: adds ``nbytes`` to the live
    HBM estimate on entry and releases it on exit, so the gauge's max
    is the peak concurrent footprint across overlapping device calls."""
    track_hbm(nbytes)
    try:
        yield
    finally:
        track_hbm(-nbytes)


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace_event export
# ---------------------------------------------------------------------------


_DEVICE_TRACK_PID = 2  # device.* spans render as their own process row


def chrome_trace_events(
    span_list: Optional[List[Dict[str, Any]]] = None
) -> List[Dict[str, Any]]:
    """Spans as Chrome ``trace_event`` complete events (``ph: "X"``,
    microsecond units).  Rows (``tid``) are shard ids when the span
    carries one, so chrome://tracing / Perfetto renders the per-shard
    waterfall directly.  ``device.*`` spans land on their own track
    (process row 2, named via metadata events), so kernel/transfer
    time reads against the host stages instead of hiding inside one
    shard's row."""
    events = []
    has_device = False
    for s in (spans() if span_list is None else span_list):
        labels = s.get("labels") or {}
        tid = labels.get("shard")
        try:
            tid = int(tid)
        except (TypeError, ValueError):
            tid = 0
        device = s["name"].startswith("device.")
        has_device = has_device or device
        events.append({
            "name": s["name"],
            "ph": "X",
            "ts": round(s["ts"] * 1e6, 3),
            "dur": round(s["dur"] * 1e6, 3),
            "pid": _DEVICE_TRACK_PID if device else 1,
            "tid": tid,
            "args": labels,
        })
    if has_device:
        events = [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": "host"}},
            {"name": "process_name", "ph": "M", "pid": _DEVICE_TRACK_PID,
             "args": {"name": "device"}},
        ] + events
    return events


def export_chrome_trace(path: str,
                        span_list: Optional[List[Dict[str, Any]]] = None
                        ) -> None:
    with open(path, "w") as f:
        # default=str: label values may be numpy scalars (voffsets)
        json.dump({"traceEvents": chrome_trace_events(span_list),
                   "displayTimeUnit": "ms"}, f, default=str)


# ---------------------------------------------------------------------------
# jax.profiler bridge + phase back-compat views
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_trace_active = False

# DISQ_TPU_TRACE_DIR and the jax import are resolved ONCE (first
# trace_phase) — the old implementation re-read os.environ and re-ran
# the import machinery on every call.
_phase_env_resolved = False
_trace_dir: Optional[str] = None
_annotation_cls = None  # jax.profiler.TraceAnnotation, or None


def _resolve_phase_env() -> None:
    global _phase_env_resolved, _trace_dir, _annotation_cls
    if _phase_env_resolved:
        return
    with _lock:
        if _phase_env_resolved:
            return
        _trace_dir = os.environ.get("DISQ_TPU_TRACE_DIR")
        try:
            import jax

            _annotation_cls = jax.profiler.TraceAnnotation
        except ImportError:  # host-only deployments: timing still works
            _annotation_cls = None
        _phase_env_resolved = True


def start_trace(trace_dir: str) -> None:
    """Begin a ``jax.profiler`` capture writing to ``trace_dir``."""
    global _trace_active
    try:
        import jax
    except ImportError:
        logger.warning("DISQ_TPU_TRACE_DIR set but jax unavailable; no trace")
        return

    with _lock:
        if _trace_active:
            return
        jax.profiler.start_trace(trace_dir)
        _trace_active = True
        atexit.register(stop_trace)


def stop_trace() -> None:
    global _trace_active
    with _lock:
        if not _trace_active:
            return
        import jax

        jax.profiler.stop_trace()
        _trace_active = False


@contextlib.contextmanager
def trace_phase(name: str, **labels: Any) -> Iterator[None]:
    """``span`` + the jax.profiler bridge: the phase also appears on
    the XLA timeline under a capture, and the first phase entered
    auto-starts a ``DISQ_TPU_TRACE_DIR`` capture."""
    _resolve_phase_env()
    if _trace_dir and not _trace_active:
        start_trace(_trace_dir)
    annotation = (_annotation_cls(f"disq_tpu.{name}")
                  if _annotation_cls is not None
                  else contextlib.nullcontext())
    with span(name, **labels):
        with annotation:
            yield


def record_phase(name: str, seconds: float, **labels: Any) -> None:
    """Back-compat alias for ``record_span``."""
    record_span(name, seconds, **labels)


def phase_report() -> Dict[str, Dict[str, float]]:
    """Aggregated ``{phase: {calls, total_s}}`` since process start —
    a thin view over the registry's duration histograms (every span /
    ``trace_phase`` books one)."""
    out: Dict[str, Dict[str, float]] = {}
    with REGISTRY._lock:
        for name, m in sorted(REGISTRY.metrics().items()):
            if m.kind != "histogram":
                continue
            calls = m.count
            if calls:
                out[name] = {"calls": calls, "total_s": round(m.sum, 6)}
    return out


def reset_phase_report() -> None:
    """Zero the duration histograms (and the span ring — a fresh phase
    report implies a fresh timeline)."""
    with REGISTRY._lock:
        for m in REGISTRY.metrics().values():
            if m.kind == "histogram":
                m._reset()
    reset_spans()


def observe_gauge(name: str, value: float, **labels: Any) -> None:
    """Record one sample of a level-style quantity — a thin wrapper
    over ``gauge(name).observe(value)``."""
    REGISTRY.gauge(name).observe(value, **labels)


def gauge_report() -> Dict[str, Dict[str, float]]:
    """Snapshot of every unlabeled gauge series (legacy shape: ``max``
    / ``last`` / ``samples``, now also ``min`` / ``mean``)."""
    out: Dict[str, Dict[str, float]] = {}
    with REGISTRY._lock:
        for name, m in sorted(REGISTRY.metrics().items()):
            if m.kind != "gauge":
                continue
            st = m.state()
            if st is not None:
                out[name] = st
            else:
                snap = m._snapshot()
                if snap:
                    out[name] = next(iter(snap.values()))
    return out


def reset_gauges() -> None:
    with REGISTRY._lock:
        for m in REGISTRY.metrics().values():
            if m.kind == "gauge":
                m._reset()


def reset_telemetry() -> None:
    """Zero everything: registry, span ring, the live-HBM estimate
    (the JSONL sink, if open, is left open — it is an append log)."""
    global _hbm_live
    REGISTRY.reset()
    reset_spans()
    with _hbm_lock:
        _hbm_live = 0
