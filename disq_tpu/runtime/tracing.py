"""Phase tracing — ``jax.profiler`` wrappers + structured wall-clock log.

The reference's observability is the Spark UI plus slf4j loggers
(SURVEY.md §5); here each pipeline phase is wrapped in
``trace_phase(name)``:

- always: wall-clock timing, accumulated in a process-local registry
  readable via ``phase_report()`` and logged at DEBUG level;
- under a profiler capture: a ``jax.profiler.TraceAnnotation`` so the
  phase shows up on the XLA timeline;
- with ``DISQ_TPU_TRACE_DIR`` set (or ``start_trace(dir)`` called), a
  perfetto/tensorboard trace of everything between the first phase
  entered and process exit (or ``stop_trace()``) is written there.
"""

from __future__ import annotations

import atexit
import contextlib
import logging
import os
import threading
import time
from typing import Dict, Iterator, List, Tuple

logger = logging.getLogger("disq_tpu.tracing")

_lock = threading.Lock()
_phases: List[Tuple[str, float]] = []
_gauges: Dict[str, Dict[str, float]] = {}
_trace_active = False


def start_trace(trace_dir: str) -> None:
    """Begin a ``jax.profiler`` capture writing to ``trace_dir``."""
    global _trace_active
    try:
        import jax
    except ImportError:
        logger.warning("DISQ_TPU_TRACE_DIR set but jax unavailable; no trace")
        return

    with _lock:
        if _trace_active:
            return
        jax.profiler.start_trace(trace_dir)
        _trace_active = True
        atexit.register(stop_trace)


def stop_trace() -> None:
    global _trace_active
    with _lock:
        if not _trace_active:
            return
        import jax

        jax.profiler.stop_trace()
        _trace_active = False


@contextlib.contextmanager
def trace_phase(name: str) -> Iterator[None]:
    trace_dir = os.environ.get("DISQ_TPU_TRACE_DIR")
    if trace_dir and not _trace_active:
        start_trace(trace_dir)
    try:
        import jax

        annotation = jax.profiler.TraceAnnotation(f"disq_tpu.{name}")
    except ImportError:  # host-only deployments: timing still works
        annotation = contextlib.nullcontext()

    t0 = time.perf_counter()
    try:
        with annotation:
            yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _phases.append((name, dt))
        logger.debug("phase %s: %.4fs", name, dt)


def record_phase(name: str, seconds: float) -> None:
    """Book an already-measured duration as a phase (for waits that are
    timed inline — e.g. the executor's ordered-emit stall — where
    wrapping the wait in ``trace_phase`` would nest a lock inside a
    condition wait)."""
    with _lock:
        _phases.append((name, seconds))
    logger.debug("phase %s: %.4fs", name, seconds)


def phase_report() -> Dict[str, Dict[str, float]]:
    """Aggregated {phase: {calls, total_s}} since process start."""
    out: Dict[str, Dict[str, float]] = {}
    with _lock:
        snapshot = list(_phases)
    for name, dt in snapshot:
        agg = out.setdefault(name, {"calls": 0, "total_s": 0.0})
        agg["calls"] += 1
        agg["total_s"] += dt
    for agg in out.values():
        agg["total_s"] = round(agg["total_s"], 6)
    return out


def reset_phase_report() -> None:
    with _lock:
        _phases.clear()


def observe_gauge(name: str, value: float) -> None:
    """Record one sample of a level-style quantity (queue depth,
    in-flight shard count): the report keeps max / last / sample
    count rather than a sum — gauges are states, not durations."""
    with _lock:
        g = _gauges.get(name)
        if g is None:
            _gauges[name] = {"max": value, "last": value, "samples": 1}
        else:
            g["max"] = max(g["max"], value)
            g["last"] = value
            g["samples"] += 1


def gauge_report() -> Dict[str, Dict[str, float]]:
    """Snapshot of every gauge observed since process start (or the
    last ``reset_gauges``)."""
    with _lock:
        return {k: dict(v) for k, v in _gauges.items()}


def reset_gauges() -> None:
    with _lock:
        _gauges.clear()
