"""ColumnarBatch — the host-or-device columnar record batch, the
universal currency between sources, device ops, and sinks.

ROADMAP item 1 ("HBM-resident fused decode"): the split decode path
inflates on device, ships the decoded blob d2h, re-parses every record
on host, and re-uploads whichever columns a device op wants — the
round-trip that pins device e2e at ~7.7 MB/s against a far higher
kernel ceiling. ``ColumnarBatch`` removes it: the fused path parses
the decoded blob into fixed columns **on device, in the same launch
chain as the inflate kernels** (``runtime/device_pipeline.
parse_columns_resident``; when the SIMD inflate ran, its still-resident
output chunks are compacted in HBM by ``assemble_device_words`` so the
payload bytes never round-trip), and the parsed columns stay resident:

- **Lazy d2h.** Attribute access (``batch.pos``, ``batch.flag``, …)
  fetches that one column, once — repeated access returns the host
  cache, so ``device.transfer`` bytes are never double-booked. Columns
  a caller never touches never cross d2h; their bytes (and columns
  consumed on device) are booked into ``device.d2h_avoided_bytes`` at
  release — a later host fetch un-marks a consumed column first, so
  nothing is ever counted both as moved and as avoided.
- **Resident consumers.** ``flagstat()`` feeds the device flag column
  straight into the flagstat kernel (zero h2d re-upload);
  ``sort_permutation()`` builds coordinate keys and the lexsort
  permutation on device and fetches only the (n,) i32 order — the u64
  key vectors never move. ``ops/depth.py`` and every existing
  ``ReadBatch`` consumer work unchanged through the lazy properties.
- **Host interop.** Ragged columns (names / cigars / seqs / quals /
  tags) come lazily from the host copy of the decoded blob (which the
  read path holds anyway for CRC verification and the record-offset
  scan); ``to_read_batch()`` / ``take()`` / ``concat()`` materialize a
  plain ``ReadBatch`` when host-side work (sorting gathers, sinks)
  needs it. ``concat`` of all-device batches stays device-backed.

Enablement: ``DisqOptions.resident_decode`` /
``ReadsStorage.resident_decode()`` / env ``DISQ_TPU_RESIDENT_DECODE``.
Disabled (the default), sources return plain host ``ReadBatch`` objects
and this module allocates nothing on device —
``scripts/check_overhead.py`` asserts ``device_batches_built() == 0``
on that path.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.util import bucket_pow2 as _bucket_n

# The 12 fields the Pallas parse kernel emits (ops/parse._FIELD_ORDER);
# the 8 ReadBatch fixed columns are a subset with narrowed dtypes.
PARSE_FIELDS = (
    "block_size", "refid", "pos", "l_read_name", "mapq", "bin",
    "n_cigar", "flag", "l_seq", "next_refid", "next_pos", "tlen",
)
FIXED_COLUMNS = ("refid", "pos", "mapq", "bin", "flag",
                 "next_refid", "next_pos", "tlen")
_COL_DTYPE = {
    "refid": np.int32, "pos": np.int32, "mapq": np.uint8,
    "bin": np.uint16, "flag": np.uint16, "next_refid": np.int32,
    "next_pos": np.int32, "tlen": np.int32,
}
_RAGGED = ("name_offsets", "names", "cigar_offsets", "cigars",
           "seq_offsets", "seqs", "quals", "tag_offsets", "tags")


_stats_lock = threading.Lock()
_device_batches_built = 0
_resident_live_bytes = 0


def device_batches_built() -> int:
    """Process-lifetime count of device-backed builds — the
    check_overhead invariant: 0 whenever resident decode is off."""
    with _stats_lock:
        return _device_batches_built


def _note_build(resident_delta: int) -> None:
    global _device_batches_built, _resident_live_bytes
    from disq_tpu.runtime.tracing import observe_gauge

    with _stats_lock:
        if resident_delta >= 0:
            _device_batches_built += 1
        _resident_live_bytes = max(
            0, _resident_live_bytes + resident_delta)
        live = _resident_live_bytes
    observe_gauge("columnar.batch.resident_bytes", live)


def resident_decode_enabled(storage) -> bool:
    """True when the fused HBM-resident decode path is on for this
    storage: ``DisqOptions.resident_decode`` or the
    ``DISQ_TPU_RESIDENT_DECODE`` env knob."""
    opts = getattr(storage, "_options", None)
    if opts is not None and getattr(opts, "resident_decode", False):
        return True
    from disq_tpu.runtime.debug import env_flag

    return env_flag("DISQ_TPU_RESIDENT_DECODE")


@functools.lru_cache(maxsize=1)
def _jax_fns():
    """Lazily-built jitted helpers (this module must import without
    jax on the disabled path)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def record_check(refid, next_refid, lrn, ncig, lseq, lens, n_ref):
        # Eager corrupt-record detection mirroring the host parser
        # (bam/codec.decode_records): impossible refIDs (when n_ref is
        # known, i.e. >= 0) or record sections overflowing the record
        # length. Restructured as slack comparisons so every term stays
        # inside i32 (lens < 2^31 is guaranteed by the from_blob size
        # guard). Returns one boolean — the only d2h of the check.
        neg = lseq < 0
        over = lseq > lens
        lseq_c = jnp.clip(lseq, 0, lens)
        head = 36 + lrn + 4 * ncig + (lseq_c + 1) // 2
        bad = neg | over | (head > (lens - lseq_c))
        refbad = ((refid >= n_ref) | (refid < -1)
                  | (next_refid >= n_ref) | (next_refid < -1))
        bad = bad | ((n_ref >= 0) & refbad)
        return jnp.any(bad)

    @jax.jit
    def coord_perm(refid, pos, n):
        # Coordinate keys + stable lexsort on device. Padded tail
        # entries (the bucket-padded parse duplicates the last record)
        # get a key above every real one — unmapped maps to 0x7FFFFFFF
        # — so order[:n] is exactly the real permutation.
        m = refid.shape[0]
        valid = jnp.arange(m, dtype=jnp.int32) < n
        rid = jnp.where(refid < 0, jnp.uint32(0x7FFFFFFF),
                        refid.astype(jnp.uint32))
        hi = jnp.where(valid, rid, jnp.uint32(0xFFFFFFFF))
        lo = (pos + 1).astype(jnp.uint32)
        return jnp.lexsort((lo, hi)).astype(jnp.int32)

    return {"jax": jax, "jnp": jnp, "coord_perm": coord_perm,
            "record_check": record_check}


class ColumnarBatch:
    """N alignment records with fixed columns resident on device (or a
    thin wrapper over a host ``ReadBatch``). Duck-compatible with
    ``ReadBatch``: every column attribute returns host numpy (lazily
    fetched, cached), so existing consumers work unchanged while
    device ops consume the resident columns without re-upload."""

    def __init__(self) -> None:
        # built via from_blob / from_host — never directly
        self._n = 0
        self._dev: Optional[Dict[str, object]] = None
        self._blob: Optional[np.ndarray] = None
        self._blob_parts: Optional[List[np.ndarray]] = None
        self._offsets: Optional[np.ndarray] = None
        # record permutation applied by ``permuted()`` (None = source
        # order): the device columns are already gathered by it; host
        # ragged/interop apply it lazily
        self._order: Optional[np.ndarray] = None
        self._n_ref: Optional[int] = None
        # batch-axis device mesh (runtime/mesh.py) the resident columns
        # are sharded over; None = plain single-device residency.
        # Carried through permuted()/concat() so every downstream
        # consumer (sort, flagstat, depth, encode) sees one sharded
        # program instead of re-deriving placement per stage.
        self._mesh = None
        self._cache: Dict[str, np.ndarray] = {}
        self._consumed: Dict[str, int] = {}
        self._ragged_rb: Optional[ReadBatch] = None
        self._rb: Optional[ReadBatch] = None
        self._hbm = 0
        self._released = False
        # True when this batch is the sole owner of its record blob
        # (a compacted filter result): in-place byte patches
        # (``or_flags``) may skip the copy-on-write
        self._blob_owned = False
        # lazy state is shared across threads (writer pipeline workers
        # slice the same dataset batch concurrently): the lock makes
        # each lazy build/fetch happen once — unlocked, W workers
        # would each host-parse the whole blob and concurrent fetches
        # of one column would double-book device.transfer
        self._lock = threading.RLock()

    # -- construction -------------------------------------------------------

    @classmethod
    def from_host(cls, batch: ReadBatch) -> "ColumnarBatch":
        self = cls()
        self._n = batch.count
        self._rb = batch
        self._ragged_rb = batch
        return self

    @classmethod
    def from_blob(
        cls,
        blob: np.ndarray,
        offsets: np.ndarray,
        n_ref: Optional[int] = None,
        device_words=None,
        origin: int = 0,
        interpret: Optional[bool] = None,
        mesh=None,
    ) -> "ColumnarBatch":
        """Fused device build: one upload (skipped when
        ``device_words`` carries the inflate kernels' still-resident
        output) + one gather/parse launch chain; fixed columns stay in
        HBM until fetched or released.

        ``blob``/``offsets`` are the host record bytes + record-offset
        manifest (held for ragged columns and identity with the host
        parser); ``origin`` rebases the offsets into ``device_words``
        when that blob covers more than the record range."""
        from disq_tpu.runtime.device_pipeline import parse_columns_resident
        from disq_tpu.runtime.tracing import span

        n = len(offsets) - 1
        if n <= 0:
            return cls.from_host(ReadBatch.empty())
        if interpret is None:
            jx = _jax_fns()["jax"]
            interpret = jx.default_backend() != "tpu"
        self = cls()
        self._n = n
        self._blob = blob
        self._offsets = np.asarray(offsets, dtype=np.int64)
        self._n_ref = n_ref
        self._mesh = mesh
        with span("columnar.batch.build", records=n,
                  bytes=int(offsets[-1])):
            # origin rebases offsets into a full-shard device blob;
            # the upload fallback stages exactly the record slice, so
            # its offsets are already correct
            cols, _word_bytes, _ = parse_columns_resident(
                blob, self._offsets, words_dev=device_words,
                origin=origin if device_words is not None else 0,
                interpret=interpret, mesh=mesh)
            # keep only the 8 reachable fixed columns resident (plus
            # next_refid for validation below); the 4 parse-only
            # length fields are derivable from the ragged offsets and
            # would pin 50% extra HBM with no consumer
            self._dev = {k: cols[k] for k in FIXED_COLUMNS}
        # Residency: the fixed columns (bucket-padded i32). The word
        # blob itself is released with the launch chain — nothing
        # downstream reads it on device (ragged comes from the host
        # copy the CRC/scan already required).
        padded = int(cols["pos"].shape[0])
        self._hbm = len(self._dev) * padded * 4
        from disq_tpu.runtime.tracing import track_hbm

        track_hbm(self._hbm)
        _note_build(self._hbm)
        # same eager corrupt-record contract as decode_records: a
        # chain-valid shard with impossible refIDs OR record sections
        # overflowing their record (the host parser's "sections exceed
        # block_size" bound) must fail HERE, so the source's
        # except-ValueError salvage path applies exactly as on the host
        # route. The check is a device reduction — one boolean crosses
        # d2h; padded lanes get a maximal record length so they never
        # flag.
        from disq_tpu.runtime.tracing import count_transfer

        rec_len = np.empty(padded, np.int32)
        rec_len[:n] = self._offsets[1:] - self._offsets[:-1]
        rec_len[n:] = np.iinfo(np.int32).max
        count_transfer("h2d", rec_len.nbytes)
        fns = _jax_fns()
        bad = fns["record_check"](
            cols["refid"], cols["next_refid"], cols["l_read_name"],
            cols["n_cigar"], cols["l_seq"], rec_len,
            np.int32(-1 if n_ref is None else n_ref))
        if bool(bad):
            self._release(book_avoided=False)
            from disq_tpu.bam.codec import decode_records

            # the host parser is the authority on the error (exact
            # message + record coordinates); if the device predicate
            # was somehow conservative, serve its host batch instead
            host = decode_records(blob, self._offsets, n_ref=n_ref)
            return cls.from_host(host)
        return self

    # -- identity -----------------------------------------------------------

    @property
    def device_backed(self) -> bool:
        return self._dev is not None

    @property
    def mesh(self):
        """The batch-axis mesh the resident columns shard over, or
        None (single-device residency / host-backed)."""
        return self._mesh if self._dev is not None else None

    @property
    def count(self) -> int:
        return self._n

    def __len__(self) -> int:
        return self._n

    # -- lazy column access -------------------------------------------------

    def _fetch_col(self, name: str) -> np.ndarray:
        arr = self._cache.get(name)
        if arr is not None:
            return arr
        with self._lock:
            arr = self._cache.get(name)
            if arr is not None:  # lost the race: fetched once, by them
                return arr
            if self._dev is None:
                if self._rb is not None:
                    return getattr(self._rb, name)
                # released device columns, but the host blob is still
                # held for ragged parsing — rebuild from it instead of
                # failing only on fixed-column access
                if self._blob is not None or self._blob_parts:
                    return getattr(self._ragged_source(), name)
                raise RuntimeError(
                    f"column {name!r} of a released ColumnarBatch — "
                    "fetch before release(), or keep the batch alive")
            from disq_tpu.runtime.tracing import count_transfer, span

            nbytes = 4 * self._n
            with span("columnar.batch.fetch", column=name, bytes=nbytes):
                raw = np.asarray(self._dev[name][: self._n])
            count_transfer("d2h", raw.nbytes)
            dt = _COL_DTYPE.get(name)
            arr = (raw.astype(dt)
                   if dt is not None and raw.dtype != dt else raw)
            self._cache[name] = arr
            # a column that DID cross d2h after all is no longer avoided
            # — consumption marks are provisional until release books
            # them
            self._consumed.pop(name, None)
            return arr

    def _consume_on_device(self, key: str, nbytes: int) -> None:
        """Mark a column (or derived result) consumed on device without
        a host fetch — d2h the split path would have paid. Booked into
        ``device.d2h_avoided_bytes`` at release (not here), so a later
        host fetch of the same column un-marks it instead of
        double-counting."""
        with self._lock:
            if key in self._consumed or key in self._cache:
                return
            self._consumed[key] = nbytes

    # fixed columns (device-parsed; lazily fetched)
    refid = property(lambda self: self._fetch_col("refid"))
    pos = property(lambda self: self._fetch_col("pos"))
    mapq = property(lambda self: self._fetch_col("mapq"))
    bin = property(lambda self: self._fetch_col("bin"))
    flag = property(lambda self: self._fetch_col("flag"))
    next_refid = property(lambda self: self._fetch_col("next_refid"))
    next_pos = property(lambda self: self._fetch_col("next_pos"))
    tlen = property(lambda self: self._fetch_col("tlen"))

    # -- ragged columns (host blob, parsed lazily once) ---------------------

    def _host_blob(self) -> Optional[np.ndarray]:
        """The record bytes as one host array, joining a concat's
        per-shard parts on first need (under the instance lock)."""
        with self._lock:
            if self._blob is None and self._blob_parts is not None:
                self._blob = np.concatenate(self._blob_parts)
                self._blob_parts = None
            return self._blob

    def _ragged_source(self) -> ReadBatch:
        if self._ragged_rb is None:
            with self._lock:
                if self._ragged_rb is None:
                    from disq_tpu.bam.codec import decode_records
                    from disq_tpu.runtime.tracing import counter

                    rb = decode_records(
                        self._host_blob(), self._offsets,
                        n_ref=self._n_ref)
                    if self._order is not None:
                        rb = rb.take(self._order)
                    self._ragged_rb = rb
                    # the operator-suite resident-leg witness: a fully
                    # resident chain never host-parses records
                    counter("columnar.batch.materializations").inc()
        return self._ragged_rb

    def __getattr__(self, name: str):
        if name in _RAGGED:
            return getattr(self._ragged_source(), name)
        raise AttributeError(name)

    # -- pickling (ReadLedger crash-resume spills) --------------------------

    def __reduce__(self):
        """Spill as HOST data, never as device arrays: pickling the
        resident columns would be an uncounted implicit d2h, and the
        restored copy would re-book their avoidance on release. A
        device-backed batch spills its host blob + offsets (plus any
        ``permuted()`` order) and re-runs the fused build on load (a
        resumed resident read stays device-backed with fresh, correct
        accounting); a host-backed one spills its plain ``ReadBatch``."""
        if self._blob is not None or self._blob_parts is not None:
            return (_rebuild_from_blob,
                    (self._host_blob(), self._offsets, self._n_ref,
                     self._order))
        return (_rebuild_from_host, (self.to_read_batch(),))

    # -- ReadBatch interop --------------------------------------------------

    def to_read_batch(self) -> ReadBatch:
        """Materialize as one plain ``ReadBatch``. The ragged columns
        force the full host parse anyway, and its fixed columns are
        byte-equal to the device-parsed ones (the identity contract) —
        so materialization takes them from the host parse instead of
        paying a pointless 32 B/record d2h fetch; columns sourced this
        way are cached as fetched so ``release`` books them neither as
        transferred nor as avoided (the host did the work, no transfer
        was saved)."""
        if self._rb is None:
            with self._lock:
                if self._rb is not None:
                    return self._rb
                rag = self._ragged_source()
                if self._dev is not None:
                    for name in FIXED_COLUMNS:
                        if name not in self._cache:
                            self._cache[name] = getattr(rag, name)
                            self._consumed.pop(name, None)
                self._rb = rag
        return self._rb

    def take(self, indices: np.ndarray) -> ReadBatch:
        return self.to_read_batch().take(indices)

    def filter(self, mask: np.ndarray) -> "ReadBatch | ColumnarBatch":
        """Keep records where ``mask`` is true. Device-backed batches
        compact ON DEVICE (operator-suite tentpole a): the fixed
        columns are gathered by the kept indices in HBM — records the
        mask drops never cross d2h — and the host record blob is
        compacted by one vectorized segment gather, so the result is a
        self-contained device-backed batch (``_order`` folded away:
        concat / pickle / encode_source all see a plain source-order
        blob). Host-backed batches materialize as before."""
        mask = np.asarray(mask)
        if self._dev_snapshot() is None or self._offsets is None:
            return self.to_read_batch().filter(mask)
        return self._compact_device(np.nonzero(mask)[0])

    def _compact_device(self, keep: np.ndarray) -> "ReadBatch | ColumnarBatch":
        """Device compaction gather behind ``filter``: ``keep`` holds
        the kept logical indices, ascending."""
        from disq_tpu.bam.columnar import segment_gather
        from disq_tpu.runtime.tracing import (
            count_transfer, span, track_hbm)

        dev = self._dev_snapshot()
        keep = np.asarray(keep, dtype=np.int64)
        k = len(keep)
        if k == 0:
            return ColumnarBatch.from_host(ReadBatch.empty())
        with span("columnar.batch.compact", records=self._n, kept=k):
            # host blob compaction: gather the kept records' byte
            # spans into a fresh contiguous blob (logical -> blob
            # record index via any pending permutation)
            src = self._order[keep] if self._order is not None else keep
            new_blob, new_off = segment_gather(
                self._host_blob(), self._offsets, src)
            fns = _jax_fns()
            jnp = fns["jnp"]
            pad = _bucket_n(k) - k
            idx_host = np.empty(k + pad, np.int32)
            idx_host[:k] = keep
            idx_host[k:] = keep[-1]
            count_transfer("h2d", idx_host.nbytes)
            idx = jnp.asarray(idx_host)
            out = ColumnarBatch.__new__(ColumnarBatch)
            ColumnarBatch.__init__(out)
            out._n = k
            out._n_ref = self._n_ref
            out._dev = {name: dev[name][idx] for name in FIXED_COLUMNS}
            if self._mesh is not None:
                from disq_tpu.runtime.mesh import mesh_put

                out._dev = {name: mesh_put(col, self._mesh)
                            for name, col in out._dev.items()}
                out._mesh = self._mesh
            out._blob = new_blob
            out._offsets = new_off
            out._blob_owned = True
            out._hbm = len(out._dev) * (k + pad) * 4
            track_hbm(out._hbm)
            _note_build(out._hbm)
        return out

    def or_flags(self, mask: np.ndarray, bits: int = 0x400) -> None:
        """OR ``bits`` into the flag of every record where ``mask`` is
        true — duplicate marking's write-back. Three synchronized
        views update: the resident flag column (in HBM, one small mask
        upload), the host record blob's flag bytes (copy-on-write
        unless this batch owns its blob), and any host caches (dropped
        so the next fetch re-derives). The blob patch is what makes
        the resident write path's output byte-identical to a
        host-marked file."""
        idx = np.nonzero(np.asarray(mask))[0]
        if len(idx) == 0:
            return
        lo_b, hi_b = bits & 0xFF, (bits >> 8) & 0xFF
        with self._lock:
            if self._offsets is not None:
                blob = self._host_blob()
                if not self._blob_owned:
                    blob = blob.copy()
                    self._blob_owned = True
                src = (self._order[idx]
                       if self._order is not None else idx)
                off = self._offsets[src]
                if lo_b:
                    blob[off + 18] |= np.uint8(lo_b)
                if hi_b:
                    blob[off + 19] |= np.uint8(hi_b)
                self._blob = blob
            dev = self._dev
            if dev is not None:
                from disq_tpu.runtime.tracing import count_transfer

                fns = _jax_fns()
                jnp = fns["jnp"]
                padded = int(dev["flag"].shape[0])
                m = np.zeros(padded, np.int32)
                m[idx] = 1
                count_transfer("h2d", m.nbytes)
                new_flag = jnp.where(
                    jnp.asarray(m) != 0, dev["flag"] | bits, dev["flag"])
                if self._mesh is not None:
                    from disq_tpu.runtime.mesh import mesh_put

                    new_flag = mesh_put(new_flag, self._mesh)
                dev["flag"] = new_flag
            elif self._rb is not None:
                self._rb.flag[idx] |= np.uint16(bits)
            # host-side derived views are stale now
            self._cache.pop("flag", None)
            if self._ragged_rb is not None and self._offsets is not None:
                self._ragged_rb = None
                self._rb = None

    def slice(self, start: int, stop: int) -> ReadBatch:
        return self.to_read_batch().slice(start, stop)

    # decoded views / derived (delegate to the materialized forms)
    def name(self, i: int) -> str:
        return self._ragged_source().name(i)

    def sequence(self, i: int) -> str:
        return self._ragged_source().sequence(i)

    def cigar_string(self, i: int) -> str:
        return self._ragged_source().cigar_string(i)

    def qual_string(self, i: int) -> str:
        return self._ragged_source().qual_string(i)

    def reference_lengths(self) -> np.ndarray:
        return self._ragged_source().reference_lengths()

    def alignment_ends(self) -> np.ndarray:
        return self._ragged_source().alignment_ends()

    # -- resident device consumers ------------------------------------------

    def _dev_snapshot(self) -> Optional[Dict[str, object]]:
        """The device column dict, taken under the lock — safe to use
        after a concurrent ``release()`` (jax arrays are immutable;
        release only drops references), so kernel launches run
        lock-free and never stall other lazy-column access."""
        with self._lock:
            return self._dev

    def device_columns(self) -> Dict[str, object]:
        """The fixed columns as device arrays in ReadBatch dtypes —
        zero transfers (the resident form IS the device form)."""
        dev = self._dev_snapshot()
        if dev is None:
            raise ValueError("host-backed batch has no device columns")
        jnp = _jax_fns()["jnp"]
        return {
            name: dev[name][: self._n].astype(
                jnp.dtype(_COL_DTYPE[name]))
            for name in FIXED_COLUMNS
        }

    def flagstat(self) -> Dict[str, int]:
        """flagstat over the resident flag column — no h2d re-upload,
        d2h is the 48-byte count row."""
        dev = self._dev_snapshot()
        if dev is None:
            from disq_tpu.ops.flagstat import flagstat_counts

            return flagstat_counts(np.asarray(self.flag))
        if self._mesh is not None:
            from disq_tpu.ops.flagstat import flagstat_resident_sharded

            out = flagstat_resident_sharded(
                dev["flag"], self._n, self._mesh)
        else:
            from disq_tpu.ops.flagstat import flagstat_resident

            out = flagstat_resident(dev["flag"], self._n)
        self._consume_on_device("flag", 4 * self._n)
        return out

    def sort_permutation(self) -> np.ndarray:
        """Coordinate-sort permutation from the resident refid/pos
        columns: keys + lexsort run on device, only the (n,) i32 order
        crosses d2h — the u64 key vectors never move."""
        dev = self._dev_snapshot()
        if dev is None:
            from disq_tpu.sort.coordinate import coordinate_keys

            return np.argsort(
                coordinate_keys(self.refid, self.pos), kind="stable")
        if self._mesh is not None:
            from disq_tpu.sort.sharded import resident_coordinate_sort

            out = resident_coordinate_sort(
                dev["refid"], dev["pos"], self._n, self._mesh)
            self._consume_on_device("sort_keys", 8 * self._n)
            return out
        fns = _jax_fns()
        jax, jnp = fns["jax"], fns["jnp"]
        from disq_tpu.runtime.tracing import count_transfer, device_span

        n_dev = jnp.asarray(np.int32(self._n))  # staged pre-guard
        with device_span("device.kernel", kernel="coordinate_keys",
                         records=self._n) as fence:
            with jax.transfer_guard("disallow"):
                order = fns["coord_perm"](
                    dev["refid"], dev["pos"], n_dev)
                jax.block_until_ready(order)
            fence.sync(order)
        out = np.asarray(order[: self._n])
        count_transfer("d2h", out.nbytes)
        # the 8-byte-per-record key vector stayed on device
        self._consume_on_device("sort_keys", 8 * self._n)
        return out

    # -- resident permutation (the device write path's sort output) ---------

    def permuted(self, order: np.ndarray) -> "ColumnarBatch":
        """A reordered batch that STAYS device-backed: the fixed
        columns are gathered by ``order`` on device (one small index
        upload, zero column round-trips), and the host record blob is
        kept with the permutation so ragged access materializes
        lazily — exactly like the unpermuted batch.  This is the sort
        output the symmetric write path consumes: its
        ``encode_source()`` triple feeds ``runtime/device_write``'s
        resident encode → deflate chain with no host record
        materialization.  Falls back to a host-backed batch when the
        device columns are gone (released / host-built)."""
        order = np.asarray(order, dtype=np.int64)
        if len(order) != self._n:
            raise ValueError(
                f"permutation of {len(order)} over {self._n} records")
        dev = self._dev_snapshot()
        if dev is None or self._offsets is None:
            return ColumnarBatch.from_host(self.to_read_batch().take(order))
        from disq_tpu.runtime.tracing import count_transfer, track_hbm

        fns = _jax_fns()
        jnp = fns["jnp"]
        base = self._order[order] if self._order is not None else order
        pad = _bucket_n(self._n) - self._n
        idx_host = np.empty(self._n + pad, np.int32)
        idx_host[: self._n] = order
        idx_host[self._n:] = order[-1] if self._n else 0
        count_transfer("h2d", idx_host.nbytes)
        idx = jnp.asarray(idx_host)
        out = ColumnarBatch.__new__(ColumnarBatch)
        ColumnarBatch.__init__(out)
        out._n = self._n
        out._n_ref = self._n_ref
        out._dev = {name: dev[name][idx] for name in FIXED_COLUMNS}
        if self._mesh is not None:
            # the gather may have collapsed placement — restore the
            # canonical batch sharding so downstream stages keep the
            # one-sharded-program shape (moved bytes are booked into
            # device.mesh.reshard_bytes, not h2d/d2h: nothing crosses
            # the host)
            from disq_tpu.runtime.mesh import mesh_put

            out._dev = {name: mesh_put(col, self._mesh)
                        for name, col in out._dev.items()}
            out._mesh = self._mesh
        out._blob = self._blob
        out._blob_parts = self._blob_parts
        out._offsets = self._offsets
        out._order = base
        out._hbm = len(out._dev) * (self._n + pad) * 4
        track_hbm(out._hbm)
        _note_build(out._hbm)
        return out

    def encode_source(self):
        """The ``(record blob, record offsets, permutation-or-None)``
        triple the resident encode path needs, or None when this batch
        holds no host record blob (host-built batches encode through
        the classic ``encode_records`` path)."""
        with self._lock:
            if self._offsets is None or (
                    self._blob is None and self._blob_parts is None):
                return None
        return self._host_blob(), self._offsets, self._order

    # -- concat -------------------------------------------------------------

    @classmethod
    def concat(cls, batches: Sequence) -> "ReadBatch | ColumnarBatch":
        """Concatenate mixed ``ReadBatch`` / ``ColumnarBatch`` shards.
        All device-backed ⇒ the result stays device-backed (fixed
        columns concatenated on device, host blobs rebased for ragged);
        otherwise everything materializes to one host ``ReadBatch``.

        CONSUMING: device-backed inputs are released into the result
        (their residency moves to the concatenated columns) — keep
        using the returned batch, not the inputs."""
        batches = list(batches)
        if not batches:
            return ReadBatch.empty()
        if len(batches) == 1:
            return batches[0]
        # empty shards (deadline fallbacks, ranges past end-of-data)
        # are neutral: they must not demote an all-resident read
        nonempty = [b for b in batches if len(b)]
        if not nonempty:
            return ReadBatch.empty()
        if len(nonempty) == 1:
            return nonempty[0]
        batches = nonempty
        resident = [b for b in batches
                    if isinstance(b, ColumnarBatch) and b.device_backed]
        if len(resident) == len(batches):
            jnp = _jax_fns()["jnp"]
            self = cls()
            self._n = sum(b._n for b in batches)
            self._n_ref = batches[0]._n_ref
            # bucket-pad the concatenated columns like from_blob does
            # (edge pads duplicate the last record): exact-length
            # results would retrace every downstream jit once per
            # distinct total record count
            pad = _bucket_n(self._n) - self._n
            self._dev = {
                name: jnp.pad(
                    jnp.concatenate(
                        [b._dev[name][: b._n] for b in batches]),
                    (0, pad), mode="edge")
                for name in FIXED_COLUMNS
            }
            # mesh carriage: a concat of same-mesh shards stays one
            # sharded program (the slice/concat/pad above may have
            # collapsed placement — normalize back to batch sharding)
            mesh = batches[0]._mesh
            if mesh is not None and all(
                    b._mesh is mesh for b in batches):
                from disq_tpu.runtime.mesh import mesh_put

                self._dev = {name: mesh_put(col, mesh)
                             for name, col in self._dev.items()}
                self._mesh = mesh
            # host blobs join LAZILY (first ragged access / pickle):
            # a flagstat-only multi-shard read never pays the
            # O(total-decoded-bytes) memcpy or its transient 2x host
            # RAM peak
            parts: List[np.ndarray] = []
            for b in batches:
                parts.extend(b._blob_parts if b._blob_parts is not None
                             else [b._blob])
            self._blob_parts = parts
            offs = np.zeros(self._n + 1, dtype=np.int64)
            at = 1
            pos = 0
            for b in batches:
                offs[at: at + b._n] = b._offsets[1:] + pos
                at += b._n
                pos += int(b._offsets[-1])
            self._offsets = offs
            self._hbm = len(self._dev) * (self._n + pad) * 4
            from disq_tpu.runtime.tracing import track_hbm

            track_hbm(self._hbm)
            _note_build(self._hbm)
            for b in batches:
                # inputs live on inside the concat — release their
                # residency without booking avoidance
                b._release(book_avoided=False)
            return self
        return ReadBatch.concat([as_read_batch(b) for b in batches])

    # -- release ------------------------------------------------------------

    def _release(self, book_avoided: bool = True) -> None:
        with self._lock:
            if self._released or self._dev is None:
                self._released = True
                return
            self._released = True
            if book_avoided:
                # only the 8 reachable fixed columns can ever be
                # fetched — the 4 parse-only fields (block_size,
                # lengths) are not d2h candidates and must not inflate
                # the metric
                avoided = sum(
                    4 * self._n
                    for name in FIXED_COLUMNS
                    if name not in self._cache
                    and name not in self._consumed)
                total = avoided + sum(self._consumed.values())
                from disq_tpu.runtime.tracing import counter, record_span

                if total:
                    counter("device.d2h_avoided_bytes").inc(total)
                record_span("columnar.batch.release", 0.0,
                            records=self._n, avoided_bytes=total)
            self._dev = None
            if self._hbm:
                from disq_tpu.runtime.tracing import track_hbm

                track_hbm(-self._hbm)
                _note_build(-self._hbm)
                self._hbm = 0

    def release(self) -> None:
        """Drop the device columns. Reachable columns never fetched,
        plus everything consumed on device (flagstat's flag column,
        sort keys), book into ``device.d2h_avoided_bytes`` — the d2h
        bytes the lazy fetch skipped — and a ``columnar.batch.release``
        span records the batch's total avoidance for
        ``trace_report --analyze``."""
        self._release(book_avoided=True)

    def __del__(self) -> None:  # pragma: no cover - GC timing
        try:
            self._release(book_avoided=True)
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass


def _rebuild_from_blob(blob, offsets, n_ref,
                       order=None) -> "ColumnarBatch":
    """Unpickle target for a spilled device-backed batch (module-level
    so pickle resolves it by name)."""
    batch = ColumnarBatch.from_blob(blob, offsets, n_ref=n_ref)
    if order is not None and isinstance(batch, ColumnarBatch):
        batch = batch.permuted(order)
    return batch


def _rebuild_from_host(batch: ReadBatch) -> "ColumnarBatch":
    """Unpickle target for a spilled host-backed batch."""
    return ColumnarBatch.from_host(batch)


def as_read_batch(batch) -> ReadBatch:
    """Whatever a source emitted (host ReadBatch or ColumnarBatch) as a
    plain host ReadBatch."""
    if isinstance(batch, ColumnarBatch):
        return batch.to_read_batch()
    return batch


def concat_batches(batches: Sequence) -> "ReadBatch | ColumnarBatch":
    """Shard concat for the read paths: stays device-resident when
    every shard is, else materializes host-side. Consuming — see
    ``ColumnarBatch.concat``: device-backed inputs are released into
    the result."""
    return ColumnarBatch.concat(batches)
