"""In-process sampling profiler — where does the CPU actually go.

The telemetry layer times *phases* (span enter/exit at the call sites
we thought to instrument); this module answers the question spans
cannot: which Python/C code is the decode stage actually burning CPU
in?  A :class:`SamplingProfiler` thread walks
``sys._current_frames()`` at ``hz`` and aggregates **folded stacks
keyed by thread role**: every pipeline thread carries a canonical
``disq-*`` name (``disq-fetch`` / ``disq-decode`` / ``disq-encode`` /
``disq-deflate`` / ``disq-stage`` / ``disq-device-dispatch`` /
``disq-hedge`` / ``disq-hostwork`` / ``disq-http-prefetch``), so
samples attribute *per pipeline stage* with no instrumentation in the
sampled code — the same names py-spy keys on from outside the process.

Exports:

- ``collapsed()`` — Brendan-Gregg collapsed-stack text
  (``role;frame;frame count`` lines): feed to ``flamegraph.pl``,
  speedscope, or ``scripts/trace_report.py --flame``.
- ``speedscope()`` — a speedscope JSON document (one sampled profile
  per thread role).

Bookkeeping: ``profile.samples{thread_role=}`` counts every sample
taken, ``profile.dropped`` counts sampling ticks skipped because a
walk overran the interval (the profile is then *sparser*, never
blocking the sampled threads).

Two lifecycles:

- **Continuous** (``DisqOptions.profile_hz`` / ``DISQ_TPU_PROFILE_HZ``
  → :func:`start_profiler`): one process-wide profiler running until
  :func:`stop_profiler`; a postmortem bundle embeds its collapsed
  stacks (``runtime/flightrec.py``).
- **Windowed** (:func:`profile_for`, behind the introspection server's
  ``/debug/profile?seconds=N``): an independent profiler for exactly N
  seconds.

Zero overhead when off (the default): no thread exists and no sample
is ever taken — enforced by ``scripts/check_overhead.py``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from disq_tpu.runtime.tracing import REGISTRY

DEFAULT_HZ = 99.0   # off the metronome: a round 100 Hz beats against
                    # periodic work and aliases it in or out entirely
MAX_STACK_DEPTH = 64

# Canonical thread-name prefix -> role. First match wins; every thread
# pool and service thread in the codebase carries one of these names
# (the check_overhead/thread-audit contract), so a profile attributes
# by pipeline stage out of the box.
THREAD_ROLES: Tuple[Tuple[str, str], ...] = (
    ("disq-fetch", "fetch"),
    ("disq-decode", "decode"),
    ("disq-encode", "encode"),
    ("disq-deflate", "deflate"),
    ("disq-stage", "stage"),
    ("disq-device-dispatch", "dispatcher"),
    ("disq-hedge", "hedge"),
    ("disq-hostwork", "hostwork"),
    ("disq-http-prefetch", "prefetch"),
    ("disq-watchdog", "watchdog"),
    ("disq-introspect", "introspect"),
    ("disq-cluster", "cluster"),
    ("disq-bench-http", "bench_http"),
    ("disq-profiler", "profiler"),
    ("MainThread", "main"),
)


def role_of(thread_name: str) -> str:
    for prefix, role in THREAD_ROLES:
        if thread_name.startswith(prefix):
            return role
    return "other"


class SamplingProfiler:
    """One sampling session: ``start()`` spawns the ``disq-profiler``
    thread, ``stop()`` joins it; the aggregate is then readable via
    ``collapsed()`` / ``speedscope()`` / ``by_role()``."""

    def __init__(self, hz: float = DEFAULT_HZ,
                 max_depth: int = MAX_STACK_DEPTH,
                 book_metrics: bool = True) -> None:
        if hz <= 0:
            raise ValueError(f"profile hz must be > 0, got {hz}")
        self.hz = float(hz)
        self.max_depth = int(max_depth)
        # ``profile.samples`` / ``profile.dropped`` are process-wide:
        # a windowed profile racing the continuous one books with
        # book_metrics=False so the shared counters never double-count
        # one process's CPU (profile_for resolves this automatically).
        self.book_metrics = bool(book_metrics)
        self._lock = threading.Lock()
        # (role, (frame, frame, ...)) -> sample count, root-first
        self._counts: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0
        self.dropped = 0
        self.started_at: Optional[float] = None
        self.stopped_at: Optional[float] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self.started_at = time.perf_counter()
        self._thread = threading.Thread(
            target=self._run, name="disq-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        thread = self._thread
        if thread is None:
            return self
        self._stop.set()
        thread.join(timeout=10)
        self._thread = None
        self.stopped_at = time.perf_counter()
        return self

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- sampling loop ------------------------------------------------------

    def _run(self) -> None:
        interval = 1.0 / self.hz
        next_tick = time.perf_counter()
        dropped_counter = REGISTRY.counter("profile.dropped")
        samples_counter = (REGISTRY.counter("profile.samples")
                           if self.book_metrics else None)
        while not self._stop.is_set():
            self._sample_once(samples_counter)
            next_tick += interval
            now = time.perf_counter()
            if now > next_tick:
                # Overran: skip the missed ticks (count them) instead
                # of bursting to catch up — a catch-up burst would
                # oversample exactly the moments the walk is slowest.
                missed = int((now - next_tick) / interval) + 1
                self.dropped += missed
                if self.book_metrics:
                    dropped_counter.inc(missed)
                next_tick = now + interval
                continue
            self._stop.wait(next_tick - now)

    def _sample_once(self, samples_counter=None) -> None:
        # Thread names re-resolve every tick — pools come and go
        # mid-run.
        names = {t.ident: t.name for t in threading.enumerate()}
        own = threading.get_ident()
        # _current_frames is one atomic C call: the dict is a snapshot,
        # the frames themselves keep mutating — fine for sampling.
        for tid, frame in sys._current_frames().items():
            if tid == own:
                continue
            role = role_of(names.get(tid, "?"))
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                code = f.f_code
                stack.append(
                    f"{os.path.basename(code.co_filename)}:{code.co_name}")
                f = f.f_back
            stack.reverse()  # root-first, the collapsed-stack order
            key = (role, tuple(stack))
            with self._lock:
                self._counts[key] = self._counts.get(key, 0) + 1
                self.samples += 1
            if samples_counter is not None:
                samples_counter.inc(thread_role=role)

    # -- views --------------------------------------------------------------

    def folded(self) -> Dict[str, int]:
        """``{"role;frame;frame": count}`` — role is the root frame so
        one folded set attributes per pipeline stage."""
        with self._lock:
            return {
                ";".join((role,) + stack): n
                for (role, stack), n in sorted(self._counts.items())
            }

    def collapsed(self) -> str:
        """Collapsed-stack text, one ``stack count`` line per folded
        stack (flamegraph.pl / speedscope / ``--flame`` input)."""
        return "".join(
            f"{stack} {n}\n" for stack, n in self.folded().items())

    def by_role(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for (role, _stack), n in self._counts.items():
                out[role] = out.get(role, 0) + n
            return out

    def speedscope(self) -> Dict[str, Any]:
        """A speedscope file document: one ``sampled`` profile per
        thread role, frames shared across them."""
        frame_index: Dict[str, int] = {}
        frames: List[Dict[str, str]] = []

        def idx(name: str) -> int:
            i = frame_index.get(name)
            if i is None:
                i = frame_index[name] = len(frames)
                frames.append({"name": name})
            return i

        with self._lock:
            items = sorted(self._counts.items())
        per_role: Dict[str, Tuple[List[List[int]], List[int]]] = {}
        for (role, stack), n in items:
            samples, weights = per_role.setdefault(role, ([], []))
            samples.append([idx(f) for f in stack])
            weights.append(n)
        profiles = []
        for role in sorted(per_role):
            samples, weights = per_role[role]
            profiles.append({
                "type": "sampled",
                "name": role,
                "unit": "none",
                "startValue": 0,
                "endValue": sum(weights),
                "samples": samples,
                "weights": weights,
            })
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames},
            "profiles": profiles,
            "exporter": "disq_tpu.runtime.profiler",
        }


# ---------------------------------------------------------------------------
# Process-wide continuous profiler + windowed helper
# ---------------------------------------------------------------------------

_LOCK = threading.RLock()
_ACTIVE: Optional[SamplingProfiler] = None
_env_resolved = False


def start_profiler(hz: float = DEFAULT_HZ) -> SamplingProfiler:
    """Start (or return) the process-wide continuous profiler."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is None or not _ACTIVE.running:
            _ACTIVE = SamplingProfiler(hz).start()
        return _ACTIVE


def stop_profiler() -> Optional[SamplingProfiler]:
    """Stop the continuous profiler and return it (with its aggregate
    intact); None if nothing was running."""
    global _ACTIVE
    with _LOCK:
        active, _ACTIVE = _ACTIVE, None
    if active is not None:
        active.stop()
    return active


def active_profiler() -> Optional[SamplingProfiler]:
    return _ACTIVE


def profile_for(seconds: float, hz: float = DEFAULT_HZ
                ) -> SamplingProfiler:
    """Run an independent profiler for ``seconds`` (blocking) and
    return it — the ``/debug/profile?seconds=N`` implementation.
    When the continuous profiler is already booking the process-wide
    ``profile.*`` counters, the windowed one samples without booking
    so concurrent profiles never double-count one process's CPU."""
    active = _ACTIVE
    prof = SamplingProfiler(
        hz, book_metrics=active is None or not active.running).start()
    time.sleep(max(0.05, float(seconds)))
    return prof.stop()


def _resolve_env() -> None:
    global _env_resolved
    if _env_resolved:
        return
    with _LOCK:
        if _env_resolved:
            return
        _env_resolved = True
        raw = os.environ.get("DISQ_TPU_PROFILE_HZ")
    if raw:
        try:
            hz = float(raw)
        except ValueError:
            return
        if hz > 0:
            start_profiler(hz)


def configure_from_options(opts) -> None:
    """Resolve one ``DisqOptions``' ``profile_hz`` knob (and the env
    knob, once).  Default path: nothing happens, no thread exists."""
    _resolve_env()
    hz = getattr(opts, "profile_hz", None) if opts is not None else None
    if hz:
        start_profiler(float(hz))


def reset_profiler() -> None:
    """Test hook: stop the continuous profiler and re-allow env
    resolution."""
    global _env_resolved
    stop_profiler()
    with _LOCK:
        _env_resolved = False
