"""Live introspection — the *while-it-runs* half of observability.

``runtime/tracing.py`` answers "what did this run cost" after the fact;
this module answers "is it still making progress *right now*":

- **PipelineHealth board** (module singleton ``HEALTH``): both pipeline
  directions (``ShardPipelineExecutor`` reads, ``ShardWritePipeline``
  writes) register each ``map_ordered`` run and stamp per-shard,
  per-stage heartbeats as stage workers start and finish work. The
  board is the single source for the watchdog, the ``/healthz`` /
  ``/progress`` endpoints, and the progress JSONL log.
- **Heartbeat watchdog**: a monitor thread flags any shard whose
  active stage has been silent past the run's
  ``DisqOptions.watchdog_stall_s`` — booking the
  ``watchdog.stalled_shards`` counter (labeled ``stage=``), emitting a
  ``watchdog.stall`` span naming shard/stage/age, writing one
  rate-limited stderr line, and flipping ``/healthz`` to ``degraded``.
  Policy ``warn`` (default) keeps going; ``abort`` cancels the run
  through the pipeline's existing first-error-abort path by raising
  ``WatchdogStallError`` at the ordered emit — deterministic enough for
  tests to assert on.
- **Progress/ETA reporter**: shard completions and the per-shard
  ``ShardCounters`` the sources already build feed rolling
  records/sec, shards done / in flight / total, byte totals and an
  ETA — served on ``/progress`` and optionally appended as a periodic
  JSONL (``DisqOptions.progress_log``) that
  ``scripts/trace_report.py --progress`` replays.
- **HTTP endpoint**: an opt-in stdlib ``http.server`` bound to
  127.0.0.1 (``DisqOptions.introspect_port`` /
  ``DISQ_TPU_INTROSPECT_PORT``; port 0 = ephemeral) serving
  ``/metrics`` (Prometheus exposition), ``/healthz`` (JSON liveness
  verdict), ``/progress`` (JSON progress view) and ``/spans`` (bounded
  tail of the in-memory span ring).  Every payload carries this
  process's identity (``multihost.process_id()`` — a
  ``disq_tpu_process_info`` series on ``/metrics``, a ``process_id``
  key on the JSON endpoints) so a cluster aggregation
  (``runtime/cluster.py``) can merge N workers with ``process``
  labels.

Zero overhead when disabled: with no endpoint, watchdog or progress
log configured, ``configure_from_options`` returns ``None``, the
pipelines carry ``health=None`` (every per-shard hook is skipped
behind one ``is None`` check), ``note_shard_counters`` returns after a
single boolean test, and no thread or socket is ever created.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.parse
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from disq_tpu.runtime import flightrec, tracing
from disq_tpu.runtime.errors import WatchdogStallError
from disq_tpu.runtime.multihost import process_id as _process_id
from disq_tpu.runtime.tracing import RUN_ID, counter, record_span

# Module lifecycle (server / monitor / progress sink) is guarded by one
# lock; the board has its own finer-grained lock for per-shard traffic.
_STATE_LOCK = threading.RLock()

DEFAULT_PROGRESS_INTERVAL_S = 0.5
_WARN_INTERVAL_S = 1.0       # stderr stall warnings, at most one per
_IDLE_TICKS_BEFORE_EXIT = 25  # monitor exits after ~5 s with nothing to do
_SPANS_TAIL_DEFAULT = 512
_SPANS_TAIL_MAX = 8192
_RATE_WINDOW_S = 10.0        # rolling-rate lookback


class _RunState:
    """One registered ``map_ordered`` run on the board."""

    __slots__ = ("token", "direction", "total", "stall_s", "policy",
                 "done", "started", "active", "flagged", "abort",
                 "abort_sent", "pending_abort")

    def __init__(self, token: int, direction: str, total: int,
                 stall_s: Optional[float], policy: str) -> None:
        self.token = token
        self.direction = direction
        self.total = total
        self.stall_s = stall_s
        self.policy = policy
        self.done = 0
        self.started = time.perf_counter()
        self.active: Dict[int, Tuple[str, float]] = {}  # shard -> (stage, since)
        self.flagged: set = set()
        self.abort: Optional[Callable[[BaseException], None]] = None
        self.abort_sent = False
        # Cooperative delivery for inline (workers=1) runs, which have
        # no pipeline to inject an error into: the run's own thread
        # picks this up at its next stage boundary (take_abort).
        self.pending_abort: Optional[BaseException] = None


def _new_agg() -> Dict[str, Any]:
    return {
        "records": 0, "bytes_compressed": 0, "bytes_uncompressed": 0,
        "shards_done": 0,
        "record_samples": deque(maxlen=512),  # (mono, cumulative records)
        "shard_samples": deque(maxlen=512),   # (mono, cumulative shards)
        "last_total": 0, "last_done": 0, "last_elapsed_s": 0.0,
    }


class PipelineHealth:
    """Shared heartbeat/progress board for both pipeline directions.

    Thread-safe; every mutator is cheap (dict/deque ops under one
    lock). The pipelines only talk to it when live-introspection is
    configured for their run — the disabled path never reaches here.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._runs: Dict[int, _RunState] = {}
        self._next_token = 0
        self._agg: Dict[str, Dict[str, Any]] = {
            "read": _new_agg(), "write": _new_agg(),
        }
        self._stall_events = 0
        self._last_warn = 0.0

    # -- liveness gate ------------------------------------------------------

    @property
    def live(self) -> bool:
        """True when any consumer of progress data exists (endpoint,
        progress log, or an introspected run in flight) — the one-test
        gate ``note_shard_counters`` uses."""
        return bool(self._runs) or _server is not None \
            or _progress_sink is not None

    # -- run lifecycle ------------------------------------------------------

    def register_run(self, direction: str, total: int,
                     stall_s: Optional[float] = None,
                     policy: str = "warn") -> int:
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._runs[token] = _RunState(token, direction, total,
                                          stall_s, policy)
        if stall_s or _progress_sink is not None:
            _ensure_monitor()
        return token

    def set_abort(self, token: int,
                  abort: Callable[[BaseException], None]) -> None:
        with self._lock:
            run = self._runs.get(token)
            if run is not None:
                run.abort = abort

    def finish_run(self, token: int) -> None:
        with self._lock:
            run = self._runs.pop(token, None)
            if run is None:
                return
            agg = self._agg[run.direction]
            agg["last_total"] = run.total
            agg["last_done"] = run.done
            agg["last_elapsed_s"] = time.perf_counter() - run.started
        _maybe_write_progress(final_direction=run.direction)

    # -- heartbeats ---------------------------------------------------------

    def beat(self, token: int, stage: str, shard_id: int) -> None:
        """A stage worker starts (or refreshes) work on one shard."""
        with self._lock:
            run = self._runs.get(token)
            if run is None:
                return
            run.active[shard_id] = (stage, time.perf_counter())
            run.flagged.discard(shard_id)

    def clear(self, token: int, stage: str, shard_id: int) -> None:
        """A stage worker finished its stage for one shard."""
        with self._lock:
            run = self._runs.get(token)
            if run is None:
                return
            entry = run.active.get(shard_id)
            if entry is not None and entry[0] == stage:
                del run.active[shard_id]
            run.flagged.discard(shard_id)

    def shard_done(self, token: int, shard_id: int) -> None:
        now = time.perf_counter()
        with self._lock:
            run = self._runs.get(token)
            if run is None:
                return
            run.done += 1
            run.active.pop(shard_id, None)
            run.flagged.discard(shard_id)
            agg = self._agg[run.direction]
            agg["shards_done"] += 1
            agg["shard_samples"].append((now, agg["shards_done"]))
            direction = run.direction
        counter("progress.shards").inc(direction=direction)

    def note_counters(self, direction: str, records: int = 0,
                      bytes_compressed: int = 0,
                      bytes_uncompressed: int = 0) -> None:
        now = time.perf_counter()
        with self._lock:
            agg = self._agg.get(direction)
            if agg is None:
                return
            agg["records"] += records
            agg["bytes_compressed"] += bytes_compressed
            agg["bytes_uncompressed"] += bytes_uncompressed
            agg["record_samples"].append((now, agg["records"]))
        if records:
            counter("progress.records").inc(records)
        if bytes_compressed:
            counter("progress.bytes").inc(bytes_compressed,
                                          kind="compressed")
        if bytes_uncompressed:
            counter("progress.bytes").inc(bytes_uncompressed,
                                          kind="uncompressed")

    # -- watchdog -----------------------------------------------------------

    def suggested_tick(self) -> float:
        with self._lock:
            stalls = [r.stall_s for r in self._runs.values() if r.stall_s]
        if not stalls:
            return 0.2
        return max(0.02, min(0.25, min(stalls) / 4.0))

    def check(self, now: Optional[float] = None) -> int:
        """One watchdog sweep: flag every shard whose active stage has
        been silent past its run's ``watchdog_stall_s``. Returns the
        number of NEW stall events flagged this sweep."""
        if now is None:
            now = time.perf_counter()
        events: List[Tuple[_RunState, int, str, float]] = []
        with self._lock:
            for run in self._runs.values():
                if not run.stall_s:
                    continue
                for shard, (stage, since) in list(run.active.items()):
                    age = now - since
                    if age >= run.stall_s and shard not in run.flagged:
                        run.flagged.add(shard)
                        events.append((run, shard, stage, age))
            self._stall_events += len(events)
        for run, shard, stage, age in events:
            counter("watchdog.stalled_shards").inc(stage=stage)
            record_span("watchdog.stall", age, shard=shard, stage=stage,
                        direction=run.direction)
            flightrec.record_event(
                "watchdog_stall", shard=shard, stage=stage,
                age_s=round(age, 3), direction=run.direction,
                policy=run.policy)
            self._warn(run, shard, stage, age, now)
            if run.policy == "abort" and not run.abort_sent:
                run.abort_sent = True
                exc = WatchdogStallError(
                    "watchdog: shard stalled past "
                    f"watchdog_stall_s={run.stall_s}s",
                    shard_id=shard, stage=stage, age_s=age,
                    direction=run.direction)
                abort = run.abort
                if abort is not None:
                    # Pipelined run: inject into the first-error-abort
                    # path, raised at the ordered emit.
                    abort(exc)
                else:
                    # Inline (workers=1) run: no pipeline to inject
                    # into — park the error for the run's own thread to
                    # raise at its next stage boundary.
                    with self._lock:
                        run.pending_abort = exc
        return len(events)

    def take_abort(self, token: int) -> Optional[BaseException]:
        """Cooperative abort pickup for inline runs: the pending
        watchdog error for this run, if any (cleared on read). The
        inline executors call this at every stage boundary."""
        with self._lock:
            run = self._runs.get(token)
            if run is None or run.pending_abort is None:
                return None
            exc, run.pending_abort = run.pending_abort, None
            return exc

    def _warn(self, run: _RunState, shard: int, stage: str, age: float,
              now: float) -> None:
        with self._lock:
            if now - self._last_warn < _WARN_INTERVAL_S:
                return
            self._last_warn = now
        sys.stderr.write(
            f"disq_tpu watchdog: {run.direction} shard {shard} stalled "
            f"in {stage} for {age:.2f}s "
            f"(watchdog_stall_s={run.stall_s}, policy={run.policy})\n")

    # -- views --------------------------------------------------------------

    def has_active_runs(self) -> bool:
        return bool(self._runs)

    def healthz(self) -> Dict[str, Any]:
        """JSON liveness verdict: ``degraded`` while any flagged stall
        is still active — or any circuit breaker is open — ``ok``
        otherwise (``stall_events`` keeps the historical total either
        way).  When the resilience layer is configured
        (``runtime/resilience.py``), its state rides along: the retry
        budget's fill level and every per-filesystem breaker."""
        from disq_tpu.runtime import resilience

        now = time.perf_counter()
        with self._lock:
            stalls = []
            watchdogged = False
            for run in self._runs.values():
                if run.stall_s:
                    watchdogged = True
                for shard in sorted(run.flagged):
                    entry = run.active.get(shard)
                    if entry is None:
                        continue
                    stage, since = entry
                    stalls.append({
                        "direction": run.direction, "shard": shard,
                        "stage": stage, "age_s": round(now - since, 3),
                        "policy": run.policy,
                    })
            doc = {
                "status": "degraded" if stalls else "ok",
                "run_id": RUN_ID,
                "active_runs": len(self._runs),
                "watchdog_active": watchdogged,
                "stall_events": self._stall_events,
                "stalls": stalls,
            }
        res = resilience.snapshot()
        if res:
            doc["resilience"] = res
            if any(b["state"] == "open"
                   for b in res.get("breakers", {}).values()):
                doc["status"] = "degraded"
        # Per-tenant SLO verdict (runtime/slo.py): a tenant burning its
        # error budget past the fast-burn threshold on the short
        # windows is a paging condition — the fleet aggregator and any
        # LB health check see it here, not just on /slo.
        from disq_tpu.runtime import slo as _slo

        ev = _slo.evaluator_if_running()
        if ev is not None:
            frag = ev.health_fragment()
            doc["slo"] = frag
            if frag.get("fast_burn_tenants"):
                doc["status"] = "degraded"
        return doc

    @staticmethod
    def _rate(samples: "deque") -> float:
        if len(samples) < 2:
            return 0.0
        t1, v1 = samples[-1]
        window = [(t, v) for t, v in samples if t1 - t <= _RATE_WINDOW_S]
        if len(window) < 2:
            window = [samples[-2], samples[-1]]
        t0, v0 = window[0]
        dt = t1 - t0
        return (v1 - v0) / dt if dt > 1e-6 else 0.0

    def progress(self) -> Dict[str, Any]:
        """Progress view per direction: shards done / in flight /
        total, records and bytes so far, rolling rates, ETA."""
        now = time.perf_counter()
        out: Dict[str, Any] = {"run_id": RUN_ID, "directions": {}}
        with self._lock:
            for direction in ("read", "write"):
                agg = self._agg[direction]
                runs = [r for r in self._runs.values()
                        if r.direction == direction]
                total = sum(r.total for r in runs) or agg["last_total"]
                done = sum(r.done for r in runs) if runs else agg["last_done"]
                if not total and not agg["records"]:
                    continue
                shards_per_sec = self._rate(agg["shard_samples"])
                remaining = max(0, total - done)
                view = {
                    "active": bool(runs),
                    "shards_total": total,
                    "shards_done": done,
                    "in_flight": sum(len(r.active) for r in runs),
                    "records": agg["records"],
                    "bytes_compressed": agg["bytes_compressed"],
                    "bytes_uncompressed": agg["bytes_uncompressed"],
                    "records_per_sec":
                        round(self._rate(agg["record_samples"]), 1),
                    "shards_per_sec": round(shards_per_sec, 3),
                    "elapsed_s": round(
                        (now - min(r.started for r in runs)) if runs
                        else agg["last_elapsed_s"], 3),
                    "eta_s": (round(remaining / shards_per_sec, 3)
                              if runs and remaining and shards_per_sec > 0
                              else (0.0 if not remaining else None)),
                }
                out["directions"][direction] = view
        return out

    def reset(self) -> None:
        """Test hook: forget every run and aggregate."""
        with self._lock:
            self._runs.clear()
            self._agg = {"read": _new_agg(), "write": _new_agg()}
            self._stall_events = 0
            self._last_warn = 0.0


HEALTH = PipelineHealth()


def note_shard_counters(direction: str, counters) -> None:
    """Feed one shard's ``ShardCounters`` into the progress view — the
    single plumbing call each source makes at ordered emit. Free when
    nothing is watching."""
    if not HEALTH.live:
        return
    HEALTH.note_counters(
        direction,
        records=int(getattr(counters, "records", 0) or 0),
        bytes_compressed=int(getattr(counters, "bytes_compressed", 0) or 0),
        bytes_uncompressed=int(
            getattr(counters, "bytes_uncompressed", 0) or 0),
    )


# ---------------------------------------------------------------------------
# Watchdog / progress monitor thread
# ---------------------------------------------------------------------------

_monitor_thread: Optional[threading.Thread] = None


def _ensure_monitor() -> None:
    global _monitor_thread
    with _STATE_LOCK:
        if _monitor_thread is not None and _monitor_thread.is_alive():
            return
        _monitor_thread = threading.Thread(
            target=_monitor_loop, name="disq-watchdog", daemon=True)
        _monitor_thread.start()


def _monitor_loop() -> None:
    global _monitor_thread
    idle = 0
    next_progress = 0.0
    while True:
        time.sleep(HEALTH.suggested_tick())
        now = time.perf_counter()
        HEALTH.check(now)
        if _progress_sink is not None and now >= next_progress:
            _maybe_write_progress()
            next_progress = now + _progress_interval
        if HEALTH.has_active_runs() or _progress_sink is not None:
            idle = 0
            continue
        idle += 1
        if idle > _IDLE_TICKS_BEFORE_EXIT:
            with _STATE_LOCK:
                if (not HEALTH.has_active_runs()
                        and _progress_sink is None):
                    _monitor_thread = None
                    return
            idle = 0


# ---------------------------------------------------------------------------
# Progress JSONL log
# ---------------------------------------------------------------------------

_progress_sink = None
_progress_path: Optional[str] = None
_progress_interval = DEFAULT_PROGRESS_INTERVAL_S


def start_progress_log(path: str,
                       interval_s: float = DEFAULT_PROGRESS_INTERVAL_S
                       ) -> None:
    """Start (or re-point) the periodic progress JSONL: one line per
    direction per ``interval_s`` while runs are active, plus a final
    line as each run finishes. Replay with
    ``scripts/trace_report.py --progress``."""
    global _progress_sink, _progress_path, _progress_interval
    with _STATE_LOCK:
        _progress_interval = max(0.05, float(interval_s))
        if _progress_sink is not None:
            if _progress_path == path:
                return
            _progress_sink.close()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _progress_sink = open(path, "a")
        _progress_path = path
        _progress_sink.write(json.dumps({
            "meta": 1, "kind": "progress", "run_id": RUN_ID,
            "pid": os.getpid(), "epoch": time.time(),
            "mono": time.perf_counter(),
        }) + "\n")
        _progress_sink.flush()
    _ensure_monitor()


def stop_progress_log() -> None:
    global _progress_sink, _progress_path
    with _STATE_LOCK:
        if _progress_sink is not None:
            _progress_sink.close()
            _progress_sink = None
            _progress_path = None


def progress_log_path() -> Optional[str]:
    return _progress_path


def _maybe_write_progress(final_direction: Optional[str] = None) -> None:
    """Append one progress line per direction that has data. With
    ``final_direction`` (a run just finished), only that direction is
    written — so even sub-interval runs leave at least one line."""
    with _STATE_LOCK:
        if _progress_sink is None:
            return
        snap = HEALTH.progress()
        now = time.perf_counter()
        for direction, view in snap["directions"].items():
            if final_direction is not None and direction != final_direction:
                continue
            rec = {"ts": round(time.time(), 6), "mono": round(now, 6),
                   "run_id": snap["run_id"], "direction": direction}
            rec.update(view)
            _progress_sink.write(json.dumps(rec, default=str) + "\n")
        _progress_sink.flush()


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------

_server: Optional[ThreadingHTTPServer] = None
_server_thread: Optional[threading.Thread] = None
_address: Optional[str] = None


class _NamedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose per-request threads carry a
    canonical ``disq-*`` name — the sampling profiler and py-spy both
    attribute by thread name, and an anonymous handler thread (e.g.
    one blocking inside ``/debug/profile``) would profile as
    ``other``."""

    # socketserver's default listen backlog of 5 predates the serving
    # plane: a burst of concurrent query clients (bench config 13 runs
    # 32) overflows it and the kernel resets the excess connects before
    # the accept loop ever sees them. Admission control must be the
    # thing that sheds load, not the listen queue.
    request_queue_size = 128

    def process_request_thread(self, request, client_address):
        threading.current_thread().name = "disq-introspect-req"
        super().process_request_thread(request, client_address)


class _Handler(BaseHTTPRequestHandler):
    server_version = "disq-tpu-introspect/1"
    # Keep-alive matters once the serving plane (runtime/serve.py) runs
    # query traffic over this endpoint: a closed-loop client holds one
    # connection instead of paying connect+teardown per request. Safe
    # because every response goes through _send, which always sets
    # Content-Length. The socket timeout reaps idle parked connections
    # so handler threads never outlive their client by more than this.
    protocol_version = "HTTP/1.1"
    timeout = 30
    # Headers and body leave as separate writes; with Nagle on, the
    # second segment waits out the peer's delayed ACK (~40 ms) — a
    # latency floor that buries every sub-millisecond cache hit.
    disable_nagle_algorithm = True

    def log_message(self, *args: Any) -> None:  # quiet by design
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, doc: Dict[str, Any], code: int = 200) -> None:
        self._send(code, json.dumps(doc, default=str).encode(),
                   "application/json")

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            # The process-identity info series is what lets a cluster
            # aggregator (runtime/cluster.py) tell N workers'
            # expositions apart and label merged series process=<id>.
            info = (
                "# TYPE disq_tpu_process_info gauge\n"
                'disq_tpu_process_info{process_id="%d",run_id="%s"} 1\n'
                % (_process_id(), RUN_ID))
            self._send(200, (info + tracing.metrics_text()).encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            doc = HEALTH.healthz()
            doc["process_id"] = _process_id()
            self._send_json(doc, 200 if doc["status"] == "ok" else 503)
        elif path == "/progress":
            doc = HEALTH.progress()
            doc["process_id"] = _process_id()
            self._send_json(doc)
        elif path == "/spans":
            n = _SPANS_TAIL_DEFAULT
            for part in query.split("&"):
                if part.startswith("n="):
                    try:
                        n = max(1, min(_SPANS_TAIL_MAX, int(part[2:])))
                    except ValueError:
                        pass
            ring = tracing.spans()
            # epoch+mono pair: lets a cross-process stitcher
            # (trace_report --request over live endpoints) align this
            # process's monotonic span timestamps to wall clock
            self._send_json({
                "run_id": RUN_ID,
                "pid": os.getpid(),
                "epoch": time.time(),
                "mono": time.perf_counter(),
                "dropped_spans":
                    counter("telemetry.dropped_spans").total(),
                "total_in_ring": len(ring),
                "spans": ring[-n:],
            })
        elif path.startswith("/sched/"):
            # Cross-host shard scheduler plane (runtime/scheduler.py):
            # resolved only when a /sched/* request actually arrives,
            # so the scheduler-off path never imports or allocates
            # anything here.
            from disq_tpu.runtime import scheduler

            # every query param flows through (run=, dir=, host=…) so
            # curl-side inspection matches the POST plane's vocabulary
            doc: Dict[str, Any] = {}
            for part in query.split("&"):
                name, eq, value = part.partition("=")
                if eq and name:
                    doc[name] = urllib.parse.unquote(value)
            code, body = scheduler.handle_http("GET", path, doc)
            self._send_json(body, code)
        elif path == "/debug/stacks":
            self._send(200, flightrec.thread_stacks_text().encode(),
                       "text/plain; charset=utf-8")
        elif path == "/debug/profile":
            self._serve_profile(query)
        elif path == "/debug/bundle":
            bundle = flightrec.dump(reason="endpoint")
            if bundle is None:
                self._send_json({
                    "error": "flight recorder disabled — set "
                             "DisqOptions.postmortem_dir or "
                             "DISQ_TPU_POSTMORTEM_DIR (or the "
                             "per-process bundle cap was reached)",
                }, 409)
            else:
                self._send_json({"bundle": bundle, "run_id": RUN_ID})
        elif path == "/slo":
            # Per-tenant SLO view (runtime/slo.py): resolved lazily —
            # the SLO-off path reports a disabled stub and never
            # creates an evaluator.
            from disq_tpu.runtime import slo

            doc = slo.slo_doc()
            doc["process_id"] = _process_id()
            self._send_json(doc)
        elif path.startswith("/serve/"):
            # Serving plane (runtime/serve.py): resolved only when a
            # /serve/* request actually arrives, so the serve-off path
            # never imports or allocates anything here. Query params
            # flow through (the cachemap's incremental ?since=N).
            from disq_tpu.runtime import serve

            doc = {
                key: values[-1]
                for key, values in urllib.parse.parse_qs(query).items()
            }
            code, body = serve.handle_http("GET", path, doc)
            self._send_json(body, code)
        elif path.startswith("/fleet/"):
            # Fleet tier (runtime/fleet.py): same lazy contract — the
            # fleet-off path never imports the router module.
            from disq_tpu.runtime import fleet

            code, body = fleet.handle_http("GET", path, {})
            self._send_json(body, code)
        else:
            self._send_json({"error": "unknown path", "endpoints": [
                "/metrics", "/healthz", "/progress", "/spans", "/slo",
                "/debug/stacks", "/debug/profile", "/debug/bundle",
                "/sched/stats", "/serve/stats", "/serve/cachemap",
                "/fleet/stats"]},
                404)

    def do_POST(self) -> None:  # noqa: N802 — http.server API
        """The mutating endpoints: the scheduler plane
        (``/sched/join|lease|done|steal`` — runtime/scheduler.py), the
        serving plane (``/query/reads|variants|stats``,
        ``/serve/register`` — runtime/serve.py) and the fleet tier
        (``/fleet/query/*``, ``/fleet/register`` — runtime/fleet.py).
        Everything else is GET-only. Each plane is resolved lazily per
        request so the disabled paths import and allocate nothing."""
        path, _, _query = self.path.partition("?")
        if not path.startswith(("/sched/", "/query/", "/serve/",
                                "/fleet/")):
            self._send_json(
                {"error": "POST only serves /sched/*, /query/*, "
                          "/serve/* and /fleet/*"}, 404)
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            doc = json.loads(self.rfile.read(length)) if length else {}
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, OSError) as e:
            self._send_json({"error": f"bad request body: {e}"}, 400)
            return
        # Adopt the client's trace context (one header lookup when the
        # caller sent none) for the whole dispatch, so every span the
        # handled request emits on this thread carries its trace id.
        ctx = tracing.trace_from_headers(self.headers)
        token = tracing.activate_trace(ctx) if ctx is not None else None
        try:
            if path.startswith("/sched/"):
                from disq_tpu.runtime import scheduler

                code, body = scheduler.handle_http("POST", path, doc)
            elif path.startswith("/fleet/"):
                from disq_tpu.runtime import fleet

                code, body = fleet.handle_http("POST", path, doc)
            else:
                from disq_tpu.runtime import serve

                code, body = serve.handle_http("POST", path, doc)
        finally:
            if token is not None:
                tracing.deactivate_trace(token)
        self._send_json(body, code)

    def _serve_profile(self, query: str) -> None:
        """``/debug/profile?seconds=N&hz=M[&format=speedscope]``:
        sample this process for N seconds (blocking this request
        only — the server is threading) and return collapsed stacks
        (default) or a speedscope JSON document."""
        from disq_tpu.runtime import profiler

        seconds, hz, fmt = 2.0, profiler.DEFAULT_HZ, "collapsed"
        for part in query.split("&"):
            key, _, value = part.partition("=")
            try:
                if key == "seconds":
                    seconds = max(0.05, min(60.0, float(value)))
                elif key == "hz":
                    hz = max(1.0, min(1000.0, float(value)))
            except ValueError:
                pass
            if key == "format":
                fmt = value
        prof = profiler.profile_for(seconds, hz)
        if fmt == "speedscope":
            self._send_json(prof.speedscope())
        else:
            self._send(200, prof.collapsed().encode(),
                       "text/plain; charset=utf-8")


def start_introspect_server(port: int = 0) -> str:
    """Start the in-process endpoint on 127.0.0.1 (``port`` 0 binds an
    ephemeral port); idempotent — returns the bound ``host:port``."""
    global _server, _server_thread, _address
    with _STATE_LOCK:
        if _server is not None:
            return _address  # type: ignore[return-value]
        srv = _NamedThreadingHTTPServer(("127.0.0.1", int(port)),
                                        _Handler)
        srv.daemon_threads = True
        _server = srv
        _address = "127.0.0.1:%d" % srv.server_address[1]
        _server_thread = threading.Thread(
            target=srv.serve_forever, name="disq-introspect", daemon=True)
        _server_thread.start()
        return _address


def stop_introspect_server() -> None:
    global _server, _server_thread, _address
    with _STATE_LOCK:
        srv, thread = _server, _server_thread
        _server = None
        _server_thread = None
        _address = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if thread is not None:
        thread.join(timeout=5)


def introspect_address() -> Optional[str]:
    """``host:port`` of the live endpoint, or None when disabled."""
    return _address


# ---------------------------------------------------------------------------
# Options plumbing
# ---------------------------------------------------------------------------

_env_resolved = False


def _resolve_env() -> None:
    """Honor ``DISQ_TPU_INTROSPECT_PORT`` once per process (an explicit
    ``DisqOptions.introspect_port`` / ``start_introspect_server`` call
    also wins, exactly like the span-log env knob)."""
    global _env_resolved
    if _env_resolved:
        return
    with _STATE_LOCK:
        if _env_resolved:
            return
        _env_resolved = True
        raw = os.environ.get("DISQ_TPU_INTROSPECT_PORT")
    if raw is not None and raw != "":
        try:
            port = int(raw)
        except ValueError:
            return
        start_introspect_server(port)


def configure_from_options(opts) -> Optional[PipelineHealth]:
    """Resolve the live-introspection knobs of one ``DisqOptions`` and
    return the health board iff this run should feed it (endpoint or
    progress log live, or a watchdog requested). Returns None on the
    default path — the pipelines then skip every per-shard hook."""
    _resolve_env()
    if opts is not None:
        port = getattr(opts, "introspect_port", None)
        if port is not None and _server is None:
            start_introspect_server(port)
        plog = getattr(opts, "progress_log", None)
        if plog:
            start_progress_log(plog)
        if getattr(opts, "watchdog_stall_s", None):
            return HEALTH
    if _server is not None or _progress_sink is not None:
        return HEALTH
    return None


def reset_introspection() -> None:
    """Test hook: stop the endpoint + progress log, clear the board,
    and allow the env knob to be re-resolved."""
    global _env_resolved
    stop_introspect_server()
    stop_progress_log()
    HEALTH.reset()
    with _STATE_LOCK:
        _env_resolved = False
