"""Small shared helpers."""

from __future__ import annotations


def resolve_num_shards(storage) -> int:
    """Shard count for write paths: the storage's ``num_shards`` override,
    else the attached device count, else 1. Single source of truth for
    every sink (BAM/SAM/VCF/CRAM)."""
    n = getattr(storage, "_num_shards", None)
    if n:
        return n
    try:
        import jax

        return len(jax.devices())
    except Exception:
        return 1


def shard_bounds(storage, count: int):
    """(n_shards, bounds) for partitioning ``count`` records across write
    shards — single source of truth for every sink."""
    import numpy as np

    n_shards = min(resolve_num_shards(storage), max(1, count))
    bounds = np.linspace(0, count, n_shards + 1).astype(np.int64)
    return n_shards, bounds
