"""Small shared helpers."""

from __future__ import annotations

import threading


def bucket_pow2(n: int, lo: int = 64) -> int:
    """Power-of-two compile-shape bucket (floor ``lo``): the ONE
    padding policy shared by the SIMD inflate chunk shapes, the device
    parse starts, and ColumnarBatch concat — so their jit caches bucket
    identically and a policy change cannot silently diverge them."""
    b = lo
    while b < n:
        b *= 2
    return b

_HOST_POOL = None
_HOST_POOL_LOCK = threading.Lock()


def shared_host_pool():
    """The process-wide helper ThreadPoolExecutor for short GIL-released
    host work on device decode paths (batch CRC verification, kernel
    host-zlib fallback lanes).  Created lazily on first use — the
    default/host path never touches it — and never shut down (stdlib
    joins idle workers at interpreter exit).  ONE pool, min(4, cpus)
    threads, shared by every caller, instead of per-call or per-module
    singletons."""
    global _HOST_POOL
    import os
    from concurrent.futures import ThreadPoolExecutor

    with _HOST_POOL_LOCK:
        if _HOST_POOL is None:
            _HOST_POOL = ThreadPoolExecutor(
                max_workers=min(4, os.cpu_count() or 1),
                thread_name_prefix="disq-hostwork")
        return _HOST_POOL


def resolve_num_shards(storage) -> int:
    """Shard count for write paths: the storage's ``num_shards`` override,
    else the attached device count, else 1. Single source of truth for
    every sink (BAM/SAM/VCF/CRAM)."""
    n = getattr(storage, "_num_shards", None)
    if n:
        return n
    try:
        import jax

        return len(jax.devices())
    except Exception:
        return 1


def shard_bounds(storage, count: int):
    """(n_shards, bounds) for partitioning ``count`` records across write
    shards — single source of truth for every sink."""
    import numpy as np

    n_shards = min(resolve_num_shards(storage), max(1, count))
    bounds = np.linspace(0, count, n_shards + 1).astype(np.int64)
    return n_shards, bounds
