"""SamSink — text SAM write paths.

Reference parity: ``impl/formats/sam/SamSink.java`` (single file: header
part + per-shard text parts + driver concat) and ``AnySamSinkMultiple``
(directory of complete per-shard SAM files), SURVEY.md §2.6.
"""

from __future__ import annotations

import os
from typing import List, Sequence

import numpy as np

from disq_tpu.api import TempPartsDirectoryWriteOption, WriteOption
from disq_tpu.fsw.filesystem import resolve_path
from disq_tpu.sam.text import batch_to_sam_lines


from disq_tpu.util import shard_bounds


def _run_sam_shards(storage, fs, dataset, bounds, n_shards, prefix_bytes,
                    part_path_for) -> List[str]:
    """Shared shard fan-out for both SAM sinks: text rendering (CPU) on
    the write pipeline's encode workers, part writes on its I/O
    workers (no deflate stage — SAM is plain text). Returns part paths
    in shard order."""
    from disq_tpu.runtime.executor import (
        WriteShardTask,
        run_write_stage,
        write_retrier_for_storage,
        writer_for_storage,
    )
    from disq_tpu.runtime.tracing import wrap_span

    batch = dataset.reads

    def make_task(k):
        def encode():
            part = batch.slice(int(bounds[k]), int(bounds[k + 1]))
            lines = batch_to_sam_lines(part, dataset.header)
            return prefix_bytes + "".join(ln + "\n" for ln in lines).encode()

        def stage(body):
            p = part_path_for(k)
            fs.write_all(p, body)
            return p

        return WriteShardTask(
            shard_id=k,
            encode=wrap_span("sam.write.encode", encode, shard=k),
            stage=wrap_span("sam.write.stage", stage, shard=k),
            retrier=write_retrier_for_storage(storage, part_path_for(k)),
            what="sam.part",
        )

    # storage+path wired through for the scheduler's write-direction
    # leasing gate (inert here: no StageManifest rides along)
    return run_write_stage(writer_for_storage(storage), n_shards,
                           make_task, storage=storage,
                           path=part_path_for(0))


class SamSink:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        temp_dir = next(
            (o.path for o in options if isinstance(o, TempPartsDirectoryWriteOption)),
            path + ".parts",
        )
        batch = dataset.reads
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(temp_dir)
        try:
            from disq_tpu.runtime.executor import write_retrier_for_storage

            driver = write_retrier_for_storage(self._storage, path)
            header_path = os.path.join(temp_dir, "_header")
            driver.call(fs.write_all, header_path,
                        dataset.header.text.encode(), what="sam.merge")
            part_paths = _run_sam_shards(
                self._storage, fs, dataset, bounds, n_shards, b"",
                lambda k: os.path.join(temp_dir, f"part-{k:05d}"),
            )
            driver.call(fs.concat, [header_path] + part_paths, path,
                        what="sam.merge")
        finally:
            fs.delete(temp_dir, recursive=True)


class SamSinkMultiple:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        batch = dataset.reads
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(path)
        _run_sam_shards(
            self._storage, fs, dataset, bounds, n_shards,
            dataset.header.text.encode(),
            lambda k: os.path.join(path, f"part-r-{k:05d}.sam"),
        )
