"""SamSink — text SAM write paths.

Reference parity: ``impl/formats/sam/SamSink.java`` (single file: header
part + per-shard text parts + driver concat) and ``AnySamSinkMultiple``
(directory of complete per-shard SAM files), SURVEY.md §2.6.
"""

from __future__ import annotations

import os
from typing import List, Sequence

import numpy as np

from disq_tpu.api import TempPartsDirectoryWriteOption, WriteOption
from disq_tpu.fsw.filesystem import resolve_path
from disq_tpu.sam.text import batch_to_sam_lines


from disq_tpu.util import shard_bounds


class SamSink:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        temp_dir = next(
            (o.path for o in options if isinstance(o, TempPartsDirectoryWriteOption)),
            path + ".parts",
        )
        batch = dataset.reads
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(temp_dir)
        try:
            header_path = os.path.join(temp_dir, "_header")
            fs.write_all(header_path, dataset.header.text.encode())
            part_paths: List[str] = []
            for k in range(n_shards):
                part = batch.slice(int(bounds[k]), int(bounds[k + 1]))
                lines = batch_to_sam_lines(part, dataset.header)
                body = "".join(ln + "\n" for ln in lines).encode()
                p = os.path.join(temp_dir, f"part-{k:05d}")
                fs.write_all(p, body)
                part_paths.append(p)
            fs.concat([header_path] + part_paths, path)
        finally:
            fs.delete(temp_dir, recursive=True)


class SamSinkMultiple:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        batch = dataset.reads
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(path)
        header_text = dataset.header.text
        for k in range(n_shards):
            part = batch.slice(int(bounds[k]), int(bounds[k + 1]))
            lines = batch_to_sam_lines(part, dataset.header)
            data = header_text.encode() + "".join(ln + "\n" for ln in lines).encode()
            fs.write_all(os.path.join(path, f"part-r-{k:05d}.sam"), data)
