class SamSink:
    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path, options=()):
        raise NotImplementedError(
            "text SAM write support lands in the next milestone "
            "(SURVEY.md §2.6)"
        )


class SamSinkMultiple(SamSink):
    pass
