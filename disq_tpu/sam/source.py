"""SamSource — plain-text SAM read path.

Reference parity: ``impl/formats/sam/SamSource.java`` (SURVEY.md §2.6):
Hadoop text line splits; ``@`` header lines skipped in-task; lines parsed
with the SAM line parser. The header is read host-side ("driver") from
the file head.
"""

from __future__ import annotations

from typing import List

from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.bam.header import SamHeader
from disq_tpu.fsw.filesystem import FileSystemWrapper, compute_path_splits, resolve_path
from disq_tpu.fsw.textsplit import lines_for_split
from disq_tpu.sam.text import sam_lines_to_batch


def read_sam_header(fs: FileSystemWrapper, path: str) -> SamHeader:
    """Read the leading ``@`` lines (header) from a SAM file."""
    text_lines: List[str] = []
    pos = 0
    length = fs.get_file_length(path)
    CHUNK = 1 << 20
    pending = b""
    done = False
    while pos < length and not done:
        data = pending + fs.read_range(path, pos, min(CHUNK, length - pos))
        pos += len(data) - len(pending)
        lines = data.split(b"\n")
        pending = lines.pop()
        for ln in lines:
            if ln.startswith(b"@"):
                text_lines.append(ln.decode())
            else:
                done = True
                break
        if not done and pending and not pending.startswith(b"@") and pos >= length:
            break
    if not done and pending.startswith(b"@"):
        # Final header line in a file without a trailing newline.
        text_lines.append(pending.decode())
    return SamHeader.from_text("\n".join(text_lines) + ("\n" if text_lines else ""))


class SamSource:
    def __init__(self, storage=None):
        self._storage = storage

    @property
    def split_size(self) -> int:
        return getattr(self._storage, "_split_size", 128 * 1024 * 1024)

    def get_reads(self, path: str, traversal=None):
        from disq_tpu.api import ReadsDataset

        if traversal is not None:
            raise ValueError(
                "interval traversal requires an indexed format (BAM/CRAM); "
                "plain SAM has no index (reference behavior)"
            )
        fs, path = resolve_path(path)
        header = read_sam_header(fs, path)
        batches = []
        for s in compute_path_splits(fs, path, self.split_size):
            lines = [
                ln.decode() for ln in lines_for_split(fs, path, s.start, s.end)
                if ln and not ln.startswith(b"@")
            ]
            batches.append(sam_lines_to_batch(lines, header))
        return ReadsDataset(header=header, reads=ReadBatch.concat(batches))
