class SamSource:
    def __init__(self, storage=None):
        self._storage = storage

    def get_reads(self, path, traversal=None):
        raise NotImplementedError(
            "text SAM read support lands in the next milestone "
            "(SURVEY.md §2.6)"
        )
