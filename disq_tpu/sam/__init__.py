"""Plain-text SAM support (reference parity: ``impl/formats/sam/``)."""
