"""SAM text ⇄ columnar batch conversion.

Replaces htsjdk's ``SAMLineParser`` / ``SAMTextWriter`` (used by the
reference's ``SamSource``/``SamSink``, SURVEY.md §2.6). Binary BAM tag
bytes convert to/from the ``TAG:TYPE:VALUE`` text forms per the SAM spec
§1.5 (types A i f Z H B; binary subtypes c C s S i I canonicalize to
text ``i``, as htsjdk does).
"""

from __future__ import annotations

import re
import struct
from typing import Iterable, List, Tuple

import numpy as np

from disq_tpu.bam.columnar import CIGAR_OPS, SEQ_NT16, ReadBatch
from disq_tpu.bam.header import SamHeader
from disq_tpu.index.bai import bins_from_cigars

_NT16_IDX = {c: i for i, c in enumerate(SEQ_NT16)}
_NT16_IDX.update({c.lower(): i for c, i in list(_NT16_IDX.items())})
_CIG_IDX = {c: i for i, c in enumerate(CIGAR_OPS)}

_B_SUBTYPES = {
    "c": ("b", 1), "C": ("B", 1), "s": ("h", 2), "S": ("H", 2),
    "i": ("i", 4), "I": ("I", 4), "f": ("f", 4),
}


def tags_to_text(tags: bytes) -> List[str]:
    """Binary tag block → list of ``TAG:TYPE:VALUE`` strings."""
    out = []
    p = 0
    n = len(tags)
    while p < n:
        if p + 3 > n:
            raise ValueError("truncated tag block")
        tag = tags[p:p + 2].decode()
        typ = chr(tags[p + 2])
        p += 3
        if typ == "A":
            out.append(f"{tag}:A:{chr(tags[p])}")
            p += 1
        elif typ in "cCsSiI":
            fmt = {"c": "b", "C": "B", "s": "h", "S": "H", "i": "i", "I": "I"}[typ]
            size = {"c": 1, "C": 1, "s": 2, "S": 2, "i": 4, "I": 4}[typ]
            (v,) = struct.unpack_from("<" + fmt, tags, p)
            out.append(f"{tag}:i:{v}")
            p += size
        elif typ == "f":
            (v,) = struct.unpack_from("<f", tags, p)
            # shortest float32 round-trip formatting (no %g truncation)
            out.append(f"{tag}:f:{np.float32(v)}")
            p += 4
        elif typ in "ZH":
            end = tags.index(b"\x00", p)
            out.append(f"{tag}:{typ}:{tags[p:end].decode()}")
            p = end + 1
        elif typ == "B":
            sub = chr(tags[p])
            (cnt,) = struct.unpack_from("<I", tags, p + 1)
            fmt, size = _B_SUBTYPES[sub]
            vals = struct.unpack_from(f"<{cnt}{fmt}", tags, p + 5)
            body = ",".join(
                str(np.float32(v)) if sub == "f" else str(v) for v in vals
            )
            out.append(f"{tag}:B:{sub}{',' + body if cnt else ''}")
            p += 5 + cnt * size
        else:
            raise ValueError(f"unknown tag type {typ!r}")
    return out


def text_to_tags(fields: Iterable[str]) -> bytes:
    """``TAG:TYPE:VALUE`` strings → binary tag block."""
    out = bytearray()
    for f in fields:
        tag, typ, val = f.split(":", 2)
        out += tag.encode()
        if typ == "A":
            out += b"A" + val.encode()
        elif typ == "i":
            v = int(val)
            if -(1 << 31) <= v < (1 << 31):
                out += b"i" + struct.pack("<i", v)
            elif v < (1 << 32):
                out += b"I" + struct.pack("<I", v)
            else:
                raise ValueError(f"integer tag value out of range: {v}")
        elif typ == "f":
            out += b"f" + struct.pack("<f", float(val))
        elif typ in ("Z", "H"):
            out += typ.encode() + val.encode() + b"\x00"
        elif typ == "B":
            sub = val[0]
            parts = val[1:].lstrip(",")
            vals = [p for p in parts.split(",") if p] if parts else []
            fmt, _ = _B_SUBTYPES[sub]
            out += b"B" + sub.encode() + struct.pack("<I", len(vals))
            conv = float if sub == "f" else int
            out += struct.pack(f"<{len(vals)}{fmt}", *[conv(v) for v in vals])
        else:
            raise ValueError(f"unknown tag type {typ!r}")
    return bytes(out)


def parse_cigar(s: str) -> List[int]:
    if s == "*":
        return []
    # Full-string anchor: partial matches must raise, not silently drop
    # unparseable segments.
    if not re.fullmatch(r"(?:\d+[MIDNSHP=X])+", s):
        raise ValueError(f"bad CIGAR {s!r}")
    return [
        (int(m.group(1)) << 4) | _CIG_IDX[m.group(2)]
        for m in re.finditer(r"(\d+)([MIDNSHP=X])", s)
    ]


def batch_to_sam_lines(batch: ReadBatch, header: SamHeader) -> List[str]:
    lines = []
    for i in range(batch.count):
        refid = int(batch.refid[i])
        nref = int(batch.next_refid[i])
        rname = header.ref_name(refid)
        if nref == -1:
            rnext = "*"
        elif nref == refid:
            rnext = "="
        else:
            rnext = header.ref_name(nref)
        ts, te = batch.tag_offsets[i], batch.tag_offsets[i + 1]
        tag_fields = tags_to_text(batch.tags[ts:te].tobytes())
        seq = batch.sequence(i) or "*"
        fields = [
            batch.name(i) or "*",
            str(int(batch.flag[i])),
            rname,
            str(int(batch.pos[i]) + 1),
            str(int(batch.mapq[i])),
            batch.cigar_string(i),
            rnext,
            str(int(batch.next_pos[i]) + 1),
            str(int(batch.tlen[i])),
            seq,
            batch.qual_string(i),
        ] + tag_fields
        lines.append("\t".join(fields))
    return lines


def sam_lines_to_batch(lines: Iterable[str], header: SamHeader) -> ReadBatch:
    refid_l, pos_l, mapq_l, flag_l = [], [], [], []
    nref_l, npos_l, tlen_l = [], [], []
    names, cigars, seqs, quals, tags = [], [], [], [], []
    for line in lines:
        line = line.rstrip("\n")
        if not line or line.startswith("@"):
            continue
        f = line.split("\t")
        if len(f) < 11:
            raise ValueError(f"SAM line has {len(f)} fields (need 11): {line[:60]!r}")
        names.append(f[0].encode() if f[0] != "*" else b"")
        flag = int(f[1])
        flag_l.append(flag)
        refid = -1 if f[2] == "*" else header.ref_index(f[2])
        refid_l.append(refid)
        pos = int(f[3]) - 1
        pos_l.append(pos)
        mapq_l.append(int(f[4]))
        ops = parse_cigar(f[5])
        cigars.append(ops)
        if f[6] == "=":
            nref_l.append(refid)
        elif f[6] == "*":
            nref_l.append(-1)
        else:
            nref_l.append(header.ref_index(f[6]))
        npos_l.append(int(f[7]) - 1)
        tlen_l.append(int(f[8]))
        seq = "" if f[9] == "*" else f[9]
        seqs.append(np.array([_NT16_IDX[c] for c in seq], dtype=np.uint8))
        if f[10] == "*":
            quals.append(np.full(len(seq), 0xFF, dtype=np.uint8))
        else:
            if len(f[10]) != len(seq):
                raise ValueError("QUAL length != SEQ length")
            quals.append(
                np.frombuffer(f[10].encode(), dtype=np.uint8) - 33
            )
        tags.append(text_to_tags(f[11:]))

    n = len(names)

    def ragged(items, dtype):
        off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(x) for x in items], out=off[1:])
        flat = (
            np.concatenate([np.asarray(x, dtype=dtype) for x in items])
            if n and off[-1]
            else np.zeros(0, dtype=dtype)
        )
        return off, flat

    name_off, names_f = ragged([np.frombuffer(x, np.uint8) for x in names], np.uint8)
    cigar_off, cigars_f = ragged([np.asarray(c, np.uint32) for c in cigars], np.uint32)
    seq_off, seqs_f = ragged(seqs, np.uint8)
    _, quals_f = ragged(quals, np.uint8)
    tag_off, tags_f = ragged([np.frombuffer(t, np.uint8) for t in tags], np.uint8)
    # bin: vectorized over the whole batch (per-record scalar reg2bin
    # was the hottest line of SAM parse, exactly as for CRAM decode)
    bin_arr = bins_from_cigars(cigars_f, cigar_off, pos_l)
    return ReadBatch(
        refid=np.asarray(refid_l, np.int32), pos=np.asarray(pos_l, np.int32),
        mapq=np.asarray(mapq_l, np.uint8),
        bin=bin_arr.astype(np.uint16),
        flag=np.asarray(flag_l, np.uint16),
        next_refid=np.asarray(nref_l, np.int32),
        next_pos=np.asarray(npos_l, np.int32),
        tlen=np.asarray(tlen_l, np.int32),
        name_offsets=name_off, names=names_f,
        cigar_offsets=cigar_off, cigars=cigars_f,
        seq_offsets=seq_off, seqs=seqs_f, quals=quals_f,
        tag_offsets=tag_off, tags=tags_f,
    )
