"""Host file layer — the analogue of disq's file abstraction.

Reference parity (see SURVEY.md §2.2):
- ``FileSystemWrapper``  ← ``impl/file/FileSystemWrapper.java`` (interface:
  exists / getFileLength / open / create / listDirectory /
  firstFileInDirectory / concat / delete)
- ``PosixFileSystemWrapper`` ← ``impl/file/NioFileSystemWrapper.java``
- ``MemoryFileSystemWrapper`` — test double (no reference counterpart)
- ``PathSplit`` + ``compute_path_splits`` ← ``impl/file/PathSplitSource.java``
  / ``PathSplit.java`` (file → byte-range splits of ``split_size``)

Remote URIs (``http(s)://``, ``gs://``, ``s3://``) dispatch to the HTTP
range-read wrapper (``disq_tpu.fsw.http``) — ``HadoopFileSystemWrapper``'s
remote role; gs/s3 map to their public endpoints, so touching them DOES
issue network requests. ``register_filesystem`` installs authenticated or
alternative wrappers per scheme without touching call sites.
"""

from __future__ import annotations

import io
import os
import shutil
import uuid
from dataclasses import dataclass
from typing import BinaryIO, Dict, List, Sequence, Tuple


@dataclass(frozen=True)
class PathSplit:
    """A byte-range split of a file (reference: ``impl/file/PathSplit.java``).

    ``end`` is exclusive. Splits tile the file exactly: split i covers
    ``[i*split_size, min((i+1)*split_size, length))``.
    """

    path: str
    start: int
    end: int

    @property
    def length(self) -> int:
        return self.end - self.start


# Default split size mirrors the Hadoop block size disq inherits via
# PathSplitSource (128 MiB).
DEFAULT_SPLIT_SIZE = 128 * 1024 * 1024


class FileSystemWrapper:
    """Uniform file ops used by every layer above.

    Mirrors ``impl/file/FileSystemWrapper.java``. All paths are plain
    strings; scheme-less paths are posix.
    """

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def get_file_length(self, path: str) -> int:
        raise NotImplementedError

    def open(self, path: str) -> BinaryIO:
        """Open a seekable binary read stream."""
        raise NotImplementedError

    def create(self, path: str) -> BinaryIO:
        """Open a binary write stream, creating parent dirs as needed."""
        raise NotImplementedError

    def read_range(self, path: str, start: int, length: int) -> bytes:
        """Range read — the staging primitive for device shard buffers."""
        with self.open(path) as f:
            f.seek(start)
            return f.read(length)

    def read_all(self, path: str) -> bytes:
        return self.read_range(path, 0, self.get_file_length(path))

    def write_all(self, path: str, data: bytes) -> None:
        with self.create(path) as f:
            f.write(data)

    def list_directory(self, path: str) -> List[str]:
        raise NotImplementedError

    def first_file_in_directory(self, path: str, suffix: str = "") -> str:
        for p in self.list_directory(path):
            if p.endswith(suffix):
                return p
        raise FileNotFoundError(f"no file with suffix {suffix!r} in {path}")

    def concat(self, parts: Sequence[str], target: str) -> None:
        """Concatenate ``parts`` into ``target`` (stream copy).

        Reference: ``impl/file/Merger.java`` uses ``FileSystem#concat``
        when available, else a stream copy; posix has no O(1) concat, so
        this is always a copy here.
        """
        with self.create(target) as out:
            for part in parts:
                with self.open(part) as f:
                    shutil.copyfileobj(f, out, 8 * 1024 * 1024)

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def is_directory(self, path: str) -> bool:
        raise NotImplementedError


class _AtomicWriteFile(io.FileIO):
    """Write stream that stages to a hidden tmp sibling and commits with
    ``os.replace`` on close — a writer killed mid-write never leaves a
    truncated file at the final path for a later ``exists()`` check to
    mistake for a complete one. Exiting a ``with`` block on an exception
    aborts instead of committing (the tmp is deleted), for the same
    reason. The tmp name is dot-prefixed so ``list_directory``'s
    hidden-file filter never surfaces orphans."""

    def __init__(self, tmp_path: str, final_path: str) -> None:
        super().__init__(tmp_path, "w")
        self._tmp_path = tmp_path
        self._final_path = final_path
        self._aborted = False

    def write(self, b) -> int:
        # io.FileIO.write is a single os.write, which may be short
        # (notably capped near 2 GiB on Linux). The open(path, "wb")
        # this replaced returned a BufferedWriter that looped; callers
        # (write_all, copyfileobj, the sinks) discard the return value,
        # so a short write here would be silently *committed* as a
        # complete file by the atomic rename. Loop until done.
        mv = memoryview(b).cast("B")
        done = 0
        while done < len(mv):
            n = super().write(mv[done:])
            if not n:
                raise IOError(
                    f"short write to {self._tmp_path!r} at byte {done}")
            done += n
        return done

    def abort(self) -> None:
        """Discard the staged bytes: close without publishing."""
        self._aborted = True
        self.close()

    def __exit__(self, exc_type, exc, tb) -> None:
        self._aborted = self._aborted or exc_type is not None
        super().__exit__(exc_type, exc, tb)

    def __del__(self) -> None:
        # A writer garbage-collected without close()/abort() was
        # abandoned mid-write: discard, never publish a partial file.
        self._aborted = True
        super().__del__()

    def close(self) -> None:
        if self.closed:
            return
        super().close()
        if self._aborted:
            try:
                os.unlink(self._tmp_path)
            except (FileNotFoundError, TypeError):
                # TypeError: os torn down during interpreter shutdown
                pass
        else:
            os.replace(self._tmp_path, self._final_path)


class PosixFileSystemWrapper(FileSystemWrapper):
    """Local-filesystem impl (reference: ``impl/file/NioFileSystemWrapper.java``)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def get_file_length(self, path: str) -> int:
        return os.path.getsize(path)

    def open(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def create(self, path: str) -> BinaryIO:
        path = os.path.abspath(path)
        parent = os.path.dirname(path)
        os.makedirs(parent, exist_ok=True)
        # pid alone is not unique enough: two threads staging the same
        # destination would truncate each other's tmp. uuid gives each
        # writer its own staging file; last close() wins the replace.
        tmp = os.path.join(
            parent,
            f".{os.path.basename(path)}.tmp-{os.getpid()}-"
            f"{uuid.uuid4().hex[:8]}",
        )
        return _AtomicWriteFile(tmp, path)

    def list_directory(self, path: str) -> List[str]:
        return sorted(
            os.path.join(path, name)
            for name in os.listdir(path)
            if not name.startswith(".") and not name.startswith("_")
        )

    def delete(self, path: str, recursive: bool = False) -> None:
        if os.path.isdir(path):
            if recursive:
                shutil.rmtree(path)
            else:
                os.rmdir(path)
        elif os.path.exists(path):
            os.remove(path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def is_directory(self, path: str) -> bool:
        return os.path.isdir(path)


class MemoryFileSystemWrapper(FileSystemWrapper):
    """In-memory FS for tests and for staging shard buffers host-side."""

    def __init__(self) -> None:
        self._files: Dict[str, bytes] = {}

    def exists(self, path: str) -> bool:
        return path in self._files or self.is_directory(path)

    def get_file_length(self, path: str) -> int:
        return len(self._files[path])

    def open(self, path: str) -> BinaryIO:
        return io.BytesIO(self._files[path])

    def create(self, path: str) -> BinaryIO:
        fs = self

        class _Writer(io.BytesIO):
            def close(self) -> None:
                fs._files[path] = self.getvalue()
                super().close()

        return _Writer()

    def list_directory(self, path: str) -> List[str]:
        prefix = path.rstrip("/") + "/"
        names = [
            p
            for p in self._files
            if p.startswith(prefix) and "/" not in p[len(prefix):]
        ]
        base = [n for n in names if not os.path.basename(n).startswith((".", "_"))]
        return sorted(base)

    def delete(self, path: str, recursive: bool = False) -> None:
        if path in self._files:
            del self._files[path]
        elif recursive:
            prefix = path.rstrip("/") + "/"
            for p in [p for p in self._files if p.startswith(prefix)]:
                del self._files[p]

    def mkdirs(self, path: str) -> None:
        pass

    def is_directory(self, path: str) -> bool:
        prefix = path.rstrip("/") + "/"
        return any(p.startswith(prefix) for p in self._files)


_POSIX = PosixFileSystemWrapper()
_SCHEME_REGISTRY: dict = {}


def register_filesystem(scheme: str, fs: FileSystemWrapper) -> None:
    """Install a wrapper for ``scheme`` (e.g. an authenticated blob
    client); overrides the built-in dispatch below."""
    _SCHEME_REGISTRY[scheme] = fs


def resolve_path(path: str) -> Tuple[FileSystemWrapper, str]:
    """Scheme dispatch: URI → (wrapper, normalized path).

    Remote schemes (``http(s)://``, ``gs://``, ``s3://``) resolve to the
    HTTP range-read wrapper (``disq_tpu.fsw.http``) — gs/s3 via their
    public endpoints; authenticated access installs a wrapper through
    ``register_filesystem``.
    """
    scheme = path.split("://", 1)[0] if "://" in path else ""
    if scheme in _SCHEME_REGISTRY:
        return _SCHEME_REGISTRY[scheme], path
    if scheme in ("http", "https", "gs", "s3"):
        from disq_tpu.fsw.http import HttpFileSystemWrapper

        fs = HttpFileSystemWrapper()
        _SCHEME_REGISTRY.setdefault(scheme, fs)
        return _SCHEME_REGISTRY[scheme], path
    if path.startswith("file://"):
        path = path[len("file://"):]
    return _POSIX, path


def get_filesystem(path: str) -> FileSystemWrapper:
    return resolve_path(path)[0]


def compute_path_splits(
    fs: FileSystemWrapper, path: str, split_size: int = DEFAULT_SPLIT_SIZE
) -> List[PathSplit]:
    """File → byte-range splits (reference: ``PathSplitSource#getPathSplits``).

    Splits tile [0, length) exactly; the *content* owned by a split is
    refined by the format layer (e.g. the BGZF "first owner" rule:
    a block whose start lies in [start, end) belongs to that split even if
    its bytes run past ``end``).
    """
    if split_size <= 0:
        raise ValueError(f"split_size must be positive, got {split_size}")
    length = fs.get_file_length(path)
    if length == 0:
        return []
    return [
        PathSplit(path, start, min(start + split_size, length))
        for start in range(0, length, split_size)
    ]
