"""Byte-range text splitting — Hadoop ``LineRecordReader`` semantics.

Reference parity: the reference reads text SAM and plain VCF through
Hadoop's ``TextInputFormat`` (SURVEY.md §2.6/§2.7): a split owns every
line that *starts* within its byte range; a reader starting mid-file
discards the partial first line (the previous split owns it) and reads
one line past its end to finish a straddling line.
"""

from __future__ import annotations

from typing import List

from disq_tpu.fsw.filesystem import FileSystemWrapper

_CHUNK = 4 * 1024 * 1024


def lines_for_split(
    fs: FileSystemWrapper, path: str, start: int, end: int
) -> List[bytes]:
    """Complete lines (no trailing newline) whose first byte lies in
    ``[start, end)``."""
    length = fs.get_file_length(path)
    if start >= length:
        return []
    pos = start
    buf = b""
    if start > 0:
        # Discard the partial first line: scan to the first newline at or
        # after start-1 … the line after it is ours. Reading from start
        # and dropping through the first newline is equivalent unless the
        # byte at start-1 is itself a newline (then the line AT start is
        # ours) — handle by peeking one byte back.
        prev = fs.read_range(path, start - 1, 1)
        if prev != b"\n":
            buf = fs.read_range(path, pos, min(_CHUNK, length - pos))
            nl = buf.find(b"\n")
            while nl < 0:
                pos += len(buf)
                if pos >= length:
                    return []
                buf = fs.read_range(path, pos, min(_CHUNK, length - pos))
                nl = buf.find(b"\n")
            buf = buf[nl + 1:]
            pos += nl + 1

    lines: List[bytes] = []
    line_start = pos  # file offset of the next line's first byte
    carry = b""
    while True:
        if not buf:
            if pos >= length:
                break
            buf = fs.read_range(path, pos, min(_CHUNK, length - pos))
        consumed = 0
        while True:
            nl = buf.find(b"\n", consumed)
            if nl < 0:
                carry += buf[consumed:]
                pos += len(buf)
                buf = b""
                break
            line = carry + buf[consumed:nl]
            carry = b""
            if line_start >= end:
                return lines
            lines.append(line)
            line_start = pos + nl + 1
            consumed = nl + 1
        if line_start >= end and not carry:
            return lines
    if carry and line_start < end:
        lines.append(carry)  # final line without trailing newline
    return lines
