from disq_tpu.fsw.filesystem import (  # noqa: F401
    FileSystemWrapper,
    PosixFileSystemWrapper,
    MemoryFileSystemWrapper,
    get_filesystem,
    resolve_path,
    PathSplit,
    compute_path_splits,
)
