from disq_tpu.fsw.filesystem import (  # noqa: F401
    FileSystemWrapper,
    PosixFileSystemWrapper,
    MemoryFileSystemWrapper,
    get_filesystem,
    register_filesystem,
    resolve_path,
    PathSplit,
    compute_path_splits,
)
from disq_tpu.fsw.faultfs import (  # noqa: F401
    FaultInjectingFileSystemWrapper,
    FaultSpec,
)
