"""Deterministic fault injection at the file layer.

``FaultInjectingFileSystemWrapper`` wraps any ``FileSystemWrapper`` and
injects *seeded, reproducible* faults into the read path, so the error
policy runtime (``disq_tpu.runtime.errors``) can be tested end-to-end —
"fault on shard 3's second block" is an addressable, repeatable event,
not a hope that the network misbehaves on cue.

Fault kinds (``FaultSpec.kind``):

- ``"transient"`` — raise ``TransientIOError`` *before* performing the
  read; a retry re-executes the read, which may fault again
  independently. The model for 5xx blips / reset connections.
- ``"stall"``     — sleep ``stall_s`` before serving (latency
  injection; the read then succeeds). The model for a wedged call the
  watchdog should flag.
- ``"slow"``      — sleep a *seeded* latency drawn uniformly from
  ``[0, slow_s)`` before serving. Unlike ``stall``'s fixed wedge, this
  models a latency distribution (a slow tail) — deterministic per
  ``(seed, call sequence)``, so hedging and deadline escalation are
  testable against a reproducible tail. A hedged duplicate is a NEW
  call and draws its own latency.
- ``"truncate"``  — serve the read but drop the final
  ``truncate_bytes`` bytes of the result. The model for a connection
  cut mid-body.
- ``"bitflip"``   — flip bit ``bit`` of the byte at absolute file
  offset ``offset`` in any read whose range covers it. The model for
  at-rest corruption — NOT transient; retries see the same bad bit.

Targeting: each spec can match by path substring, by a Bernoulli
``probability`` (seeded — the whole schedule is a pure function of
``seed`` and the call sequence), by ``call_index`` (the Nth matching
read), and by ``offset`` (reads covering an absolute byte). ``times``
bounds how often a spec fires (-1 = unlimited).

Each spec also targets one direction via ``op``: ``"read"`` (the
default — existing schedules keep their exact meaning) fires on
``read_range``; ``"write"`` fires on the write-side entry points
(``write_all`` / ``create`` / ``concat``), so the parallel write
pipeline's retry + manifest-resume behavior is deterministically
testable. ``write_all`` supports every kind (``truncate`` /
``bitflip`` mutate the bytes *before* they are durably staged — the
model for a partial or corrupted upload); ``create`` and ``concat``
support the pre-op kinds (``transient`` / ``stall``).

All reads — including ``open()`` streams — are routed through
``read_range``, so a single injection point covers header reads, block
walks, and bulk staging alike. The ``injected`` log records every fired
fault for assertions and post-mortems.
"""

from __future__ import annotations

import io
import random
import threading
import time
from dataclasses import dataclass, field
from typing import BinaryIO, List, Optional, Sequence, Tuple

from disq_tpu.fsw.filesystem import FileSystemWrapper
from disq_tpu.runtime.errors import TransientIOError


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault. Matching is AND across the set criteria."""

    kind: str                       # transient|stall|slow|truncate|bitflip
    path_substr: str = ""           # match paths containing this
    probability: float = 0.0        # Bernoulli per matching call (seeded)
    call_index: Optional[int] = None  # fire on the Nth matching call (0-based)
    offset: Optional[int] = None    # fire when the read covers this byte
    times: int = -1                 # max fires; -1 = unlimited
    stall_s: float = 0.0            # kind="stall"
    slow_s: float = 0.0             # kind="slow": max seeded latency
    truncate_bytes: int = 1         # kind="truncate": bytes dropped from tail
    bit: int = 0                    # kind="bitflip": bit index 0..7
    op: str = "read"                # direction: "read" | "write"

    def __post_init__(self) -> None:
        if self.kind not in ("transient", "stall", "slow", "truncate",
                             "bitflip"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.op not in ("read", "write"):
            raise ValueError(f"unknown fault op {self.op!r}")
        if self.kind == "bitflip" and self.offset is None:
            raise ValueError("bitflip faults need an absolute byte offset")


@dataclass
class _Injection:
    """Log entry for one fired fault."""

    kind: str
    op: str
    path: str
    start: int
    length: int
    call: int


class FaultInjectingFileSystemWrapper(FileSystemWrapper):
    """Wraps ``inner``, injecting the ``faults`` schedule into reads.

    When registered under a scheme (``register_filesystem("fault",
    fsw)``), paths like ``fault:///data/x.bam`` are served by stripping
    the scheme and delegating to ``inner`` — so the *public* read entry
    points can be driven end-to-end through injected faults.
    """

    def __init__(
        self,
        inner: FileSystemWrapper,
        faults: Sequence[FaultSpec] = (),
        seed: int = 0,
        scheme: str = "fault",
    ) -> None:
        self.inner = inner
        self.faults = list(faults)
        self.scheme = scheme
        self._rng = random.Random(seed)
        # The parallel shard executor drives reads from worker threads:
        # the schedule's bookkeeping (call counter, per-spec matched /
        # fired counts, RNG draws) must stay consistent — racing
        # threads must not double-consume one call_index or skip a draw.
        # The inner read itself runs unlocked, so injected stalls and
        # real I/O still overlap.
        self._mutex = threading.Lock()
        self._pending_stall = 0.0            # booked under the mutex,
        self._calls = 0                      # slept outside it
        self._fired: List[int] = [0] * len(self.faults)
        self._matched: List[int] = [0] * len(self.faults)
        self.injected: List[_Injection] = []
        self._sleep = time.sleep

    # -- plumbing ----------------------------------------------------------

    def _strip(self, path: str) -> str:
        prefix = self.scheme + "://"
        return path[len(prefix):] if path.startswith(prefix) else path

    def _spec_matches(
        self, i: int, spec: FaultSpec, path: str, start: int, length: int,
        op: str = "read_range",
    ) -> bool:
        if spec.op != ("read" if op == "read_range" else "write"):
            return False
        if spec.path_substr and spec.path_substr not in path:
            return False
        if spec.offset is not None and not (
            start <= spec.offset < start + length
        ):
            return False
        if spec.times >= 0 and self._fired[i] >= spec.times:
            return False
        # Positional / probabilistic gates consume the per-spec match
        # counter and the seeded RNG — deterministic per (seed, call seq).
        idx = self._matched[i]
        self._matched[i] += 1
        if spec.call_index is not None and idx != spec.call_index:
            return False
        if spec.probability > 0.0 and self._rng.random() >= spec.probability:
            return False
        if (
            spec.probability == 0.0
            and spec.call_index is None
            and spec.offset is None
            and not spec.path_substr
        ):
            return False  # a spec must target *something*
        return True

    def _apply_faults(self, path: str, start: int, length: int,
                      data: Optional[bytes], call: int,
                      op: str = "read_range") -> Optional[bytes]:
        """Run the schedule for one call. ``data=None`` = pre-op phase
        (raise/stall); bytes = mutation phase (post-read for reads,
        pre-commit for writes — the staged bytes are damaged before
        they land)."""
        for i, spec in enumerate(self.faults):
            pre = spec.kind in ("transient", "stall", "slow")
            if pre != (data is None):
                continue
            if not self._spec_matches(i, spec, path, start, length, op):
                continue
            self._fired[i] += 1
            self.injected.append(
                _Injection(spec.kind, op, path, start, length, call)
            )
            if spec.kind == "transient":
                raise TransientIOError(
                    f"injected transient fault #{call} on {op} {path} "
                    f"[{start}, {start + length})"
                )
            if spec.kind == "stall":
                self._pending_stall += spec.stall_s
            elif spec.kind == "slow":
                # Seeded tail latency: the draw consumes the schedule
                # RNG under the mutex, so the whole latency sequence is
                # a pure function of (seed, call sequence).
                self._pending_stall += self._rng.uniform(0.0, spec.slow_s)
            elif spec.kind == "truncate" and data:
                data = data[: max(0, len(data) - spec.truncate_bytes)]
            elif spec.kind == "bitflip" and data:
                rel = spec.offset - start
                if 0 <= rel < len(data):
                    buf = bytearray(data)
                    buf[rel] ^= 1 << spec.bit
                    data = bytes(buf)
        return data

    # -- FileSystemWrapper interface --------------------------------------

    def read_range(self, path: str, start: int, length: int) -> bytes:
        real = self._strip(path)
        # Pre-read faults raise/stall; the matched-call and RNG state
        # advance exactly once per attempt, so a retry is a NEW draw.
        with self._mutex:
            self._calls += 1
            call = self._calls
            self._apply_faults(real, start, length, None, call)
            stall, self._pending_stall = self._pending_stall, 0.0
        if stall:
            # Injected latency must not serialize concurrent readers:
            # sleep outside the schedule mutex.
            self._sleep(stall)
        data = self.inner.read_range(real, start, length)
        with self._mutex:
            return self._apply_faults(real, start, length, data, call)

    def open(self, path: str) -> BinaryIO:
        # Route stream reads through read_range so every byte a caller
        # sees passes the single injection point.
        return _RangeReader(self, path)

    def exists(self, path: str) -> bool:
        return self.inner.exists(self._strip(path))

    def get_file_length(self, path: str) -> int:
        return self.inner.get_file_length(self._strip(path))

    def _pre_write_faults(self, real: str, length: int, op: str) -> None:
        """Pre-op phase for a write-side call: transient raises and
        stall booking under the mutex, sleeping outside it."""
        with self._mutex:
            self._calls += 1
            call = self._calls
            self._apply_faults(real, 0, length, None, call, op=op)
            stall, self._pending_stall = self._pending_stall, 0.0
        if stall:
            self._sleep(stall)

    def write_all(self, path: str, data: bytes) -> None:
        real = self._strip(path)
        with self._mutex:
            self._calls += 1
            call = self._calls
            self._apply_faults(real, 0, len(data), None, call,
                               op="write_all")
            # Mutation phase BEFORE the durable write: a truncate or
            # bitflip here models a partial/corrupted upload that the
            # store nevertheless committed.
            data = self._apply_faults(real, 0, len(data), data, call,
                                      op="write_all")
            stall, self._pending_stall = self._pending_stall, 0.0
        if stall:
            self._sleep(stall)
        self.inner.write_all(real, data)

    def create(self, path: str) -> BinaryIO:
        real = self._strip(path)
        self._pre_write_faults(real, 0, "create")
        return self.inner.create(real)

    def concat(self, parts, target: str) -> None:
        real = self._strip(target)
        self._pre_write_faults(real, 0, "concat")
        self.inner.concat([self._strip(p) for p in parts], real)

    def list_directory(self, path: str) -> List[str]:
        return self.inner.list_directory(self._strip(path))

    def delete(self, path: str, recursive: bool = False) -> None:
        self.inner.delete(self._strip(path), recursive)

    def mkdirs(self, path: str) -> None:
        self.inner.mkdirs(self._strip(path))

    def is_directory(self, path: str) -> bool:
        return self.inner.is_directory(self._strip(path))

    # -- introspection -----------------------------------------------------

    def fired_counts(self) -> List[Tuple[str, int]]:
        return [(s.kind, n) for s, n in zip(self.faults, self._fired)]

    def reset(self, seed: Optional[int] = None) -> None:
        """Rewind the schedule (same seed ⇒ identical fault sequence)."""
        if seed is not None:
            self._rng = random.Random(seed)
        self._calls = 0
        self._fired = [0] * len(self.faults)
        self._matched = [0] * len(self.faults)
        self.injected.clear()


class _RangeReader(io.RawIOBase):
    """Seekable read stream over ``read_range`` (mirrors
    ``fsw.http._HttpReader``): gives ``open()`` the same fault surface
    as bulk staging reads.

    Reads ahead in ``readahead``-sized chunks, like any real remote
    stream (the HTTP wrapper stages 4 MiB blocks): a sequential
    header-scan issues a handful of faultable range reads, not one per
    BGZF block — which also keeps whole-phase retries convergent under
    a sustained injected fault rate."""

    READAHEAD = 256 * 1024

    def __init__(self, fs: FaultInjectingFileSystemWrapper, path: str) -> None:
        self._fs = fs
        self._path = path
        self._pos = 0
        self._len = fs.get_file_length(path)
        self._buf = b""
        self._buf_start = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = pos
        elif whence == 1:
            self._pos += pos
        else:
            self._pos = self._len + pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._len - self._pos
        if n <= 0:
            return b""
        lo = self._pos - self._buf_start
        if 0 <= lo and lo + n <= len(self._buf):
            data = self._buf[lo: lo + n]
            self._pos += len(data)
            return data
        want = min(max(n, self.READAHEAD), self._len - self._pos)
        if want <= 0:
            return b""
        self._buf = self._fs.read_range(self._path, self._pos, want)
        self._buf_start = self._pos
        data = self._buf[:n]
        self._pos += len(data)
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)
