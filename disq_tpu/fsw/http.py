"""HTTP range-read filesystem wrapper — the remote FSW.

Replaces the reference's ``HadoopFileSystemWrapper`` remote role
(``impl/file/HadoopFileSystemWrapper.java``: Hadoop FileSystem URIs —
gs://, s3a://, hdfs:// — behind the same interface). The TPU-native
equivalent speaks plain HTTP/1.1 range requests, which is the wire
protocol every blob store exposes:

- ``http(s)://`` — used directly.
- ``gs://bucket/key`` — ``https://storage.googleapis.com/bucket/key``
  (public objects / anonymous access; authenticated access needs a
  credential signer, which this zero-egress build gates).
- ``s3://bucket/key`` — ``https://bucket.s3.amazonaws.com/key``.

Reads are served from a block cache of fixed-size ranges with an
**async prefetch** of the next block on every cache miss, so a
sequential scan (the BamSource staging pattern) always has the next
range in flight while the current one decodes. The wrapper's ``stats``
(range_requests / bytes_fetched / prefetch_issued / prefetch_hits /
cache_hits / cache_misses / cache_evictions) makes the staging
behavior observable and testable; the same events feed the telemetry
registry (``fsw.http.cache.*`` counters and the
``fsw.http.range_get`` latency histogram).

Writes are not supported (the reference writes through Hadoop's
committer; our sinks stage locally and upload out-of-band).
"""

from __future__ import annotations

import io
import os
import threading
import urllib.error
import urllib.request
from collections import OrderedDict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import BinaryIO, List, Optional, Tuple

from disq_tpu.fsw.filesystem import FileSystemWrapper
from disq_tpu.runtime.tracing import counter as _counter
from disq_tpu.runtime.tracing import (
    inject_trace_headers as _inject_trace_headers)
from disq_tpu.runtime.tracing import observe_gauge as _observe_gauge
from disq_tpu.runtime.tracing import span as _span

DEFAULT_BLOCK = 4 * 1024 * 1024
DEFAULT_CACHED_BLOCKS = 32

# Process-wide cache-capacity override installed by
# ``configure_cache_blocks`` (DisqOptions.http_cache_blocks): applied
# to every registered wrapper AND to wrappers constructed later.
_configured_cache_blocks: Optional[int] = None


def _default_cache_blocks() -> int:
    """Capacity resolution for a wrapper built without an explicit
    ``max_cached_blocks``: the options-installed override, then
    ``DISQ_TPU_HTTP_CACHE_BLOCKS``, then the built-in 32."""
    if _configured_cache_blocks is not None:
        return _configured_cache_blocks
    raw = os.environ.get("DISQ_TPU_HTTP_CACHE_BLOCKS")
    if raw:
        try:
            n = int(raw)
            if n >= 1:
                return n
        except ValueError:
            pass
    return DEFAULT_CACHED_BLOCKS


def configure_cache_blocks(n: int) -> None:
    """Size the HTTP block-LRU process-wide (``DisqOptions.
    http_cache_blocks`` plumbing): updates every registered HTTP
    wrapper (including ones wrapped by the fault injector) and becomes
    the default for wrappers constructed later."""
    global _configured_cache_blocks
    n = int(n)
    if n < 1:
        raise ValueError(f"http cache capacity must be >= 1, got {n}")
    _configured_cache_blocks = n
    from disq_tpu.fsw import filesystem

    for fs in list(filesystem._SCHEME_REGISTRY.values()):
        inner = getattr(fs, "inner", fs)
        if isinstance(inner, HttpFileSystemWrapper):
            inner.set_max_cached_blocks(n)


def rewrite_remote_uri(path: str) -> str:
    """gs:// and s3:// → their public HTTP endpoints."""
    if path.startswith("gs://"):
        return "https://storage.googleapis.com/" + path[len("gs://"):]
    if path.startswith("s3://"):
        bucket, _, key = path[len("s3://"):].partition("/")
        return f"https://{bucket}.s3.amazonaws.com/{key}"
    return path


class _Stats:
    __slots__ = ("range_requests", "bytes_fetched", "prefetch_hits",
                 "prefetch_issued", "retries", "cache_hits",
                 "cache_misses", "cache_evictions")

    def __init__(self) -> None:
        self.range_requests = 0
        self.bytes_fetched = 0
        self.prefetch_hits = 0
        self.prefetch_issued = 0
        self.retries = 0
        # Block-LRU efficacy (mirrored as fsw.http.cache.* telemetry
        # counters): a hit is a ``_block`` call served from cached
        # bytes or a completed prefetch; a miss pays an inline fetch;
        # an eviction drops one completed block from the LRU head.
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0


class HttpFileSystemWrapper(FileSystemWrapper):
    """Read-only remote FSW over HTTP range requests."""

    def __init__(self, block_size: int = DEFAULT_BLOCK,
                 prefetch: bool = True,
                 max_cached_blocks: Optional[int] = None) -> None:
        self.block_size = block_size
        self.prefetch = prefetch
        # None ⇒ DisqOptions.http_cache_blocks override, then the
        # DISQ_TPU_HTTP_CACHE_BLOCKS env knob, then 32 — operators size
        # the LRU to the workload, and the scheduler's locality scorer
        # reads occupancy off the fsw.http.cache.blocks gauge.
        self.max_cached_blocks = (int(max_cached_blocks)
                                  if max_cached_blocks is not None
                                  else _default_cache_blocks())
        self.stats = _Stats()
        # Canonical thread naming: the sampling profiler
        # (runtime/profiler.py) and py-spy both attribute samples by
        # disq-* thread names, so an anonymous pool would profile as
        # "other".
        self._pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="disq-http-prefetch")
        self._lock = threading.Lock()
        # (url, block_index) -> bytes or in-flight Future; LRU-bounded
        # (the wrapper is process-global via the scheme registry, so an
        # unbounded cache would retain a whole remote file)
        self._cache: "OrderedDict[Tuple[str, int], object]" = OrderedDict()
        self._lengths: dict = {}

    def set_max_cached_blocks(self, n: int) -> None:
        """Resize the block LRU; shrinking trims completed blocks from
        the LRU head immediately (in-flight prefetches are never
        dropped, exactly like steady-state eviction)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"http cache capacity must be >= 1, got {n}")
        with self._lock:
            self.max_cached_blocks = n
            for old_key in list(self._cache):
                if len(self._cache) <= n:
                    break
                old = self._cache[old_key]
                if isinstance(old, Future) and not old.done():
                    continue
                self._cache.pop(old_key)
                self.stats.cache_evictions += 1
                _counter("fsw.http.cache.evictions").inc()
            _observe_gauge("fsw.http.cache.blocks", len(self._cache))

    def cached_block_indices(self, path: str) -> List[int]:
        """The completed block indices this cache holds for ``path`` —
        the occupancy a scheduler worker reports in its lease request
        so shards land on the host whose cache already covers their
        byte range (``runtime/scheduler.py`` locality scoring)."""
        url = rewrite_remote_uri(path)
        with self._lock:
            return sorted(idx for (u, idx), v in self._cache.items()
                          if u == url and isinstance(v, bytes))

    def cached_block_ranges(self, path: str) -> List[Tuple[int, int]]:
        """Coalesced ``(lo, hi)`` byte ranges of the completed blocks
        this cache holds for ``path`` — the ``(path, coffset range)``
        form of :meth:`cached_block_indices` that the fleet tier's
        cache digests key by, with adjacent blocks merged so a warm
        contiguous region reads as one range."""
        ranges: List[Tuple[int, int]] = []
        for idx in self.cached_block_indices(path):
            lo = idx * self.block_size
            hi = lo + self.block_size
            if ranges and ranges[-1][1] == lo:
                ranges[-1] = (ranges[-1][0], hi)
            else:
                ranges.append((lo, hi))
        return ranges

    def _cache_put(self, key, value) -> None:
        # caller holds self._lock
        self._cache[key] = value
        self._cache.move_to_end(key)
        if len(self._cache) <= self.max_cached_blocks:
            _observe_gauge("fsw.http.cache.blocks", len(self._cache))
            return
        # Evict from the LRU head, *skipping* (never dropping) in-flight
        # prefetches: an in-flight Future at the head must not shield
        # completed blocks behind it from eviction, or the cache grows
        # past max_cached_blocks for as long as fetches stall.
        for old_key in list(self._cache):
            if len(self._cache) <= self.max_cached_blocks:
                break
            if old_key == key:
                # With everything older in flight, the walk reaches the
                # entry just inserted — evicting it would refetch the
                # block on the very next read. Let the cache run over by
                # the in-flight count instead.
                continue
            old = self._cache[old_key]
            if isinstance(old, Future) and not old.done():
                continue  # never drop an in-flight prefetch
            self._cache.pop(old_key)
            self.stats.cache_evictions += 1
            _counter("fsw.http.cache.evictions").inc()
        _observe_gauge("fsw.http.cache.blocks", len(self._cache))

    # -- plumbing ----------------------------------------------------------

    _RETRIES = 3          # transient-failure retries (5xx / network)
    _BACKOFF_S = 0.1      # doubled per attempt
    _TIMEOUT_S = 60.0     # per-request; a stalled connection must fail
                          # into the retry loop, not hang a worker

    def _retrying(self, op):
        """Run ``op()`` under the read stack's shared transient
        classification and bounded backoff
        (``runtime.errors.ShardRetrier`` / ``is_transient``) — one
        definition of "transient", shared by ranged GETs and HEADs.
        Client errors (4xx) raise immediately; 5xx, network errors and
        stalls back off and retry; the last transient error surfaces
        once the budget is spent.

        When the resilience layer has breakers armed
        (``DisqOptions.breaker_window``), every HTTP request is gated
        by the ``http`` filesystem's circuit breaker: a fault storm
        trips it, and subsequent requests fail fast with
        ``BreakerOpenError`` instead of stacking timeouts.  Each retry
        also draws from the shared retry budget (both through the
        retrier — no breaker configured means no extra work here)."""
        from disq_tpu.runtime.errors import ShardRetrier
        from disq_tpu.runtime.resilience import breaker_for

        retrier = ShardRetrier(self._RETRIES, self._BACKOFF_S,
                               breaker=breaker_for("http://"))
        try:
            return retrier.call(op, what="http")
        finally:
            if retrier.retried:
                with self._lock:
                    self.stats.retries += retrier.retried

    # A server that ignores Range replies 200 with the body from byte 0;
    # bytes up to ``end_incl`` must be read regardless (HTTP streams
    # can't seek) but everything past it is pure slack — read at most
    # this many blocks of it (they seed the cache), then abandon the
    # connection instead of buffering a possibly-multi-GB object on
    # every attempt.
    _FULL_READ_SLACK_BLOCKS = 32

    def _fetch(self, url: str, start: int, end_incl: int) -> bytes:
        """One ranged GET via ``_retrying``. A server ignoring Range
        (200 with the whole object) is stream-read to a bounded prefix
        — the requested range plus ``_FULL_READ_SLACK_BLOCKS`` blocks —
        sliced, accounted at its REAL transfer size, and seeds the
        block cache so a scan doesn't re-download the object per
        block."""
        def ranged_get():
            req = urllib.request.Request(
                url, headers=_inject_trace_headers(
                    {"Range": f"bytes={start}-{end_incl}"}))
            with urllib.request.urlopen(
                    req, timeout=self._TIMEOUT_S) as resp:
                if resp.status != 200:  # 206: the server honored Range
                    return resp.read(), None
                cap = (end_incl + 1
                       + self._FULL_READ_SLACK_BLOCKS * self.block_size)
                chunks: List[bytes] = []
                got = 0
                while got < cap:
                    chunk = resp.read(min(1 << 20, cap - got))
                    if not chunk:
                        break
                    chunks.append(chunk)
                    got += len(chunk)
                full = b"".join(chunks)
                return full[start: end_incl + 1], full

        with _span("fsw.http.range_get", start=start, end=end_incl):
            data, full = self._retrying(ranged_get)
        if full is not None:
            bs = self.block_size
            want = start // bs
            total = self._lengths.get(url)
            with self._lock:
                self.stats.range_requests += 1
                self.stats.bytes_fetched += len(full)
                for bi in range((len(full) + bs - 1) // bs):
                    blk = full[bi * bs: (bi + 1) * bs]
                    # Only complete blocks may seed the cache: the
                    # capped prefix can end mid-block, and a short
                    # cached block would silently truncate later reads.
                    complete = len(blk) == bs or (
                        total is not None and (bi + 1) * bs >= total)
                    if bi != want and complete:
                        self._cache_put((url, bi), blk)
                # the requested block last, so LRU keeps it
                want_blk = full[want * bs: (want + 1) * bs]
                if len(want_blk) == bs or (
                        total is not None and (want + 1) * bs >= total):
                    self._cache_put((url, want), want_blk)
        else:
            with self._lock:
                self.stats.range_requests += 1
                self.stats.bytes_fetched += len(data)
        return data

    def _block(self, url: str, idx: int, length: int) -> bytes:
        key = (url, idx)
        with self._lock:
            entry = self._cache.get(key)
            if entry is not None:
                self._cache.move_to_end(key)
        if isinstance(entry, bytes):
            with self._lock:
                self.stats.cache_hits += 1
            _counter("fsw.http.cache.hits").inc()
            return entry
        if isinstance(entry, Future):
            try:
                data = entry.result()
            except Exception:
                # transient prefetch failure must not poison the block:
                # drop the future and fetch inline (which may raise a
                # fresh, retryable error)
                with self._lock:
                    if self._cache.get(key) is entry:
                        self._cache.pop(key)
                entry = None
            else:
                with self._lock:
                    self._cache_put(key, data)
                    self.stats.prefetch_hits += 1
                    self.stats.cache_hits += 1
                _counter("fsw.http.cache.hits").inc()
                return data
        with self._lock:
            self.stats.cache_misses += 1
        _counter("fsw.http.cache.misses").inc()
        start = idx * self.block_size
        end = min(start + self.block_size, length) - 1
        data = self._fetch(url, start, end)
        with self._lock:
            self._cache_put(key, data)
        # async prefetch of the NEXT block while the caller decodes
        nxt = idx + 1
        if self.prefetch and nxt * self.block_size < length:
            nkey = (url, nxt)
            with self._lock:
                if nkey not in self._cache:
                    ns = nxt * self.block_size
                    ne = min(ns + self.block_size, length) - 1
                    self._cache_put(nkey, self._pool.submit(
                        self._fetch, url, ns, ne))
                    self.stats.prefetch_issued += 1
        return data

    # -- FileSystemWrapper interface --------------------------------------

    def exists(self, path: str) -> bool:
        """HEAD through the same ``_retrying`` timeout + transient-retry
        discipline as ``_fetch``: a stalled or 5xx HEAD must not hang a
        worker or misreport a live object as missing."""
        url = rewrite_remote_uri(path)
        req = urllib.request.Request(
            url, headers=_inject_trace_headers({}), method="HEAD")

        def head():
            with urllib.request.urlopen(
                    req, timeout=self._TIMEOUT_S) as resp:
                return resp.headers.get("Content-Length")

        try:
            clen = self._retrying(head)
        except urllib.error.HTTPError as e:
            # S3 answers 403 for missing keys without list permission
            if e.code in (403, 404):
                return False
            raise
        if clen is None:
            # a length-less HEAD would make every read clamp to b"" — a
            # deterministic protocol defect, not transient: fail loudly
            raise IOError(
                f"HEAD {url} returned no Content-Length; "
                "range staging needs a sized object")
        self._lengths[url] = int(clen)
        return True

    def get_file_length(self, path: str) -> int:
        url = rewrite_remote_uri(path)
        if url not in self._lengths:
            if not self.exists(path):
                raise FileNotFoundError(path)
        return self._lengths[url]

    def read_range(self, path: str, start: int, length: int) -> bytes:
        url = rewrite_remote_uri(path)
        total = self.get_file_length(path)
        end = min(start + length, total)
        if end <= start:
            return b""
        first = start // self.block_size
        last = (end - 1) // self.block_size
        parts: List[bytes] = []
        for idx in range(first, last + 1):
            blk = self._block(url, idx, total)
            lo = max(start - idx * self.block_size, 0)
            hi = min(end - idx * self.block_size, len(blk))
            parts.append(blk[lo:hi])
        return b"".join(parts)

    def open(self, path: str) -> BinaryIO:
        return _HttpReader(self, path)

    def create(self, path: str) -> BinaryIO:
        raise NotImplementedError(
            "remote HTTP filesystem is read-only; sinks stage locally")

    def list_directory(self, path: str) -> List[str]:
        raise NotImplementedError(
            "HTTP has no directory listing; pass explicit object paths")

    def is_directory(self, path: str) -> bool:
        return False

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError("remote HTTP filesystem is read-only")

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError("remote HTTP filesystem is read-only")


class _HttpReader(io.RawIOBase):
    """Seekable read stream over the block cache (htsjdk-style usage:
    seek to a virtual offset's coffset, stream forward)."""

    def __init__(self, fs: HttpFileSystemWrapper, path: str) -> None:
        self._fs = fs
        self._path = path
        self._pos = 0
        self._len = fs.get_file_length(path)

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = pos
        elif whence == 1:
            self._pos += pos
        else:
            self._pos = self._len + pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._len - self._pos
        data = self._fs.read_range(self._path, self._pos, n)
        self._pos += len(data)
        return data

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[: len(data)] = data
        return len(data)
