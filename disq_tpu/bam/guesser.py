"""BAM record-boundary guessing inside decompressed BGZF data.

Reference parity: ``impl/formats/bam/BamRecordGuesser.java`` (descendant
of Hadoop-BAM's ``BAMSplitGuesser``): given an arbitrary position in
decompressed data, decide whether it begins a real BAM record by
structural validation — ``refID``/``next_refID`` ∈ [-1, n_ref), ``pos``
∈ [-1, ref_len), ``l_read_name`` ≥ 1 with NUL at the claimed length,
CIGAR op codes < 9, component lengths consistent with ``block_size`` —
then chain-check the following records so false positives die
geometrically.

TPU-first shape: the cheap per-candidate rejects run as one vectorized
numpy pass over all candidate offsets (the validity-mask formulation from
SURVEY.md §7 step 3); only survivors pay the sequential chain check.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

_FIXED = 32
# A sane upper bound on one record's size (long-read BAMs stay far under
# this; disq bounds its scan window similarly).
MAX_BLOCK_SIZE = 1 << 26
CHAIN_RECORDS = 10


class BamRecordGuesser:
    def __init__(self, n_ref: int, ref_lengths: Optional[Sequence[int]] = None):
        self.n_ref = n_ref
        self.ref_lengths = (
            np.asarray(ref_lengths, dtype=np.int64) if ref_lengths is not None else None
        )

    # -- single-candidate validation ---------------------------------------

    def looks_like_record(
        self, buf: np.ndarray, c: int, allow_partial: bool = False
    ) -> bool:
        """Structural validation of a candidate record start at ``c``.

        With ``allow_partial`` (used for the record straddling the end of
        a bounded window), every *visible* byte must still satisfy its
        constraint — a partially visible record is never accepted blindly.
        """
        end = len(buf)
        if c + 4 + _FIXED > end:
            if not allow_partial:
                return False
            return self._visible_prefix_ok(buf, c)
        block_size = int(buf[c:c + 4].view("<i4")[0])
        if not (_FIXED <= block_size < MAX_BLOCK_SIZE):
            return False
        refid = int(buf[c + 4:c + 8].view("<i4")[0])
        pos = int(buf[c + 8:c + 12].view("<i4")[0])
        if not (-1 <= refid < self.n_ref) or pos < -1:
            return False
        if (
            self.ref_lengths is not None
            and 0 <= refid < len(self.ref_lengths)
            and pos >= int(self.ref_lengths[refid])
        ):
            return False
        l_read_name = int(buf[c + 12])
        if l_read_name < 1:
            return False
        n_cigar = int(buf[c + 16:c + 18].view("<u2")[0])
        l_seq = int(buf[c + 20:c + 24].view("<i4")[0])
        if l_seq < 0:
            return False
        next_refid = int(buf[c + 24:c + 28].view("<i4")[0])
        next_pos = int(buf[c + 28:c + 32].view("<i4")[0])
        if not (-1 <= next_refid < self.n_ref) or next_pos < -1:
            return False
        sections = _FIXED + l_read_name + 4 * n_cigar + (l_seq + 1) // 2 + l_seq
        if sections > block_size:
            return False
        if not allow_partial and c + 4 + block_size > end:
            return False
        # Name NUL-terminated exactly at its claimed length.
        name_end = c + 4 + _FIXED + l_read_name - 1
        if name_end < end and int(buf[name_end]) != 0:
            return False
        # CIGAR op codes must be < 9 (ops MIDNSHP=X).
        cig_start = c + 4 + _FIXED + l_read_name
        cig_end = min(cig_start + 4 * n_cigar, end)
        if cig_end > cig_start:
            ops = buf[cig_start:cig_end]
            n_whole = (cig_end - cig_start) // 4
            if n_whole and (ops[: 4 * n_whole].view("<u4") & 0xF > 8).any():
                return False
        return True

    def _visible_prefix_ok(self, buf: np.ndarray, c: int) -> bool:
        """Validate the visible bytes of a record whose 36-byte prefix is
        cut off by the window end. Checks every field whose bytes are
        fully visible; returns False on any contradiction."""
        end = len(buf)
        if c + 4 <= end:
            block_size = int(buf[c:c + 4].view("<i4")[0])
            if not (_FIXED <= block_size < MAX_BLOCK_SIZE):
                return False
        if c + 8 <= end:
            refid = int(buf[c + 4:c + 8].view("<i4")[0])
            if not (-1 <= refid < self.n_ref):
                return False
        if c + 12 <= end:
            pos = int(buf[c + 8:c + 12].view("<i4")[0])
            if pos < -1:
                return False
        if c + 13 <= end and int(buf[c + 12]) < 1:
            return False
        if c + 24 <= end and int(buf[c + 20:c + 24].view("<i4")[0]) < 0:
            return False
        if c + 28 <= end:
            next_refid = int(buf[c + 24:c + 28].view("<i4")[0])
            if not (-1 <= next_refid < self.n_ref):
                return False
        if c + 32 <= end and int(buf[c + 28:c + 32].view("<i4")[0]) < -1:
            return False
        return True

    def check_chain(self, buf: np.ndarray, c: int, depth: int = CHAIN_RECORDS) -> bool:
        """Validate ``depth`` successive records from ``c``. A chain that
        runs off the window is accepted only if the straddling record's
        visible bytes validate."""
        end = len(buf)
        pos = c
        for _ in range(depth):
            if pos == end:
                return True
            if not self.looks_like_record(buf, pos, allow_partial=True):
                return False
            if pos + 4 > end:
                return True  # block_size itself not visible; prefix held
            block_size = int(buf[pos:pos + 4].view("<i4")[0])
            if pos + 4 + block_size > end:
                return True  # straddles the window; visible bytes held
            pos += 4 + block_size
        return True

    # -- search -------------------------------------------------------------

    def find_first_record(self, buf: np.ndarray) -> Optional[int]:
        """Offset of the first real record boundary in ``buf``, or None.

        Vectorized prefilter: refID and next_refID windows, l_read_name,
        block_size bounds — then chain-validate survivors in order.
        """
        buf = np.ascontiguousarray(buf)
        n = len(buf)
        if n < 4 + _FIXED:
            return None
        limit = n - (4 + _FIXED) + 1
        i32 = np.lib.stride_tricks.sliding_window_view(buf, 4).view("<i4").ravel()

        def at(off):  # i32 value at byte offset c+off for all candidates
            return i32[off: off + limit]

        cand = (
            (at(4) >= -1) & (at(4) < self.n_ref)
            & (at(24) >= -1) & (at(24) < self.n_ref)
            & (at(8) >= -1) & (at(28) >= -1)
            & (at(0) >= _FIXED) & (at(0) < MAX_BLOCK_SIZE)
            & (buf[12:12 + limit] >= 1)
            & (at(20) >= 0)
        )
        for c in np.nonzero(cand)[0]:
            c = int(c)
            if self.looks_like_record(buf, c) and self.check_chain(buf, c):
                return c
        return None
