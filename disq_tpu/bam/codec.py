"""BAM record codec: record bytes ⇄ columnar ``ReadBatch``.

Replaces htsjdk's ``BAMRecordCodec`` (SURVEY.md §2.8) with a two-pass
vectorized design (the same shape as the planned Pallas parse kernel,
SURVEY.md §7 step 3):

  pass 1 — walk the ``block_size`` chain to produce the record-offset
  vector (sequential by nature; lives on host, with a C++ fast path in
  ``disq_tpu.native`` when built);

  pass 2 — all field extraction is vectorized numpy over the whole blob:
  fixed columns come from one strided gather, ragged columns (name /
  cigar / seq / qual / tags) from segment gathers whose index arithmetic
  is derived from the fixed columns. No per-record Python loop.

BAM record layout after the 4-byte ``block_size`` (SAM spec §4.2):
refID i32 · pos i32 · l_read_name u8 · mapq u8 · bin u16 · n_cigar_op u16
· flag u16 · l_seq i32 · next_refID i32 · next_pos i32 · tlen i32 (32 B
fixed) · read_name (l_read_name, NUL-terminated) · cigar (4·n_cigar_op) ·
seq ((l_seq+1)/2 packed nibbles) · qual (l_seq) · tags (to end).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from disq_tpu.bam.columnar import ReadBatch

_FIXED = 32  # bytes after block_size


def scan_record_offsets(blob: bytes | np.ndarray, base: int = 0) -> np.ndarray:
    """Pass 1: offsets of every record's ``block_size`` field in ``blob``,
    starting at ``base``; returns ``(N+1,)`` int64 (last = end offset).

    Sequential chain walk; prefers the native C++ scanner when available.
    """
    buf = np.asarray(memoryview(blob), dtype=np.uint8) if not isinstance(blob, np.ndarray) else blob
    try:
        from disq_tpu.native import scan_bam_offsets_native

        # Match the Python fallback's semantics exactly: scanning starts
        # AT `base` (bytes before it are not part of the record chain).
        return scan_bam_offsets_native(buf[base:] if base else buf, base)
    except ImportError:
        pass
    offsets = _walk_record_chain(buf, base, strict=True)
    return np.asarray(offsets, dtype=np.int64)


def scan_record_offsets_tolerant(blob: bytes | np.ndarray) -> np.ndarray:
    """``scan_record_offsets`` for a buffer whose *tail* may be cut off
    (the run of good blocks before a skipped corrupt block): walks the
    ``block_size`` chain and stops cleanly at the last complete record
    instead of raising — the partial straddler is dropped by policy.

    A chain link that is structurally impossible (``block_size`` below
    the fixed section) still stops the walk rather than raising: under
    skip/quarantine the caller keeps what decoded cleanly.
    """
    buf = (
        np.asarray(memoryview(blob), dtype=np.uint8)
        if not isinstance(blob, np.ndarray)
        else blob
    )
    return np.asarray(_walk_record_chain(buf, 0, strict=False),
                      dtype=np.int64)


def _walk_record_chain(buf: np.ndarray, base: int, strict: bool) -> list:
    """The sequential ``block_size`` chain walk shared by the strict and
    tolerant scanners: strict raises on an impossible link or trailing
    garbage, tolerant stops at the last complete record."""
    end = len(buf)
    offsets = [base]
    pos = base
    # int.from_bytes over a memoryview is the fastest pure-Python path.
    mv = memoryview(buf)
    while pos + 4 <= end:
        block_size = int.from_bytes(mv[pos: pos + 4], "little")
        nxt = pos + 4 + block_size
        if block_size < _FIXED or nxt > end:
            if strict:
                raise ValueError(
                    f"corrupt BAM record at offset {pos}: "
                    f"block_size={block_size}"
                )
            break
        offsets.append(nxt)
        pos = nxt
    if strict and pos != end:
        raise ValueError(f"trailing garbage after records: {end - pos} bytes")
    return offsets


def decode_records(
    blob: bytes | np.ndarray,
    offsets: Optional[np.ndarray] = None,
    n_ref: Optional[int] = None,
) -> ReadBatch:
    """Pass 2: vectorized field extraction into a ``ReadBatch``."""
    buf = (
        np.frombuffer(blob, dtype=np.uint8)
        if not isinstance(blob, np.ndarray)
        else blob
    )
    if offsets is None:
        offsets = scan_record_offsets(buf)
    offsets = offsets.astype(np.int64)
    n = len(offsets) - 1
    if n == 0:
        return ReadBatch.empty()

    try:
        from disq_tpu.native import decode_records_native

        cols = decode_records_native(buf, offsets)
        _check_refids(cols["refid"], cols["next_refid"], n_ref)
        return ReadBatch(**cols)
    except ImportError:
        pass

    starts = offsets[:-1]
    # One strided gather pulls every record's 4+32-byte prefix as (N, 36).
    fixed = buf[starts[:, None] + np.arange(4 + _FIXED)]
    as_i32 = fixed.view("<i4")      # (N, 9)
    as_u16 = fixed.view("<u2")      # (N, 18)
    refid = as_i32[:, 1].copy()
    pos = as_i32[:, 2].copy()
    l_read_name = fixed[:, 12].astype(np.int64)
    mapq = fixed[:, 13].copy()
    bin_ = as_u16[:, 7].copy()
    n_cigar = as_u16[:, 8].astype(np.int64)
    flag = as_u16[:, 9].copy()
    l_seq = as_i32[:, 5].astype(np.int64)
    next_refid = as_i32[:, 6].copy()
    next_pos = as_i32[:, 7].copy()
    tlen = as_i32[:, 8].copy()

    _check_refids(refid, next_refid, n_ref)

    # Section start offsets, derived arithmetically from the fixed columns.
    name_start = starts + 4 + _FIXED
    cigar_start = name_start + l_read_name
    seq_start = cigar_start + 4 * n_cigar
    n_seq_bytes = (l_seq + 1) // 2
    qual_start = seq_start + n_seq_bytes
    tag_start = qual_start + l_seq
    rec_end = offsets[1:]
    if (tag_start > rec_end).any():
        i = int(np.nonzero(tag_start > rec_end)[0][0])
        raise ValueError(f"record {i}: sections exceed block_size")

    # Names (drop the NUL terminator).
    name_len = l_read_name - 1
    names, name_off = _ragged_gather(buf, name_start, name_len)

    # CIGAR: gather bytes then view as u32 op-words.
    cigar_bytes, _ = _ragged_gather(buf, cigar_start, 4 * n_cigar)
    cigars = cigar_bytes.view("<u4").copy() if len(cigar_bytes) else np.zeros(0, np.uint32)
    cigar_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(n_cigar, out=cigar_off[1:])

    # Seq: gather packed bytes, then unpack nibbles (hi first).
    packed, packed_off = _ragged_gather(buf, seq_start, n_seq_bytes)
    seq_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(l_seq, out=seq_off[1:])
    total_bases = int(seq_off[-1])
    seqs = np.zeros(total_bases, dtype=np.uint8)
    if total_bases:
        # For base k of record i: byte = packed[packed_off[i] + k//2],
        # hi nibble when k even.
        seg = np.repeat(np.arange(n), l_seq)
        within = np.arange(total_bases, dtype=np.int64) - seq_off[seg]
        byte_idx = packed_off[seg] + within // 2
        vals = packed[byte_idx]
        seqs = np.where(within % 2 == 0, vals >> 4, vals & 0xF).astype(np.uint8)

    quals, _ = _ragged_gather(buf, qual_start, l_seq)
    tags, tag_off = _ragged_gather(buf, tag_start, rec_end - tag_start)

    return ReadBatch(
        refid=refid, pos=pos, mapq=mapq, bin=bin_, flag=flag,
        next_refid=next_refid, next_pos=next_pos, tlen=tlen,
        name_offsets=name_off, names=names,
        cigar_offsets=cigar_off, cigars=cigars,
        seq_offsets=seq_off, seqs=seqs, quals=quals,
        tag_offsets=tag_off, tags=tags,
    )


def _check_refids(refid, next_refid, n_ref) -> None:
    if n_ref is None:
        return
    bad = (refid >= n_ref) | (refid < -1) | (next_refid >= n_ref) | (next_refid < -1)
    if bad.any():
        i = int(np.nonzero(bad)[0][0])
        raise ValueError(f"record {i}: refID out of range ({refid[i]})")


def _ragged_gather(
    buf: np.ndarray, starts: np.ndarray, lens: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Gather per-record byte ranges into (flat, offsets)."""
    lens = np.maximum(lens, 0)
    off = np.zeros(len(starts) + 1, dtype=np.int64)
    np.cumsum(lens, out=off[1:])
    total = int(off[-1])
    if total == 0:
        return np.zeros(0, dtype=buf.dtype), off
    seg = np.repeat(np.arange(len(starts)), lens)
    within = np.arange(total, dtype=np.int64) - off[seg]
    return buf[starts[seg] + within], off


def encode_records(batch: ReadBatch) -> bytes:
    """Columnar batch → concatenated BAM record bytes (vectorized scatter).

    Byte-identical round trip with ``decode_records`` (the ``bin`` column
    is preserved verbatim; seq nibble padding is zero as per spec).
    """
    return encode_records_with_offsets(batch)[0]


def encode_records_with_offsets(batch: ReadBatch) -> tuple[bytes, np.ndarray]:
    """Like ``encode_records`` but also returns the ``(N+1,)`` record
    byte-offset vector — the input to virtual-offset / index computation
    (single source of truth for the record-size arithmetic)."""
    n = batch.count
    if n == 0:
        return b"", np.zeros(1, dtype=np.int64)
    try:
        from disq_tpu.native import encode_records_native

        return encode_records_native(batch)
    except ImportError:
        pass
    name_len = np.diff(batch.name_offsets)
    if (name_len > 254).any():
        i = int(np.nonzero(name_len > 254)[0][0])
        raise ValueError(
            f"record {i}: read name of {int(name_len[i])} bytes exceeds the "
            "BAM limit of 254 (l_read_name is u8 incl. NUL)"
        )
    n_cigar_check = np.diff(batch.cigar_offsets)
    if (n_cigar_check > 0xFFFF).any():
        i = int(np.nonzero(n_cigar_check > 0xFFFF)[0][0])
        raise ValueError(
            f"record {i}: {int(n_cigar_check[i])} CIGAR ops exceeds the BAM "
            "field limit of 65535 (n_cigar_op is u16; the SAM-spec CG-tag "
            "spill is not implemented yet)"
        )
    n_cigar = np.diff(batch.cigar_offsets)
    l_seq = np.diff(batch.seq_offsets)
    tag_len = np.diff(batch.tag_offsets)
    n_seq_bytes = (l_seq + 1) // 2
    block_size = _FIXED + (name_len + 1) + 4 * n_cigar + n_seq_bytes + l_seq + tag_len
    rec_starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(4 + block_size, out=rec_starts[1:])
    out = np.zeros(int(rec_starts[-1]), dtype=np.uint8)

    fixed = np.zeros((n, 4 + _FIXED), dtype=np.uint8)
    fi32 = fixed.view("<i4")
    fu16 = fixed.view("<u2")
    fi32[:, 0] = block_size
    fi32[:, 1] = batch.refid
    fi32[:, 2] = batch.pos
    fixed[:, 12] = (name_len + 1).astype(np.uint8)
    fixed[:, 13] = batch.mapq
    fu16[:, 7] = batch.bin
    fu16[:, 8] = n_cigar.astype(np.uint16)
    fu16[:, 9] = batch.flag
    fi32[:, 5] = l_seq
    fi32[:, 6] = batch.next_refid
    fi32[:, 7] = batch.next_pos
    fi32[:, 8] = batch.tlen
    out[rec_starts[:-1, None] + np.arange(4 + _FIXED)] = fixed

    name_start = rec_starts[:-1] + 4 + _FIXED
    _ragged_scatter(out, name_start, batch.names, batch.name_offsets)
    # NUL terminators land one past each name.
    out[name_start + name_len] = 0

    cigar_start = name_start + name_len + 1
    cigar_bytes = batch.cigars.view(np.uint8) if len(batch.cigars) else np.zeros(0, np.uint8)
    _ragged_scatter(out, cigar_start, cigar_bytes, batch.cigar_offsets * 4)

    seq_start = cigar_start + 4 * n_cigar
    total_bases = int(batch.seq_offsets[-1])
    if total_bases:
        packed_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(n_seq_bytes, out=packed_off[1:])
        packed = np.zeros(int(packed_off[-1]), dtype=np.uint8)
        seg = np.repeat(np.arange(n), l_seq)
        within = np.arange(total_bases, dtype=np.int64) - batch.seq_offsets[seg]
        byte_idx = packed_off[seg] + within // 2
        hi = within % 2 == 0
        np.bitwise_or.at(
            packed, byte_idx,
            np.where(hi, batch.seqs << 4, batch.seqs & 0xF).astype(np.uint8),
        )
        _ragged_scatter(out, seq_start, packed, packed_off)

    qual_start = seq_start + n_seq_bytes
    _ragged_scatter(out, qual_start, batch.quals, batch.seq_offsets)

    tag_start = qual_start + l_seq
    _ragged_scatter(out, tag_start, batch.tags, batch.tag_offsets)
    return out.tobytes(), rec_starts


def _ragged_scatter(
    out: np.ndarray, dst_starts: np.ndarray, flat: np.ndarray, offsets: np.ndarray
) -> None:
    """Scatter ragged segments i (given by offsets) to ``dst_starts[i]``."""
    offsets = offsets.astype(np.int64)
    lens = np.diff(offsets)
    total = int(offsets[-1] - offsets[0])
    if total == 0:
        return
    n = len(lens)
    seg = np.repeat(np.arange(n), lens)
    within = np.arange(len(flat) - int(offsets[0]), dtype=np.int64)
    within = within - (offsets[seg] - offsets[0])
    out[dst_starts[seg] + within] = flat[int(offsets[0]):]
