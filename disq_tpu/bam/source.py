"""BamSource — the parallel BAM read path.

Reference parity: ``impl/formats/bam/BamSource.java`` (SURVEY.md §2.4,
call stack §3.1): header read on the host ("driver"); the file is cut
into byte-range splits; each split resolves its first whole-record
boundary — via the ``.sbi`` splitting index when present, else the
``BgzfBlockGuesser`` + ``BamRecordGuesser`` chain — and decodes records
from its own boundary up to the *next* split's boundary, reading past its
byte-range end to finish the straddling record ("first owner" rule).

TPU-first shape: each split yields a columnar ``ReadBatch`` (not record
objects); split workers are host-side and feed device shards. Interval
traversal (``.bai``) lives in ``disq_tpu.traversal``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from disq_tpu.bam.codec import decode_records, scan_record_offsets
from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.bam.guesser import BamRecordGuesser
from disq_tpu.bam.header import SamHeader
from disq_tpu.bgzf.block import BGZF_EOF_MARKER, make_virtual_offset
from disq_tpu.bgzf.codec import BgzfReader, inflate_blocks
from disq_tpu.bgzf.guesser import BgzfBlockGuesser, _walk_blocks_collect
from disq_tpu.fsw.filesystem import (
    FileSystemWrapper,
    PathSplit,
    compute_path_splits,
    resolve_path,
)
from disq_tpu.index.sbi import SbiIndex


def read_header(fs: FileSystemWrapper, path: str) -> Tuple[SamHeader, int]:
    """Host-side header read; returns (header, virtual offset of the first
    record) — the analogue of ``AbstractSamSource#getFileHeader``."""
    with fs.open(path) as raw:
        r = BgzfReader(raw)
        header = SamHeader.from_bam_stream(r)
        return header, r.tell_virtual()


class BamSource:
    def __init__(self, storage=None):
        self._storage = storage
        self._last_counters = []

    @property
    def split_size(self) -> int:
        return getattr(self._storage, "_split_size", 128 * 1024 * 1024)

    # -- public -------------------------------------------------------------

    def get_reads(self, path: str, traversal=None):
        from disq_tpu.api import ReadsDataset
        from disq_tpu.runtime import (
            check_read_batch,
            debug_enabled,
            reduce_counters,
            trace_phase,
        )

        fs, path = resolve_path(path)
        with trace_phase("bam.read.header"):
            header, first_voffset = read_header(fs, path)
        if traversal is not None:
            from disq_tpu.traversal.bai_query import read_with_traversal

            with trace_phase("bam.read.traversal"):
                batch = read_with_traversal(fs, path, header, traversal, self)
            return ReadsDataset(header=header, reads=batch)
        with trace_phase("bam.read.splits"):
            batches = self.read_split_batches(fs, path, header, first_voffset)
            batch = ReadBatch.concat(batches)
        if debug_enabled():
            check_read_batch(batch, n_ref=header.n_ref)
        return ReadsDataset(
            header=header,
            reads=batch,
            counters=reduce_counters(self._last_counters),
        )

    # -- split machinery ----------------------------------------------------

    def read_split_batches(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        first_voffset: int,
        split_size: Optional[int] = None,
    ) -> List[ReadBatch]:
        """One columnar batch per split — the unit that maps 1:1 onto
        device shards in the distributed pipeline."""
        import time

        from disq_tpu.runtime import ShardCounters

        splits = compute_path_splits(fs, path, split_size or self.split_size)
        sbi = self._try_load_sbi(fs, path)
        boundaries = self._split_boundaries(fs, path, header, first_voffset, splits, sbi)
        out = []
        self._last_counters = []
        for i in range(len(splits)):
            lo, hi = boundaries[i], boundaries[i + 1]
            t0 = time.perf_counter()
            batch, stats = self._decode_range_with_stats(fs, path, header, lo, hi)
            self._last_counters.append(
                ShardCounters(
                    shard_id=i,
                    records=batch.count,
                    blocks=stats[0],
                    bytes_compressed=stats[1],
                    bytes_uncompressed=stats[2],
                    wall_seconds=time.perf_counter() - t0,
                )
            )
            out.append(batch)
        return out

    def _try_load_sbi(self, fs: FileSystemWrapper, path: str) -> Optional[SbiIndex]:
        sbi_path = path + ".sbi"
        if fs.exists(sbi_path):
            return SbiIndex.from_bytes(fs.read_all(sbi_path))
        return None

    def _data_end_voffset(self, fs: FileSystemWrapper, path: str) -> int:
        """Virtual offset one past the last record: EOF minus terminator."""
        length = fs.get_file_length(path)
        tail = fs.read_range(path, max(0, length - len(BGZF_EOF_MARKER)), len(BGZF_EOF_MARKER))
        end = length - len(BGZF_EOF_MARKER) if tail == BGZF_EOF_MARKER else length
        return make_virtual_offset(end, 0)

    def _split_boundaries(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        first_voffset: int,
        splits: List[PathSplit],
        sbi: Optional[SbiIndex],
    ) -> List[int]:
        """Virtual offsets b[0..n]: split i decodes records in
        [b[i], b[i+1]). b[0] = first record (from the header read);
        b[n] = end of data."""
        end_vo = self._data_end_voffset(fs, path)
        bounds = [first_voffset]
        for s in splits[1:]:
            if sbi is not None:
                vo = sbi.first_offset_at_or_after(s.start)
            else:
                vo = self._guess_record_voffset(fs, path, header, s.start)
                if vo is None:
                    vo = end_vo
            bounds.append(max(min(vo, end_vo), bounds[-1]))
        bounds.append(end_vo)
        return bounds

    def _guess_record_voffset(
        self, fs: FileSystemWrapper, path: str, header: SamHeader, file_offset: int
    ) -> Optional[int]:
        """First record boundary at-or-after ``file_offset`` (SURVEY §3.1:
        BgzfBlockGuesser → BamRecordGuesser over a decompressed window)."""
        if file_offset == 0:
            raise ValueError("offset 0 is resolved by the header read")
        bg = BgzfBlockGuesser(fs, path)
        block_start = bg.guess_block_start(file_offset)
        if block_start is None:
            return None
        g = BamRecordGuesser(header.n_ref, [s.length for s in header.sequences])
        file_length = fs.get_file_length(path)
        # Decompress a window and search; a single huge record (long-read
        # BAMs) can exceed any fixed window, so grow geometrically until a
        # boundary is found or the window reaches EOF.
        window_csize = 4 * 0x10000
        while True:
            window_blocks, data = _walk_blocks_collect(
                fs, path, block_start, block_start + window_csize, file_length
            )
            if not window_blocks:
                return None
            window = inflate_blocks(
                data, window_blocks, base=block_start, as_array=True
            )
            u = g.find_first_record(window)
            at_eof = window_blocks[-1].end >= file_length
            if u is not None:
                # Map window offset u back to a (block, within) voffset
                # using the block usize table (ISIZE is verified on
                # inflate, so cumulative usize == window offsets).
                acc = 0
                for b in window_blocks:
                    if u < acc + b.usize:
                        return make_virtual_offset(b.pos, u - acc)
                    acc += b.usize
                return None
            if at_eof:
                return None
            window_csize *= 4

    def _decode_range(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        lo_voffset: int,
        hi_voffset: int,
    ) -> ReadBatch:
        return self._decode_range_with_stats(
            fs, path, header, lo_voffset, hi_voffset
        )[0]

    def _decode_range_with_stats(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        lo_voffset: int,
        hi_voffset: int,
    ) -> Tuple[ReadBatch, Tuple[int, int, int]]:
        """Decode all records whose start lies in [lo, hi) virtual space.

        Reads compressed blocks from lo's block through hi's block — i.e.
        past the split's byte-range end when a record straddles it.
        Returns (batch, (blocks, compressed bytes, uncompressed bytes))
        where the stats count only blocks *owned* by this range —
        ``pos ∈ [lo_block, hi_block)`` — so a block straddling a split
        boundary is attributed to exactly one side and reduced totals
        match the file.
        """
        if hi_voffset <= lo_voffset:
            return ReadBatch.empty(), (0, 0, 0)
        lo_block, lo_u = lo_voffset >> 16, lo_voffset & 0xFFFF
        hi_block, hi_u = hi_voffset >> 16, hi_voffset & 0xFFFF
        length = fs.get_file_length(path)
        # Walk blocks from lo_block through hi_block (inclusive iff hi_u>0);
        # the walk stages the compressed bytes so inflation re-uses them.
        want_end = hi_block + (1 if hi_u > 0 else 0)
        blocks, data = _walk_blocks_collect(
            fs, path, lo_block, max(want_end, lo_block + 1), length
        )
        if not blocks:
            return ReadBatch.empty(), (0, 0, 0)
        # Consecutive split ranges partition [first_block, data_end) in
        # block space, so this never under/over-counts across a whole read
        # (a sub-block range owns nothing: its block belongs to whichever
        # range starts at or before the block's start).
        owned = [b for b in blocks if b.pos < hi_block]
        stats = (
            len(owned),
            sum(b.csize for b in owned),
            sum(b.usize for b in owned),
        )
        blob = inflate_blocks(data, blocks, base=lo_block, as_array=True)
        if hi_u > 0:
            acc_before_hi = sum(b.usize for b in blocks if b.pos < hi_block)
            end_u = acc_before_hi + hi_u
        else:
            end_u = len(blob)
        record_bytes = blob[lo_u:end_u]
        offsets = scan_record_offsets(record_bytes)
        return decode_records(record_bytes, offsets, n_ref=header.n_ref), stats
