"""BamSource — the parallel BAM read path.

Reference parity: ``impl/formats/bam/BamSource.java`` (SURVEY.md §2.4,
call stack §3.1): header read on the host ("driver"); the file is cut
into byte-range splits; each split resolves its first whole-record
boundary — via the ``.sbi`` splitting index when present, else the
``BgzfBlockGuesser`` + ``BamRecordGuesser`` chain — and decodes records
from its own boundary up to the *next* split's boundary, reading past its
byte-range end to finish the straddling record ("first owner" rule).

TPU-first shape: each split yields a columnar ``ReadBatch`` (not record
objects); split workers are host-side and feed device shards. Interval
traversal (``.bai``) lives in ``disq_tpu.traversal``.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from disq_tpu.bam.codec import (
    decode_records,
    scan_record_offsets,
    scan_record_offsets_tolerant,
)
from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.bam.guesser import BamRecordGuesser
from disq_tpu.bam.header import SamHeader
from disq_tpu.bgzf.block import (
    BGZF_EOF_MARKER,
    BgzfBlock,
    make_virtual_offset,
)
from disq_tpu.bgzf.codec import BgzfReader, inflate_blocks
from disq_tpu.bgzf.guesser import (
    BgzfBlockGuesser,
    _walk_blocks_collect,
    walk_blocks_salvage,
)
from disq_tpu.fsw.filesystem import (
    FileSystemWrapper,
    PathSplit,
    compute_path_splits,
    resolve_path,
)
from disq_tpu.index.sbi import SbiIndex


def read_header(fs: FileSystemWrapper, path: str) -> Tuple[SamHeader, int]:
    """Host-side header read; returns (header, virtual offset of the first
    record) — the analogue of ``AbstractSamSource#getFileHeader``."""
    with fs.open(path) as raw:
        r = BgzfReader(raw)
        header = SamHeader.from_bam_stream(r)
        return header, r.tell_virtual()


class BamSource:
    def __init__(self, storage=None):
        self._storage = storage
        self._last_counters = []

    @property
    def split_size(self) -> int:
        return getattr(self._storage, "_split_size", 128 * 1024 * 1024)

    # -- public -------------------------------------------------------------

    def get_reads(self, path: str, traversal=None):
        from disq_tpu.api import ReadsDataset
        from disq_tpu.runtime import (
            check_read_batch,
            debug_enabled,
            reduce_counters,
            trace_phase,
        )

        from disq_tpu.runtime.errors import context_for_storage

        fs, path = resolve_path(path)
        ctx = context_for_storage(self._storage, path)
        with trace_phase("bam.read.header"):
            header, first_voffset = ctx.retrier.call(
                read_header, fs, path, what="header")
        if traversal is not None:
            from disq_tpu.traversal.bai_query import read_with_traversal

            # Index-driven reads retry transient faults whole-phase (the
            # read is bounded by the queried intervals); corrupt blocks
            # inside the traversal always raise, regardless of policy.
            with trace_phase("bam.read.traversal"):
                batch = ctx.retrier.call(
                    read_with_traversal, fs, path, header, traversal, self,
                    what="traversal",
                )
            counters = reduce_counters([])
            counters.retried_reads += ctx.retrier.retried
            return ReadsDataset(header=header, reads=batch,
                                counters=counters)
        with trace_phase("bam.read.splits"):
            from disq_tpu.runtime.columnar import concat_batches

            batches = self.read_split_batches(
                fs, path, header, first_voffset, ctx=ctx)
            # all-resident shards concatenate ON DEVICE and the dataset
            # stays a device-backed ColumnarBatch (lazy d2h per column);
            # any host shard (salvage paths, disabled knob) materializes
            # the whole read host-side exactly as before
            batch = concat_batches(batches)
        if debug_enabled():
            check_read_batch(batch, n_ref=header.n_ref)
        counters = reduce_counters(self._last_counters)
        # Header/boundary-phase retries happened outside any shard.
        counters.retried_reads += ctx.retrier.retried
        counters.skipped_blocks += ctx.skipped_blocks
        counters.quarantined_blocks += ctx.quarantined_blocks
        return ReadsDataset(header=header, reads=batch, counters=counters)

    # -- split machinery ----------------------------------------------------

    def read_split_batches(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        first_voffset: int,
        split_size: Optional[int] = None,
        ctx=None,
    ) -> List[ReadBatch]:
        """One columnar batch per split — the unit that maps 1:1 onto
        device shards in the distributed pipeline. ``ctx`` (a
        ``ShardErrorContext``) carries the error policy; each shard gets
        its own retrier + corrupt-block counters via ``ctx.for_shard``.

        Splits run through the shard-pipeline executor
        (``runtime/executor.py``): stage A range-reads + walks the
        split's compressed blocks, stage B inflates and decodes
        records, stage C emits batches in split order — so with
        ``DisqOptions.executor_workers > 1`` the I/O of split i+1
        overlaps the inflate of split i while output stays
        byte-identical to the sequential path."""
        import functools

        from disq_tpu.runtime import ShardCounters, ShardTask
        from disq_tpu.runtime.errors import (
            DisqOptions,
            context_for_storage,
            deadline_fallback_for,
        )
        from disq_tpu.runtime.executor import (
            executor_for_storage,
            read_ledger_for_storage,
        )

        if ctx is None:
            ctx = context_for_storage(self._storage, path)
        opts = getattr(self._storage, "_options", None) or DisqOptions()
        splits = compute_path_splits(fs, path, split_size or self.split_size)
        sbi = ctx.retrier.call(self._try_load_sbi, fs, path, what="sbi")
        boundaries = self._split_boundaries(
            fs, path, header, first_voffset, splits, sbi, ctx=ctx
        )
        tasks = []
        shard_ctxs = []
        for i in range(len(splits)):
            lo, hi = boundaries[i], boundaries[i + 1]
            shard_ctx = ctx.for_shard(i)
            shard_ctxs.append(shard_ctx)
            tasks.append(ShardTask(
                shard_id=i,
                fetch=functools.partial(
                    self._fetch_range, fs, path, lo, hi, shard_ctx),
                decode=functools.partial(
                    self._decode_fetched, header, ctx=shard_ctx),
                retrier=shard_ctx.retrier,
                what=f"shard{i}",
                # Deadline escalation terminal under skip/quarantine:
                # an over-budget shard is set aside as one empty batch.
                deadline_fallback=deadline_fallback_for(
                    opts, shard_ctx,
                    lambda: (ReadBatch.empty(), (0, 0, 0))),
                # Compressed byte window (coffsets) — the scheduler's
                # locality coordinate.
                byte_range=(lo >> 16, (hi >> 16) + 1),
            ))
        from disq_tpu.runtime.introspect import note_shard_counters
        from disq_tpu.runtime.scheduler import scheduled_map_ordered

        out = []
        self._last_counters = []
        ledger = read_ledger_for_storage(self._storage, path, len(tasks))
        # scheduler off (default): scheduled_map_ordered IS
        # map_ordered_resumable; on: this process leases shards from
        # the shared cross-host queue and emits only the ones it wins.
        for res in scheduled_map_ordered(
                self._storage, fs, path,
                executor_for_storage(self._storage), tasks, ledger):
            batch, stats = res.value
            shard_ctx = shard_ctxs[res.shard_id]
            c = ShardCounters(
                shard_id=res.shard_id,
                records=batch.count,
                blocks=stats[0],
                bytes_compressed=stats[1],
                bytes_uncompressed=stats[2],
                wall_seconds=res.wall_seconds,
                skipped_blocks=shard_ctx.skipped_blocks,
                quarantined_blocks=shard_ctx.quarantined_blocks,
                retried_reads=shard_ctx.retrier.retried,
            )
            self._last_counters.append(c)
            note_shard_counters("read", c)  # live /progress feed
            out.append(batch)
        return out

    def _try_load_sbi(self, fs: FileSystemWrapper, path: str) -> Optional[SbiIndex]:
        sbi_path = path + ".sbi"
        if fs.exists(sbi_path):
            return SbiIndex.from_bytes(fs.read_all(sbi_path))
        return None

    def _data_end_voffset(self, fs: FileSystemWrapper, path: str) -> int:
        """Virtual offset one past the last record: EOF minus terminator."""
        length = fs.get_file_length(path)
        tail = fs.read_range(path, max(0, length - len(BGZF_EOF_MARKER)), len(BGZF_EOF_MARKER))
        end = length - len(BGZF_EOF_MARKER) if tail == BGZF_EOF_MARKER else length
        return make_virtual_offset(end, 0)

    def _split_boundaries(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        first_voffset: int,
        splits: List[PathSplit],
        sbi: Optional[SbiIndex],
        ctx=None,
    ) -> List[int]:
        """Virtual offsets b[0..n]: split i decodes records in
        [b[i], b[i+1]). b[0] = first record (from the header read);
        b[n] = end of data.

        Transient-fault retry is *per boundary* (each boundary guess is
        a handful of reads), not around the whole phase — a whole-phase
        retry would re-execute every read and never converge under a
        sustained fault rate."""
        def _call(fn, *args, what):
            if ctx is None:
                return fn(*args)
            return ctx.retrier.call(fn, *args, what=what)

        end_vo = _call(self._data_end_voffset, fs, path, what="data_end")
        bounds = [first_voffset]
        for s in splits[1:]:
            if sbi is not None:
                vo = sbi.first_offset_at_or_after(s.start)
            else:
                vo = _call(self._guess_record_voffset, fs, path, header,
                           s.start, ctx, what="boundary")
                if vo is None:
                    vo = end_vo
            bounds.append(max(min(vo, end_vo), bounds[-1]))
        bounds.append(end_vo)
        return bounds

    def _guess_record_voffset(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        file_offset: int,
        ctx=None,
    ) -> Optional[int]:
        """First record boundary at-or-after ``file_offset`` (SURVEY §3.1:
        BgzfBlockGuesser → BamRecordGuesser over a decompressed window).

        Under a skip/quarantine ``ctx``, a corrupt block inside the
        search window is stepped over *silently* (per good-block run) —
        the shard that owns the block does the counting/quarantining
        when it decodes; counting here would double-book it."""
        from disq_tpu.runtime.errors import TruncatedReadError

        if file_offset == 0:
            raise ValueError("offset 0 is resolved by the header read")
        bg = BgzfBlockGuesser(fs, path)
        block_start = bg.guess_block_start(file_offset)
        if block_start is None:
            return None
        g = BamRecordGuesser(header.n_ref, [s.length for s in header.sequences])
        file_length = fs.get_file_length(path)
        # Decompress a window and search; a single huge record (long-read
        # BAMs) can exceed any fixed window, so grow geometrically until a
        # boundary is found or the window reaches EOF.
        window_csize = 4 * 0x10000
        while True:
            try:
                window_blocks, data = _walk_blocks_collect(
                    fs, path, block_start, block_start + window_csize,
                    file_length,
                )
            except TruncatedReadError:
                raise  # short range read: retried by the phase retrier
            except ValueError:
                if ctx is None:
                    raise
                # Malformed block header in the window: salvage-walk it
                # (silently — the owning shard books the corruption;
                # STRICT still raises with coordinates) and search each
                # good run.
                from disq_tpu.runtime.errors import inflate_blocks_salvage

                window_blocks, data, gaps = walk_blocks_salvage(
                    fs, path, block_start, block_start + window_csize,
                    file_length, ctx, owned_until=block_start,
                )
                if not window_blocks:
                    return None
                payloads = inflate_blocks_salvage(
                    data, window_blocks, block_start, ctx.silent())
                u_vo = self._search_payload_runs(g, window_blocks, payloads)
                if u_vo is not None:
                    return u_vo
                if window_blocks[-1].end >= file_length or (
                        gaps and gaps[-1][1] >= file_length):
                    return None
                window_csize *= 4
                continue
            if not window_blocks:
                return None
            try:
                window = inflate_blocks(
                    data, window_blocks, base=block_start, as_array=True
                )
            except ValueError as e:
                u_vo = self._guess_around_corruption(
                    path, g, window_blocks, data, block_start, ctx, e
                )
                if u_vo is not None:
                    return u_vo
                u = None
            else:
                u = g.find_first_record(window)
            at_eof = window_blocks[-1].end >= file_length
            if u is not None:
                # Map window offset u back to a (block, within) voffset
                # using the block usize table (ISIZE is verified on
                # inflate, so cumulative usize == window offsets).
                acc = 0
                for b in window_blocks:
                    if u < acc + b.usize:
                        return make_virtual_offset(b.pos, u - acc)
                    acc += b.usize
                return None
            if at_eof:
                return None
            window_csize *= 4

    def _guess_around_corruption(
        self, path, g, window_blocks, data, base, ctx, err
    ) -> Optional[int]:
        """Boundary search when the window holds a corrupt block: under
        STRICT (or no ctx) apply the policy — which raises with the
        block's coordinates; otherwise search each good run and return a
        virtual offset directly."""
        from disq_tpu.runtime.errors import (
            ErrorPolicy,
            ShardErrorContext,
            inflate_blocks_salvage,
        )

        if ctx is None:
            silent = ShardErrorContext(policy=ErrorPolicy.STRICT, path=path)
        else:
            silent = ctx.silent()
        payloads = inflate_blocks_salvage(data, window_blocks, base, silent)
        if all(p is not None for p in payloads):
            raise err  # batch inflate bug, not corruption — surface it
        return self._search_payload_runs(g, window_blocks, payloads)

    def _search_payload_runs(self, g, blocks, payloads) -> Optional[int]:
        """First record boundary across the contiguous good runs of a
        salvaged window: each run is searched independently (never
        spliced across a corrupt hole, which could chain-validate a
        false boundary)."""
        n = len(blocks)
        i = 0
        while i < n:
            if payloads[i] is None:
                i += 1
                continue
            j = i
            while j + 1 < n and payloads[j + 1] is not None:
                j += 1
            blob = np.frombuffer(
                b"".join(payloads[i: j + 1]), dtype=np.uint8)
            u = g.find_first_record(blob)
            if u is not None:
                acc = 0
                for k in range(i, j + 1):
                    if u < acc + len(payloads[k]):
                        return make_virtual_offset(
                            blocks[k].pos, u - acc)
                    acc += len(payloads[k])
            i = j + 1
        return None

    def _decode_range(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        lo_voffset: int,
        hi_voffset: int,
    ) -> ReadBatch:
        return self._decode_range_with_stats(
            fs, path, header, lo_voffset, hi_voffset
        )[0]

    def _decode_range_with_stats(
        self,
        fs: FileSystemWrapper,
        path: str,
        header: SamHeader,
        lo_voffset: int,
        hi_voffset: int,
        ctx=None,
    ) -> Tuple[ReadBatch, Tuple[int, int, int]]:
        """Decode all records whose start lies in [lo, hi) virtual space
        — the sequential fetch+decode composition; the executor runs the
        same two stages (``_fetch_range`` → ``_decode_fetched``) on
        separate pools."""
        from disq_tpu.runtime.errors import ErrorPolicy, ShardErrorContext

        if ctx is None:
            ctx = ShardErrorContext(policy=ErrorPolicy.STRICT, path=path)
        return self._decode_fetched(
            header,
            self._fetch_range(fs, path, lo_voffset, hi_voffset, ctx),
            ctx=ctx,
        )

    def _fetch_range(
        self,
        fs: FileSystemWrapper,
        path: str,
        lo_voffset: int,
        hi_voffset: int,
        ctx,
    ) -> Optional[Tuple]:
        """``_fetch_range_inner`` under a per-split ``bam.split.fetch``
        span carrying the shard id and virtual-offset range — one
        timeline event per split fetch, replayable by
        ``scripts/trace_report.py``."""
        from disq_tpu.runtime.tracing import span

        with span("bam.split.fetch", shard=ctx.shard_id,
                  lo=lo_voffset, hi=hi_voffset, path=path):
            return self._fetch_range_inner(
                fs, path, lo_voffset, hi_voffset, ctx)

    def _fetch_range_inner(
        self,
        fs: FileSystemWrapper,
        path: str,
        lo_voffset: int,
        hi_voffset: int,
        ctx,
    ) -> Optional[Tuple]:
        """Stage A: range-read and walk the compressed blocks covering
        [lo, hi) virtual space — from lo's block through hi's block,
        i.e. past the split's byte-range end when a record straddles it.
        Returns the staged payload for ``_decode_fetched`` (None for an
        empty range).

        ``ctx`` (``ShardErrorContext``) governs corrupt block *headers*
        found by the salvage walk; a retried attempt resets the
        corrupt-block counters here so the previous attempt's blocks
        are never double-counted (quarantine sidecar writes are
        idempotent)."""
        from disq_tpu.runtime.errors import TruncatedReadError

        ctx.skipped_blocks = 0
        ctx.quarantined_blocks = 0
        if hi_voffset <= lo_voffset:
            return None
        lo_block = lo_voffset >> 16
        hi_block, hi_u = hi_voffset >> 16, hi_voffset & 0xFFFF
        length = fs.get_file_length(path)
        # Walk blocks from lo_block through hi_block (inclusive iff hi_u>0);
        # the walk stages the compressed bytes so inflation re-uses them.
        want_end = hi_block + (1 if hi_u > 0 else 0)
        gaps = []
        try:
            blocks, data = _walk_blocks_collect(
                fs, path, lo_block, max(want_end, lo_block + 1), length
            )
        except TruncatedReadError:
            raise  # short range read: the shard retrier re-reads
        except ValueError:
            # A corrupt block HEADER breaks the BSIZE chain itself:
            # re-walk one block at a time, policy-handling each corrupt
            # span and re-syncing with the block guesser.
            blocks, data, gaps = walk_blocks_salvage(
                fs, path, lo_block, max(want_end, lo_block + 1), length,
                ctx, owned_until=hi_block,
            )
        return blocks, data, gaps, lo_voffset, hi_voffset

    def _decode_fetched(
        self,
        header: SamHeader,
        fetched: Optional[Tuple],
        ctx,
    ) -> Tuple[ReadBatch, Tuple[int, int, int]]:
        """``_decode_fetched_inner`` under a per-split
        ``bam.split.decode`` span carrying the shard id.

        A configured read filter (``DisqOptions.read_filter`` /
        ``DISQ_TPU_READ_FILTER``) applies HERE — inside the decode
        stage, per shard, before any d2h or host materialization —
        covering the resident, host, and salvage inner paths alike
        (and the BAI traversal route, which decodes through this
        method too)."""
        from disq_tpu.runtime.tracing import span

        with span("bam.split.decode", shard=ctx.shard_id):
            batch, stats = self._decode_fetched_inner(header, fetched, ctx)
            rf = self._read_filter()
            if rf is not None and batch.count:
                from disq_tpu.ops.rfilter import apply_read_filter

                batch = apply_read_filter(batch, rf)
            return batch, stats

    def _read_filter(self):
        """The storage's parsed ``ReadFilter``, or None — the operator
        module is only imported once a spec is actually set (the
        suite-off zero-work guard)."""
        import os

        opts = getattr(self._storage, "_options", None)
        spec = getattr(opts, "read_filter", None) if opts else None
        if spec is None:
            spec = os.environ.get("DISQ_TPU_READ_FILTER") or None
        if not spec:
            return None
        from disq_tpu.ops.rfilter import parse_read_filter

        return parse_read_filter(spec)

    def _decode_fetched_inner(
        self,
        header: SamHeader,
        fetched: Optional[Tuple],
        ctx,
    ) -> Tuple[ReadBatch, Tuple[int, int, int]]:
        """Stage B: inflate + record-decode a staged range.

        Returns (batch, (blocks, compressed bytes, uncompressed bytes))
        where the stats count only blocks *owned* by this range —
        ``pos ∈ [lo_block, hi_block)`` — so a block straddling a split
        boundary is attributed to exactly one side and reduced totals
        match the file.

        ``ctx`` (``ShardErrorContext``) governs corrupt blocks: the
        fault-free fast path is the one batched inflate below; only when
        it fails does the per-block salvage path run, applying the
        policy (strict raise with coordinates / skip / quarantine).

        With resident decode on (``DisqOptions.resident_decode`` /
        ``DISQ_TPU_RESIDENT_DECODE``) the fault-free fast path parses
        the shard into a device-backed ``ColumnarBatch`` in the same
        launch chain as the device codecs — when the SIMD inflate
        kernel decoded the blocks, its still-HBM-resident output is
        parsed in place (no re-upload).  Every salvage/tolerant path
        stays host-side, so error semantics (and owner-shard
        quarantine accounting) are identical.
        """
        from disq_tpu.runtime.columnar import resident_decode_enabled
        from disq_tpu.runtime.errors import inflate_blocks_salvage

        if fetched is None:
            return ReadBatch.empty(), (0, 0, 0)
        blocks, data, gaps, lo_voffset, hi_voffset = fetched
        lo_block, lo_u = lo_voffset >> 16, lo_voffset & 0xFFFF
        hi_block, hi_u = hi_voffset >> 16, hi_voffset & 0xFFFF
        if not blocks:
            return ReadBatch.empty(), (0, 0, 0)
        # Consecutive split ranges partition [first_block, data_end) in
        # block space, so this never under/over-counts across a whole read
        # (a sub-block range owns nothing: its block belongs to whichever
        # range starts at or before the block's start).
        owned = [b for b in blocks if b.pos < hi_block]
        stats = (
            len(owned),
            sum(b.csize for b in owned),
            sum(b.usize for b in owned),
        )
        if gaps:
            # Corrupt-header spans already handled by the salvage walk:
            # inflate per block and splice None sentinels at each gap so
            # record runs break there (a record straddling INTO a gap
            # must not concatenate across it).
            payloads = inflate_blocks_salvage(
                data, blocks, lo_block, ctx, owned_until=hi_block
            )
            merged = sorted(
                list(zip(blocks, payloads))
                + [(BgzfBlock(pos=lo, csize=hi - lo, usize=0), None)
                   for lo, hi in gaps],
                key=lambda bp: bp[0].pos,
            )
            batch = self._decode_runs(
                header, [b for b, _ in merged], [p for _, p in merged],
                lo_u, hi_block, hi_u, ctx=ctx,
            )
            return batch, stats
        resident = resident_decode_enabled(self._storage)
        # the device parse indexes with i32: a (pathological) >=2 GiB
        # decoded shard silently demotes to the host path instead of
        # tripping the corruption handler on valid data
        if resident and sum(b.usize for b in blocks) >= 2 ** 31:
            resident = False
        dev_handle = None
        try:
            if resident:
                blob, dev_handle = inflate_blocks(
                    data, blocks, base=lo_block, as_array=True,
                    keep_device=True)
            else:
                blob = inflate_blocks(
                    data, blocks, base=lo_block, as_array=True)
        except ValueError as first_err:
            # At least one block is corrupt: per-block salvage under the
            # policy (STRICT raises CorruptBlockError with coordinates).
            payloads = inflate_blocks_salvage(
                data, blocks, lo_block, ctx, owned_until=hi_block
            )
            if all(p is not None for p in payloads):
                # The batch inflate failed but every block decodes alone:
                # a codec-path bug, not data corruption — surface it.
                raise first_err
            batch = self._decode_runs(
                header, blocks, payloads, lo_u, hi_block, hi_u, ctx=ctx
            )
            return batch, stats
        if hi_u > 0:
            acc_before_hi = sum(b.usize for b in blocks if b.pos < hi_block)
            end_u = acc_before_hi + hi_u
        else:
            end_u = len(blob)
        record_bytes = blob[lo_u:end_u]
        try:
            offsets = scan_record_offsets(record_bytes)
            if resident:
                from disq_tpu.runtime.columnar import ColumnarBatch

                words = (dev_handle.assemble()
                         if dev_handle is not None else None)
                dev_handle = None
                # mesh-native build (runtime/mesh.py): with the knob
                # armed the parse shards over the batch axis and the
                # batch carries its mesh so sort/flagstat/depth stay
                # one sharded program; mesh_for_storage is two
                # attribute reads when off
                from disq_tpu.runtime.mesh import mesh_for_storage

                batch = ColumnarBatch.from_blob(
                    record_bytes, offsets, n_ref=header.n_ref,
                    device_words=words, origin=lo_u,
                    mesh=mesh_for_storage(self._storage))
            else:
                batch = decode_records(
                    record_bytes, offsets, n_ref=header.n_ref)
        except ValueError as e:
            if dev_handle is not None:
                dev_handle.release()
                dev_handle = None
            # Record framing/content damage inside intact BGZF blocks
            # (corruption that predates compression, so no single block
            # is identifiable): STRICT raises with the shard's
            # coordinates; skip/quarantine keep the clean prefix found
            # by the tolerant scan.
            ctx.handle_corrupt_block(
                e, block_offset=lo_block, virtual_offset=lo_voffset,
                kind="record run",
            )
            try:
                offsets = scan_record_offsets_tolerant(record_bytes)
                batch = decode_records(
                    record_bytes, offsets, n_ref=header.n_ref)
            except ValueError:
                batch = ReadBatch.empty()
        return batch, stats

    def _decode_runs(
        self,
        header: SamHeader,
        blocks,
        payloads,
        lo_u: int,
        hi_block: int,
        hi_u: int,
        ctx=None,
    ) -> ReadBatch:
        """Decode the contiguous runs of good blocks around skipped
        corrupt ones. A record straddling INTO a corrupt block is
        dropped (its tail bytes are gone); after a gap, the first record
        boundary is re-found with the ``BamRecordGuesser`` — exactly the
        machinery that already resolves split starts. ``ctx`` governs
        record-framing damage *inside* a good run (or a false post-gap
        re-sync): without it the strict scan raises as before."""
        guesser = BamRecordGuesser(
            header.n_ref, [s.length for s in header.sequences]
        )
        batches: List[ReadBatch] = []
        n = len(blocks)
        i = 0
        while i < n:
            if payloads[i] is None:
                i += 1
                continue
            j = i
            while j + 1 < n and payloads[j + 1] is not None:
                j += 1
            run_blocks = blocks[i: j + 1]
            run_payloads = payloads[i: j + 1]
            blob = np.frombuffer(b"".join(run_payloads), dtype=np.uint8)
            start_u = lo_u if i == 0 else 0
            if hi_u > 0 and any(b.pos == hi_block for b in run_blocks):
                end_u = (
                    sum(len(p) for b, p in zip(run_blocks, run_payloads)
                        if b.pos < hi_block)
                    + hi_u
                )
            else:
                end_u = len(blob)
            seg = blob[start_u:end_u]
            after_gap = i > 0 and payloads[i - 1] is None
            ends_at_gap = j + 1 < n  # next block was skipped
            if after_gap and len(seg):
                first = guesser.find_first_record(seg)
                if first is None:
                    i = j + 1
                    continue
                seg = seg[first:]
            if len(seg) == 0:
                i = j + 1
                continue
            try:
                offsets = (
                    scan_record_offsets_tolerant(seg)
                    if ends_at_gap
                    else scan_record_offsets(seg)
                )
                batches.append(
                    decode_records(seg, offsets, n_ref=header.n_ref))
            except ValueError as e:
                if ctx is None:
                    raise
                ctx.handle_corrupt_block(
                    e, block_offset=int(run_blocks[0].pos),
                    virtual_offset=make_virtual_offset(
                        int(run_blocks[0].pos), 0),
                    kind="record run",
                )
                try:
                    batches.append(decode_records(
                        seg, scan_record_offsets_tolerant(seg),
                        n_ref=header.n_ref))
                except ValueError:
                    pass  # keep the other runs
            i = j + 1
        if not batches:
            return ReadBatch.empty()
        return ReadBatch.concat(batches)
