from disq_tpu.bam.header import SamHeader, SamSequence  # noqa: F401
from disq_tpu.bam.columnar import ReadBatch  # noqa: F401
from disq_tpu.bam.codec import (  # noqa: F401
    decode_records,
    encode_records,
    scan_record_offsets,
)
from disq_tpu.bam.guesser import BamRecordGuesser  # noqa: F401
