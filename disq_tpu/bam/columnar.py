"""Columnar alignment-record batch — the TPU-native record layout.

Replaces htsjdk's per-record ``SAMRecord`` heap objects (SURVEY.md §2.8):
a batch of N records is a struct-of-arrays with fixed-width columns plus
ragged columns (name / CIGAR / seq / qual / tags) stored as flat arrays
with ``(N+1,)`` offset vectors. Fixed columns map directly onto device
arrays for masking/sorting/filtering on the VPU; ragged columns reorder
via vectorized segment gathers.

Sequence bases are stored *unpacked* (one 4-bit code per byte, values
0–15, the BAM nibble alphabet ``=ACMGRSVTWYHKDBN``) — friendlier to
vector compute than packed nibbles; packing back to BAM bytes happens in
the encoder.
"""

from __future__ import annotations

from dataclasses import dataclass, fields as dc_fields
from typing import List, Sequence

import numpy as np

SEQ_NT16 = "=ACMGRSVTWYHKDBN"
CIGAR_OPS = "MIDNSHP=X"
_NT16_CHARS = np.frombuffer(SEQ_NT16.encode(), dtype=np.uint8)


def segment_gather(
    flat: np.ndarray, offsets: np.ndarray, indices: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Gather ragged segments ``indices`` from (flat, offsets) into a new
    (flat, offsets) pair. Native per-segment memcpy when the C runtime
    is available (~10x the numpy construction on the sort permute
    path), else fully vectorized numpy (no per-record Python loop)."""
    try:
        from disq_tpu.native import segment_gather_native

        return segment_gather_native(flat, offsets, indices)
    except ImportError:
        pass
    offsets = offsets.astype(np.int64)
    lens = np.diff(offsets)[indices]
    new_off = np.zeros(len(indices) + 1, dtype=np.int64)
    np.cumsum(lens, out=new_off[1:])
    total = int(new_off[-1])
    if total == 0:
        return flat[:0].copy(), new_off
    # within[k] = k - new_off[seg(k)]  (position inside its segment)
    seg_ids = np.repeat(np.arange(len(indices)), lens)
    within = np.arange(total, dtype=np.int64) - new_off[seg_ids]
    src = offsets[indices][seg_ids] + within
    return flat[src], new_off


def _concat_ragged(
    flats: Sequence[np.ndarray], offsets: Sequence[np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    lens = [np.diff(o.astype(np.int64)) for o in offsets]
    all_lens = np.concatenate(lens) if lens else np.zeros(0, np.int64)
    new_off = np.zeros(len(all_lens) + 1, dtype=np.int64)
    np.cumsum(all_lens, out=new_off[1:])
    return (
        np.concatenate([f for f in flats])
        if flats
        else np.zeros(0, np.uint8),
        new_off,
    )


@dataclass
class ReadBatch:
    """N alignment records, struct-of-arrays.

    Fixed columns (shape ``(N,)``):
      ``refid`` i32, ``pos`` i32 (0-based), ``mapq`` u8, ``bin`` u16,
      ``flag`` u16, ``next_refid`` i32, ``next_pos`` i32, ``tlen`` i32.
    Ragged columns: ``names`` (bytes, no NUL) / ``cigars`` (u32 op-words)
    / ``seqs`` (u8 nibble codes) / ``quals`` (u8) / ``tags`` (raw bytes),
    each with its ``*_offsets`` vector of shape ``(N+1,)`` i64.
    ``quals`` shares ``seq_offsets`` (same per-record length, l_seq).
    """

    refid: np.ndarray
    pos: np.ndarray
    mapq: np.ndarray
    bin: np.ndarray
    flag: np.ndarray
    next_refid: np.ndarray
    next_pos: np.ndarray
    tlen: np.ndarray
    name_offsets: np.ndarray
    names: np.ndarray
    cigar_offsets: np.ndarray
    cigars: np.ndarray
    seq_offsets: np.ndarray
    seqs: np.ndarray
    quals: np.ndarray
    tag_offsets: np.ndarray
    tags: np.ndarray

    @property
    def count(self) -> int:
        return len(self.refid)

    def __len__(self) -> int:
        return self.count

    @classmethod
    def empty(cls) -> "ReadBatch":
        z = lambda dt: np.zeros(0, dtype=dt)  # noqa: E731
        off = np.zeros(1, dtype=np.int64)
        return cls(
            refid=z(np.int32), pos=z(np.int32), mapq=z(np.uint8),
            bin=z(np.uint16), flag=z(np.uint16), next_refid=z(np.int32),
            next_pos=z(np.int32), tlen=z(np.int32),
            name_offsets=off.copy(), names=z(np.uint8),
            cigar_offsets=off.copy(), cigars=z(np.uint32),
            seq_offsets=off.copy(), seqs=z(np.uint8), quals=z(np.uint8),
            tag_offsets=off.copy(), tags=z(np.uint8),
        )

    # -- reordering ---------------------------------------------------------

    def take(self, indices: np.ndarray) -> "ReadBatch":
        """Gather records by index — the primitive behind sort/filter."""
        indices = np.asarray(indices, dtype=np.int64)
        names, name_off = segment_gather(self.names, self.name_offsets, indices)
        cigars, cigar_off = segment_gather(self.cigars, self.cigar_offsets, indices)
        seqs, seq_off = segment_gather(self.seqs, self.seq_offsets, indices)
        quals, _ = segment_gather(self.quals, self.seq_offsets, indices)
        tags, tag_off = segment_gather(self.tags, self.tag_offsets, indices)
        return ReadBatch(
            refid=self.refid[indices], pos=self.pos[indices],
            mapq=self.mapq[indices], bin=self.bin[indices],
            flag=self.flag[indices], next_refid=self.next_refid[indices],
            next_pos=self.next_pos[indices], tlen=self.tlen[indices],
            name_offsets=name_off, names=names,
            cigar_offsets=cigar_off, cigars=cigars,
            seq_offsets=seq_off, seqs=seqs, quals=quals,
            tag_offsets=tag_off, tags=tags,
        )

    def filter(self, mask: np.ndarray) -> "ReadBatch":
        return self.take(np.nonzero(np.asarray(mask))[0])

    def slice(self, start: int, stop: int) -> "ReadBatch":
        return self.take(np.arange(start, stop, dtype=np.int64))

    @classmethod
    def concat(cls, batches: Sequence["ReadBatch"]) -> "ReadBatch":
        batches = [b for b in batches]
        if not batches:
            return cls.empty()
        if len(batches) == 1:
            return batches[0]
        names, name_off = _concat_ragged(
            [b.names for b in batches], [b.name_offsets for b in batches]
        )
        cigars, cigar_off = _concat_ragged(
            [b.cigars for b in batches], [b.cigar_offsets for b in batches]
        )
        seqs, seq_off = _concat_ragged(
            [b.seqs for b in batches], [b.seq_offsets for b in batches]
        )
        quals, _ = _concat_ragged(
            [b.quals for b in batches], [b.seq_offsets for b in batches]
        )
        tags, tag_off = _concat_ragged(
            [b.tags for b in batches], [b.tag_offsets for b in batches]
        )
        cat = lambda attr: np.concatenate([getattr(b, attr) for b in batches])  # noqa: E731
        return cls(
            refid=cat("refid"), pos=cat("pos"), mapq=cat("mapq"),
            bin=cat("bin"), flag=cat("flag"), next_refid=cat("next_refid"),
            next_pos=cat("next_pos"), tlen=cat("tlen"),
            name_offsets=name_off, names=names,
            cigar_offsets=cigar_off, cigars=cigars,
            seq_offsets=seq_off, seqs=seqs, quals=quals,
            tag_offsets=tag_off, tags=tags,
        )

    # -- decoded views ------------------------------------------------------

    def name(self, i: int) -> str:
        s, e = self.name_offsets[i], self.name_offsets[i + 1]
        return self.names[s:e].tobytes().decode()

    def sequence(self, i: int) -> str:
        s, e = self.seq_offsets[i], self.seq_offsets[i + 1]
        # vectorized nibble->char table lookup (a per-char genexpr here
        # was the hottest line of SAM text write)
        return _NT16_CHARS[self.seqs[s:e]].tobytes().decode("ascii")

    def cigar_string(self, i: int) -> str:
        s, e = self.cigar_offsets[i], self.cigar_offsets[i + 1]
        ops = self.cigars[s:e]
        if len(ops) == 0:
            return "*"
        return "".join(f"{int(op) >> 4}{CIGAR_OPS[int(op) & 0xF]}" for op in ops)

    def qual_string(self, i: int) -> str:
        s, e = self.seq_offsets[i], self.seq_offsets[i + 1]
        q = self.quals[s:e]
        if len(q) == 0 or (len(q) > 0 and q[0] == 0xFF):
            return "*"
        return (q + 33).astype(np.uint8).tobytes().decode("latin-1")

    # Reference-consumed length on the genome, per record (vectorized):
    # ops M/D/N/=/X (0,2,3,7,8) consume reference. Used by BAI binning
    # and interval overlap.
    def reference_lengths(self) -> np.ndarray:
        op = (self.cigars & 0xF).astype(np.int64)
        ln = (self.cigars >> 4).astype(np.int64)
        consumes = np.isin(op, (0, 2, 3, 7, 8))
        contrib = np.where(consumes, ln, 0)
        sums = np.add.reduceat(
            np.concatenate([contrib, [0]]),
            np.minimum(self.cigar_offsets[:-1], len(contrib)),
        ) if self.count else np.zeros(0, np.int64)
        # reduceat quirk: empty segments (no cigar) produce the next
        # element's value; mask them to 0.
        empty = np.diff(self.cigar_offsets) == 0
        sums = np.where(empty, 0, sums)
        return sums

    def alignment_ends(self) -> np.ndarray:
        """0-based exclusive end positions (pos + reflen, min 1 consumed)."""
        reflen = self.reference_lengths()
        return self.pos + np.maximum(reflen, 1).astype(np.int32)
