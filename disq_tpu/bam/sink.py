"""BamSink — single-file and multi-file BAM write paths.

Reference parity: ``impl/formats/bam/BamSink.java`` +
``HeaderlessBamOutputFormat`` + ``AnySamSinkMultiple`` (SURVEY.md §2.4,
call stack §3.3). Single-file protocol: shards write *headerless,
terminatorless* BGZF parts to a temp dir, each emitting part-local BAI /
SBI index fragments; the driver writes a header-only BGZF prefix,
concatenates prefix + parts, appends the 28-byte terminator, and merges
the index fragments by shifting each part's virtual offsets by its
absolute start position.

TPU-first twist: per-record virtual offsets inside a part are computed
*vectorized* — the canonical BGZF blocking is deterministic (65280-byte
payload per block), so ``voffset(u) = (block_comp_start[u // 65280] << 16)
| (u % 65280)`` is array arithmetic over the record-offset vector, not a
per-record stream query. This is what makes index construction a
"segmented scan over sorted virtual offsets" (BASELINE.json north star).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from disq_tpu.api import (
    BaiWriteOption,
    SbiWriteOption,
    TempPartsDirectoryWriteOption,
    WriteOption,
)
from disq_tpu.bam.codec import encode_records, encode_records_with_offsets
from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.bam.header import SamHeader
from disq_tpu.bgzf.block import BGZF_EOF_MARKER, BGZF_MAX_PAYLOAD
from disq_tpu.bgzf.codec import compress_to_bgzf, deflate_blob
from disq_tpu.fsw.filesystem import FileSystemWrapper, resolve_path
from disq_tpu.index.bai import BaiIndex, build_bai, merge_bai_fragments
from disq_tpu.index.sbi import SbiIndex
from disq_tpu.util import resolve_num_shards, shard_bounds

SBI_GRANULARITY = 4096  # htsjdk SBIIndexWriter default


def _opt_enabled(options: Sequence[WriteOption], cls, default: bool) -> bool:
    for o in options:
        if isinstance(o, cls):
            return bool(o.value)
    return default


def bgzf_compress_with_voffsets(
    blob: bytes, record_offsets: np.ndarray
) -> Tuple[bytes, np.ndarray, np.ndarray]:
    """Deflate ``blob`` into canonical BGZF (no terminator) and return
    (compressed bytes, start voffsets, end voffsets) for the records whose
    uncompressed offsets are ``record_offsets`` ((N+1,): starts + end)."""
    comp, csizes = deflate_blob(blob)
    block_comp_start = np.zeros(len(csizes) + 1, dtype=np.int64)
    np.cumsum(csizes, out=block_comp_start[1:])
    offs = record_offsets.astype(np.int64)
    block_idx = offs // BGZF_MAX_PAYLOAD
    within = offs % BGZF_MAX_PAYLOAD
    voffs = (block_comp_start[block_idx].astype(np.uint64) << np.uint64(16)) | within.astype(np.uint64)
    return comp, voffs[:-1], voffs[1:]


class BamSink:
    """Single-file BAM write (``FileCardinalityWriteOption.SINGLE``)."""

    def __init__(self, storage=None):
        self._storage = storage

    def _num_shards(self) -> int:
        return resolve_num_shards(self._storage)

    def save(
        self, dataset, path: str, options: Sequence[WriteOption] = ()
    ) -> None:
        fs, path = resolve_path(path)
        header: SamHeader = dataset.header
        batch: ReadBatch = dataset.reads
        write_bai = _opt_enabled(options, BaiWriteOption, False)
        write_sbi = _opt_enabled(options, SbiWriteOption, False)
        temp_dir = next(
            (o.path for o in options if isinstance(o, TempPartsDirectoryWriteOption)),
            path + ".parts",
        )
        if write_bai and header.sort_order != "coordinate":
            raise ValueError(
                "BAI requires a coordinate-sorted header; "
                "sort first (ReadsStorage.write(..., sort=True))"
            )

        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(temp_dir)
        try:
            self._write_parts_and_merge(
                fs, header, batch, path, temp_dir, n_shards, bounds,
                write_bai, write_sbi,
            )
        finally:
            # Idempotent write protocol (SURVEY.md §5): the merge is the
            # commit point; the staging dir never outlives save(), whether
            # it succeeds or raises.
            fs.delete(temp_dir, recursive=True)

    def _write_parts_and_merge(
        self, fs, header, batch, path, temp_dir, n_shards, bounds,
        write_bai, write_sbi,
    ) -> None:
        part_paths: List[str] = []
        part_lens: List[int] = []
        sbi_frags: List[SbiIndex] = []
        bai_frags: List[BaiIndex] = []
        for k in range(n_shards):
            part = batch.slice(int(bounds[k]), int(bounds[k + 1]))
            blob, rec_offs = encode_records_with_offsets(part)
            comp, voffs, end_voffs = bgzf_compress_with_voffsets(blob, rec_offs)
            part_path = os.path.join(temp_dir, f"part-{k:05d}")
            fs.write_all(part_path, comp)
            part_paths.append(part_path)
            part_lens.append(len(comp))
            if write_sbi:
                sbi_frags.append(
                    SbiIndex.build(
                        voffs, int(end_voffs[-1]) if part.count else 0,
                        0, granularity=SBI_GRANULARITY,
                    )
                )
            if write_bai:
                bai_frags.append(
                    build_bai(
                        part.refid, part.pos, part.alignment_ends(),
                        part.flag, voffs, end_voffs, header.n_ref,
                    )
                )

        # Driver side: header-only BGZF prefix, concat, terminator.
        header_comp = compress_to_bgzf(header.to_bam_bytes(), with_terminator=False)
        header_path = os.path.join(temp_dir, "_header")
        fs.write_all(header_path, header_comp)
        term_path = os.path.join(temp_dir, "_terminator")
        fs.write_all(term_path, BGZF_EOF_MARKER)
        fs.concat([header_path] + part_paths + [term_path], path)

        part_starts = np.zeros(len(part_lens) + 1, dtype=np.int64)
        np.cumsum(part_lens, out=part_starts[1:])
        part_starts = part_starts[:-1] + len(header_comp)
        file_length = fs.get_file_length(path)
        if write_sbi:
            merged = SbiIndex.merge(sbi_frags, list(part_starts), file_length)
            fs.write_all(path + ".sbi", merged.to_bytes())
        if write_bai:
            merged_bai = merge_bai_fragments(bai_frags, list(part_starts))
            fs.write_all(path + ".bai", merged_bai.to_bytes())


class BamSinkMultiple:
    """Directory-of-complete-BAMs write (``MULTIPLE`` cardinality;
    ref: ``AnySamSinkMultiple.java``)."""

    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        fs, path = resolve_path(path)
        header: SamHeader = dataset.header
        batch: ReadBatch = dataset.reads
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(path)
        header_bytes = header.to_bam_bytes()
        for k in range(n_shards):
            part = batch.slice(int(bounds[k]), int(bounds[k + 1]))
            data = compress_to_bgzf(header_bytes + encode_records(part))
            fs.write_all(os.path.join(path, f"part-r-{k:05d}.bam"), data)
