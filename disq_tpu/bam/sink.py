"""BamSink — single-file and multi-file BAM write paths.

Reference parity: ``impl/formats/bam/BamSink.java`` +
``HeaderlessBamOutputFormat`` + ``AnySamSinkMultiple`` (SURVEY.md §2.4,
call stack §3.3). Single-file protocol: shards write *headerless,
terminatorless* BGZF parts to a temp dir, each emitting part-local BAI /
SBI index fragments; the driver writes a header-only BGZF prefix,
concatenates prefix + parts, appends the 28-byte terminator, and merges
the index fragments by shifting each part's virtual offsets by its
absolute start position.

TPU-first twist: per-record virtual offsets inside a part are computed
*vectorized* — the canonical BGZF blocking is deterministic (65280-byte
payload per block), so ``voffset(u) = (block_comp_start[u // 65280] << 16)
| (u % 65280)`` is array arithmetic over the record-offset vector, not a
per-record stream query. This is what makes index construction a
"segmented scan over sorted virtual offsets" (BASELINE.json north star).

Shards run through the shard write pipeline
(``runtime/executor.ShardWritePipeline``): encode (slice + record
encode) → deflate (BGZF + voffset/index arithmetic) → stage (durable
part + fragment writes), overlapped across shards at
``DisqOptions.writer_workers > 1`` with byte-identical output; the
deterministic per-shard bytes + ordered concat of the part-merge
protocol are what make that safe.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from disq_tpu.api import (
    BaiWriteOption,
    SbiWriteOption,
    StageManifestWriteOption,
    TempPartsDirectoryWriteOption,
    WriteOption,
)
from disq_tpu.bam.codec import encode_records, encode_records_with_offsets
from disq_tpu.bam.columnar import ReadBatch
from disq_tpu.bam.header import SamHeader
from disq_tpu.bgzf.block import BGZF_EOF_MARKER, BGZF_MAX_PAYLOAD
from disq_tpu.bgzf.codec import (
    compress_to_bgzf,
    deflate_blob,
    device_deflate_enabled,
)
from disq_tpu.fsw.filesystem import FileSystemWrapper, resolve_path
from disq_tpu.index.bai import BaiIndex, build_bai, merge_bai_fragments
from disq_tpu.index.sbi import SbiIndex
from disq_tpu.util import resolve_num_shards, shard_bounds

SBI_GRANULARITY = 4096  # htsjdk SBIIndexWriter default


def _batch_digest(batch) -> int:
    """Content fingerprint for resume-safety: a manifest written against
    one dataset must not adopt staged parts encoded from another. CRC32
    over every column (one vectorized pass; ~GB/s, negligible next to
    deflate)."""
    import zlib

    crc = 0
    for col in (
        batch.refid, batch.pos, batch.mapq, batch.flag, batch.tlen,
        batch.names, batch.cigars, batch.seqs, batch.quals, batch.tags,
    ):
        crc = zlib.crc32(np.ascontiguousarray(col).tobytes(), crc)
    return crc


def _pickle_dumps(obj) -> bytes:
    import pickle

    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def _pickle_loads(data: bytes):
    import pickle

    return pickle.loads(data)


def _opt_enabled(options: Sequence[WriteOption], cls, default: bool) -> bool:
    for o in options:
        if isinstance(o, cls):
            return bool(o.value)
    return default


def voffsets_from_csizes(
    csizes: np.ndarray, record_offsets: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """(start voffsets, end voffsets) for records at uncompressed
    offsets ``record_offsets`` ((N+1,)) inside a BGZF stream whose
    per-block compressed sizes are ``csizes`` — pure array arithmetic,
    shared by the host deflate and the device write path (whose csizes
    are the only thing that crosses d2h)."""
    block_comp_start = np.zeros(len(csizes) + 1, dtype=np.int64)
    np.cumsum(csizes, out=block_comp_start[1:])
    offs = record_offsets.astype(np.int64)
    block_idx = offs // BGZF_MAX_PAYLOAD
    within = offs % BGZF_MAX_PAYLOAD
    voffs = (block_comp_start[block_idx].astype(np.uint64) << np.uint64(16)) | within.astype(np.uint64)
    return voffs[:-1], voffs[1:]


def bgzf_compress_with_voffsets(
    blob: bytes, record_offsets: np.ndarray, device: Optional[bool] = None
) -> Tuple[bytes, np.ndarray, np.ndarray]:
    """Deflate ``blob`` into canonical BGZF (no terminator) and return
    (compressed bytes, start voffsets, end voffsets) for the records whose
    uncompressed offsets are ``record_offsets`` ((N+1,): starts + end).
    ``device`` routes the deflate like ``bgzf.codec.deflate_blob``."""
    comp, csizes = deflate_blob(blob, device=device)
    voffs, end_voffs = voffsets_from_csizes(csizes, record_offsets)
    return comp, voffs, end_voffs


class _LazySlice:
    """Deferred shard slice for the resident write path: the SBI/BAI
    fragment builders touch host columns only when an index was
    requested, so a plain (no-index) resident write never materializes
    host records at all."""

    __slots__ = ("_batch", "_lo", "_hi", "_part")

    def __init__(self, batch, lo: int, hi: int) -> None:
        self._batch = batch
        self._lo, self._hi = lo, hi
        self._part = None

    @property
    def count(self) -> int:
        return self._hi - self._lo

    def _mat(self):
        if self._part is None:
            self._part = self._batch.slice(self._lo, self._hi)
        return self._part

    def alignment_ends(self):
        return self._mat().alignment_ends()

    def __getattr__(self, name: str):
        return getattr(self._mat(), name)


class BamSink:
    """Single-file BAM write (``FileCardinalityWriteOption.SINGLE``).

    With ``DisqOptions.device_deflate`` armed, the per-shard deflate
    routes through the device SIMD encoder (service-coalesced across
    in-flight write shards), and a sorted device-backed
    ``ColumnarBatch`` additionally encodes its records ON DEVICE
    (``runtime/device_write.py``): sort permutation → record-byte
    gather → entropy coder run HBM-resident, and only compressed
    blocks (plus csizes for the voffset/BAI arithmetic) cross d2h."""

    def __init__(self, storage=None):
        self._storage = storage
        self._device = False

    def _num_shards(self) -> int:
        return resolve_num_shards(self._storage)

    def save(
        self, dataset, path: str, options: Sequence[WriteOption] = ()
    ) -> None:
        fs, path = resolve_path(path)
        header: SamHeader = dataset.header
        batch: ReadBatch = dataset.reads
        write_bai = _opt_enabled(options, BaiWriteOption, False)
        write_sbi = _opt_enabled(options, SbiWriteOption, False)
        temp_dir = next(
            (o.path for o in options if isinstance(o, TempPartsDirectoryWriteOption)),
            path + ".parts",
        )
        if write_bai and header.sort_order != "coordinate":
            raise ValueError(
                "BAI requires a coordinate-sorted header; "
                "sort first (ReadsStorage.write(..., sort=True))"
            )

        manifest = None
        manifest_opt = next(
            (o for o in options if isinstance(o, StageManifestWriteOption)), None
        )
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        self._device = device_deflate_enabled(self._storage)
        resident = None
        if self._device:
            from disq_tpu.runtime.device_write import resident_encoder_for

            resident = resident_encoder_for(self._storage, batch)
        if manifest_opt is not None:
            from disq_tpu.runtime import StageManifest

            manifest = StageManifest(
                manifest_opt.path,
                params={
                    "target": path,
                    "records": int(batch.count),
                    "digest": _batch_digest(batch),
                    "n_shards": int(n_shards),
                    "bai": write_bai,
                    "sbi": write_sbi,
                    # the device coder's bytes are valid but not
                    # byte-identical to the zlib pin: flipping the knob
                    # between a crash and a resume must reset staging,
                    # not concatenate mixed-provenance parts
                    "device_deflate": bool(self._device),
                },
            )
        fs.mkdirs(temp_dir)
        try:
            self._write_parts_and_merge(
                fs, header, batch, path, temp_dir, n_shards, bounds,
                write_bai, write_sbi, manifest, resident,
            )
        except BaseException:
            # Idempotent write protocol (SURVEY.md §5): the merge is the
            # commit point. Without a manifest the staging dir never
            # outlives save(); with one, staged parts survive the failure
            # so a re-run resumes shard-level instead of starting over.
            if manifest is None:
                fs.delete(temp_dir, recursive=True)
            raise
        else:
            # Commit order matters: retire the manifest FIRST. A crash
            # between the two steps then leaks only a stale staging dir
            # (harmless; recreated next run) rather than a manifest whose
            # recorded part paths no longer exist.
            if manifest is not None:
                manifest.finish()
            fs.delete(temp_dir, recursive=True)

    # -- pipeline stage bodies (encode → deflate → stage) -------------------

    def _encode_shard(self, batch, bounds, k, resident=None):
        """Stage 1: slice shard ``k`` and encode its records — on host
        (CPU record encode), or as a device record-byte gather when the
        resident write path is armed (the encoded blob then stays in
        HBM for the deflate stage; host columns materialize only if an
        index build asks for them)."""
        lo, hi = int(bounds[k]), int(bounds[k + 1])
        if resident is not None:
            enc = resident.encode_shard(lo, hi)
            return _LazySlice(batch, lo, hi), enc, enc.record_offsets
        part = batch.slice(lo, hi)
        blob, rec_offs = encode_records_with_offsets(part)
        return part, blob, rec_offs

    def _deflate_shard(self, header, write_bai, write_sbi, payload):
        """Stage 2 (native-threaded CPU, or the device SIMD coder):
        BGZF deflate, vectorized voffset arithmetic, and index-fragment
        build.  A resident-encoded shard deflates straight from its
        device blob — only compressed blocks and csizes come back."""
        from disq_tpu.runtime import check_voffsets, debug_enabled

        part, blob, rec_offs = payload
        if hasattr(blob, "deflate"):  # runtime/device_write.EncodedShard
            comp, csizes = blob.deflate()
            voffs, end_voffs = voffsets_from_csizes(csizes, rec_offs)
        else:
            comp, voffs, end_voffs = bgzf_compress_with_voffsets(
                blob, rec_offs, device=self._device)
        if debug_enabled():
            check_voffsets(voffs)
        sbi_frag = bai_frag = None
        if write_sbi:
            sbi_frag = SbiIndex.build(
                voffs, int(end_voffs[-1]) if part.count else 0,
                0, granularity=SBI_GRANULARITY,
            )
        if write_bai:
            bai_frag = build_bai(
                part.refid, part.pos, part.alignment_ends(),
                part.flag, voffs, end_voffs, header.n_ref,
            )
        return comp, sbi_frag, bai_frag

    def _stage_shard(self, fs, temp_dir, k, frag_cache, payload) -> dict:
        """Stage 3 (I/O): durably write the part (and pickled index
        fragments when checkpointing); returns the shard's manifest
        record. Fragments land in ``frag_cache`` in memory and are
        pickled beside the part only when checkpointing (frag_cache is
        None ⇒ persist — the manifest path always resumes from disk)."""
        comp, sbi_frag, bai_frag = payload
        part_path = os.path.join(temp_dir, f"part-{k:05d}")
        fs.write_all(part_path, comp)
        info = {"part": part_path, "len": len(comp), "sbi": None, "bai": None}
        persist = frag_cache is None
        if sbi_frag is not None:
            info["sbi"] = part_path + ".sbi-frag"
            if persist:
                fs.write_all(info["sbi"], _pickle_dumps(sbi_frag))
        if bai_frag is not None:
            info["bai"] = part_path + ".bai-frag"
            if persist:
                fs.write_all(info["bai"], _pickle_dumps(bai_frag))
        if frag_cache is not None:
            frag_cache[k] = (sbi_frag, bai_frag)
        return info

    def _write_one_part(
        self, fs, header, batch, temp_dir, bounds, write_bai, write_sbi, k,
        frag_cache=None, resident=None,
    ) -> dict:
        """Whole-shard unit (encode + deflate + stage in one call) —
        the sequential manifest path's work function, and the
        composition the pipeline stages split apart."""
        from disq_tpu.runtime.tracing import span

        with span("bam.write.encode", shard=k):
            payload = self._encode_shard(batch, bounds, k, resident)
        with span("bam.write.deflate", shard=k):
            payload = self._deflate_shard(header, write_bai, write_sbi,
                                          payload)
        with span("bam.write.stage", shard=k):
            return self._stage_shard(fs, temp_dir, k, frag_cache, payload)

    @staticmethod
    def _part_byte_ranges(batch, bounds):
        """Exact uncompressed output byte range of every part within
        the merged record stream (the ``encode_records`` size
        arithmetic at shard bounds) — the write-lease locality hint.
        Computed only when write leasing is armed; None when the batch
        can't answer cheaply (the leases then stay FIFO, the truth)."""
        try:
            name_len = np.diff(batch.name_offsets)
            n_cigar = np.diff(batch.cigar_offsets)
            l_seq = np.diff(batch.seq_offsets)
            tag_len = np.diff(batch.tag_offsets)
        except Exception:  # noqa: BLE001 — hint-only, never fail a save
            return None
        sizes = (36 + (name_len + 1) + 4 * n_cigar + (l_seq + 1) // 2
                 + l_seq + tag_len).astype(np.int64)
        cum = np.zeros(len(sizes) + 1, np.int64)
        np.cumsum(sizes, out=cum[1:])
        return [(int(cum[int(bounds[k])]), int(cum[int(bounds[k + 1])]))
                for k in range(len(bounds) - 1)]

    def _make_write_task(self, fs, header, batch, temp_dir, bounds,
                         write_bai, write_sbi, k, frag_cache,
                         resident=None, byte_range=None):
        from disq_tpu.runtime.executor import (
            WriteShardTask,
            write_retrier_for_storage,
        )
        from disq_tpu.runtime.tracing import wrap_span

        return WriteShardTask(
            byte_range=byte_range,
            shard_id=k,
            encode=wrap_span(
                "bam.write.encode",
                lambda: self._encode_shard(batch, bounds, k, resident),
                shard=k),
            deflate=wrap_span(
                "bam.write.deflate",
                lambda p: self._deflate_shard(
                    header, write_bai, write_sbi, p), shard=k),
            stage=wrap_span(
                "bam.write.stage",
                lambda p: self._stage_shard(
                    fs, temp_dir, k, frag_cache, p), shard=k),
            # temp_dir carries the output's scheme, so the part writes
            # share the destination filesystem's breaker.
            retrier=write_retrier_for_storage(self._storage, temp_dir),
            what="bam.part",
        )

    def _write_parts_and_merge(
        self, fs, header, batch, path, temp_dir, n_shards, bounds,
        write_bai, write_sbi, manifest=None, resident=None,
    ) -> None:
        from disq_tpu.runtime import trace_phase
        from disq_tpu.runtime.executor import (
            run_write_stage,
            write_retrier_for_storage,
            writer_for_storage,
        )

        pipeline = writer_for_storage(self._storage)
        # Checkpointed: fragments must survive the process, so each
        # shard pickles them beside its part (frag_cache unused);
        # resumed shards reload from disk below.
        frag_cache = None if manifest is not None else {}

        # the historical 9-arg call survives when the resident path is
        # off (tests wrap _write_one_part with that exact signature);
        # the device write path extends it only when armed
        if resident is None:
            def one_part(k):
                return self._write_one_part(
                    fs, header, batch, temp_dir, bounds,
                    write_bai, write_sbi, k)
        else:
            def one_part(k):
                return self._write_one_part(
                    fs, header, batch, temp_dir, bounds,
                    write_bai, write_sbi, k, resident=resident)
        try:
            with trace_phase("bam.write.parts"):
                from disq_tpu.runtime.scheduler import write_leasing_armed

                leasing = write_leasing_armed(self._storage)
                if (manifest is not None and pipeline.workers == 1
                        and not leasing):
                    # Historical sequential-checkpoint path: run_stage
                    # owns skip/retry/RuntimeError semantics per shard.
                    infos = manifest.run_stage(
                        "bam.parts", n_shards, one_part)
                else:
                    # byte ranges feed write-lease locality scoring;
                    # off-path saves skip the O(n) size walk entirely
                    ranges = (self._part_byte_ranges(batch, bounds)
                              if leasing and manifest is not None
                              else None)
                    infos = run_write_stage(
                        pipeline, n_shards,
                        lambda k: self._make_write_task(
                            fs, header, batch, temp_dir, bounds,
                            write_bai, write_sbi, k, frag_cache,
                            resident,
                            byte_range=(ranges[k] if ranges else None)),
                        manifest=manifest, stage_name="bam.parts",
                        storage=self._storage, path=path, fs=fs,
                    )
        finally:
            if resident is not None:
                # the shared record-blob upload dies with the parts
                # stage; the merge below is host-side concat only
                resident.release()
        part_paths = [i["part"] for i in infos]
        part_lens = [i["len"] for i in infos]

        def _frag(k: int, which: int, key: str):
            if frag_cache is not None and k in frag_cache:
                return frag_cache[k][which]
            return _pickle_loads(fs.read_all(infos[k][key]))

        sbi_frags = [
            _frag(k, 0, "sbi") for k in range(n_shards) if infos[k]["sbi"]
        ]
        bai_frags = [
            _frag(k, 1, "bai") for k in range(n_shards) if infos[k]["bai"]
        ]

        # Driver side: header-only BGZF prefix, concat, terminator.
        # Every durable driver write runs under the same transient
        # retry budget the staged parts get (atomic create makes a
        # retried write/concat safe).
        driver = write_retrier_for_storage(self._storage, path)
        with trace_phase("bam.write.merge"):
            header_comp = compress_to_bgzf(
                header.to_bam_bytes(), with_terminator=False,
                device=self._device)
            header_path = os.path.join(temp_dir, "_header")
            driver.call(fs.write_all, header_path, header_comp,
                        what="bam.merge")
            term_path = os.path.join(temp_dir, "_terminator")
            driver.call(fs.write_all, term_path, BGZF_EOF_MARKER,
                        what="bam.merge")
            driver.call(fs.concat, [header_path] + part_paths + [term_path],
                        path, what="bam.merge")

        part_starts = np.zeros(len(part_lens) + 1, dtype=np.int64)
        np.cumsum(part_lens, out=part_starts[1:])
        part_starts = part_starts[:-1] + len(header_comp)
        file_length = fs.get_file_length(path)
        if write_sbi:
            merged = SbiIndex.merge(sbi_frags, list(part_starts), file_length)
            driver.call(fs.write_all, path + ".sbi", merged.to_bytes(),
                        what="bam.merge")
        if write_bai:
            merged_bai = merge_bai_fragments(bai_frags, list(part_starts))
            driver.call(fs.write_all, path + ".bai", merged_bai.to_bytes(),
                        what="bam.merge")


class BamSinkMultiple:
    """Directory-of-complete-BAMs write (``MULTIPLE`` cardinality;
    ref: ``AnySamSinkMultiple.java``)."""

    def __init__(self, storage=None):
        self._storage = storage

    def save(self, dataset, path: str, options: Sequence[WriteOption] = ()) -> None:
        from disq_tpu.runtime.executor import (
            WriteShardTask,
            run_write_stage,
            write_retrier_for_storage,
            writer_for_storage,
        )
        from disq_tpu.runtime.tracing import wrap_span

        fs, path = resolve_path(path)
        header: SamHeader = dataset.header
        batch: ReadBatch = dataset.reads
        n_shards, bounds = shard_bounds(self._storage, batch.count)
        fs.mkdirs(path)
        header_bytes = header.to_bam_bytes()
        device = device_deflate_enabled(self._storage)

        def make_task(k):
            def encode():
                part = batch.slice(int(bounds[k]), int(bounds[k + 1]))
                return header_bytes + encode_records(part)

            def stage(data):
                p = os.path.join(path, f"part-r-{k:05d}.bam")
                fs.write_all(p, data)
                return p

            return WriteShardTask(
                shard_id=k,
                encode=wrap_span("bam.write.encode", encode, shard=k),
                deflate=wrap_span(
                    "bam.write.deflate",
                    lambda data: compress_to_bgzf(data, device=device),
                    shard=k),
                stage=wrap_span("bam.write.stage", stage, shard=k),
                retrier=write_retrier_for_storage(self._storage, path),
                what="bam.part",
            )

        # no manifest ⇒ no durable side: the write-leasing path stays
        # off for directory-of-BAMs saves regardless of scheduler mode
        run_write_stage(writer_for_storage(self._storage), n_shards,
                        make_task, storage=self._storage, path=path)
