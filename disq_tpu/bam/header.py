"""SAM/BAM header model.

Replaces htsjdk's ``SAMFileHeader`` + ``SAMSequenceDictionary`` for this
framework. The header is host-side metadata: in the sharded pipeline it is
broadcast (replicated) to all devices' host workers, the analogue of
disq's Spark broadcast of the header (SURVEY.md §3.1).

Binary BAM header layout (SAM spec §4.2): magic ``BAM\\1``, ``l_text``,
header text, ``n_ref``, then per reference ``l_name`` (incl. NUL), name,
``l_ref``.
"""

from __future__ import annotations

import re
import struct
from dataclasses import dataclass, field, replace
from typing import BinaryIO, Dict, List, Optional, Tuple

BAM_MAGIC = b"BAM\x01"


@dataclass(frozen=True)
class SamSequence:
    """One @SQ entry / binary reference entry."""

    name: str
    length: int


@dataclass(frozen=True)
class SamHeader:
    """Immutable SAM header: raw text + parsed sequence dictionary.

    The text is authoritative (round-trips byte-identically); the
    sequence list is the parsed view used by decode/sort/index layers.
    """

    text: str
    sequences: Tuple[SamSequence, ...] = ()

    @property
    def n_ref(self) -> int:
        return len(self.sequences)

    @property
    def sort_order(self) -> str:
        m = re.search(r"^@HD\t.*\bSO:(\S+)", self.text, re.MULTILINE)
        return m.group(1) if m else "unknown"

    def with_sort_order(self, so: str) -> "SamHeader":
        if re.search(r"^@HD\t", self.text, re.MULTILINE):
            if re.search(r"^@HD\t.*\bSO:\S+", self.text, re.MULTILINE):
                text = re.sub(
                    r"(^@HD\t.*\bSO:)\S+", lambda m: m.group(1) + so,
                    self.text, count=1, flags=re.MULTILINE,
                )
            else:
                text = re.sub(
                    r"^(@HD\t[^\n]*)", lambda m: m.group(1) + f"\tSO:{so}",
                    self.text, count=1, flags=re.MULTILINE,
                )
        else:
            text = f"@HD\tVN:1.6\tSO:{so}\n" + self.text
        return replace(self, text=text)

    def ref_index(self, name: str) -> int:
        for i, s in enumerate(self.sequences):
            if s.name == name:
                return i
        raise KeyError(f"reference {name!r} not in sequence dictionary")

    def ref_name(self, index: int) -> str:
        if index == -1:
            return "*"
        return self.sequences[index].name

    # -- construction -------------------------------------------------------

    @classmethod
    def from_text(cls, text: str) -> "SamHeader":
        seqs = []
        for line in text.splitlines():
            if line.startswith("@SQ"):
                fields = dict(
                    f.split(":", 1) for f in line.split("\t")[1:] if ":" in f
                )
                seqs.append(SamSequence(fields["SN"], int(fields["LN"])))
        return cls(text=text, sequences=tuple(seqs))

    @classmethod
    def build(cls, sequences: List[Tuple[str, int]], sort_order: str = "unsorted") -> "SamHeader":
        lines = [f"@HD\tVN:1.6\tSO:{sort_order}"]
        lines += [f"@SQ\tSN:{n}\tLN:{l}" for n, l in sequences]
        return cls.from_text("\n".join(lines) + "\n")

    # -- binary BAM header --------------------------------------------------

    def to_bam_bytes(self) -> bytes:
        """Serialize as the binary BAM header block (magic..refs)."""
        text_b = self.text.encode()
        out = bytearray()
        out += BAM_MAGIC
        out += struct.pack("<i", len(text_b))
        out += text_b
        out += struct.pack("<i", len(self.sequences))
        for s in self.sequences:
            name_b = s.name.encode() + b"\x00"
            out += struct.pack("<i", len(name_b))
            out += name_b
            out += struct.pack("<i", s.length)
        return bytes(out)

    @classmethod
    def from_bam_stream(cls, stream) -> "SamHeader":
        """Parse the binary BAM header from a decompressed stream
        (``BgzfReader`` or any object with ``read_exact``/``read``)."""
        read = getattr(stream, "read_exact", None) or (
            lambda n: _read_exact(stream, n)
        )
        magic = read(4)
        if magic != BAM_MAGIC:
            raise ValueError(f"not a BAM stream (magic {magic!r})")
        (l_text,) = struct.unpack("<i", read(4))
        text = read(l_text).decode(errors="replace")
        # Some writers NUL-pad the text field.
        text = text.rstrip("\x00")
        (n_ref,) = struct.unpack("<i", read(4))
        seqs = []
        for _ in range(n_ref):
            (l_name,) = struct.unpack("<i", read(4))
            name = read(l_name)[:-1].decode()
            (l_ref,) = struct.unpack("<i", read(4))
            seqs.append(SamSequence(name, l_ref))
        binary_seqs = tuple(seqs)
        hdr = cls.from_text(text)
        # The binary sequence list is authoritative when the text lacks @SQ.
        if not hdr.sequences and binary_seqs:
            hdr = replace(hdr, sequences=binary_seqs)
        return hdr


def _read_exact(stream: BinaryIO, n: int) -> bytes:
    data = b""
    while len(data) < n:
        chunk = stream.read(n - len(data))
        if not chunk:
            raise EOFError("truncated BAM header")
        data += chunk
    return data
